"""Continuous PromQL rule engine: recording + alerting rules as
incremental tile maintenance.

Reference: the Prometheus rule manager (rules/manager.go — groups on an
interval, recording rules written back as series, alert rules with
``for``-duration pending→firing state machines), rebuilt on the tiled
range-vector engine's ms lattice (ops/prom.py, TiLT arXiv:2301.12030):
instead of re-scanning every rule's full window each tick, the group
keeps PER-TILE partial records per matched series and the ingest path
marks tiles dirty (storage/engine.py calls ``note_write_*`` PRE-apply,
the write-ahead-mark contract of storage/rollup.py), so a tick refolds
only dirtied/new tiles and answers every rule window from a merged tile
prefix — O(new tiles), not O(window × rules).  Taurus (arXiv:2506.20010)
makes the same mergeable-cell argument for maintenance near the data.

Division of labor across the three continuous tiers (see also
services/stream.py and services/continuous.py):

  * StreamService — ingest-time fold of InfluxQL accumulable aggregates
    into in-memory window cells; never re-reads storage.
  * ContinuousQueryService — scheduled SELECT ... INTO re-reading
    storage for closed windows; arbitrary InfluxQL, no incrementality.
  * RuleManager (this module) — PromQL rule fleets over *incrementally
    maintained* tile state, with a full-rescan fallback for expressions
    the tile algebra cannot express.

Correctness contract: every tick's incremental answer is BITWISE
identical to a from-scratch evaluation (fold every window tile off one
full scan, merge identically) — ``OGT_RULES_VERIFY=1`` asserts it on
every tick (bench/loadgen/tests run with it on).  That contract pins the
fold/merge arithmetic to host numpy float64 in a canonical series order;
the matcher probes still ride the columnar label tier (index/labels.py)
and the full-rescan fallback leg evaluates through the ordinary
planner-routed engine kernels (query/offload.py decides host/device/
mesh), with fold timings fed to the planner's observations.

Durability (the rules-state dir ``<root>/rules/<db>/<group>.json``):
group config, the last-evaluated watermark, pending/firing alert state
and per-rule fire/resolve counts persist with the rollup state-save
pattern (tmp + fsync + rename, version-skippable snapshots).  A tick
CLAIMS its eval time durably *before* evaluating (failpoint
``rules-mark-before-eval`` sits on that edge); alert transitions and the
watermark land in one final fsync — so a crash anywhere mid-tick either
re-evaluates the tick from scratch (fires counted once, recording
write-back is last-write-wins idempotent) or has already recorded the
transition: never a double-fire, never a silently un-fired alert.

``OGT_RULES=0`` disables the subsystem: no manager is constructed, the
engine's ``rules_hook`` stays None and every write/query path is
bit-identical to the pre-rules tree (one ``is None`` check).
"""

from __future__ import annotations

import json
import math
import os
import time as _time
from contextlib import contextmanager

import numpy as np

from opengemini_tpu.ops import prom as promops
from opengemini_tpu.promql import parser as pp
from opengemini_tpu.record import FieldType
from opengemini_tpu.utils import lockdep, tracing
from opengemini_tpu.utils.failpoint import inject as _fp
from opengemini_tpu.utils.querytracker import GLOBAL as TRACKER
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.utils.stats import observe_ns as _observe_ns

NS = 1_000_000_000
MS_NS = 1_000_000  # ns per ms

_MAX_DIRTY = 4096  # beyond this a selector collapses to full re-dirty


def enabled_by_env() -> bool:
    return os.environ.get("OGT_RULES", "1") != "0"


def default_interval_s() -> float:
    return float(os.environ.get("OGT_RULES_INTERVAL_S", "") or 15.0)


def default_lateness_s() -> float:
    return float(os.environ.get("OGT_RULES_LATENESS_S", "") or 0.0)


def verify_enabled() -> bool:
    return os.environ.get("OGT_RULES_VERIFY", "0") == "1"


def max_window_tiles() -> int:
    return int(os.environ.get("OGT_RULES_MAX_TILES", "") or 4096)


class RuleError(ValueError):
    pass


# -- expression compiler ------------------------------------------------------

_OVER_TIME_MAP = {
    "sum_over_time": "sum", "count_over_time": "count",
    "avg_over_time": "avg", "min_over_time": "min",
    "max_over_time": "max", "stddev_over_time": "stddev",
    "stdvar_over_time": "stdvar", "last_over_time": "last",
    "present_over_time": "present",
}
_RANGE_FUNCS = {"rate": "rate", "increase": "increase", "delta": "delta",
                "changes": "changes", "resets": "resets",
                **_OVER_TIME_MAP}
_CMP_OPS = {">": np.greater, "<": np.less, ">=": np.greater_equal,
            "<=": np.less_equal, "==": np.equal, "!=": np.not_equal}
_AGG_OPS = {"sum", "avg", "min", "max", "count"}


class _Compiled:
    """The tile-eligible normal form of a rule expression:

        [agg_op by/without (...)] func(metric{matchers}[w]) [cmp literal]

    with func answerable from merged tile partials (ops/prom.py
    PARTIAL_* sets).  Anything else keeps ``tiled=False`` and the rule
    evaluates through the engine's full rescan each tick."""

    __slots__ = ("tiled", "metric", "matchers", "window_s", "func",
                 "agg_op", "agg_grouping", "agg_without",
                 "cmp_op", "cmp_thr", "cmp_flip")

    def __init__(self):
        self.tiled = False
        self.metric = ""
        self.matchers: list = []
        self.window_s = 0.0
        self.func = ""
        self.agg_op: str | None = None
        self.agg_grouping: list[str] = []
        self.agg_without = False
        self.cmp_op: str | None = None
        self.cmp_thr = 0.0
        self.cmp_flip = False  # literal was on the LHS

    @property
    def window_ms(self) -> int:
        return int(round(self.window_s * 1000.0))


def compile_expr(text: str) -> _Compiled:
    """Parse + shape-match.  Raises on a parse error (a rule that can
    never evaluate must be rejected at declare time); an unmatched but
    valid shape compiles to the fallback."""
    node = pp.parse(text)
    c = _Compiled()
    if isinstance(node, pp.BinaryOp) and node.op in _CMP_OPS \
            and not node.bool_mod:
        if isinstance(node.rhs, pp.NumberLit):
            c.cmp_op, c.cmp_thr = node.op, float(node.rhs.val)
            node = node.lhs
        elif isinstance(node.lhs, pp.NumberLit):
            c.cmp_op, c.cmp_thr = node.op, float(node.lhs.val)
            c.cmp_flip = True
            node = node.rhs
    if isinstance(node, pp.Aggregation) and node.op in _AGG_OPS \
            and node.param is None:
        c.agg_op = node.op
        c.agg_grouping = list(node.grouping)
        c.agg_without = bool(node.without)
        node = node.expr
    if not (isinstance(node, pp.FunctionCall)
            and node.name in _RANGE_FUNCS and len(node.args) == 1
            and isinstance(node.args[0], pp.MatrixSelector)):
        return c
    ms = node.args[0]
    vs = ms.vector
    if not vs.metric or vs.offset_s != 0:
        return c
    w_ms = ms.range_s * 1000.0
    if w_ms <= 0 or w_ms != round(w_ms):
        return c  # sub-ms window edges can't land on an ms lattice
    c.tiled = True
    c.metric = vs.metric
    c.matchers = list(vs.matchers)
    c.window_s = ms.range_s
    c.func = _RANGE_FUNCS[node.name]
    return c


# -- rule model ---------------------------------------------------------------

class Rule:
    """One rule in a group.  kind 'recording' writes its result vector
    back as series named `name`; kind 'alerting' drives a for-duration
    pending→firing state machine keyed by output label set."""

    def __init__(self, name: str, expr: str, kind: str = "recording",
                 labels: dict | None = None, for_s: float = 0.0,
                 annotations: dict | None = None):
        if kind not in ("recording", "alerting"):
            raise RuleError(f"unknown rule kind {kind!r}")
        if not name:
            raise RuleError("rule name required")
        if kind == "recording" and not name.replace("_", "").replace(
                ":", "").isalnum():
            raise RuleError(f"invalid recording rule metric name {name!r}")
        self.name = name
        self.expr = expr
        self.kind = kind
        self.labels = dict(labels or {})
        self.for_s = float(for_s)
        self.annotations = dict(annotations or {})
        self.compiled = compile_expr(expr)

    def to_json(self) -> dict:
        return {"name": self.name, "expr": self.expr, "kind": self.kind,
                "labels": self.labels, "for_s": self.for_s,
                "annotations": self.annotations}

    @classmethod
    def from_json(cls, j: dict) -> "Rule":
        return cls(j["name"], j["expr"], j.get("kind", "recording"),
                   j.get("labels"), j.get("for_s", 0.0),
                   j.get("annotations"))


def _sel_sig(metric: str, matchers) -> tuple:
    return (metric, tuple(sorted((m.name, m.op, m.value)
                                 for m in matchers)))


class _SelState:
    """Per-(group, selector) incremental tile state: a series registry
    (accretion-ordered, with a cached canonical sort for deterministic
    aggregation) plus {tile_idx: partial record} for every computed
    non-empty tile and the `covered` set distinguishing computed-empty
    from never-computed."""

    def __init__(self, metric: str, matchers):
        self.vs = pp.VectorSelector(metric=metric, matchers=list(matchers))
        self.metric = metric
        self.key2row: dict[tuple, int] = {}
        self.keys: list[tuple] = []
        self.labels: list[dict] = []
        self.tiles: dict[int, dict] = {}
        self.covered: set[int] = set()
        self.dirty: set[int] = set()
        self.dirty_all = True  # bootstrap: first tick folds the window
        self._canon: np.ndarray | None = None

    @property
    def n_series(self) -> int:
        return len(self.keys)

    def canon_order(self) -> np.ndarray:
        """Registry rows sorted by series key — the canonical reduction
        order both evaluation legs share (bit-identity needs ONE order,
        and the incremental registry accretes in arrival order)."""
        if self._canon is None or len(self._canon) != len(self.keys):
            self._canon = np.array(
                sorted(range(len(self.keys)), key=lambda i: self.keys[i]),
                dtype=np.int64)
        return self._canon

    def intern_rows(self, labels: list[dict]) -> np.ndarray:
        rows = np.empty(len(labels), np.int64)
        for i, tags in enumerate(labels):
            key = tuple(sorted(tags.items()))
            row = self.key2row.get(key)
            if row is None:
                row = len(self.keys)
                self.key2row[key] = row
                self.keys.append(key)
                self.labels.append(dict(tags))
                self._canon = None
            rows[i] = row
        return rows

    def rec_view(self, tile: int) -> dict | None:
        """The tile's record padded to the CURRENT registry size (tiles
        folded before a series appeared stay stored at their old size)."""
        rec = self.tiles.get(tile)
        if rec is None:
            return None
        S = self.n_series
        have = len(rec["n"])
        if have == S:
            return rec
        out = promops.empty_tile_partials(S)
        for f, _fill in promops.TILE_PARTIAL_FIELDS:
            out[f][:have] = rec[f]
        self.tiles[tile] = out
        return out


class RuleGroup:
    """Rules sharing one evaluation interval, one ms lattice (g = gcd of
    the interval and every tiled window), and one durable state file."""

    def __init__(self, db: str, name: str, interval_s: float,
                 lateness_s: float, state_path: str):
        if interval_s <= 0:
            raise RuleError("group interval must be positive")
        self.db = db
        self.name = name
        self.interval_s = float(interval_s)
        self.lateness_s = float(lateness_s)
        self.state_path = state_path
        self.rules: list[Rule] = []
        # serializes ticks (and ctrl-forced ticks) per group; the
        # manager-wide lock is never held across a storage scan
        self.m_lock = lockdep.Lock()
        self.io_lock = lockdep.Lock()
        self.ver = 0
        self._saved_ver = -1
        self.g_ms = max(1, int(round(self.interval_s * 1000.0)))
        self.last_eval_ns: int | None = None
        self.claimed_ns: int | None = None
        # rule name -> {key_json: {"state","active_since_ns","fired_at_ns",
        #               "value"}}
        self.alerts: dict[str, dict] = {}
        self.fires: dict[str, int] = {}
        self.resolves: dict[str, int] = {}
        self.last_tick_ms = 0.0
        self.last_results: dict[str, dict] = {}  # in-memory, per tick
        self.last_e_tile: int | None = None
        self._sels: dict[tuple, _SelState] = {}
        # (lo_ms, hi_ms] spans of writes between note_write_* and
        # write_done: tiles overlapping one stay dirty this tick (a fold
        # scanning mid-apply rows would clear a mark the rows need)
        self.inflight: list[tuple[int, int]] = []

    # -- lattice / shape -------------------------------------------------

    def interval_ms(self) -> int:
        return max(1, int(round(self.interval_s * 1000.0)))

    def relattice(self) -> None:
        """g = gcd(interval, tiled windows); windows whose tile count
        would blow the budget demote to the rescan fallback."""
        g = self.interval_ms()
        for r in self.rules:
            if r.compiled.tiled:
                g = math.gcd(g, r.compiled.window_ms)
        cap = max_window_tiles()
        for r in self.rules:
            if r.compiled.tiled and r.compiled.window_ms // g > cap:
                r.compiled.tiled = False
        self.g_ms = g
        self._sels = {}
        for r in self.rules:
            c = r.compiled
            if not c.tiled:
                continue
            sig = _sel_sig(c.metric, c.matchers)
            if sig not in self._sels:
                self._sels[sig] = _SelState(c.metric, c.matchers)
        # lattice moved: all cached tiles are keyed on the old g
        for s in self._sels.values():
            s.dirty_all = True

    def sel_for(self, c: _Compiled) -> _SelState:
        return self._sels[_sel_sig(c.metric, c.matchers)]

    def max_window_tiles_of(self, sel: _SelState) -> int:
        wt = 0
        for r in self.rules:
            c = r.compiled
            if c.tiled and self.sel_for(c) is sel:
                wt = max(wt, c.window_ms // self.g_ms)
        return wt

    def watched_metrics(self) -> set[str]:
        return {s.metric for s in self._sels.values()}

    # -- durable state ---------------------------------------------------

    def snapshot(self) -> tuple:
        self.ver += 1
        return (self.ver, json.dumps({
            "name": self.name, "db": self.db,
            "interval_s": self.interval_s, "lateness_s": self.lateness_s,
            "rules": [r.to_json() for r in self.rules],
            "last_eval_ns": self.last_eval_ns,
            "claimed_ns": self.claimed_ns,
            "alerts": self.alerts,
            "fires": self.fires, "resolves": self.resolves,
        }))

    def save(self, snap: tuple) -> None:
        ver, payload = snap
        with self.io_lock:
            if ver <= self._saved_ver:
                return  # a newer snapshot is already durable
            os.makedirs(os.path.dirname(self.state_path), exist_ok=True)
            tmp = self.state_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.state_path)
            self._saved_ver = ver

    @classmethod
    def load(cls, path: str) -> "RuleGroup | None":
        try:
            with open(path, encoding="utf-8") as f:
                j = json.load(f)
        except (OSError, ValueError):
            return None
        try:
            g = cls(j["db"], j["name"], j["interval_s"],
                    j.get("lateness_s", 0.0), path)
            for rj in j.get("rules", []):
                g.rules.append(Rule.from_json(rj))
        except (KeyError, RuleError):
            return None
        g.last_eval_ns = j.get("last_eval_ns")
        g.claimed_ns = j.get("claimed_ns")
        g.alerts = j.get("alerts", {})
        g.fires = {k: int(v) for k, v in j.get("fires", {}).items()}
        g.resolves = {k: int(v) for k, v in j.get("resolves", {}).items()}
        g.relattice()
        return g


@contextmanager
def _stage(name: str):
    t0 = _time.perf_counter_ns()
    try:
        yield
    finally:
        ns = _time.perf_counter_ns() - t0
        tracing.record_stage(name, ns)
        TRACKER.add_stage_ns(TRACKER.current_qid(), name, ns)


def _overlaps(inflight, lo_ms: int, hi_ms: int) -> bool:
    return any(a < hi_ms and lo_ms < b for a, b in inflight)


class RuleManager:
    """Owns every rule group of one engine: the write-path dirty hook
    (engine.rules_hook), the governed tick (services/rules.py), the
    durable alert/watermark state, and the /api/v1/rules surfaces."""

    def __init__(self, engine, prom=None):
        from opengemini_tpu.promql.engine import PromEngine

        self.engine = engine
        self.prom = prom if prom is not None else PromEngine(engine)
        self._lock = lockdep.mark_hot(lockdep.RLock(), "rules.manager_lock")
        self._groups: dict[tuple[str, str], RuleGroup] = {}
        self._watched: dict[str, set[str]] = {}  # db -> metric names
        self._closed = False
        self._load_all()
        engine.rules_hook = self
        self._stats_provider = self._gauges
        STATS.register_provider("rules", self._stats_provider)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._lock:
            groups = list(self._groups.values())
        for g in groups:
            with g.m_lock:
                g.save(g.snapshot())
        STATS.unregister_provider("rules", self._stats_provider)
        if getattr(self.engine, "rules_hook", None) is self:
            self.engine.rules_hook = None

    # -- config ----------------------------------------------------------

    def _dir(self, db: str) -> str:
        return os.path.join(self.engine.root, "rules", db)

    def _load_all(self) -> None:
        root = os.path.join(self.engine.root, "rules")
        if not os.path.isdir(root):
            return
        for db in sorted(os.listdir(root)):
            dbdir = os.path.join(root, db)
            if not os.path.isdir(dbdir):
                continue
            for fn in sorted(os.listdir(dbdir)):
                if not fn.endswith(".json"):
                    continue
                g = RuleGroup.load(os.path.join(dbdir, fn))
                if g is not None:
                    self._groups[(g.db, g.name)] = g
        self._rebuild_watched()

    def _rebuild_watched(self) -> None:
        watched: dict[str, set[str]] = {}
        for (db, _n), g in self._groups.items():
            watched.setdefault(db, set()).update(g.watched_metrics())
        self._watched = watched

    def declare_group(self, db: str, name: str,
                      interval_s: float | None = None,
                      lateness_s: float | None = None) -> RuleGroup:
        if db not in self.engine.databases:
            raise RuleError(f"database {db!r} does not exist")
        with self._lock:
            g = self._groups.get((db, name))
            if g is None:
                g = RuleGroup(
                    db, name,
                    interval_s if interval_s is not None
                    else default_interval_s(),
                    lateness_s if lateness_s is not None
                    else default_lateness_s(),
                    os.path.join(self._dir(db), f"{name}.json"))
                self._groups[(db, name)] = g
            elif interval_s is not None or lateness_s is not None:
                if interval_s is not None:
                    g.interval_s = float(interval_s)
                if lateness_s is not None:
                    g.lateness_s = float(lateness_s)
                g.relattice()
            self._rebuild_watched()
        with g.m_lock:
            g.save(g.snapshot())
        return g

    def add_rule(self, db: str, group: str, rule: Rule,
                 interval_s: float | None = None,
                 lateness_s: float | None = None) -> RuleGroup:
        return self.add_rules(db, group, [rule], interval_s, lateness_s)

    def add_rules(self, db: str, group: str, rules: list,
                  interval_s: float | None = None,
                  lateness_s: float | None = None) -> RuleGroup:
        """Batch declare: one relattice + one state fsync for the whole
        list (a fleet declare is one durable write, not thousands)."""
        g = self.declare_group(db, group, interval_s, lateness_s)
        with self._lock:
            names = {r.name for r in rules}
            g.rules = [r for r in g.rules
                       if r.name not in names] + list(rules)
            g.relattice()
            self._rebuild_watched()
        with g.m_lock:
            g.save(g.snapshot())
        return g

    def drop_rule(self, db: str, group: str, name: str) -> None:
        with self._lock:
            g = self._groups.get((db, group))
            if g is None:
                raise RuleError(f"unknown rule group {db}.{group}")
            before = len(g.rules)
            g.rules = [r for r in g.rules if r.name != name]
            if len(g.rules) == before:
                raise RuleError(f"unknown rule {name!r} in {db}.{group}")
            g.alerts.pop(name, None)
            g.relattice()
            self._rebuild_watched()
        with g.m_lock:
            g.save(g.snapshot())

    def drop_group(self, db: str, group: str) -> None:
        with self._lock:
            g = self._groups.pop((db, group), None)
            self._rebuild_watched()
        if g is None:
            raise RuleError(f"unknown rule group {db}.{group}")
        try:
            os.remove(g.state_path)
        except OSError:
            pass

    def drop_db_state(self, db: str) -> None:
        """DROP DATABASE cleanup (mirrors rollup.drop_db_state)."""
        import shutil

        with self._lock:
            for key in [k for k in self._groups if k[0] == db]:
                self._groups.pop(key)
            self._rebuild_watched()
        shutil.rmtree(self._dir(db), ignore_errors=True)

    def groups_for(self, db: str | None = None) -> list[RuleGroup]:
        with self._lock:
            return [g for (d, _n), g in sorted(self._groups.items())
                    if db is None or d == db]

    def dbs_with_groups(self) -> list[str]:
        with self._lock:
            return sorted({d for d, _n in self._groups})

    def invalidate(self, db: str, group: str | None = None) -> int:
        """Drop every cached tile of the matching groups so the next
        tick refolds whole windows from storage — the forced from-
        scratch leg bench/loadgen measure the incremental path against
        (and the repair hammer if tile state is ever suspect)."""
        n = 0
        with self._lock:
            for (d, name), g in self._groups.items():
                if d != db or (group is not None and name != group):
                    continue
                for sel in g._sels.values():
                    sel.dirty_all = True
                    n += 1
        return n

    # -- write-path dirty marking (engine.rules_hook) --------------------

    def note_write_points(self, db: str, rp: str | None, points):
        watched = self._watched.get(db)
        if not watched:
            return None
        by_mst: dict[str, list[int]] = {}
        for p in points:
            if p[0] in watched:
                by_mst.setdefault(p[0], []).append(p[2])
        if not by_mst:
            return None
        spans = {m: (min(ts), max(ts)) for m, ts in by_mst.items()}
        return self._note_spans(db, spans)

    def note_write_columnar(self, db: str, rp: str | None, batch):
        watched = self._watched.get(db)
        if not watched:
            return None
        hit = [(i, m) for i, m in enumerate(batch.measurements)
               if m in watched]
        if not hit:
            return None
        row_mst = batch.row_mst()
        spans: dict[str, tuple[int, int]] = {}
        for mid, m in hit:
            ts = batch.ts[row_mst == mid]
            if len(ts):
                spans[m] = (int(ts.min()), int(ts.max()))
        if not spans:
            return None
        return self._note_spans(db, spans)

    def _note_spans(self, db: str, spans: dict[str, tuple[int, int]]):
        """Write-ahead mark: dirty the touched tiles of every watching
        selector and register the span in flight BEFORE the rows apply
        (storage/rollup.py note contract); the engine's write_done
        releases the floor once the rows are readable."""
        token: list = []
        with self._lock:
            for g in self._groups.values():
                if g.db != db:
                    continue
                marked = False
                for sel in g._sels.values():
                    span = spans.get(sel.metric)
                    if span is None:
                        continue
                    lo_t = int((span[0] // MS_NS - 1) // g.g_ms)
                    hi_t = int((span[1] // MS_NS + g.g_ms - 1) // g.g_ms) + 1
                    if hi_t - lo_t > _MAX_DIRTY \
                            or len(sel.dirty) > _MAX_DIRTY:
                        sel.dirty_all = True
                    else:
                        sel.dirty.update(range(lo_t, hi_t))
                    marked = True
                if marked:
                    span_lo = min(s[0] for m, s in spans.items()
                                  if any(sel.metric == m
                                         for sel in g._sels.values()))
                    span_hi = max(s[1] for m, s in spans.items()
                                  if any(sel.metric == m
                                         for sel in g._sels.values()))
                    ent = (span_lo // MS_NS, span_hi // MS_NS + 1)
                    g.inflight.append(ent)
                    token.append((g, ent))
                    STATS.incr("rules", "dirty_marks")
        return token or None

    def write_done(self, token) -> None:
        with self._lock:
            for g, ent in token:
                try:
                    g.inflight.remove(ent)
                except ValueError:
                    pass

    # -- evaluation ------------------------------------------------------

    def tick(self, now_ns: int | None = None, db: str | None = None,
             stop=None) -> int:
        """Evaluate every group whose next lattice eval time has
        arrived.  Returns the number of groups evaluated."""
        if now_ns is None:
            now_ns = _time.time_ns()
        ran = 0
        for g in self.groups_for(db):
            if stop is not None and stop.is_set():
                break
            if self.tick_group(g, now_ns):
                ran += 1
        return ran

    def eval_time(self, g: RuleGroup, now_ns: int) -> int:
        interval_ns = int(round(g.interval_s * NS))
        return ((now_ns - int(round(g.lateness_s * NS)))
                // interval_ns * interval_ns)

    def tick_group(self, g: RuleGroup, now_ns: int) -> bool:
        te_ns = self.eval_time(g, now_ns)
        if te_ns <= (g.last_eval_ns or 0) or not g.rules:
            return False
        with g.m_lock:
            # re-check under the tick lock (ctrl tick racing the service)
            if te_ns <= (g.last_eval_ns or 0):
                return False
            t0 = _time.perf_counter_ns()
            qid = TRACKER.register(f"rules {g.db}.{g.name}", g.db)
            try:
                self._tick_locked(g, te_ns)
            finally:
                dur_ns = _time.perf_counter_ns() - t0
                g.last_tick_ms = dur_ns / 1e6
                _observe_ns("rules_tick_seconds", dur_ns)
                from opengemini_tpu.utils.slowlog import GLOBAL as SLOWLOG

                if SLOWLOG.enabled():
                    SLOWLOG.note(qid, f"rules {g.db}.{g.name}", g.db,
                                 dur_ns / 1e6,
                                 stages=TRACKER.stages_of(qid),
                                 extra={"kind": "rules"})
                TRACKER.unregister(qid)
        STATS.incr("rules", "ticks")
        return True

    def _tick_locked(self, g: RuleGroup, te_ns: int) -> None:
        # -- mark: durably claim the tick BEFORE evaluating.  A crash
        # past this point re-runs the same te (last_eval unmoved), and
        # alert transitions/fire counts only land in the final save — so
        # the re-run cannot double-count, and recording write-back is
        # last-write-wins idempotent.
        with _stage("rules_mark"):
            g.claimed_ns = te_ns
            g.save(g.snapshot())
        _fp("rules-mark-before-eval")

        te_ms = te_ns // MS_NS
        e_tile = te_ms // g.g_ms
        with self._lock:
            inflight = list(g.inflight)

        # -- fold: refold dirty/new tiles per selector (one storage scan
        # per coalesced run), matcher probes through the label tier
        claimed: list[tuple[_SelState, set[int]]] = []
        lagged = False
        try:
            with _stage("rules_fold"):
                for sel in g._sels.values():
                    wt = g.max_window_tiles_of(sel)
                    if wt == 0:
                        continue
                    lo_needed = int(e_tile - wt)
                    needed = set(range(lo_needed, int(e_tile)))
                    with self._lock:
                        if sel.dirty_all:
                            sel.dirty_all = False
                            sel.tiles.clear()
                            sel.covered.clear()
                        todo = (needed - sel.covered) | (sel.dirty & needed)
                        live = {t for t in todo if not _overlaps(
                            inflight, t * g.g_ms, (t + 1) * g.g_ms)}
                        if live != todo:
                            lagged = True
                        sel.dirty -= live
                        claimed.append((sel, live))
                        # evict tiles behind every window
                        for t in [t for t in sel.covered if t < lo_needed]:
                            sel.covered.discard(t)
                            sel.tiles.pop(t, None)
                        sel.dirty = {t for t in sel.dirty if t >= lo_needed}
                    if live:
                        self._fold_tiles(g, sel, live)
                        STATS.incr("rules", "tiles_folded", len(live))
            claimed = []
        finally:
            if claimed:  # aborted mid-fold: the marks go back
                with self._lock:
                    for sel, live in claimed:
                        sel.dirty |= live

        # -- merge + eval: answer every rule from merged tile prefixes
        # (canonical series order), fallback rules through the engine.
        # The memo shares one merge+answer across every rule with the
        # same (selector, func, window) — the fleet economy: thousands
        # of threshold rules over one selector cost ONE merge per tick.
        results: dict[str, dict] = {}
        memo: dict = {}
        with _stage("rules_merge"):
            for r in g.rules:
                if r.compiled.tiled:
                    results[r.name] = self._eval_tiled(g, r, e_tile,
                                                       memo=memo)
                else:
                    results[r.name] = self._eval_fallback(g, r, te_ns)
        g.last_results = results
        g.last_e_tile = int(e_tile)

        # -- verify: the from-scratch leg must agree bit-for-bit
        if verify_enabled():
            with _stage("rules_verify"):
                if lagged or inflight:
                    # a mid-apply write makes the two legs read
                    # different storage states: not a counterexample
                    STATS.incr("rules", "verify_skips")
                else:
                    self._verify(g, e_tile, results)
                    STATS.incr("rules", "verify_ticks")

        # -- effects: recording write-back + alert transitions
        with _stage("rules_write"):
            points = []
            vf = self.prom.value_field
            for r in g.rules:
                if r.kind != "recording":
                    continue
                for key, val in sorted(results[r.name].items()):
                    tags = dict(key)
                    tags.update(r.labels)
                    points.append((r.name,
                                   tuple(sorted(tags.items())),
                                   te_ns,
                                   {vf: (FieldType.FLOAT, float(val))}))
            if points:
                self.engine.write_rows(g.db, points)
                STATS.incr("rules", "series_written", len(points))
        with _stage("rules_alerts"):
            for r in g.rules:
                if r.kind == "alerting":
                    self._advance_alerts(g, r, results[r.name], te_ns)

        # -- final mark: watermark + alert state in ONE durable save
        g.last_eval_ns = te_ns
        g.claimed_ns = None
        g.save(g.snapshot())

    def _collect(self, sel: _SelState, db: str, lo_ms: int, hi_ms: int):
        """(labels, t_ms, v, lens) for the selector over (lo_ms, hi_ms]
        — the engine's run-encoded collection (bulk decode + label-tier
        matcher probes)."""
        got = self.prom._collect_series(
            sel.vs, lo_ms * MS_NS + 1, hi_ms * MS_NS + 1, db)
        return got[:4]

    def _fold_tiles(self, g: RuleGroup, sel: _SelState,
                    tiles: set[int]) -> None:
        from opengemini_tpu.query import offload

        runs: list[list[int]] = []
        for t in sorted(tiles):
            if runs and runs[-1][1] == t:
                runs[-1][1] = t + 1
            else:
                runs.append([t, t + 1])
        for lo_t, hi_t in runs:
            t0 = _time.perf_counter_ns()
            labels, t_ms, v, lens = self._collect(
                sel, g.db, lo_t * g.g_ms, hi_t * g.g_ms)
            rows = sel.intern_rows(labels)
            recs = promops.fold_tile_partials(
                t_ms, v, lens, 0, g.g_ms, lo_t, hi_t)
            S = sel.n_series
            with self._lock:
                for t in range(lo_t, hi_t):
                    sel.covered.add(t)
                    rec = recs.get(t)
                    if rec is None:
                        sel.tiles.pop(t, None)
                        continue
                    full = promops.empty_tile_partials(S)
                    for f, _fill in promops.TILE_PARTIAL_FIELDS:
                        full[f][rows] = rec[f]
                    sel.tiles[t] = full
            # host-pinned fold (the bitwise contract needs a
            # deterministic reduction order); the planner still sees its
            # cost so /debug/offload attributes rule maintenance
            offload.GLOBAL.observe(
                "rules_fold", (S, hi_t - lo_t), "host",
                (_time.perf_counter_ns() - t0) / 1e9)

    def _eval_tiled(self, g: RuleGroup, r: Rule, e_tile: int,
                    sel: _SelState | None = None,
                    tile_of=None, memo: dict | None = None) -> dict:
        """{output label key: value} for one tiled rule at eval tile
        `e_tile`.  `sel`/`tile_of` override the group's cached state for
        the verify leg (same arithmetic, fresh tiles).  `memo` shares
        the merged-window answer across rules with the same (selector,
        func, window) within one tick — aggregation/threshold layers
        stay per-rule."""
        c = r.compiled
        if sel is None:
            sel = g.sel_for(c)
        if tile_of is None:
            tile_of = sel.rec_view
        wt = c.window_ms // g.g_ms
        S = sel.n_series
        mkey = (id(sel), c.func, c.window_ms)
        # two memo layers: the merged-window answer per (selector, func,
        # window), and the pre-threshold output vector per (that + agg
        # shape) — a fleet of threshold rules differing only in the
        # literal shares everything up to the final comparison
        okey = (id(sel), c.func, c.window_ms, c.agg_op,
                tuple(c.agg_grouping), c.agg_without)
        pre = memo.get(okey) if memo is not None else None
        if pre is None:
            got = memo.get(mkey) if memo is not None else None
            if got is not None:
                values, valid = got
            else:
                merged = promops.merge_tile_partials(
                    [tile_of(int(t))
                     for t in range(e_tile - wt, e_tile)], S)
                ws_ms = (e_tile - wt) * g.g_ms
                we_ms = e_tile * g.g_ms
                values, valid = promops.partials_answer(
                    merged, c.func, ws_ms, we_ms)
                if memo is not None:
                    memo[mkey] = (values, valid)
            order = sel.canon_order()
            pre = {}
            if c.agg_op is None:
                for i in order:
                    if valid[i]:
                        pre[sel.keys[i]] = float(values[i])
            else:
                groups: dict[tuple, list[int]] = {}
                for i in order:
                    if not valid[i]:
                        continue
                    tags = sel.labels[i]
                    if c.agg_without:
                        key = tuple(sorted(
                            (k, v) for k, v in tags.items()
                            if k not in c.agg_grouping))
                    else:
                        key = tuple(sorted(
                            (k, tags[k])
                            for k in c.agg_grouping if k in tags))
                    groups.setdefault(key, []).append(int(i))
                for key in sorted(groups):
                    vals = values[np.array(groups[key], np.int64)]
                    if c.agg_op == "sum":
                        pre[key] = float(np.sum(vals))
                    elif c.agg_op == "avg":
                        pre[key] = float(np.sum(vals) / len(vals))
                    elif c.agg_op == "min":
                        pre[key] = float(np.min(vals))
                    elif c.agg_op == "max":
                        pre[key] = float(np.max(vals))
                    else:  # count
                        pre[key] = float(len(vals))
            if memo is not None:
                memo[okey] = pre
        out: dict[tuple, float] = dict(pre)
        if c.cmp_op is not None:
            fn = _CMP_OPS[c.cmp_op]
            if c.cmp_flip:
                out = {k: v for k, v in out.items()
                       if bool(fn(c.cmp_thr, v))}
            else:
                out = {k: v for k, v in out.items()
                       if bool(fn(v, c.cmp_thr))}
        return out

    def _eval_fallback(self, g: RuleGroup, r: Rule, te_ns: int) -> dict:
        """Full evaluation through the engine for tile-ineligible
        expressions — planner-routed kernels, label-tier matching, the
        works."""
        STATS.incr("rules", "fallback_evals")
        res = self.prom.query_instant(r.expr, te_ns / 1e9, g.db)
        out: dict[tuple, float] = {}
        if res.get("resultType") != "vector":
            return out
        for s in res["result"]:
            labels = {k: v for k, v in s["metric"].items()
                      if k != "__name__"}
            out[tuple(sorted(labels.items()))] = float(s["value"][1])
        return out

    def verify_last_tick(self, g: RuleGroup) -> bool:
        """Re-run the from-scratch leg against the last tick's retained
        results (bench/loadgen: assert bit-identity on a measured tick
        without paying the verify rescan INSIDE the timed tick).
        Raises on mismatch; False when no tick has run yet."""
        if g.last_e_tile is None:
            return False
        with g.m_lock:
            self._verify(g, g.last_e_tile, g.last_results)
        return True

    def _verify(self, g: RuleGroup, e_tile: int, got: dict) -> None:
        """The from-scratch leg: fold EVERY window tile off one full
        scan per selector, merge with the same arithmetic, compare
        bitwise.  A mismatch is a maintenance bug — raise loudly."""
        fresh: dict[int, tuple] = {}
        for sig, sel in g._sels.items():
            wt = g.max_window_tiles_of(sel)
            if wt == 0:
                continue
            lo_t = int(e_tile - wt)
            f_sel = _SelState(sel.metric, sel.vs.matchers)
            f_sel.dirty_all = False
            labels, t_ms, v, lens = self._collect(
                f_sel, g.db, lo_t * g.g_ms, int(e_tile) * g.g_ms)
            rows = f_sel.intern_rows(labels)
            recs = promops.fold_tile_partials(
                t_ms, v, lens, 0, g.g_ms, lo_t, int(e_tile))
            S = f_sel.n_series
            for t, rec in recs.items():
                full = promops.empty_tile_partials(S)
                for f, _fill in promops.TILE_PARTIAL_FIELDS:
                    full[f][rows] = rec[f]
                f_sel.tiles[t] = full
                f_sel.covered.add(t)
            fresh[id(sel)] = (f_sel,)
        memo: dict = {}
        for r in g.rules:
            if not r.compiled.tiled:
                continue
            sel = g.sel_for(r.compiled)
            f_sel = fresh[id(sel)][0]
            want = self._eval_tiled(g, r, e_tile, sel=f_sel,
                                    tile_of=f_sel.rec_view, memo=memo)
            have = got[r.name]
            same = have.keys() == want.keys() and all(
                have[k] == want[k]
                or (math.isnan(have[k]) and math.isnan(want[k]))
                for k in want)
            if not same:
                STATS.incr("rules", "verify_failures")
                raise RuntimeError(
                    f"rules verify mismatch for {g.db}.{g.name}/{r.name}: "
                    f"incremental {have!r} != rescan {want!r}")

    # -- alert state machine ---------------------------------------------

    def _advance_alerts(self, g: RuleGroup, r: Rule, result: dict,
                        te_ns: int) -> None:
        """pending→firing→resolved per output label set.  Transitions
        mutate IN-MEMORY state here; they become observable (and
        counted) only at the tick's final fsync — the no-double-fire
        edge."""
        st = g.alerts.setdefault(r.name, {})
        for_ns = int(round(r.for_s * NS))
        active_keys = set()
        for key, val in result.items():
            labels = dict(key)
            labels["alertname"] = r.name
            labels.update(r.labels)
            kjson = json.dumps(sorted(labels.items()))
            active_keys.add(kjson)
            ent = st.get(kjson)
            if ent is None:
                ent = st[kjson] = {
                    "state": "pending", "active_since_ns": te_ns,
                    "fired_at_ns": None, "value": val,
                    "labels": labels}
            ent["value"] = val
            if ent["state"] == "pending" \
                    and te_ns - ent["active_since_ns"] >= for_ns:
                ent["state"] = "firing"
                ent["fired_at_ns"] = te_ns
                g.fires[r.name] = g.fires.get(r.name, 0) + 1
                STATS.incr("rules", "alerts_fired")
        for kjson in [k for k in st if k not in active_keys]:
            if st[kjson]["state"] == "firing":
                g.resolves[r.name] = g.resolves.get(r.name, 0) + 1
                STATS.incr("rules", "alerts_resolved")
            del st[kjson]

    # -- surfaces --------------------------------------------------------

    def status(self) -> dict:
        out = {}
        for g in self.groups_for():
            with self._lock:
                dirty = sum(len(s.dirty) for s in g._sels.values())
                tiles = sum(len(s.tiles) for s in g._sels.values())
                series = sum(s.n_series for s in g._sels.values())
            out[f"{g.db}.{g.name}"] = {
                "interval_s": g.interval_s,
                "lateness_s": g.lateness_s,
                "g_ms": g.g_ms,
                "rules": [
                    {"name": r.name, "kind": r.kind,
                     "tiled": r.compiled.tiled} for r in g.rules],
                "last_eval_ns": g.last_eval_ns,
                "claimed_ns": g.claimed_ns,
                "last_tick_ms": round(g.last_tick_ms, 3),
                "dirty_tiles": dirty,
                "cached_tiles": tiles,
                "tracked_series": series,
                "alerts_firing": sum(
                    1 for rs in g.alerts.values()
                    for e in rs.values() if e["state"] == "firing"),
                "alerts_pending": sum(
                    1 for rs in g.alerts.values()
                    for e in rs.values() if e["state"] == "pending"),
                "fires": dict(g.fires),
                "resolves": dict(g.resolves),
            }
        return out

    def rules_api(self) -> dict:
        """GET /api/v1/rules payload (prometheus rules endpoint)."""
        groups = []
        for g in self.groups_for():
            rules = []
            for r in g.rules:
                j = {"name": r.name, "query": r.expr, "health": "ok",
                     "labels": r.labels,
                     "evaluationTime": g.last_tick_ms / 1e3,
                     "type": "recording" if r.kind == "recording"
                     else "alerting"}
                if r.kind == "alerting":
                    ents = list(g.alerts.get(r.name, {}).values())
                    j["duration"] = r.for_s
                    j["annotations"] = r.annotations
                    j["state"] = (
                        "firing" if any(e["state"] == "firing"
                                        for e in ents)
                        else "pending" if ents else "inactive")
                    j["alerts"] = [self._alert_json(e) for e in ents]
                rules.append(j)
            groups.append({
                "name": g.name, "file": g.db,
                "interval": g.interval_s, "rules": rules,
                "lastEvaluation": (
                    None if g.last_eval_ns is None
                    else g.last_eval_ns / 1e9)})
        return {"groups": groups}

    def alerts_api(self) -> dict:
        """GET /api/v1/alerts payload: every pending/firing alert."""
        alerts = []
        for g in self.groups_for():
            for r in g.rules:
                for e in g.alerts.get(r.name, {}).values():
                    alerts.append(self._alert_json(e, r))
        return {"alerts": alerts}

    @staticmethod
    def _alert_json(e: dict, r: Rule | None = None) -> dict:
        j = {"labels": e.get("labels", {}),
             "state": e["state"],
             "activeAt": e["active_since_ns"] / 1e9,
             "value": str(e["value"])}
        if e.get("fired_at_ns"):
            j["firedAt"] = e["fired_at_ns"] / 1e9
        if r is not None:
            j["annotations"] = r.annotations
        return j

    def _gauges(self) -> dict:
        with self._lock:
            groups = list(self._groups.values())
        firing = pending = dirty = 0
        for g in groups:
            for rs in g.alerts.values():
                for e in rs.values():
                    if e["state"] == "firing":
                        firing += 1
                    else:
                        pending += 1
            dirty += sum(len(s.dirty) for s in g._sels.values())
        return {
            "groups": len(groups),
            "rules_total": sum(len(g.rules) for g in groups),
            "alerts_firing": firing,
            "alerts_pending": pending,
            "dirty_tiles": dirty,
        }
