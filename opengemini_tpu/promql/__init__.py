"""PromQL front-end (reference: lib/util/lifted/promql2influxql transpiler
+ the prometheus promql engine glue). Here PromQL evaluates directly
against the storage engine through the same device kernels as InfluxQL,
rather than transpiling to InfluxQL text."""
