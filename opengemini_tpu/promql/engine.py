"""PromQL evaluation engine over the storage engine + device kernels.

Reference path: servePromRead -> promql2influxql.Transpile -> influx SELECT
with prom logical nodes + prom cursors (SURVEY.md §3.3). Here the AST
evaluates directly: selectors scan the same shards/index as InfluxQL, the
range-vector math runs in ops/prom.py device kernels over dense
(series, steps) grids, and label aggregation happens on the host.

Data model (matching the reference's prom-on-influx mapping): metric name
= measurement, labels = tags, sample value = field "value".
"""

from __future__ import annotations

import functools
import math
import os
import re
import time as _time
from contextlib import contextmanager

import numpy as np

from opengemini_tpu.ops import prom as promops
from opengemini_tpu.promql import parser as pp
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.governor import _env_int
from opengemini_tpu.utils.querytracker import GLOBAL as TRACKER
from opengemini_tpu.utils.stats import GLOBAL as STATS

MS = 1_000_000  # ns per ms
DEFAULT_LOOKBACK_S = 300.0


class PromError(ValueError):
    pass


# -- tiled-engine knobs (documented in README "PromQL engine") -----------


def _tiled_enabled() -> bool:
    return os.environ.get("OGT_PROM_TILED", "1") != "0"


def _bulk_sids_min() -> int:
    return max(1, _env_int("OGT_PROM_BULK_SIDS", 1))


def _tile_cells_mult() -> int:
    return max(1, _env_int("OGT_PROM_TILE_CELLS", 8))


class _EncSlice:
    """One series' untrimmed all-valid slice of a still-encoded bulk
    value column (record.EncodedColumn) — resolved at assembly into
    either a (ftype, blocks, segments, slices) device-decode
    descriptor or, on any
    fallback, the decoded values."""

    __slots__ = ("col", "lo", "hi")

    def __init__(self, col, lo: int, hi: int):
        self.col = col
        self.lo = lo
        self.hi = hi


def _materialize_slice(v):
    if isinstance(v, _EncSlice):
        # slice BEFORE the astype: converting the whole column per
        # slice would be O(series x column) copies on fallback
        return v.col.values[v.lo:v.hi].astype(np.float64)
    return v


def _assemble_enc(v_parts):
    """(ftype, blocks, segments, slices) when every per-series part
    is a slice of
    ONE still-encoded column, else None (values materialize eagerly)."""
    col = None
    slices = []
    for v in v_parts:
        if not isinstance(v, _EncSlice):
            return None
        if col is None:
            col = v.col
        elif v.col is not col:
            return None  # cross-shard/cross-column: host merge path
        slices.append((v.lo, v.hi))
    if col is None or col.is_decoded:
        return None
    return (col.ftype, tuple(col.blocks), col.segments, tuple(slices))


def _want_encoded() -> bool:
    """Collect still-encoded value columns only when the traced kernel
    path will run (device decode is pointless under host kernels) and
    the device decoder is usable."""
    if _host_kernels():
        return False
    from opengemini_tpu.ops import device_decode

    return device_decode.active()


@functools.lru_cache(maxsize=1)
def _backend_is_cpu() -> bool:
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — no backend = host kernels
        return True


def _host_kernels() -> bool:
    """numpy (host) vs jax.numpy (device) for the tiled kernels: on CPU
    backends numpy answers without dispatch or per-shape compile cost;
    accelerators keep the traced path.  OGT_PROM_HOST_KERNELS resolves
    ONCE through the offload knob layer (hot-reloadable via
    /debug/ctrl?mod=offload) — not re-read from the environment on
    every evaluation."""
    from opengemini_tpu.query import offload

    v = offload.prom_host_kernels_mode()
    if v == "1":
        return True
    if v == "0":
        return False
    return _backend_is_cpu()


def _mesh_for_tiled():
    """The configured device mesh when the tiled kernels should shard
    their series axis over it (ops/prom.py ShardedTiled). A set mesh
    overrides the host-kernel CPU shortcut — multi-chip execution is the
    point of configuring one; OGT_PROM_MESH=0 opts the PromQL engine out
    (grid/bucketed batches keep their own mesh paths)."""
    if os.environ.get("OGT_PROM_MESH", "1") == "0":
        return None
    from opengemini_tpu.parallel import runtime as prt

    return prt.get_mesh()


@contextmanager
def _stage(name: str):
    """Per-stage attribution: /debug/vars query_stages + the per-query
    stage map in /debug/queries and the slow-query log."""
    t0 = _time.perf_counter_ns()
    try:
        yield
    finally:
        ns = _time.perf_counter_ns() - t0
        tracing.record_stage(name, ns)
        TRACKER.add_stage_ns(TRACKER.current_qid(), name, ns)


def _anchor(pattern: str) -> str:
    return "^(?:" + pattern + ")$"


def _match_sids(sh, metric: str, matchers) -> np.ndarray:
    """Series ids matching prom label matchers, as a SORTED unique
    int64 array (prometheus fully anchors label-matcher regexes). The
    columnar label tier (index.labels) answers each matcher with a
    posting array and composition is np.intersect1d, matchers ordered
    cheapest-first; with the tier knob-disabled the legacy set walk
    runs and the result converts — same sids either way."""
    from opengemini_tpu.index import labels as _labels

    tier = _labels.tier_for(sh.index)
    if tier is not None:
        return _match_sids_tier(tier, metric, matchers)
    sids = sh.index.series_ids(metric)
    for m in matchers:
        if m.name == "__name__":
            continue
        try:
            if m.op == "=":
                sids &= sh.index.match_eq(metric, m.name, m.value)
            elif m.op == "!=":
                sids &= sh.index.match_neq(metric, m.name, m.value)
            elif m.op == "=~":
                sids &= sh.index.match_regex(metric, m.name, _anchor(m.value))
            elif m.op == "!~":
                sids &= sh.index.match_regex(
                    metric, m.name, _anchor(m.value), negate=True
                )
        except re.error as e:
            raise PromError(f"invalid regex in matcher {m.name!r}: {e}") from None
    if not sids:
        return np.empty(0, np.int64)
    return np.fromiter(sorted(sids), np.int64, len(sids))


def _match_sids_tier(tier, metric: str, matchers) -> np.ndarray:
    from opengemini_tpu.index import labels as _labels
    from opengemini_tpu.utils.stats import GLOBAL as _stats

    snap = tier.snapshot(metric)
    ms = [m for m in matchers
          if m.name != "__name__" and m.op in ("=", "!=", "=~", "!~")]
    if not ms:
        return snap.sids
    for m in ms:
        if m.op in ("=~", "!~"):
            try:
                re.compile(_anchor(m.value))  # re caches the program
            except re.error as e:
                raise PromError(
                    f"invalid regex in matcher {m.name!r}: {e}") from None
    # cheapest matcher first: its postings bound every later intersect,
    # and an empty prefix short-circuits the regex automaton passes
    est = [snap.estimate(m.op, m.name,
                         m.value if m.op in ("=", "!=") else None)
           for m in ms]
    order = sorted(range(len(ms)), key=est.__getitem__)
    if order != list(range(len(ms))):
        _stats.incr("index", "matcher_reorders_total")
    sids = None
    for i in order:
        m = ms[i]
        if m.op == "=":
            cur = snap.match_eq(m.name, m.value)
        elif m.op == "!=":
            cur = snap.match_neq(m.name, m.value)
        elif m.op == "=~":
            cur = snap.match_regex(m.name, _anchor(m.value),
                                   head=_labels._literal_head(m.value))
        else:
            cur = snap.match_regex(m.name, _anchor(m.value), negate=True,
                                   head=_labels._literal_head(m.value))
        sids = cur if sids is None else np.intersect1d(
            sids, cur, assume_unique=True)
        if sids.size == 0:
            return sids
    return sids


class Frame:
    """Evaluation result: per-series (S, K) values over the step grid."""

    __slots__ = ("labels", "values", "valid", "is_scalar")

    def __init__(self, labels, values, valid, is_scalar=False):
        self.labels = labels  # list[dict]
        self.values = values  # (S, K) float
        self.valid = valid  # (S, K) bool
        self.is_scalar = is_scalar

    @classmethod
    def scalar(cls, v: float, k: int):
        return cls([{}], np.full((1, k), v), np.ones((1, k), bool), True)


class PromEngine:
    def __init__(self, engine, value_field: str = "value",
                 lookback_s: float = DEFAULT_LOOKBACK_S):
        self.engine = engine
        self.value_field = value_field
        self.lookback_s = lookback_s

    # -- public API -----------------------------------------------------

    def query_range(self, text: str, start_s: float, end_s: float, step_s: float,
                    db: str) -> dict:
        self._check_readable()
        if step_s <= 0:
            raise PromError("step must be positive")
        if not (math.isfinite(start_s) and math.isfinite(end_s) and math.isfinite(step_s)):
            raise PromError("start/end/step must be finite")
        n_steps = int(math.floor((end_s - start_s) / step_s)) + 1
        if n_steps <= 0:
            raise PromError("empty step range")
        if n_steps > 11_000:
            raise PromError("too many steps (max 11000)")
        steps = start_s + np.arange(n_steps) * step_s
        expr = pp.parse(text)
        with self._tracked(text, db):
            frame = self._eval(expr, steps, db)
        result = []
        for i, labels in enumerate(frame.labels):
            pts = [
                [float(steps[k]), _fmt(frame.values[i, k])]
                for k in range(n_steps)
                if frame.valid[i, k]
            ]
            if pts:
                result.append({"metric": labels, "values": pts})
        result.sort(key=lambda r: sorted(r["metric"].items()))
        return {"resultType": "matrix", "result": result}

    def query_instant(self, text: str, time_s: float, db: str) -> dict:
        self._check_readable()
        steps = np.array([time_s])
        expr = pp.parse(text)
        with self._tracked(text, db):
            frame = self._eval(expr, steps, db)
        if frame.is_scalar:
            return {"resultType": "scalar", "result": [time_s, _fmt(frame.values[0, 0])]}
        result = []
        for i, labels in enumerate(frame.labels):
            if frame.valid[i, 0]:
                result.append(
                    {"metric": labels, "value": [float(time_s), _fmt(frame.values[i, 0])]}
                )
        # top-level sort()/sort_desc()/sort_by_label() own the output
        # order; everything else gets the stable by-labels order
        if not (isinstance(expr, pp.FunctionCall)
                and expr.name in ("sort", "sort_desc", "sort_by_label",
                                  "sort_by_label_desc")):
            result.sort(key=lambda r: sorted(r["metric"].items()))
        return {"resultType": "vector", "result": result}

    def series_labels(self, vs: "pp.VectorSelector", db: str) -> list[dict]:
        """Label sets of series matching a selector — INDEX-ONLY, no data
        decode (the /api/v1/series metadata surface). Unlike the query
        path, ALL __name__ matcher operators are honored (=, !=, =~, !~)
        by filtering the measurement set."""
        self._check_readable()
        shards = self.engine.shards_for_range(db, None, -(2**62), 2**62)
        metrics: set[str] | None = {vs.metric} if vs.metric else None
        for m in vs.matchers:
            if m.name != "__name__":
                continue
            if metrics is None:
                metrics = {n for sh in shards for n in sh.index.measurements()}
            try:
                if m.op == "=":
                    metrics &= {m.value}
                elif m.op == "!=":
                    metrics -= {m.value}
                elif m.op in ("=~", "!~"):
                    rx = re.compile(_anchor(m.value))
                    hit = {n for n in metrics if rx.search(n)}
                    metrics = hit if m.op == "=~" else metrics - hit
            except re.error as e:
                raise PromError(f"invalid __name__ regex: {e}") from None
        if metrics is None:
            raise PromError("metric name required")
        seen = set()
        out = []
        for sh in shards:
            for metric in sorted(metrics):
                for sid in _match_sids(sh, metric, vs.matchers):
                    tags = sh.index.tags_of(sid)
                    key = (metric, tuple(sorted(tags.items())))
                    if key not in seen:
                        seen.add(key)
                        labels = dict(tags)
                        labels["__name__"] = metric
                        out.append(labels)
        return out

    def _check_readable(self) -> None:
        if getattr(self.engine, "read_disabled", False):
            raise PromError("reads are disabled (syscontrol)")

    @contextmanager
    def _tracked(self, text: str, db: str):
        """Register the PromQL evaluation with the running-query registry
        (shows in /debug/queries with per-stage attribution, KILL QUERY
        cancels it between shard scans) and capture slow evaluations in
        the slow-query log — the /api/v1/query_range surface was
        previously invisible to both."""
        t0 = _time.perf_counter_ns()
        qid = TRACKER.register(text, db)
        try:
            yield
        finally:
            dur_ns = _time.perf_counter_ns() - t0
            from opengemini_tpu.utils.slowlog import GLOBAL as SLOWLOG

            if SLOWLOG.enabled():
                SLOWLOG.note(qid, text, db, dur_ns / 1e6,
                             stages=TRACKER.stages_of(qid),
                             extra={"kind": "promql"})
            TRACKER.unregister(qid)

    # -- evaluation -------------------------------------------------------

    def _eval(self, node, steps: np.ndarray, db: str) -> Frame:
        k = len(steps)
        if isinstance(node, pp.NumberLit):
            return Frame.scalar(node.val, k)
        if isinstance(node, pp.VectorSelector):
            return self._eval_selector(node, steps, db, self.lookback_s, instant=True)
        if isinstance(node, (pp.MatrixSelector, pp.Subquery)):
            raise PromError("range vector must be wrapped in a function (e.g. rate)")
        if isinstance(node, pp.FunctionCall):
            return self._eval_function(node, steps, db)
        if isinstance(node, pp.Aggregation):
            return self._eval_aggregation(node, steps, db)
        if isinstance(node, pp.BinaryOp):
            return self._eval_binop(node, steps, db)
        raise PromError(f"unsupported expression {type(node).__name__}")

    def _collect_series(self, vs: pp.VectorSelector, t_min_ns: int,
                        t_max_ns: int, db: str, want_encoded: bool = False):
        """-> run-encoded (labels list, t_ms_all, v_all, lens[, enc]):
        one concatenated (times, values) pair with per-series lengths,
        ready for prepare_matrix_runs' flat scatter / the tiled prepare —
        no per-series matrix fill loop downstream.

        ``want_encoded=True`` (the traced tiled path with device decode
        active) additionally tries to keep the value column in its
        on-disk encoded blocks: when the whole match resolves to
        untrimmed all-valid slices of ONE still-encoded bulk column, the
        5th return is (ftype, blocks, segments, slices) and v_all is None — the
        device decodes (ops/device_decode.decode_rows_matrix); any
        cross-shard merge, partial validity, or decoded column falls
        back to returning the values eagerly, exactly as before."""
        metric = self._metric_of(vs)
        shards = self.engine.shards_for_range(db, None, t_min_ns, t_max_ns)
        # series may span shards: merge by label key.
        # per_key: key -> (tags, [(times_ms, values)])
        per_key: dict[tuple, tuple] = {}

        def add(tags: dict, t_ms: np.ndarray, vals: np.ndarray) -> None:
            key = tuple(sorted(tags.items()))
            got = per_key.get(key)
            if got is None:
                per_key[key] = (tags, [(t_ms, vals)])
            else:
                got[1].append((t_ms, vals))

        vf = self.value_field
        bulk_min = _bulk_sids_min()
        for sh in shards:
            TRACKER.check()  # KILL QUERY cancellation point per shard
            sids = _match_sids(sh, metric, vs.matchers)
            if sids.size == 0:
                continue
            if sids.size >= bulk_min and hasattr(sh, "read_series_bulk"):
                # batched multi-series decode: packed (colstore) chunks
                # decode once for every matched series.  Default for ANY
                # match size (OGT_PROM_BULK_SIDS=1); raise the knob to
                # make the per-sid decode loop handle small matches.
                # _match_sids already hands the sorted int64 array — no
                # tags_of label materialization on the match path
                sid_arr, rec = sh.read_series_bulk(
                    metric, sids, t_min_ns, t_max_ns, fields=[vf])
                col = rec.columns.get(vf)
                if col is None or len(rec) == 0:
                    continue
                times_ms = rec.times // MS
                # keep a still-encoded column encoded: per-series slices
                # become (col, lo, hi) markers resolved at assembly; any
                # partial-validity slice decodes the whole column (lazy
                # .values — the bit-identical host path)
                enc_col = (col if want_encoded
                           and getattr(col, "is_decoded", True) is False
                           else None)
                vals64 = (None if enc_col is not None
                          else col.values.astype(np.float64))
                uniq, starts = np.unique(sid_arr, return_index=True)
                ends = np.append(starts[1:], len(sid_arr))
                if hasattr(sh.index, "entries_bulk"):
                    entries = sh.index.entries_bulk(uniq)
                else:
                    entries = [(None, tuple(sh.index.tags_of(int(s)).items()))
                               for s in uniq]
                for (sid, lo, hi), entry in zip(
                        zip(uniq, starts, ends), entries):
                    if entry is None:
                        continue
                    m = col.valid[lo:hi]
                    if not m.any():
                        continue
                    if enc_col is not None and m.all():
                        add(dict(entry[1]), times_ms[lo:hi],
                            _EncSlice(enc_col, int(lo), int(hi)))
                        continue
                    if vals64 is None:
                        vals64 = col.values.astype(np.float64)
                    add(dict(entry[1]), times_ms[lo:hi][m],
                        vals64[lo:hi][m])
            else:
                for sid in sids.tolist():
                    rec = sh.read_series(metric, sid, t_min_ns, t_max_ns,
                                         fields=[vf])
                    col = rec.columns.get(vf)
                    if col is None or len(rec) == 0:
                        continue
                    valid = col.valid
                    if not valid.any():
                        continue
                    add(sh.index.tags_of(sid),
                        rec.times[valid] // MS,
                        col.values[valid].astype(np.float64))
        out_labels: list[dict] = []
        t_parts: list[np.ndarray] = []
        v_parts: list = []
        lens: list[int] = []
        for key in sorted(per_key):
            tags, parts = per_key[key]
            if len(parts) == 1:
                t, v = parts[0]
            else:
                t = np.concatenate([p[0] for p in parts])
                v = np.concatenate([_materialize_slice(p[1])
                                    for p in parts])
                order = np.argsort(t, kind="stable")
                t, v = t[order], v[order]
            labels = dict(tags)
            labels["__name__"] = metric
            out_labels.append(labels)
            t_parts.append(t)
            v_parts.append(v)
            lens.append(len(t))
        t_ms_all = (np.concatenate(t_parts) if t_parts
                    else np.empty(0, np.int64)).astype(np.int64, copy=False)
        enc = None
        if want_encoded and v_parts:
            enc = _assemble_enc(v_parts)
        if enc is not None:
            v_all = None
        else:
            v_all = (np.concatenate(
                [_materialize_slice(v) for v in v_parts]) if v_parts
                else np.empty(0, np.float64))
        if want_encoded:
            return (out_labels, t_ms_all, v_all,
                    np.asarray(lens, np.int64), enc)
        return out_labels, t_ms_all, v_all, np.asarray(lens, np.int64)

    def _eval_selector(self, vs, steps, db, window_s, instant):
        eval_times = steps - vs.offset_s
        t_max_ns = int(eval_times[-1] * 1e9) + 1
        t_min_ns = int((eval_times[0] - window_s) * 1e9)
        with _stage("prom_collect"):
            labels, t_ms_all, v_all, lens = self._collect_series(
                vs, t_min_ns, t_max_ns, db)
        k = len(steps)
        if not labels:
            return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
        with _stage("prom_prepare"):
            times, values, counts, base_ms = promops.prepare_matrix_runs(
                t_ms_all, v_all, lens, dtype=np.float64)
        rel = eval_times - base_ms / 1000.0
        with _stage("prom_kernel"):
            vals, valid = promops.instant_values(times, values, counts, rel,
                                                 window_s)
        return Frame(labels, np.asarray(vals), np.asarray(valid))

    def _eval_function(self, node: pp.FunctionCall, steps, db) -> Frame:
        name = node.name
        range_fns = {
            "rate": (True, True), "increase": (True, False), "delta": (False, False),
        }
        if name in range_fns:
            is_counter, is_rate = range_fns[name]
            ms_sel = _expect_matrix(node, 0)
            return self._eval_range_fn(
                ms_sel, steps, db,
                {"kind": "rate", "is_counter": is_counter, "is_rate": is_rate})
        if name in ("changes", "resets"):
            ms_sel = _expect_matrix(node, 0)
            return self._eval_range_fn(
                ms_sel, steps, db, {"kind": "changes_resets", "which": name})
        if name == "absent":
            if not node.args:
                raise PromError("absent() requires an argument")
            f = self._eval(node.args[0], steps, db)
            k = len(steps)
            present = f.valid.any(axis=0) if len(f.labels) else np.zeros(k, bool)
            # prometheus derives the output labels from the selector's
            # equality matchers (promql/functions.go createLabelsForAbsent)
            labels = {}
            arg = node.args[0]
            if isinstance(arg, pp.VectorSelector):
                for m in arg.matchers:
                    if m.op == "=" and m.name != "__name__":
                        labels[m.name] = m.value
            return Frame([labels], np.ones((1, k)), ~present[None, :])
        if name == "histogram_quantile":
            if len(node.args) != 2:
                raise PromError("histogram_quantile(q, vector) takes 2 arguments")
            q = _expect_number(node, 0)
            f = self._eval(node.args[1], steps, db)
            return _histogram_quantile(q, f, len(steps))
        if name in ("irate", "idelta"):
            ms_sel = _expect_matrix(node, 0)
            return self._eval_range_fn(
                ms_sel, steps, db,
                {"kind": "instant_rate", "per_second": name == "irate"})
        if name == "quantile_over_time":
            q = _expect_number(node, 0)
            ms_sel = _expect_matrix(node, 1)
            return self._eval_range_fn(
                ms_sel, steps, db, {"kind": "quantile", "q": q})
        if name == "mad_over_time":
            ms_sel = _expect_matrix(node, 0)
            return self._eval_range_fn(ms_sel, steps, db, {"kind": "mad"})
        if name == "absent_over_time":
            ms_sel = _expect_matrix(node, 0)
            f = self._eval_range_fn(
                ms_sel, steps, db, {"kind": "over_time", "func": "present"})
            k = len(steps)
            present = f.valid.any(axis=0) if len(f.labels) else np.zeros(k, bool)
            labels = {}
            vec = getattr(ms_sel, "vector", None)
            if vec is not None:
                for m in vec.matchers:
                    if m.op == "=" and m.name != "__name__":
                        labels[m.name] = m.value
            return Frame([labels], np.ones((1, k)), ~present[None, :])
        if name.endswith("_over_time"):
            func = name[: -len("_over_time")]
            ms_sel = _expect_matrix(node, 0)
            return self._eval_range_fn(
                ms_sel, steps, db, {"kind": "over_time", "func": func})
        if name == "deriv":
            ms_sel = _expect_matrix(node, 0)
            return self._eval_range_fn(ms_sel, steps, db, {"kind": "deriv"})
        if name == "predict_linear":
            ms_sel = _expect_matrix(node, 0)
            dur = _expect_number(node, 1)
            return self._eval_range_fn(
                ms_sel, steps, db, {"kind": "predict", "dur": dur})
        if name in ("holt_winters", "double_exponential_smoothing"):
            ms_sel = _expect_matrix(node, 0)
            sf = _expect_number(node, 1)
            tf = _expect_number(node, 2)
            if not (0 < sf < 1 and 0 < tf < 1):
                raise PromError(
                    "holt_winters smoothing factors must be in (0, 1)"
                )
            return self._eval_range_fn(
                ms_sel, steps, db, {"kind": "holt", "sf": sf, "tf": tf})
        if name == "scalar":
            f = self._eval(node.args[0], steps, db)
            if len(f.labels) == 1:
                # steps where the series had no sample become NaN (prom)
                vals = np.where(f.valid[:1], f.values[:1], np.nan)
                return Frame([{}], vals, np.ones((1, len(steps)), bool), True)
            vals = np.full((1, len(steps)), np.nan)
            return Frame([{}], vals, np.ones_like(vals, dtype=bool), True)
        if name == "vector":
            f = self._eval(node.args[0], steps, db)
            f.is_scalar = False
            return f
        # elementwise math (prom promql/functions.go simple call table)
        elem = {
            "abs": np.abs, "ceil": np.ceil, "floor": np.floor, "exp": np.exp,
            "ln": np.log, "log2": np.log2, "log10": np.log10, "sqrt": np.sqrt,
            "round": np.round, "sgn": np.sign,
            "sin": np.sin, "cos": np.cos, "tan": np.tan,
            "asin": np.arcsin, "acos": np.arccos, "atan": np.arctan,
            "sinh": np.sinh, "cosh": np.cosh, "tanh": np.tanh,
            "asinh": np.arcsinh, "acosh": np.arccosh, "atanh": np.arctanh,
            "deg": np.degrees, "rad": np.radians,
        }
        if name in elem:
            f = self._eval(node.args[0], steps, db)
            with np.errstate(all="ignore"):
                f.values = elem[name](f.values)
            f.labels = [_drop_name(l) for l in f.labels]
            return f
        if name in ("clamp_min", "clamp_max"):
            f = self._eval(node.args[0], steps, db)
            bound = _expect_number(node, 1)
            f.values = (
                np.maximum(f.values, bound) if name == "clamp_min"
                else np.minimum(f.values, bound)
            )
            f.labels = [_drop_name(l) for l in f.labels]
            return f
        if name == "clamp":
            f = self._eval(node.args[0], steps, db)
            lo = _expect_number(node, 1)
            hi = _expect_number(node, 2)
            if lo > hi:
                # prom: clamp with min > max returns an empty vector
                k = len(steps)
                return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
            f.values = np.clip(f.values, lo, hi)
            f.labels = [_drop_name(l) for l in f.labels]
            return f
        if name == "timestamp":
            f = self._eval(node.args[0], steps, db)
            f.values = np.broadcast_to(steps[None, :], f.values.shape).copy()
            f.labels = [_drop_name(l) for l in f.labels]
            return f
        if name == "pi":
            return Frame.scalar(math.pi, len(steps))
        if name == "time":
            k = len(steps)
            return Frame([{}], steps[None, :].astype(float).copy(),
                         np.ones((1, k), bool), True)
        if name in _CLOCK_FNS:
            # clock functions take an optional vector defaulting to time()
            if node.args:
                f = self._eval(node.args[0], steps, db)
                f.labels = [_drop_name(l) for l in f.labels]
            else:
                f = Frame([{}], steps[None, :].astype(float).copy(),
                          np.ones((1, len(steps)), bool), True)
            f.values = _CLOCK_FNS[name](f.values)
            return f
        if name == "label_replace":
            return self._label_replace(node, steps, db)
        if name == "label_join":
            return self._label_join(node, steps, db)
        if name in ("sort", "sort_desc"):
            f = self._eval(node.args[0], steps, db)
            if len(f.labels) > 1:
                # order by the (last) evaluated value; range queries sort
                # by series labels at output regardless (prom ignores sort
                # for range queries)
                key = np.where(f.valid[:, -1], f.values[:, -1], -np.inf)
                order = np.argsort(-key if name == "sort_desc" else key,
                                   kind="stable")
                f.labels = [f.labels[i] for i in order]
                f.values = f.values[order]
                f.valid = f.valid[order]
            return f
        if name in ("sort_by_label", "sort_by_label_desc"):
            f = self._eval(node.args[0], steps, db)
            keys = [_expect_string(node, i) for i in range(1, len(node.args))]
            if not keys:
                raise PromError(f"{name}() expects at least one label")
            order = sorted(
                range(len(f.labels)),
                key=lambda i: tuple(f.labels[i].get(k, "") for k in keys),
                reverse=name.endswith("_desc"),
            )
            f.labels = [f.labels[i] for i in order]
            f.values = f.values[order]
            f.valid = f.valid[order]
            return f
        raise PromError(f"unsupported function {name!r}")

    def _label_replace(self, node, steps, db) -> Frame:
        """label_replace(v, dst, replacement, src, regex) — prom
        funcLabelReplace: fully-anchored regex against src; on match, dst
        is set to the expanded replacement ($1 group refs)."""
        if len(node.args) != 5:
            raise PromError("label_replace takes 5 arguments")
        f = self._eval(node.args[0], steps, db)
        dst = _expect_string(node, 1)
        repl = _expect_string(node, 2)
        src = _expect_string(node, 3)
        pattern = _expect_string(node, 4)
        if not _LABEL_NAME_RE.match(dst):
            raise PromError(f"invalid destination label name {dst!r}")
        try:
            rx = re.compile("^(?:" + pattern + ")$")
        except re.error as e:
            raise PromError(f"invalid regex in label_replace: {e}") from None
        out_labels = []
        for labels in f.labels:
            val = labels.get(src, "")
            m = rx.match(val)
            if m is None:
                out_labels.append(labels)
                continue
            new = dict(labels)
            expanded = _go_expand(repl, m)
            if expanded:
                new[dst] = expanded
            else:
                new.pop(dst, None)
            out_labels.append(new)
        f.labels = out_labels
        return f

    def _label_join(self, node, steps, db) -> Frame:
        """label_join(v, dst, sep, src...) — prom funcLabelJoin."""
        if len(node.args) < 3:
            raise PromError("label_join takes at least 3 arguments")
        f = self._eval(node.args[0], steps, db)
        dst = _expect_string(node, 1)
        sep = _expect_string(node, 2)
        srcs = [_expect_string(node, i) for i in range(3, len(node.args))]
        if not _LABEL_NAME_RE.match(dst):
            raise PromError(f"invalid destination label name {dst!r}")
        out_labels = []
        for labels in f.labels:
            joined = sep.join(labels.get(s, "") for s in srcs)
            new = dict(labels)
            if joined:
                new[dst] = joined
            else:
                new.pop(dst, None)
            out_labels.append(new)
        f.labels = out_labels
        return f

    # default subquery resolution when [range:] omits the step (the
    # Prometheus global evaluation interval analogue)
    subquery_default_step_s = 60.0

    def _subquery_samples(self, sq: "pp.Subquery", steps, db):
        """Evaluate the inner expression on an absolutely-aligned step
        grid covering the outer window -> run-encoded
        (labels, t_ms_all, v_all, lens) shaped like _collect_series."""
        # explicit None check: `or` would silently turn [range:0s] into
        # the default step instead of rejecting it
        step = self.subquery_default_step_s if sq.step_s is None else sq.step_s
        if step <= 0:
            raise PromError("subquery step must be positive")
        t_end = float(steps[-1]) - sq.offset_s
        t_start = float(steps[0]) - sq.offset_s - sq.range_s
        first = math.ceil(t_start / step) * step  # absolute alignment
        n = int(math.floor((t_end - first) / step)) + 1
        empty = ([], np.empty(0, np.int64), np.empty(0, np.float64),
                 np.empty(0, np.int64))
        if n <= 0:
            return empty
        if n > 11_000:
            raise PromError("subquery produces too many steps (max 11000)")
        sub_steps = first + np.arange(n) * step
        inner = self._eval(sq.expr, sub_steps, db)
        if inner.is_scalar:
            raise PromError("subquery is only allowed on instant vector")
        # rint, not truncation: x.2999999*1000 would land 1ms early and
        # flip boundary inclusion in the (start, end] kernel windows
        times_ms = np.rint(sub_steps * 1000.0).astype(np.int64)
        labels, t_parts, v_parts, lens = [], [], [], []
        for i in range(len(inner.labels)):
            mask = inner.valid[i]
            if not mask.any():
                continue
            labels.append(inner.labels[i])
            t_parts.append(times_ms[mask])
            v_parts.append(np.asarray(inner.values[i][mask], np.float64))
            lens.append(int(mask.sum()))
        if not labels:
            return empty
        return (labels, np.concatenate(t_parts), np.concatenate(v_parts),
                np.asarray(lens, np.int64))

    # range-function kinds the tiled engine lowers; everything else
    # (quantile/mad/holt_winters — no prefix form) keeps the chunked
    # dense fallback
    _TILED_KINDS = frozenset(
        ["rate", "instant_rate", "changes_resets", "deriv", "predict"])
    _TILED_OVER_TIME = frozenset(
        ["sum", "avg", "count", "last", "present", "stddev", "stdvar",
         "min", "max"])

    def _eval_range_fn(self, ms_sel, steps, db, spec: dict) -> Frame:
        if isinstance(ms_sel, pp.Subquery):
            w = ms_sel.range_s
            eval_times = steps - ms_sel.offset_s
            labels, t_ms_all, v_all, lens = self._subquery_samples(
                ms_sel, steps, db)
            enc = None
        else:
            vs = ms_sel.vector
            w = ms_sel.range_s
            eval_times = steps - vs.offset_s
            t_max_ns = int(eval_times[-1] * 1e9) + 1
            t_min_ns = int((eval_times[0] - w) * 1e9)
            with _stage("prom_collect"):
                got = self._collect_series(
                    vs, t_min_ns, t_max_ns, db,
                    want_encoded=_want_encoded())
                labels, t_ms_all, v_all, lens = got[:4]
                enc = got[4] if len(got) > 4 else None
        k = len(steps)
        if not labels:
            return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
        out, valid = self._run_range_kernel(
            spec, t_ms_all, v_all, lens, eval_times, float(w), enc=enc)
        labels = [_drop_name(l) for l in labels]
        return Frame(labels, out, valid)

    def _tiled_prep(self, spec, t_ms_all, v_all, lens, eval_times, w,
                    enc=None):
        """TiledPrepared for this (samples, window grid) pair, or None
        when the spec or the grid is ineligible (dense fallback)."""
        kind = spec["kind"]
        if kind not in self._TILED_KINDS and not (
                kind == "over_time" and spec["func"] in self._TILED_OVER_TIME):
            return None
        if not _tiled_enabled():
            return None
        n_max = int(lens.max())
        s_dim = len(lens)
        cells = _tile_cells_mult()
        max_tiles = min(max(cells * n_max + 64, 1024),
                        max((1 << 28) // max(s_dim, 1), 64))
        plan = promops.plan_tiles(
            eval_times - w, eval_times, int(t_ms_all.min()),
            int(t_ms_all.max()), max_tiles)
        if plan is None:
            return None
        host = _host_kernels()
        lane_q = 1
        if not host:
            from opengemini_tpu.models.grid import lane_quantum

            lane_q = lane_quantum()
        return promops.prepare_tiled(
            plan, t_ms_all, v_all, lens, dtype=np.float64,
            max_gather_cols=cells * n_max + 64, lane_quantum=lane_q,
            enc=enc)

    def _run_mesh_kernel(self, spec, kind, prep, mesh):
        """Multi-chip tiled kernels: series axis sharded over the mesh,
        one jit program per kernel (zero collectives); results sliced
        back to the real (S, k) window grid on the host."""
        STATS.incr("prom", "tiled_mesh_kernels")
        # sharding transfer attributed to the prepare stage (it is
        # part of building this query's device state, and hiding it
        # would make /debug/queries' stage sums lie about mesh cost).
        # NOTE: like every device path here (the dense fallback
        # included), the mesh kernels compute in the device dtype —
        # f32 when jax x64 is off — while the host-numpy path is
        # true f64 (README "Multi-chip execution").
        with _stage("prom_prepare"):
            sharded = prep.sharded(mesh)
        with _stage("prom_kernel"):
            if kind == "rate":
                out, valid = sharded.rate(
                    is_counter=spec["is_counter"],
                    is_rate=spec["is_rate"])
            elif kind == "instant_rate":
                out, valid = sharded.instant_rate(
                    per_second=spec["per_second"])
            elif kind == "changes_resets":
                out, valid = sharded.changes_resets(kind=spec["which"])
            elif kind == "deriv":
                out, _icept, valid = sharded.linear_regression()
            elif kind == "predict":
                slope, icept, valid = sharded.linear_regression()
                out = icept + slope * spec["dur"]
            else:
                out, valid = sharded.over_time(func=spec["func"])
        kr = prep.k_real
        from opengemini_tpu.utils import devobs

        return (devobs.fetch_np(out)[:prep.S, :kr],
                devobs.fetch_np(valid)[:prep.S, :kr])

    def _run_tiled_kernel(self, spec, kind, prep, host: bool):
        """Single-device tiled kernels: host numpy or jax.numpy per the
        planner's route."""
        STATS.incr("prom", "tiled_kernels")
        xp = np
        if not host:
            import jax.numpy as xp  # noqa: F811 — device path
        with _stage("prom_kernel"):
            if kind == "rate":
                out, valid = prep.rate(
                    xp, is_counter=spec["is_counter"],
                    is_rate=spec["is_rate"])
            elif kind == "instant_rate":
                out, valid = prep.instant_rate(
                    xp, per_second=spec["per_second"])
            elif kind == "changes_resets":
                out, valid = prep.changes_resets(xp, kind=spec["which"])
            elif kind == "deriv":
                out, _icept, valid = prep.linear_regression(xp)
            elif kind == "predict":
                slope, icept, valid = prep.linear_regression(xp)
                out = icept + slope * spec["dur"]
            else:
                out, valid = prep.over_time(xp, func=spec["func"])
        kr = prep.k_real
        from opengemini_tpu.utils import devobs

        return (devobs.fetch_np(out)[:, :kr],
                devobs.fetch_np(valid)[:, :kr])

    def _run_range_kernel(self, spec, t_ms_all, v_all, lens, eval_times,
                          w, enc=None):
        """Dispatch one range-vector spec: tiled interval reductions when
        the window grid fits the ms tile lattice, dense kernels otherwise.
        Returns host numpy (out, valid)."""
        kind = spec["kind"]
        with _stage("prom_prepare"):
            prep = self._tiled_prep(spec, t_ms_all, v_all, lens,
                                    eval_times, w, enc=enc)
        if prep is None and v_all is None:
            # dense fallback needs host values: materialize the encoded
            # descriptor (bit-identical host decode)
            from opengemini_tpu.ops import device_decode

            v_all = device_decode.materialize_enc(enc)
        mesh = _mesh_for_tiled() if prep is not None else None
        if prep is not None:
            # route through the offload planner (query/offload.py): the
            # static prior reproduces today's dispatch exactly — mesh
            # when configured (a set mesh overrides the host-kernel CPU
            # shortcut), else host numpy per _host_kernels() — and the
            # OGT_PROM_HOST_KERNELS override prunes the candidate set,
            # so the pin and the planner are ONE mechanism
            from opengemini_tpu.query import offload

            geo = (prep.S, prep.N, prep.k_real)
            mode = offload.prom_host_kernels_mode()
            candidates = [c for c in ("host", "device")
                          if not (mode == "1" and c == "device")
                          and not (mode == "0" and c == "host")]
            if mesh is not None:
                candidates.append("mesh")
            static = ("mesh" if mesh is not None
                      else "host" if _host_kernels() else "device")
            route = offload.GLOBAL.decide(
                "prom_" + kind, geo, tuple(candidates), static,
                stage="prom_kernel")
            t_route = _time.perf_counter()
            if route == "mesh":
                out, valid = self._run_mesh_kernel(spec, kind, prep, mesh)
            else:
                out, valid = self._run_tiled_kernel(
                    spec, kind, prep, host=(route == "host"))
            offload.GLOBAL.observe("prom_" + kind, geo, route,
                                   _time.perf_counter() - t_route)
            return out, valid
        # dense fallback (searchsorted window bounds)
        STATS.incr("prom", "dense_kernels")
        with _stage("prom_prepare"):
            times, values, counts, base_ms = promops.prepare_matrix_runs(
                t_ms_all, v_all, lens, dtype=np.float64)
        ends = eval_times - base_ms / 1000.0
        starts = ends - w
        with _stage("prom_kernel"):
            if kind == "rate":
                out, valid = promops.extrapolated_rate(
                    times, values, counts, starts, ends, w,
                    spec["is_counter"], spec["is_rate"])
            elif kind == "instant_rate":
                out, valid = promops.instant_rate(
                    times, values, counts, starts, ends, spec["per_second"])
            elif kind == "changes_resets":
                out, valid = promops.changes_resets(
                    times, values, counts, starts, ends, spec["which"])
            elif kind == "deriv":
                out, _icept, valid = promops.linear_regression(
                    times, values, counts, starts, ends)
            elif kind == "predict":
                slope, icept, valid = promops.linear_regression(
                    times, values, counts, starts, ends)
                out = icept + slope * spec["dur"]
            elif kind == "quantile":
                out, valid = promops.quantile_over_time(
                    times, values, counts, starts, ends, spec["q"])
            elif kind == "mad":
                out, valid = promops.mad_over_time(
                    times, values, counts, starts, ends)
            elif kind == "holt":
                out, valid = promops.holt_winters_window(
                    times, values, counts, starts, ends, spec["sf"],
                    spec["tf"])
            else:
                out, valid = promops.over_time(
                    times, values, counts, starts, ends, spec["func"])
        return np.asarray(out), np.asarray(valid)

    def _metric_of(self, vs: pp.VectorSelector) -> str:
        metric = vs.metric
        for m in vs.matchers:
            if m.name == "__name__":
                if m.op != "=":
                    raise PromError("__name__ supports only '=' here")
                metric = m.value
        if not metric:
            raise PromError("metric name required")
        return metric

    def _collect_runs(self, vs, t_min_ns: int, t_max_ns: int, db: str):
        """Label-free bulk collection for the lazy aggregation fast path:
        (shard, metric, uniq_sids, t_ms_all, v_all, lens) or None when
        ineligible (multi-shard ranges must merge series by label, small
        matches gain nothing)."""
        metric = self._metric_of(vs)
        shards = self.engine.shards_for_range(db, None, t_min_ns, t_max_ns)
        if (len(shards) != 1
                or not hasattr(shards[0], "read_series_bulk")
                or not hasattr(shards[0].index, "entries_bulk")):
            return None  # dict-index fallback has no bulk label fetch
        sh = shards[0]
        sids = _match_sids(sh, metric, vs.matchers)
        if sids.size < 4096:
            return None  # eager path is fine at low cardinality
        sid_arr, rec = sh.read_series_bulk(
            metric, sids, t_min_ns, t_max_ns,
            fields=[self.value_field])
        col = rec.columns.get(self.value_field)
        if col is None or len(rec) == 0:
            return (sh, metric, np.empty(0, np.int64),
                    np.empty(0, np.int64), np.empty(0, np.float64),
                    np.empty(0, np.int64))
        keep = col.valid
        sid_k = sid_arr[keep]
        uniq, lens = np.unique(sid_k, return_counts=True)
        return (sh, metric, uniq, rec.times[keep] // MS,
                col.values[keep].astype(np.float64), lens)

    def _eval_agg_fast(self, node: pp.Aggregation, steps, db):
        """topk/bottomk/count_values over a bare high-cardinality selector
        without materializing input labels: the winners' (or none of the)
        labels resolve AFTER selection. At 1M series the eager path spends
        ~85% of its time building label dicts that the result never uses
        (BASELINE.md config #5). Returns None when inapplicable.

        Exact-value ties at the topk/bottomk boundary may admit a
        different (equally-valid) subset than the eager path: this path
        scans rows in sid order, the eager path in label order, and
        Prometheus defines boundary ties as arbitrary."""
        if (node.op not in ("topk", "bottomk", "count_values")
                or node.grouping or node.without
                or not isinstance(node.expr, pp.VectorSelector)):
            return None
        vs = node.expr
        window_s = self.lookback_s
        eval_times = steps - vs.offset_s
        t_max_ns = int(eval_times[-1] * 1e9) + 1
        t_min_ns = int((eval_times[0] - window_s) * 1e9)
        got = self._collect_runs(vs, t_min_ns, t_max_ns, db)
        if got is None:
            return None
        sh, metric, uniq, t_ms_all, v_all, lens = got
        k = len(steps)
        if len(uniq) == 0:
            return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
        times, values, counts, base_ms = promops.prepare_matrix_runs(
            t_ms_all, v_all, lens, dtype=np.float64)
        rel = eval_times - base_ms / 1000.0
        vals, valid = promops.instant_values(times, values, counts, rel,
                                             window_s)
        vals, valid = np.asarray(vals), np.asarray(valid)

        def resolve(rows):
            entries = sh.index.entries_bulk(uniq[rows])
            out = []
            for e in entries:
                lbl = dict(e[1]) if e is not None else {}
                lbl["__name__"] = metric
                out.append(lbl)
            return out

        if node.op in ("topk", "bottomk"):
            nv = _expect_number_node(node.param)
            if math.isnan(nv) or math.isinf(nv):
                raise PromError(f"invalid {node.op} parameter: {_fmt(nv)}")
            n = int(nv)
            if n <= 0:
                return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
            keep = _topk_keep(vals, valid, min(n, len(uniq)),
                              descending=(node.op == "topk"))
            rows = np.flatnonzero(keep.any(axis=1))
            labels = resolve(rows)
            order = sorted(range(len(rows)),
                           key=lambda i: tuple(sorted(labels[i].items())))
            rows = rows[order]
            return Frame([labels[i] for i in order], vals[rows], keep[rows])

        # count_values: input labels are never consulted (no grouping)
        if not isinstance(node.param, pp.StringLit):
            raise PromError("count_values expects a label-name string")
        out_labels, out_rows = _count_values_cells(
            vals, valid, k, {}, node.param.val)
        if not out_labels:
            return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
        out = np.vstack(out_rows)
        return Frame(out_labels, out, out > 0)

    def _eval_aggregation(self, node: pp.Aggregation, steps, db) -> Frame:
        fast = self._eval_agg_fast(node, steps, db)
        if fast is not None:
            return fast
        f = self._eval(node.expr, steps, db)
        k = len(steps)
        if not f.labels:
            return f
        # group key per series
        keys = []
        out_labels_by_key: dict[tuple, dict] = {}
        for labels in f.labels:
            l = _drop_name(labels)
            if node.without:
                grp = {n: v for n, v in l.items() if n not in node.grouping}
            elif node.grouping:
                grp = {n: v for n, v in l.items() if n in node.grouping}
            else:
                grp = {}
            key = tuple(sorted(grp.items()))
            keys.append(key)
            out_labels_by_key[key] = grp
        uniq = sorted(out_labels_by_key)
        key_idx = {kk: i for i, kk in enumerate(uniq)}
        g = len(uniq)
        vals = np.where(f.valid, f.values, 0.0)
        member = np.zeros((g, len(f.labels)), dtype=bool)
        for si, kk in enumerate(keys):
            member[key_idx[kk], si] = True
        counts = member.astype(np.float64) @ f.valid.astype(np.float64)
        any_valid = counts > 0

        op = node.op
        if op in ("sum", "avg", "count", "stddev", "stdvar", "group"):
            s = member.astype(np.float64) @ vals
            if op == "sum":
                out = s
            elif op == "count":
                out = counts
            elif op == "group":
                out = np.ones_like(s)
            else:
                mean = s / np.maximum(counts, 1)
                sq = member.astype(np.float64) @ np.where(f.valid, f.values**2, 0.0)
                var = sq / np.maximum(counts, 1) - mean**2
                var = np.maximum(var, 0)
                if op == "avg":
                    out = mean
                elif op == "stdvar":
                    out = var
                else:
                    out = np.sqrt(var)
            if op == "avg":
                out = s / np.maximum(counts, 1)
            return Frame([dict(u) for u in (out_labels_by_key[kk] for kk in uniq)],
                         out, any_valid)
        if op in ("min", "max"):
            fill = np.inf if op == "min" else -np.inf
            masked = np.where(f.valid, f.values, fill)
            out = np.full((g, k), fill)
            for si, kk in enumerate(keys):
                gi = key_idx[kk]
                out[gi] = np.minimum(out[gi], masked[si]) if op == "min" else np.maximum(out[gi], masked[si])
            return Frame([dict(u) for u in (out_labels_by_key[kk] for kk in uniq)],
                         out, any_valid)
        if op in ("topk", "bottomk"):
            nv = _expect_number_node(node.param)
            if math.isnan(nv) or math.isinf(nv):
                raise PromError(f"invalid {op} parameter: {_fmt(nv)}")
            n = int(nv)
            keep = np.zeros_like(f.valid)
            if n > 0:
                for gi in range(g):
                    rows = np.flatnonzero(member[gi])
                    keep[rows] = _topk_keep(
                        f.values[rows], f.valid[rows],
                        min(n, len(rows)), descending=(op == "topk"),
                    )
            return Frame(f.labels, f.values, keep)
        if op == "quantile":
            # vectorized Prom quantile: sort once per group, linear
            # interpolation at rank q*(n_valid-1) per step column
            q = float(_expect_number_node(node.param))
            out = np.full((g, k), np.nan)
            if math.isnan(q):  # Prom: NaN phi -> NaN for every group
                return Frame([dict(u) for u in (out_labels_by_key[kk] for kk in uniq)],
                             out, any_valid)
            for gi in range(g):
                rows = np.flatnonzero(member[gi])
                sub_valid = f.valid[rows]
                nvalid = sub_valid.sum(axis=0)  # (K,)
                has = nvalid > 0
                if q < 0 or q > 1:
                    out[gi] = np.where(has, -np.inf if q < 0 else np.inf,
                                       np.nan)
                    continue
                srt = np.sort(np.where(sub_valid, f.values[rows], np.inf),
                              axis=0)
                rank = q * np.maximum(nvalid - 1, 0)
                lo = np.floor(rank).astype(np.int64)
                hi = np.minimum(lo + 1, np.maximum(nvalid - 1, 0))
                w = rank - lo
                cols = np.arange(k)
                cap = len(rows) - 1
                vlo = srt[np.minimum(lo, cap), cols]
                vhi = srt[np.minimum(hi, cap), cols]
                res = np.where(has, vlo * (1 - w) + vhi * w, np.nan)
                # a valid NaN sample poisons its column's quantile (the
                # +Inf padding above would otherwise sort before it and
                # fabricate +Inf where Prometheus interpolates to NaN)
                nan_col = (sub_valid & np.isnan(f.values[rows])).any(axis=0)
                out[gi] = np.where(nan_col, np.nan, res)
            return Frame([dict(u) for u in (out_labels_by_key[kk] for kk in uniq)],
                         out, any_valid)
        if op == "count_values":
            if not isinstance(node.param, pp.StringLit):
                raise PromError("count_values expects a label-name string")
            label = node.param.val
            out_labels, out_rows = [], []
            for gi, kk in enumerate(uniq):
                rows = np.flatnonzero(member[gi])
                lbls, rws = _count_values_cells(
                    f.values[rows], f.valid[rows], k,
                    out_labels_by_key[kk], label)
                out_labels.extend(lbls)
                out_rows.extend(rws)
            if not out_labels:
                return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
            counts_m = np.stack(out_rows)
            return Frame(out_labels, counts_m, counts_m > 0)
        raise PromError(f"unsupported aggregation {op!r}")

    def _eval_binop(self, node: pp.BinaryOp, steps, db) -> Frame:
        lhs = self._eval(node.lhs, steps, db)
        rhs = self._eval(node.rhs, steps, db)
        op = node.op
        k = len(steps)
        if op in pp.SET_OPS:
            if lhs.is_scalar or rhs.is_scalar:
                raise PromError(
                    f"set operator {op!r} not allowed in binary scalar "
                    "expression")
            return _eval_set_op(op, lhs, rhs, node.matching, k)
        if lhs.is_scalar and rhs.is_scalar:
            if op in pp.COMPARISONS:
                # Prometheus: "comparisons between scalars must use BOOL"
                if not node.bool_mod:
                    raise PromError(
                        "comparisons between scalars must use BOOL modifier")
                v = _cmp(op, lhs.values, rhs.values).astype(np.float64)
                return Frame([{}], v, lhs.valid & rhs.valid, True)
            v = _apply_op(op, lhs.values, rhs.values, comparison_keep=False)
            return Frame([{}], v, lhs.valid & rhs.valid, True)
        if lhs.is_scalar or rhs.is_scalar:
            vec, sc, flipped = (rhs, lhs, True) if lhs.is_scalar else (lhs, rhs, False)
            a, b = (sc.values, vec.values) if flipped else (vec.values, sc.values)
            if op in pp.COMPARISONS:
                m = _cmp(op, a, b)
                if node.bool_mod:
                    labels = [_drop_name(l) for l in vec.labels]
                    vals = np.where(m, 1.0, 0.0)
                    return Frame(labels,
                                 np.broadcast_to(vals, vec.values.shape).copy(),
                                 vec.valid.copy())
                return Frame(vec.labels, vec.values, vec.valid & m)
            v = _apply_op(op, a, b, comparison_keep=False)
            labels = [_drop_name(l) for l in vec.labels]
            return Frame(labels, np.broadcast_to(v, vec.values.shape).copy(), vec.valid)
        return _eval_vector_binop(op, lhs, rhs, node.matching,
                                  node.bool_mod, k)


def _signature(labels: dict, matching: "pp.VectorMatching | None") -> tuple:
    """Match signature of a series under on()/ignoring() (Prometheus
    signatureFunc): on() hashes exactly the named labels (absent = ""),
    ignoring() hashes everything else minus __name__."""
    base = _drop_name(labels)
    if matching is not None and matching.on:
        return tuple(base.get(n, "") for n in sorted(set(matching.labels)))
    ignored = set(matching.labels) if matching is not None else ()
    return tuple(sorted((n, v) for n, v in base.items() if n not in ignored))


def _eval_set_op(op: str, lhs: Frame, rhs: Frame,
                 matching, k: int) -> Frame:
    """and/or/unless (VectorAnd/VectorOr/VectorUnless): set membership by
    match signature, applied per step via the validity masks."""
    rsig_valid: dict[tuple, np.ndarray] = {}
    for j, rl in enumerate(rhs.labels):
        s = _signature(rl, matching)
        got = rsig_valid.get(s)
        rsig_valid[s] = rhs.valid[j] if got is None else (got | rhs.valid[j])
    if op == "or":
        lsig_valid: dict[tuple, np.ndarray] = {}
        for i, ll in enumerate(lhs.labels):
            s = _signature(ll, matching)
            got = lsig_valid.get(s)
            lsig_valid[s] = lhs.valid[i] if got is None else (got | lhs.valid[i])
        labels = list(lhs.labels)
        vals = [lhs.values[i] for i in range(len(lhs.labels))]
        valid = [lhs.valid[i] for i in range(len(lhs.labels))]
        for j, rl in enumerate(rhs.labels):
            s = _signature(rl, matching)
            lv = lsig_valid.get(s)
            v = rhs.valid[j] if lv is None else (rhs.valid[j] & ~lv)
            if v.any():
                labels.append(rl)
                vals.append(rhs.values[j])
                valid.append(v)
        if not labels:
            return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
        return Frame(labels, np.stack(vals), np.stack(valid))
    # and / unless keep lhs rows, gated by rhs presence at the step
    labels, vals, valid = [], [], []
    zero = np.zeros(k, bool)
    for i, ll in enumerate(lhs.labels):
        rv = rsig_valid.get(_signature(ll, matching), zero)
        v = (lhs.valid[i] & rv) if op == "and" else (lhs.valid[i] & ~rv)
        if v.any():
            labels.append(ll)
            vals.append(lhs.values[i])
            valid.append(v)
    if not labels:
        return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
    return Frame(labels, np.stack(vals), np.stack(valid))


_DROP_NAME_OPS = {"+", "-", "*", "/", "%", "^", "atan2"}


def _result_metric(many_labels: dict, one_labels: dict, op: str,
                   matching, bool_mod: bool) -> dict:
    """Prometheus resultMetric (promql/engine.go): output labels start
    from the many side; one-to-one restricts by on/ignoring; group
    modifiers graft include labels from the one side."""
    out = dict(many_labels)
    if op in _DROP_NAME_OPS or bool_mod:
        out.pop("__name__", None)
    if matching.card == "one-to-one":
        if matching.on:
            keep = set(matching.labels)
            out = {n: v for n, v in out.items() if n in keep}
        else:
            for n in matching.labels:
                out.pop(n, None)
    for n in matching.include:
        v = one_labels.get(n, "")
        if v != "":
            out[n] = v
        else:
            out.pop(n, None)
    return out


def _eval_vector_binop(op: str, lhs: Frame, rhs: Frame, matching,
                       bool_mod: bool, k: int) -> Frame:
    """Vector/vector arithmetic and comparison with full matching
    semantics (Prometheus VectorBinop; reference transpiler surface:
    promql2influxql/binary_expr.go:308)."""
    if matching is None:
        matching = pp.VectorMatching(False, [], "one-to-one")
    # orient so `one` is the side that must have unique signatures
    if matching.card == "one-to-many":  # group_right: lhs is the one side
        many, one, swapped = rhs, lhs, True
    else:
        many, one, swapped = lhs, rhs, False
    # index the one side; equal signatures are an error when both series
    # are present at any step, else the disjoint rows merge
    one_rows: dict[tuple, tuple[np.ndarray, np.ndarray, dict]] = {}
    for j, ol in enumerate(one.labels):
        s = _signature(ol, matching)
        got = one_rows.get(s)
        if got is None:
            one_rows[s] = (one.values[j], one.valid[j], ol)
            continue
        gv, gval, glabels = got
        if (gval & one.valid[j]).any():
            side = "right" if not swapped else "left"
            raise PromError(
                "found duplicate series for the match group on the "
                f"{side} hand-side of the operation; many-to-many "
                "matching not allowed: matching labels must be unique "
                "on one side")
        if matching.include and any(
                glabels.get(n) != one.labels[j].get(n)
                for n in matching.include):
            raise PromError(
                "found series with conflicting group_left/group_right "
                "include labels in the match group")
        one_rows[s] = (
            np.where(one.valid[j], one.values[j], gv),
            gval | one.valid[j], glabels,
        )
    out_labels, out_vals, out_valid = [], [], []
    # result-series uniqueness: Prometheus errors when two matches land
    # on the same output labels at the same step
    seen: dict[tuple, np.ndarray] = {}
    for i, ml in enumerate(many.labels):
        got = one_rows.get(_signature(ml, matching))
        if got is None:
            continue
        ov, oval, olabels = got
        both = many.valid[i] & oval
        vl, vr = (many.values[i], ov) if not swapped else (ov, many.values[i])
        if op in pp.COMPARISONS:
            m = _cmp(op, vl, vr)
            if bool_mod:
                vals = np.where(m, 1.0, 0.0)
                valid = both
            else:
                vals = vl
                valid = both & m
        else:
            vals = _apply_op(op, vl, vr, comparison_keep=False)
            valid = both
        labels = _result_metric(ml, olabels, op, matching, bool_mod)
        sig = tuple(sorted(labels.items()))
        prev = seen.get(sig)
        if prev is not None:
            if (prev & valid).any():
                if matching.card == "one-to-one":
                    raise PromError(
                        "multiple matches for labels: many-to-one "
                        "matching must be explicit (group_left/"
                        "group_right)")
                raise PromError(
                    "multiple matches for labels: grouping labels must "
                    "ensure unique matches")
            seen[sig] = prev | valid
        else:
            seen[sig] = valid.copy()
        if valid.any():
            out_labels.append(labels)
            out_vals.append(np.asarray(vals, np.float64))
            out_valid.append(valid)
    if not out_labels:
        return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
    return Frame(out_labels, np.stack(out_vals), np.stack(out_valid))


def _histogram_quantile(q: float, f: Frame, k: int) -> Frame:
    """Prom histogram_quantile over `le`-bucketed series
    (promql/quantile.go bucketQuantile): group by labels minus `le`,
    sort buckets, interpolate within the winning bucket. Vectorized over
    steps per group (one (B, K) matrix pass, no per-column python loops).

    Prom edge semantics: q > 1 -> +Inf, q < 0 -> -Inf; a winning FIRST
    bucket with upperBound <= 0 returns that bound (interpolation starts
    at 0 only for positive first buckets); a winning +Inf bucket returns
    the previous bound."""
    groups: dict[tuple, list[tuple[float, int]]] = {}
    labels_of: dict[tuple, dict] = {}
    for i, labels in enumerate(f.labels):
        le = labels.get("le")
        if le is None:
            continue
        le_v = float("inf") if le in ("+Inf", "inf", "Inf") else float(le)
        rest = {kk: v for kk, v in labels.items() if kk not in ("le", "__name__")}
        key = tuple(sorted(rest.items()))
        groups.setdefault(key, []).append((le_v, i))
        labels_of[key] = rest
    out_labels, out_vals, out_valid = [], [], []
    for key in sorted(groups):
        buckets = sorted(groups[key])
        les = np.array([le for le, _i in buckets])  # (B,), ascending
        rows = [i for _le, i in buckets]
        if len(buckets) < 2 or not math.isinf(les[-1]):
            continue
        counts = f.values[rows]  # (B, K) cumulative by le
        bvalid = f.valid[rows]
        valid = bvalid.all(axis=0)  # all buckets present at the step
        total = counts[-1]
        valid &= total > 0
        if q > 1 or q < 0:
            vals = np.full(k, np.inf if q > 1 else -np.inf)
            out_labels.append(labels_of[key])
            out_vals.append(vals)
            out_valid.append(valid)
            continue
        rank = q * total  # (K,)
        # first bucket index with count >= rank
        hit = counts >= rank[None, :]
        win = np.argmax(hit, axis=0)  # (K,)
        prev = np.clip(win - 1, 0, len(buckets) - 1)
        prev_c = np.where(win > 0, counts[prev, np.arange(k)], 0.0)
        prev_le = np.where(win > 0, les[prev], 0.0)
        win_le = les[win]
        win_c = counts[win, np.arange(k)]
        span = win_c - prev_c
        with np.errstate(invalid="ignore", divide="ignore"):
            frac = np.where(span > 0, (rank - prev_c) / np.where(span == 0, 1, span), 1.0)
            vals = prev_le + (win_le - prev_le) * frac
        # +Inf winning bucket -> previous bound (second-highest le)
        vals = np.where(np.isinf(win_le), les[-2] if len(les) >= 2 else 0.0, vals)
        # first bucket with non-positive bound -> the bound itself
        vals = np.where((win == 0) & (win_le <= 0), win_le, vals)
        out_labels.append(labels_of[key])
        out_vals.append(vals)
        out_valid.append(valid)
    if not out_labels:
        return Frame([], np.zeros((0, k)), np.zeros((0, k), bool))
    return Frame(out_labels, np.stack(out_vals), np.stack(out_valid))


def _count_values_cells(sub, sub_valid, k: int, base_labels: dict,
                        label: str):
    """Shared count_values bucketing (eager grouped path + lazy fast
    path): one pass over valid cells — unique codes + bincount,
    O(cells + distinct x steps) — plus the NaN bucket. Returns
    (labels, rows)."""
    cell_cols = np.broadcast_to(np.arange(k), sub.shape)[sub_valid]
    seen = sub[sub_valid]
    out_labels, out_rows = [], []
    if not len(seen):
        return out_labels, out_rows
    nanmask = np.isnan(seen)
    vals_f, cols_f = seen[~nanmask], cell_cols[~nanmask]
    uvals, inv = np.unique(vals_f, return_inverse=True)
    counts = np.bincount(
        inv * k + cols_f, minlength=len(uvals) * k
    ).reshape(len(uvals), k).astype(np.float64)
    for ui, v in enumerate(uvals):
        lbl = dict(base_labels)
        lbl[label] = _fmt(float(v))
        out_labels.append(lbl)
        out_rows.append(counts[ui])
    if nanmask.any():
        lbl = dict(base_labels)
        lbl[label] = "NaN"
        out_labels.append(lbl)
        out_rows.append(
            np.bincount(cell_cols[nanmask], minlength=k).astype(np.float64))
    return out_labels, out_rows


def _topk_keep(values: np.ndarray, valid: np.ndarray, m: int,
               descending: bool) -> np.ndarray:
    """(R, K) keep-mask of the m largest (descending) / smallest VALID
    entries per column. Exact f64 comparisons, O(R x K) via partition
    (full argsort of a 1M-series group would pay R log R per column);
    invalid cells never rank; valid NaN cells rank below every comparable
    value but still fill leftover room (Prometheus pushes NaN samples
    while the heap has room); boundary ties resolve to the lowest row
    index, deterministically."""
    if m <= 0:
        return np.zeros_like(valid)
    keyx = np.where(valid, -values if descending else values, np.nan)
    R = keyx.shape[0]
    if m >= R:
        return valid.copy()
    part = np.partition(keyx, m - 1, axis=0)  # NaN sorts last
    b = part[m - 1]  # per-column boundary (m-th best), NaN if < m usable
    strict = keyx < b
    ties = keyx == b
    need = m - strict.sum(axis=0)
    tie_rank = np.cumsum(ties, axis=0) - 1
    keep = strict | (ties & (tie_rank < need))
    short = np.isnan(b)  # fewer than m comparable cells in the column
    if short.any():
        keep[:, short] = valid[:, short] & ~np.isnan(values[:, short])
    # leftover room (columns with < m comparable cells) fills with valid
    # NaN samples in row order, matching the Prometheus heap
    room = m - keep.sum(axis=0)
    if (room > 0).any():
        nanv = valid & np.isnan(values)
        nan_rank = np.cumsum(nanv, axis=0) - 1
        keep |= nanv & (nan_rank < room)
    return keep


def _prom_quantile(q: float, vals: list[float]) -> float:
    if not vals:
        return float("nan")
    if q < 0:
        return float("-inf")
    if q > 1:
        return float("inf")
    s = sorted(vals)
    n = len(s)
    rank = q * (n - 1)
    lo = int(math.floor(rank))
    hi = min(lo + 1, n - 1)
    w = rank - lo
    return s[lo] * (1 - w) + s[hi] * w


def _apply_op(op, a, b, comparison_keep):
    with np.errstate(all="ignore"):
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return np.where(b != 0, a / np.where(b == 0, 1, b), np.inf * np.sign(a))
        if op == "%":
            return np.mod(a, np.where(b == 0, np.nan, b))
        if op == "^":
            return np.power(a, b)
        if op == "atan2":
            return np.arctan2(a, b)
    raise PromError(f"unsupported operator {op!r}")


def _cmp(op, a, b):
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == ">":
        return a > b
    if op == "<=":
        return a <= b
    return a >= b


def _drop_name(labels: dict) -> dict:
    return {k: v for k, v in labels.items() if k != "__name__"}


def _expect_matrix(node, i):
    if i >= len(node.args) or not isinstance(
            node.args[i], (pp.MatrixSelector, pp.Subquery)):
        raise PromError(f"{node.name}() expects a range vector")
    return node.args[i]


def _const_fold(e):
    """Constant expression value or None (unary minus parses as -1 * x)."""
    if isinstance(e, pp.NumberLit):
        return e.val
    if isinstance(e, pp.BinaryOp):
        lv, rv = _const_fold(e.lhs), _const_fold(e.rhs)
        if lv is None or rv is None:
            return None
        return float(_apply_op(e.op, np.float64(lv), np.float64(rv),
                               comparison_keep=False))
    return None


def _expect_number(node, i) -> float:
    v = _const_fold(node.args[i]) if i < len(node.args) else None
    if v is None:
        raise PromError(f"{node.name}() expects a number argument")
    return v


def _expect_number_node(n) -> float:
    v = _const_fold(n) if n is not None else None
    if v is None:
        raise PromError("expected a number parameter")
    return v


def _expect_string(node, i) -> str:
    arg = node.args[i] if i < len(node.args) else None
    if not isinstance(arg, pp.StringLit):
        raise PromError(f"{node.name}() expects a string argument at position {i}")
    return arg.val


_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

_GO_REF_RE = re.compile(r"\$(?:\{(\w+)\}|(\w+))")


def _go_expand(template: str, m: re.Match) -> str:
    """Go Regexp.Expand semantics for label_replace replacements: $1 /
    ${name} group refs, a missing or out-of-range group expands to ""
    (never an error), no backslash escape processing."""

    def sub(ref: re.Match) -> str:
        name = ref.group(1) or ref.group(2)
        try:
            got = m.group(int(name)) if name.isdigit() else m.group(name)
        except (IndexError, re.error):
            return ""
        return got or ""

    return _GO_REF_RE.sub(sub, template)


def _clock_days(t: np.ndarray) -> np.ndarray:
    safe = np.where(np.isfinite(t), t, 0.0)
    return np.floor(safe).astype("int64").astype("datetime64[s]").astype("datetime64[D]")


def _clock(fn):
    def wrapped(t: np.ndarray) -> np.ndarray:
        with np.errstate(all="ignore"):
            return fn(t).astype(float)

    return wrapped


# prom clock functions (UTC; promql/functions.go funcHour et al.)
_CLOCK_FNS = {
    "minute": _clock(lambda t: np.floor(t / 60) % 60),
    "hour": _clock(lambda t: np.floor(t / 3600) % 24),
    "day_of_week": _clock(lambda t: (np.floor(t / 86400) + 4) % 7),
    "day_of_month": _clock(
        lambda t: (_clock_days(t) - _clock_days(t).astype("datetime64[M]")
                   ).astype(int) + 1
    ),
    "day_of_year": _clock(
        lambda t: (_clock_days(t) - _clock_days(t).astype("datetime64[Y]")
                   ).astype(int) + 1
    ),
    "days_in_month": _clock(
        lambda t: (
            (_clock_days(t).astype("datetime64[M]") + 1).astype("datetime64[D]")
            - _clock_days(t).astype("datetime64[M]").astype("datetime64[D]")
        ).astype(int)
    ),
    "month": _clock(
        lambda t: _clock_days(t).astype("datetime64[M]").astype(int) % 12 + 1
    ),
    "year": _clock(
        lambda t: _clock_days(t).astype("datetime64[Y]").astype(int) + 1970
    ),
}


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))
