"""PromQL parser (executed subset).

Grammar covered: vector selectors with label matchers, range selectors,
offset, number literals, function calls, aggregation operators with
by/without clauses, scalar<->vector binary arithmetic and vector/vector
arithmetic on matching label sets, parentheses.

Reference grammar: promql2influxql (transpiler.go:45) drives Prometheus'
own parser; this is a standalone hand-written equivalent for the engine's
surface.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


class PromParseError(ValueError):
    pass


@dataclass(frozen=True)
class LabelMatcher:
    name: str
    op: str  # = != =~ !~
    value: str


@dataclass
class VectorSelector:
    metric: str = ""
    matchers: list[LabelMatcher] = field(default_factory=list)
    offset_s: float = 0.0


@dataclass
class MatrixSelector:
    vector: VectorSelector = None
    range_s: float = 0.0


@dataclass
class Subquery:
    """expr[range:step] — the inner expression evaluated on an
    absolutely-aligned step grid, consumed like a range vector."""

    expr: object = None
    range_s: float = 0.0
    step_s: float | None = None  # None: engine default resolution
    offset_s: float = 0.0


@dataclass
class NumberLit:
    val: float = 0.0


@dataclass
class StringLit:
    val: str = ""


@dataclass
class FunctionCall:
    name: str = ""
    args: list = field(default_factory=list)


@dataclass
class Aggregation:
    op: str = ""
    expr: object = None
    grouping: list[str] = field(default_factory=list)
    without: bool = False
    param: object = None  # topk/quantile first arg


@dataclass
class VectorMatching:
    """on()/ignoring() + group_left/group_right modifiers.
    Reference: promql2influxql/binary_expr.go:308 (On/MatchKeys/
    MatchCard/IncludeKeys) driving Prometheus' VectorMatching."""

    on: bool = False  # True: on(labels); False: ignoring(labels)
    labels: list[str] = field(default_factory=list)
    card: str = "one-to-one"  # |many-to-one|one-to-many|many-to-many
    include: list[str] = field(default_factory=list)


@dataclass
class BinaryOp:
    op: str = ""
    lhs: object = None
    rhs: object = None
    bool_mod: bool = False
    matching: VectorMatching | None = None


AGG_OPS = {"sum", "avg", "min", "max", "count", "topk", "bottomk", "quantile",
           "stddev", "stdvar", "group", "count_values"}
FUNCTIONS = {
    "rate", "irate", "increase", "delta", "idelta", "changes", "resets",
    "avg_over_time", "min_over_time", "max_over_time", "sum_over_time",
    "count_over_time", "last_over_time", "stddev_over_time",
    "stdvar_over_time", "quantile_over_time", "mad_over_time",
    "present_over_time", "absent_over_time",
    "deriv", "predict_linear", "holt_winters", "double_exponential_smoothing",
    "abs", "ceil", "floor", "round", "exp", "ln", "log2", "log10", "sqrt",
    "sgn", "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "tanh", "asinh", "acosh", "atanh", "deg", "rad", "pi",
    "clamp", "clamp_min", "clamp_max", "scalar", "vector", "timestamp",
    "histogram_quantile", "absent", "time", "minute", "hour",
    "day_of_month", "day_of_week", "day_of_year", "days_in_month",
    "month", "year", "label_replace", "label_join",
    "sort", "sort_desc", "sort_by_label", "sort_by_label_desc",
}

_DUR = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h|d|w|y)")
_DUR_S = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0,
          "w": 604800.0, "y": 31536000.0}


def parse_duration_s(s: str) -> float:
    total = 0.0
    pos = 0
    while pos < len(s):
        m = _DUR.match(s, pos)
        if not m:
            raise PromParseError(f"bad duration {s!r}")
        total += float(m.group(1)) * _DUR_S[m.group(2)]
        pos = m.end()
    return total


class _Lexer:
    _TOKEN = re.compile(
        r"\s*(?:"
        r"(?P<dur>\d+(?:\.\d+)?(?:ms|s|m|h|d|w|y)(?:\d+(?:\.\d+)?(?:ms|s|m|h|d|w|y))*)"
        r"|(?P<num>\d+\.?\d*(?:[eE][+-]?\d+)?)"
        r"|(?P<id>[a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"|(?P<str>\"(?:[^\"\\]|\\.)*\"|'(?:[^'\\]|\\.)*')"
        r"|(?P<op>=~|!~|!=|==|>=|<=|[-+*/%^(){}\[\],=<>])"
        r")"
    )

    def __init__(self, text: str):
        self.text = text
        self.pos = 0
        self.toks: list[tuple[str, str]] = []
        self._tokenize()
        self.i = 0

    def _tokenize(self):
        n = len(self.text)
        pos = 0
        while pos < n:
            if self.text[pos].isspace():
                pos += 1
                continue
            m = self._TOKEN.match(self.text, pos)
            if not m:
                raise PromParseError(f"bad token at {pos}: {self.text[pos:pos+10]!r}")
            if m.group("dur"):
                self.toks.append(("DUR", m.group("dur")))
            elif m.group("num"):
                self.toks.append(("NUM", m.group("num")))
            elif m.group("id"):
                self.toks.append(("ID", m.group("id")))
            elif m.group("str"):
                raw = m.group("str")
                self.toks.append(("STR", _unquote(raw)))
            else:
                self.toks.append(("OP", m.group("op")))
            pos = m.end()

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else ("EOF", "")

    def next(self):
        t = self.peek()
        self.i += 1
        return t


def _unquote(raw: str) -> str:
    body = raw[1:-1]
    return body.replace('\\"', '"').replace("\\'", "'").replace("\\\\", "\\")


_PREC = {"or": 1, "and": 2, "unless": 2, "==": 3, "!=": 3, "<": 3, ">": 3,
         "<=": 3, ">=": 3, "+": 4, "-": 4, "*": 5, "/": 5, "%": 5,
         "atan2": 5, "^": 6}
COMPARISONS = {"==", "!=", "<", ">", "<=", ">="}
SET_OPS = {"and", "or", "unless"}


def parse(text: str):
    lx = _Lexer(text)
    expr = _parse_expr(lx, 1)
    if lx.peek()[0] != "EOF":
        raise PromParseError(f"unexpected trailing token {lx.peek()[1]!r}")
    return expr


def _parse_expr(lx: _Lexer, min_prec: int):
    lhs = _parse_primary(lx)
    while True:
        kind, val = lx.peek()
        op = None
        if kind == "OP" and val in _PREC:
            op = val
        elif kind == "ID" and val in ("and", "or", "unless", "atan2"):
            op = val
        if op is None or _PREC[op] < min_prec:
            return lhs
        lx.next()
        bool_mod, matching = _parse_binop_modifiers(lx, op)
        # ^ is right-associative in PromQL; all others left-associative
        next_min = _PREC[op] if op == "^" else _PREC[op] + 1
        rhs = _parse_expr(lx, next_min)
        lhs = BinaryOp(op, lhs, rhs, bool_mod, matching)


def _parse_binop_modifiers(lx: _Lexer, op: str):
    """[bool] [on(...)|ignoring(...)] [group_left|group_right [(...)]]
    after a binary operator, with Prometheus' validity rules."""
    bool_mod = False
    if lx.peek() == ("ID", "bool"):
        if op not in COMPARISONS:
            raise PromParseError(
                "bool modifier can only be used on comparison operators")
        lx.next()
        bool_mod = True
    matching = None
    if lx.peek() in (("ID", "on"), ("ID", "ignoring")):
        on = lx.next()[1] == "on"
        matching = VectorMatching(
            on, _parse_grouping(lx),
            "many-to-many" if op in SET_OPS else "one-to-one",
        )
        if lx.peek() in (("ID", "group_left"), ("ID", "group_right")):
            which = lx.next()[1]
            if op in SET_OPS:
                raise PromParseError(
                    f"no grouping allowed for {op!r} operation")
            matching.card = ("many-to-one" if which == "group_left"
                             else "one-to-many")
            if lx.peek() == ("OP", "("):
                matching.include = _parse_grouping(lx)
            if on:
                for ln in matching.include:
                    if ln in matching.labels:
                        raise PromParseError(
                            f"label {ln!r} must not occur in ON and "
                            "GROUP clauses at once")
    elif op in SET_OPS:
        matching = VectorMatching(False, [], "many-to-many")
    if lx.peek() in (("ID", "group_left"), ("ID", "group_right")):
        raise PromParseError(
            f"unexpected {lx.peek()[1]!r}: grouping modifiers require "
            "on(...) or ignoring(...) first")
    return bool_mod, matching


def _parse_primary(lx: _Lexer):
    kind, val = lx.peek()
    if kind == "NUM":
        lx.next()
        return NumberLit(float(val))
    if kind == "STR":
        lx.next()
        return StringLit(val)
    if kind == "OP" and val == "-":
        lx.next()
        # unary minus binds looser than ^ in PromQL: -2^2 == -(2^2)
        inner = _parse_expr(lx, _PREC["^"])
        return BinaryOp("*", NumberLit(-1.0), inner)
    if kind == "OP" and val == "(":
        lx.next()
        e = _parse_expr(lx, 1)
        _expect(lx, ")")
        return _maybe_range(lx, e)
    if kind == "OP" and val == "{":
        vs = _parse_selector(lx, "")
        return _maybe_range(lx, vs)
    if kind == "ID":
        lx.next()
        if val in AGG_OPS:
            return _maybe_range(lx, _parse_aggregation(lx, val))
        if lx.peek() == ("OP", "(") and val in FUNCTIONS:
            lx.next()
            args = []
            if lx.peek() != ("OP", ")"):
                args.append(_parse_expr(lx, 1))
                while lx.peek() == ("OP", ","):
                    lx.next()
                    args.append(_parse_expr(lx, 1))
            _expect(lx, ")")
            return _maybe_range(lx, FunctionCall(val, args))
        return _maybe_range(lx, _parse_selector(lx, val))
    raise PromParseError(f"unexpected token {val!r}")


def _parse_selector(lx: _Lexer, metric: str) -> VectorSelector:
    matchers: list[LabelMatcher] = []
    if lx.peek() == ("OP", "{"):
        lx.next()
        while lx.peek() != ("OP", "}"):
            kind, name = lx.next()
            if kind != "ID":
                raise PromParseError(f"expected label name, got {name!r}")
            okind, op = lx.next()
            if okind != "OP" or op not in ("=", "!=", "=~", "!~"):
                raise PromParseError(f"bad matcher op {op!r}")
            skind, sval = lx.next()
            if skind != "STR":
                raise PromParseError("matcher value must be a string")
            matchers.append(LabelMatcher(name, op, sval))
            if lx.peek() == ("OP", ","):
                lx.next()
        _expect(lx, "}")
    vs = VectorSelector(metric, matchers)
    if lx.peek() == ("ID", "offset"):
        lx.next()
        kind, d = lx.next()
        if kind != "DUR":
            raise PromParseError("offset expects a duration")
        vs.offset_s = parse_duration_s(d)
    return vs


def _maybe_range(lx: _Lexer, expr):
    if lx.peek() == ("OP", "["):
        lx.next()
        kind, d = lx.next()
        if kind != "DUR":
            raise PromParseError("range selector expects a duration")
        nk, nv = lx.peek()
        if nk == "ID" and nv.startswith(":"):
            # subquery: expr[range:step] (the lexer folds ':1m' into one
            # ID token because recording-rule names may contain colons)
            lx.next()
            step_txt = nv[1:]
            if not step_txt and lx.peek()[0] == "DUR":  # '[5m : 1m]'
                step_txt = lx.next()[1]
            step_s = parse_duration_s(step_txt) if step_txt else None
            _expect(lx, "]")
            sq = Subquery(expr, parse_duration_s(d), step_s)
            sq.offset_s = _maybe_offset(lx)
            return _maybe_range(lx, sq)  # nested subqueries: sq[r:s]
        _expect(lx, "]")
        if not isinstance(expr, VectorSelector):
            raise PromParseError(
                "range selector requires a vector selector "
                "(use expr[range:step] for subqueries)"
            )
        ms = MatrixSelector(expr, parse_duration_s(d))
        expr.offset_s = _maybe_offset(lx) or expr.offset_s
        return ms
    return expr


def _maybe_offset(lx: _Lexer) -> float:
    if lx.peek() == ("ID", "offset"):
        lx.next()
        k2, d2 = lx.next()
        if k2 != "DUR":
            raise PromParseError("offset expects a duration")
        return parse_duration_s(d2)
    return 0.0


def _parse_aggregation(lx: _Lexer, op: str) -> Aggregation:
    agg = Aggregation(op)
    # by/without before parens
    if lx.peek() in (("ID", "by"), ("ID", "without")):
        agg.without = lx.next()[1] == "without"
        agg.grouping = _parse_grouping(lx)
    _expect(lx, "(")
    first = _parse_expr(lx, 1)
    if lx.peek() == ("OP", ","):
        lx.next()
        agg.param = first
        agg.expr = _parse_expr(lx, 1)
    else:
        agg.expr = first
    _expect(lx, ")")
    if lx.peek() in (("ID", "by"), ("ID", "without")):
        agg.without = lx.next()[1] == "without"
        agg.grouping = _parse_grouping(lx)
    return agg


def _parse_grouping(lx: _Lexer) -> list[str]:
    _expect(lx, "(")
    names = []
    while lx.peek() != ("OP", ")"):
        kind, v = lx.next()
        if kind != "ID":
            raise PromParseError(f"expected label, got {v!r}")
        names.append(v)
        if lx.peek() == ("OP", ","):
            lx.next()
    _expect(lx, ")")
    return names


def _expect(lx: _Lexer, op: str):
    kind, val = lx.next()
    if kind != "OP" or val != op:
        raise PromParseError(f"expected {op!r}, got {val!r}")
