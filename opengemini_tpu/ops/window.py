"""CPU-side (numpy) window/segment-id derivation.

Timestamps are int64 nanoseconds and never go to the device raw: window
indices and group ids are derived here exactly in int64, and only compact
int32 segment ids plus int32 *relative* times (ms) are transferred. This
keeps device arrays narrow and avoids int64 on TPU (where x64 is disabled).

Replaces the reference's per-row `getIntervalIndex`
(engine/aggregate_cursor.go:343) with a vectorized bucketize.
"""

from __future__ import annotations

import numpy as np

MIN_TIME = -(2**63) + 1
MAX_TIME = 2**63 - 1


def window_start(t_ns: np.ndarray | int, every_ns: int, offset_ns: int = 0):
    """InfluxDB GROUP BY time() bucket start: epoch-aligned floor.

    wstart = floor((t - offset) / every) * every + offset  (floor division,
    exact for negative times too — numpy // is floor division on int64).
    """
    return (t_ns - offset_ns) // every_ns * every_ns + offset_ns


def window_index(
    times_ns: np.ndarray,
    range_start_ns: int,
    every_ns: int,
    offset_ns: int = 0,
) -> tuple[np.ndarray, int]:
    """Map each timestamp to a window ordinal relative to the (aligned)
    range start. Returns (int32 indices, aligned_start_ns).

    Callers mask rows outside [aligned_start, range_end) themselves; indices
    for such rows may be negative or past the window count.
    """
    aligned = int(window_start(range_start_ns, every_ns, offset_ns))
    idx = (times_ns - offset_ns) // every_ns - (aligned - offset_ns) // every_ns
    return idx.astype(np.int32), aligned


def num_windows(range_start_ns: int, range_end_ns: int, every_ns: int, offset_ns: int = 0) -> int:
    """Number of buckets covering [range_start, range_end)."""
    aligned = int(window_start(range_start_ns, every_ns, offset_ns))
    if range_end_ns <= aligned:
        return 0
    return int((range_end_ns - 1 - offset_ns) // every_ns - (aligned - offset_ns) // every_ns) + 1


def tile_index(t_ms: np.ndarray, anchor_ms: int, g_ms: int) -> np.ndarray:
    """Left-OPEN right-CLOSED tile ordinal: tile i covers
    (anchor + i*g, anchor + (i+1)*g].

    The PromQL tiled range-vector engine's bucketize (ops/prom.py): prom
    windows are (s, e], so its tiles close on the right — the mirror of
    window_index's [start, end) InfluxQL buckets, same exact int64
    floor-division idiom, no searchsorted."""
    return (np.asarray(t_ms, np.int64) - anchor_ms - 1) // g_ms


def relative_ms(times_ns: np.ndarray, base_ns: int) -> np.ndarray:
    """int32 milliseconds relative to base — the device-side time column.

    ~24 days of range fit in int32 ms; shard time ranges (default 7d groups,
    reference lib/util/lifted/influx/meta shard-group durations) stay within
    this. Used only for first/last tie-breaking and prom rate windows.
    """
    rel = (times_ns - base_ns) // 1_000_000
    return rel.astype(np.int32)


def dictionary_encode(keys: list) -> tuple[np.ndarray, list]:
    """Dictionary-encode arbitrary hashable group keys to int32 codes.

    Group (tag-value) keys are encoded on CPU; the device only ever sees
    int32 codes (SURVEY.md §7 'String/tag columns').
    Returns (codes int32, unique keys in first-appearance order).
    """
    mapping: dict = {}
    codes = np.empty(len(keys), dtype=np.int32)
    uniques: list = []
    for i, k in enumerate(keys):
        code = mapping.get(k)
        if code is None:
            code = len(uniques)
            mapping[k] = code
            uniques.append(k)
        codes[i] = code
    return codes, uniques


def pad_to(n: int, multiple: int = 1024) -> int:
    """Pad row counts to coarse buckets so jit caches stay small
    (the reference's plan-template cache idea — engine/executor/select.go:121 —
    applied to array shapes)."""
    if n <= multiple:
        m = 8
        while m < n:
            m *= 2
        return max(m, 8)
    return ((n + multiple - 1) // multiple) * multiple
