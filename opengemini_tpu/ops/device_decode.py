"""Device-side decode of TSF device-profile blocks, fused into the grid
aggregation data path.

Cold scans used to pay CPU decode (zlib + delta reconstruction) and then
a FULL-WIDTH host->device transfer of the padded grid — 8-byte values
plus a mask byte for every padded cell.  This module moves the decode
onto the accelerator for the block shapes that allow it ("GPU
Acceleration of SQL Analytics on Compressed Data", arXiv:2506.10092;
"Data Path Fusion", arXiv:2605.10511): the writer's device profile
(storage/encoding.py, OGT_DEVICE_PROFILE=1) keeps int/float payloads in
a raw envelope, the cold scan ships those encoded bytes (plus int32
scatter slots and packed mask bits) to the device, and ONE jit program
decodes, scatters into the (S_pad, k, W_pad) grid, and runs the basic
window reduce — compressed-bytes -> decode -> group -> reduce with no
decoded column ever materializing on the host.

Decodable block kinds (encoding.DeviceBlock):

  const   first + step * iota — pure header, zero payload bytes
  delta   frame-of-reference deltas at fixed byte width: widen, +step,
          int64 cumsum, +first (exactly the host decode_ints arithmetic,
          so results are bit-identical)
  raw64   little-endian float64 values: an 8-byte bitcast

Everything else (zlib envelopes, gorilla, varint, bool/string blocks)
keeps the host decode — EncodedColumn.values decodes lazily and the
existing path runs unchanged.  `OGT_DEVICE_DECODE=0` disables this
module entirely (bit-identical host path); x64 is required for
bit-identity (int64 cumsum, f64 bitcast), so non-x64 backends answer
inactive and fall back silently.

The widen step routes through a Pallas kernel
(ops/pallas_segment.widen_packed) for width-1/2 blocks where the
backend supports Pallas (devobs.backend_capabilities probe + the
use_pallas routing); the jnp bitcast path serves everywhere else.

Program caching: one jitted program per static geometry (block
signature, row count, grid shape, dtype, mask presence), registered
with the devobs compile inventory — a warm loop repeating the same scan
reuses the program, so the recompile tripwire stays clean.

Counters (module `device`, /metrics `ogt_device_decode_*`):
decode_blocks_total, decode_payload_bytes_total, decode_rows_total,
decode_fallbacks_total.  Transfers land on the `device-decode` site of
the `ogt_device_h2d_*` histograms via devobs.note_transfer.
"""

from __future__ import annotations

import functools
import os
import time

import numpy as np

from opengemini_tpu.storage import encoding
from opengemini_tpu.utils import devobs
from opengemini_tpu.utils.stats import GLOBAL as _STATS

# past this many blocks the unrolled decode program's compile time would
# dominate what it saves; the host pool decode handles the long tail
_MAX_BLOCKS = 256

_XFER_SITE = "device-decode"


def enabled() -> bool:
    """The OGT_DEVICE_DECODE knob alone (README "Decode on device")."""
    return os.environ.get("OGT_DEVICE_DECODE", "1") not in ("", "0")


@functools.lru_cache(maxsize=1)
def _backend_ok() -> bool:
    """One-time probe: a live jax backend."""
    try:
        import jax

        jax.devices()
        return True
    except Exception:  # noqa: BLE001 — no backend = host decode
        return False


def _x64_on() -> bool:
    """Read the x64 flag FRESH every time — it is runtime-togglable,
    and a stale cached True would run the int64 cumsum / f64 bitcast in
    32-bit and silently diverge from the host path."""
    try:
        import jax

        return bool(jax.config.jax_enable_x64)
    except Exception:  # noqa: BLE001
        return False


def active() -> bool:
    """Device decode usable in this process (knob + x64 + backend).
    x64 is what makes the int64 cumsum and f64 bitcast bit-identical to
    the host decoders."""
    return enabled() and _x64_on() and _backend_ok()


def classify(blocks) -> list | None:
    """DeviceBlock views of every raw block buffer, or None when any
    block (or the block count) is not device-decodable."""
    if len(blocks) > _MAX_BLOCKS:
        return None
    out = []
    for buf in blocks:
        db = encoding.device_block(buf)
        if db is None:
            return None
        out.append(db)
    return out


def _pack_blocks(dbs):
    """(sig, payload, scalars) of classified DeviceBlocks — THE block
    assembly every program entry point shares, so the jit cache key
    (sig) can never desynchronize from the shipped bytes."""
    sig = tuple((b.kind, b.n, b.width) for b in dbs)
    payload = np.frombuffer(
        b"".join(bytes(b.payload) for b in dbs), np.uint8)
    scalars = np.array([[b.first, b.step] for b in dbs],
                       np.int64).reshape(len(dbs), 2)
    return sig, payload, scalars


def note_fallback(n: int = 1) -> None:
    """Count an eligible-looking encoded scan that ended up on the host
    decode path anyway (ineligible blocks, mesh configured, knob off at
    freeze time) — the triage counter for "why didn't H2D drop"."""
    _STATS.incr("device", "decode_fallbacks_total", n)


class GridPlan:
    """Host-side inputs + static geometry of one fused decode->scatter->
    reduce program invocation.  The scatter slots travel either as an
    explicit int32 `flat` array (4 bytes/row) or — when every series run
    is constant-stride and the window arithmetic verifies on the host —
    as `runmeta` (rel0, stride, start_row) int64 triples plus one phase
    scalar (~24 bytes/RUN), reconstructed on device."""

    __slots__ = ("geom", "payload", "scalars", "viewruns", "flat",
                 "runmeta", "consts", "maskbits", "n")

    def __init__(self, geom, payload, scalars, viewruns, flat, runmeta,
                 consts, maskbits, n):
        self.geom = geom
        self.payload = payload
        self.scalars = scalars
        self.viewruns = viewruns
        self.flat = flat
        self.runmeta = runmeta
        self.consts = consts
        self.maskbits = maskbits
        self.n = n

    def transfer_nbytes(self) -> int:
        nb = int(self.payload.nbytes) + int(self.scalars.nbytes)
        for a in (self.viewruns, self.flat, self.runmeta, self.consts,
                  self.maskbits):
            if a is not None:
                nb += int(a.nbytes)
        return nb


def _affine_scatter(flat, rel, starts, every_ns, dt, k, w_pad):
    """(runmeta, consts) when the scatter slots are reconstructible
    on device from per-run scalars, else None.

    Requirements, each VERIFIED on the host against the actual arrays
    (vectorized int compares — far cheaper than the transfer they save):
    every run's times are affine (rel0 + j*stride), and the window
    ordinal follows one global phase: w == (rel - woff) // every.  Then
    the device recomputes flat = (rid*k + (rel - w*every)//dt)*w_pad + w
    exactly — any offset/edge subtlety just fails verification and the
    plan ships the explicit flat array instead."""
    n = len(rel)
    runs = len(starts)
    if n == 0 or runs == 0 or every_ns is None or not every_ns or not dt:
        return None
    lens = np.diff(np.append(starts, n))
    rel0 = rel[starts]
    stride = np.zeros(runs, np.int64)
    multi = lens > 1
    if multi.any():
        d = np.diff(rel)
        stride[multi] = d[starts[multi]]
    rid = np.repeat(np.arange(runs, dtype=np.int64), lens)
    j = np.arange(n, dtype=np.int64) - np.repeat(starts, lens)
    if not np.array_equal(rel0[rid] + j * stride[rid], rel):
        return None  # gaps / irregular spacing inside a run
    w = flat % w_pad
    # window phase: any valid woff satisfies woff + w*every <= rel <
    # woff + (w+1)*every for EVERY row; the supremum of that interval,
    # min(rel - w*every), is valid whenever any woff is — and the full
    # verification below rejects the rest
    woff = int((rel - w * every_ns).min())
    if not np.array_equal((rel - woff) // every_ns, w):
        return None
    r = (rel - w * every_ns) // dt
    if not np.array_equal((rid * k + r) * w_pad + w, flat):
        return None
    # (rel0, stride, start_row) per run — all DYNAMIC program inputs
    # (~24 bytes/run): baking row offsets in as program constants would
    # make every distinct series count a fresh multi-second compile
    runmeta = np.stack([rel0, stride, starts.astype(np.int64)], axis=1)
    return runmeta, np.array([woff], np.int64)


def combine_views(views):
    """Flatten per-column (blocks, segments, n_full) views into one
    block list plus the absolute row runs of the combined view over the
    combined decode (adjacent runs merged; None = identity).  Returns
    (blocks, runs|None, n_view, n_full)."""
    blocks: list = []
    runs = []
    base = 0
    n_view = 0
    for vb, segs, n_full in views:
        blocks.extend(vb)
        for a, b in np.asarray(segs, np.int64):
            a, b = int(a) + base, int(b) + base
            n_view += b - a
            if runs and runs[-1][1] == a:
                runs[-1][1] = b  # adjacent runs merge
            else:
                runs.append([a, b])
        base += int(n_full)
    if len(runs) == 1 and runs[0] == [0, base]:
        return blocks, None, n_view, base  # identity view
    return blocks, np.asarray(runs, np.int64), n_view, base


def build_grid_plan(views, flat, mask, shape, dtype, rel=None,
                    starts=None, every_ns=None, dt=None) -> GridPlan | None:
    """Plan the fused program for one frozen grid: `views` are the
    still-encoded value columns' (blocks, segments, n_full) triples in
    row order, `flat` the host-computed scatter slots (injective,
    < prod(shape)), `mask` the row validity.  `rel`/`starts`/
    `every_ns`/`dt` (the freeze's run layout) enable the per-run scatter
    reconstruction.  Returns None when the blocks are not
    device-decodable or the transfer would not beat the decoded grid —
    the caller host-decodes exactly as before."""
    if not active():
        return None
    blocks, viewruns, n_view, n_full = combine_views(views)
    dbs = classify(blocks)
    if dbs is None:
        note_fallback()
        return None
    if sum(b.n for b in dbs) != n_full or n_view != len(flat):
        note_fallback()
        return None  # defensive: blocks must cover the view exactly
    n = n_view
    sig, payload, scalars = _pack_blocks(dbs)
    maskbits = None
    if mask is not None and not mask.all():
        maskbits = np.packbits(np.asarray(mask, np.bool_))
    affine = None
    if rel is not None and starts is not None:
        affine = _affine_scatter(flat, rel, np.asarray(starts),
                                 every_ns, dt, shape[1], shape[2])
    if affine is not None:
        runmeta, consts = affine
        flat32 = None
        nruns_affine = len(runmeta)
    else:
        runmeta, consts, nruns_affine = None, None, None
        flat32 = np.ascontiguousarray(flat, np.int32)
    geom = (sig, n, tuple(shape), np.dtype(dtype).str,
            maskbits is not None, nruns_affine,
            every_ns if nruns_affine else None,
            dt if nruns_affine else None,
            None if viewruns is None else len(viewruns))
    plan = GridPlan(geom, payload, scalars, viewruns, flat32, runmeta,
                    consts, maskbits, n)
    # cost gate: the fused path must genuinely shrink the transfer below
    # the decoded grid it replaces (values + mask bytes per padded cell)
    if plan.transfer_nbytes() >= int(np.prod(shape)) * 9:
        note_fallback()
        return None
    return plan


def run_grid_plan(plan: GridPlan):
    """Execute the fused program: one H2D of the encoded inputs (site
    `device-decode`), then decode+scatter+reduce in a single jit program.
    Returns ({count,sum,mean,min,max} device arrays, vt, mt, flat) —
    vt/mt are the decoded grid buffers, ready for colcache device-tier
    retention and the ssd/selector kernels; flat is the device-resident
    scatter-slot vector (imat_from_flat builds the selector index grid
    from it without a host round-trip)."""
    import jax

    t0 = time.perf_counter_ns()
    inputs = [plan.payload, plan.scalars]
    if plan.viewruns is not None:
        inputs.append(plan.viewruns)
    if plan.flat is not None:
        inputs.append(plan.flat)
    else:
        inputs.extend((plan.runmeta, plan.consts))
    if plan.maskbits is not None:
        inputs.append(plan.maskbits)
    dev = [jax.device_put(a) for a in inputs]
    devobs.note_transfer("h2d", _XFER_SITE, plan.transfer_nbytes(),
                         (time.perf_counter_ns() - t0) / 1e9)
    _STATS.incr("device", "decode_blocks_total", len(plan.geom[0]))
    _STATS.incr("device", "decode_payload_bytes_total",
                int(plan.payload.nbytes))
    _STATS.incr("device", "decode_rows_total", plan.n)
    fn = _grid_program(plan.geom)
    t = devobs.t0()
    stats, vt, mt, flat = fn(*dev)
    if t:
        devobs.note_exec(t)
    return stats, vt, mt, flat


def imat_from_flat(flat_dev, shape):
    """Selector index grid (sample ordinal per grid slot) from the
    device-resident scatter slots a fused decode left behind — replaces
    the host imat build + its full-grid transfer on the cold selector
    path."""
    return _imat_program(int(flat_dev.shape[0]), tuple(shape))(flat_dev)


@functools.lru_cache(maxsize=256)
def _imat_program(n: int, shape):
    import jax
    import jax.numpy as jnp

    devobs.note_compile("grid_decode_imat", (n, shape))
    cells = int(np.prod(shape))

    def run(flat):
        return jnp.zeros(cells, jnp.int32).at[flat].set(
            jnp.arange(n, dtype=jnp.int32),
            unique_indices=True).reshape(shape)

    return jax.jit(run)


def decode_to_device(blocks, dtype=None):
    """Standalone device decode of raw block buffers -> one device value
    vector (int64/float64, or `dtype` when given).  The non-fused entry
    point: tests assert bit-identity against the host decoders with it,
    and column-shaped consumers can device_put encoded bytes directly."""
    import jax

    dbs = classify(blocks)
    if dbs is None:
        raise ValueError("blocks are not device-decodable")
    out_dtype = np.dtype(dtype) if dtype is not None else (
        np.dtype(np.float64) if any(b.kind == "raw64" for b in dbs)
        else np.dtype(np.int64))
    sig, payload, scalars = _pack_blocks(dbs)
    t0 = time.perf_counter_ns()
    payload_d, scalars_d = jax.device_put(payload), jax.device_put(scalars)
    devobs.note_transfer(
        "h2d", _XFER_SITE, int(payload.nbytes) + int(scalars.nbytes),
        (time.perf_counter_ns() - t0) / 1e9)
    return _decode_program(sig, out_dtype.str)(payload_d, scalars_d)


def materialize_enc(enc) -> np.ndarray:
    """Host materialization of a (ftype, blocks, segments, slices)
    encoded-column descriptor into the concatenated f64 sample vector —
    the bit-identical fallback for consumers that need host values
    (dense prom kernels, mesh sharding)."""
    ftype, blocks, segments, slices = enc
    d = encoding.decode_value_blocks(ftype, list(blocks)).astype(
        np.float64)
    if segments is not None:
        d = (np.concatenate([d[a:b] for a, b in segments])
             if len(segments) else d[:0])
    if not slices:
        return np.empty(0, np.float64)
    if len(slices) == 1:
        lo, hi = slices[0]
        return d[lo:hi]
    return np.concatenate([d[lo:hi] for lo, hi in slices])


def decode_rows_matrix(enc, shape, dtype):
    """Decode raw blocks ON device and lay the per-series sample slices
    into a zero-padded (S, N) row matrix — the PromQL tiled kernels'
    value matrix without the padded-f64 H2D (the transfer is the raw
    payload + two ints per series).  `enc` is the (ftype, blocks,
    segments, slices) descriptor (slices in VIEW coordinates).  Returns
    the device matrix, or None when the blocks are not device-decodable
    (caller host-materializes, bit-identically)."""
    import jax

    if not active():
        return None
    ftype, blocks, segments, slices = enc
    dbs = classify(list(blocks))
    if dbs is None:
        note_fallback()
        return None
    n_full = sum(b.n for b in dbs)
    if segments is None:
        viewruns, n_view = None, n_full
    else:
        segments = np.asarray(segments, np.int64).reshape(-1, 2)
        viewruns = segments
        n_view = int((segments[:, 1] - segments[:, 0]).sum())
        if len(segments) and (segments[:, 0] < 0).any() \
                or len(segments) and (segments[:, 1] > n_full).any():
            note_fallback()
            return None
    S, N = shape
    lo = np.array([s[0] for s in slices], np.int64)
    ln = np.array([s[1] - s[0] for s in slices], np.int64)
    if len(slices) != S or (ln > N).any() or (lo < 0).any() \
            or (lo + ln > n_view).any():
        note_fallback()
        return None
    sig, payload, scalars = _pack_blocks(dbs)
    host_in = [payload, scalars, lo, ln]
    if viewruns is not None:
        host_in.append(viewruns)
    # cost gate: the encoded transfer must beat the padded value matrix
    # it replaces (whole-block payloads can exceed a heavily trimmed
    # view — raw64 floats have no width compression to amortize it)
    if sum(int(a.nbytes) for a in host_in) >= \
            S * N * np.dtype(dtype).itemsize:
        note_fallback()
        return None
    t0 = time.perf_counter_ns()
    dev = [jax.device_put(a) for a in host_in]
    devobs.note_transfer(
        "h2d", _XFER_SITE, sum(int(a.nbytes) for a in host_in),
        (time.perf_counter_ns() - t0) / 1e9)
    _STATS.incr("device", "decode_blocks_total", len(sig))
    _STATS.incr("device", "decode_payload_bytes_total",
                int(payload.nbytes))
    _STATS.incr("device", "decode_rows_total", n_view)
    fn = _rows_program(sig, n_view, (S, N), np.dtype(dtype).str,
                       None if viewruns is None else len(viewruns))
    t = devobs.t0()
    out = fn(*dev)
    if t:
        devobs.note_exec(t)
    return out


@functools.lru_cache(maxsize=256)
def _rows_program(sig, n: int, shape, dtype_str, nruns):
    import jax
    import jax.numpy as jnp

    devobs.note_compile("prom_decode_rows", (len(sig), n, shape))
    S, N = shape
    out_dt = jnp.dtype(dtype_str)
    decode = _decode_expr(sig, dtype_str)

    def run(payload, scalars, lo, ln, viewruns=None):
        if n == 0:
            return jnp.zeros((S, N), out_dt)
        vals = decode(payload, scalars)
        if nruns is not None:
            vals = _view_gather(vals, viewruns, n)
        col = jnp.arange(N, dtype=jnp.int64)[None, :]
        idx = jnp.clip(lo[:, None] + col, 0, n - 1)
        m = col < ln[:, None]
        return jnp.where(m, vals[idx], jnp.zeros((), out_dt))

    return jax.jit(run)


# -- jit program construction -------------------------------------------------


def _view_gather(vals_full, viewruns, n_view: int):
    """Gather a column VIEW (absolute [lo, hi) row runs) out of the
    fully-decoded block concatenation, on device.  `viewruns` is the
    dynamic (k, 2) run array; `n_view` is static."""
    import jax.numpy as jnp

    run_len = viewruns[:, 1] - viewruns[:, 0]
    ends = jnp.cumsum(run_len)
    pos = jnp.arange(n_view, dtype=jnp.int64)
    rid = jnp.searchsorted(ends, pos, side="right")
    start_out = ends - run_len
    return vals_full[viewruns[rid, 0] + pos - start_out[rid]]


def _widen(raw, width: int, cnt: int):
    """(cnt*width,) LE bytes -> (cnt,) int64, matching the host
    frombuffer(...).astype(int64) exactly (zero-extend below 8 bytes,
    bit-reinterpretation at 8).  Width-1/2 blocks route through the
    Pallas widen kernel where the backend supports it."""
    import jax
    import jax.numpy as jnp

    if width in (1, 2) and _pallas_widen_ok():
        from opengemini_tpu.ops import pallas_segment as ps

        return ps.widen_packed(raw, width, cnt).astype(jnp.int64)
    if width == 1:
        return raw.astype(jnp.int64)
    if width == 8:
        # bitcast, not convert: uint64 values >= 2^63 must wrap to
        # negative int64 exactly like numpy's astype
        return jax.lax.bitcast_convert_type(
            raw.reshape(cnt, 8), jnp.int64)
    dt = {2: jnp.uint16, 4: jnp.uint32}[width]
    return jax.lax.bitcast_convert_type(
        raw.reshape(cnt, width), dt).astype(jnp.int64)


def _pallas_widen_ok() -> bool:
    from opengemini_tpu.ops import pallas_segment as ps

    return ps.use_pallas() and devobs.pallas_supported()[0]


def _decode_expr(sig, dtype_str):
    """The unrolled per-block decode, shared by the standalone and fused
    programs.  Returns a traced fn (payload, scalars) -> (n,) values in
    `dtype_str`.  Offsets are static (they come from the signature), so
    every slice lowers to a static-slice."""
    import jax
    import jax.numpy as jnp

    out_dt = jnp.dtype(dtype_str)

    def decode(payload, scalars):
        pieces = []
        off = 0
        for i, (kind, bn, width) in enumerate(sig):
            if bn == 0:
                continue
            first = scalars[i, 0]
            step = scalars[i, 1]
            if kind == "const":
                piece = first + step * jnp.arange(bn, dtype=jnp.int64)
            elif kind == "delta":
                m = (bn - 1) * width
                raw = jax.lax.slice(payload, (off,), (off + m,))
                off += m
                d = _widen(raw, width, bn - 1) + step
                piece = jnp.concatenate(
                    [first[None], first + jnp.cumsum(d)])
            else:  # raw64
                m = 8 * bn
                raw = jax.lax.slice(payload, (off,), (off + m,))
                off += m
                piece = jax.lax.bitcast_convert_type(
                    raw.reshape(bn, 8), jnp.float64)
            pieces.append(piece.astype(out_dt))
        if not pieces:
            return jnp.zeros((0,), out_dt)
        return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)

    return decode


@functools.lru_cache(maxsize=256)
def _decode_program(sig, dtype_str):
    import jax

    devobs.note_compile("device_decode",
                        (len(sig), sum(b[1] for b in sig), dtype_str))
    return jax.jit(_decode_expr(sig, dtype_str))


@functools.lru_cache(maxsize=256)
def _grid_program(geom):
    """One fused program per static geometry: decode the blocks, scatter
    values+mask into the padded grid, and reduce the basic window stats
    — the compressed-bytes->decode->group->reduce pipeline of the
    data-path-fusion literature as a single XLA program."""
    import jax
    import jax.numpy as jnp

    (sig, n, shape, dtype_str, has_mask, nruns_affine, every_ns, dt,
     nruns) = geom
    devobs.note_compile("grid_decode_fused",
                        (len(sig), n, shape, dtype_str,
                         nruns_affine is not None))
    out_dt = jnp.dtype(dtype_str)
    cells = int(np.prod(shape))
    k, w_pad = shape[1], shape[2]
    decode = _decode_expr(sig, dtype_str)

    def scatter_slots(args):
        if nruns_affine is None:
            return args[0], args[1:]  # explicit flat
        # runmeta rows: (rel0, stride, start_row) — all dynamic, so the
        # program is free of run-count-sized constants
        runmeta, consts = args[0], args[1]
        starts_c = runmeta[:, 2]
        ar = jnp.arange(n, dtype=jnp.int64)
        rid = jnp.searchsorted(starts_c, ar, side="right") - 1
        j = ar - starts_c[rid]
        rel = runmeta[:, 0][rid] + j * runmeta[:, 1][rid]
        w = (rel - consts[0]) // every_ns
        r = (rel - w * every_ns) // dt
        return ((rid * k + r) * w_pad + w).astype(jnp.int32), args[2:]

    def run(payload, scalars, *rest):
        from opengemini_tpu.ops import segment as seg

        vals = decode(payload, scalars)
        if nruns is not None:
            vals = _view_gather(vals, rest[0], n)
            rest = rest[1:]
        flat, rest2 = scatter_slots(rest)
        vt = jnp.zeros(cells, out_dt).at[flat].set(
            vals, unique_indices=True).reshape(shape)
        if has_mask:
            shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
            bits = (rest2[0][:, None] >> shifts) & jnp.uint8(1)
            mrow = bits.reshape(-1)[:n].astype(bool)
        else:
            mrow = jnp.ones((n,), bool)
        mt = jnp.zeros(cells, bool).at[flat].set(
            mrow, unique_indices=True).reshape(shape)
        stats = seg.grid_window_agg_t(vt, mt)
        return stats, vt, mt, flat

    return jax.jit(run)
