"""Device-side decode of TSF device-profile blocks, fused into the grid
aggregation data path.

Cold scans used to pay CPU decode (zlib + delta reconstruction) and then
a FULL-WIDTH host->device transfer of the padded grid — 8-byte values
plus a mask byte for every padded cell.  This module moves the decode
onto the accelerator for the block shapes that allow it ("GPU
Acceleration of SQL Analytics on Compressed Data", arXiv:2506.10092;
"Data Path Fusion", arXiv:2605.10511): the writer's device profile
(storage/encoding.py, OGT_DEVICE_PROFILE=1) keeps int/float payloads in
a raw envelope, the cold scan ships those encoded bytes (plus int32
scatter slots and packed mask bits) to the device, and ONE jit program
decodes, scatters into the (S_pad, k, W_pad) grid, and runs the basic
window reduce — compressed-bytes -> decode -> group -> reduce with no
decoded column ever materializing on the host.

Decodable block kinds (encoding.DeviceBlock):

  const    first + step * iota — pure header, zero payload bytes
  delta    frame-of-reference deltas at fixed byte width: widen, +step,
           int64 cumsum, +first (exactly the host decode_ints
           arithmetic, so results are bit-identical)
  raw64    little-endian float64 values: an 8-byte bitcast
  gorilla  XOR-compressed float64: a host structural scan walks the
           control bits once per block (cached) and emits per-value
           (bitpos, mbits, shift) aux vectors; the device unpacks the
           payload to bits (Pallas unpack_bits where probed, jnp
           shift/mask fallback), gathers each value's meaningful-bit
           window, and reconstructs with a parallel XOR prefix scan —
           bit-identical to the host decoder including NaN/±0.0
  varint   delta+zigzag LEB128 int64: fully data-parallel — terminator
           bits mark value ids, a segmented shift/or rebuilds each
           varint, zigzag + wrapping int64 cumsum match the host's
           mod-2^64 arithmetic exactly
  strdict  dictionary-coded strings: the min-width index array decodes
           on device (widen); the uniq table stays host-side for label
           work (encoding.DeviceBlock.table)

Everything else (zlib envelopes, bool/plain-string blocks) keeps the
host decode — EncodedColumn.values decodes lazily and the existing path
runs unchanged.  `OGT_DEVICE_DECODE=0` disables this module entirely
(bit-identical host path); `OGT_DEVICE_DECODE_CODECS` restricts the
device family to a comma list of the kinds above (default: all); x64 is
required for bit-identity (int64 cumsum, f64 bitcast), so non-x64
backends answer inactive and fall back silently.

The widen and bit-unpack steps route through Pallas kernels
(ops/pallas_segment.widen_packed / unpack_bits) where the backend
supports Pallas (devobs.backend_capabilities probe + the use_pallas
routing); the jnp bitcast/shift paths serve everywhere else.

Mesh sharding: under a configured device mesh, build_mesh_grid_plan
splits one grid plan into per-output-row-shard sub-plans (series runs
never straddle a shard boundary because the scatter row ids are
non-decreasing), ships each shard's encoded bytes to its own device,
runs the same fused per-shard programs, and assembles vt/mt/stats as
NamedSharding global arrays partitioned on the row axis — zero
collectives, and the sharded colcache device tier retains the result
for warm repeats.

Program caching: one jitted program per static geometry (block
signature, row count, grid shape, dtype, mask presence), registered
with the devobs compile inventory — a warm loop repeating the same scan
reuses the program, so the recompile tripwire stays clean.

Counters (module `device`, /metrics `ogt_device_decode_*`):
decode_blocks_total, decode_payload_bytes_total, decode_rows_total,
decode_fallbacks_total, plus the per-codec split
decode_blocks_<codec>_total / decode_payload_bytes_<codec>_total for
codec in const/delta/raw64/gorilla/varint/strdict — /debug/device shows
which codecs actually ship encoded.  Transfers land on the
`device-decode` site of the `ogt_device_h2d_*` histograms via
devobs.note_transfer; mesh-sharded transfers carry a `mesh="on"` label
on the same site.
"""

from __future__ import annotations

import functools
import os
import struct
import time

import numpy as np

from opengemini_tpu.query import offload
from opengemini_tpu.storage import encoding
from opengemini_tpu.utils import devobs
from opengemini_tpu.utils.stats import GLOBAL as _STATS

# past this many blocks the unrolled decode program's compile time would
# dominate what it saves; the host pool decode handles the long tail
_MAX_BLOCKS = 256

_XFER_SITE = "device-decode"


def enabled() -> bool:
    """The OGT_DEVICE_DECODE knob alone (README "Decode on device")."""
    return os.environ.get("OGT_DEVICE_DECODE", "1") not in ("", "0")


_ALL_CODECS = ("const", "delta", "raw64", "gorilla", "varint", "strdict")


def codecs_enabled() -> frozenset:
    """The device codec family (OGT_DEVICE_DECODE_CODECS, README "Decode
    on device"): a comma list of block kinds allowed to decode on the
    accelerator; unset/empty means all of them.  Read fresh every plan —
    it is a triage knob (pin a suspect codec to the host path live)."""
    raw = os.environ.get("OGT_DEVICE_DECODE_CODECS", "")
    if not raw.strip():
        return frozenset(_ALL_CODECS)
    return frozenset(t.strip().lower() for t in raw.split(",") if t.strip())


@functools.lru_cache(maxsize=1)
def _backend_ok() -> bool:
    """One-time probe: a live jax backend."""
    try:
        import jax

        jax.devices()
        return True
    except Exception:  # noqa: BLE001 — no backend = host decode
        return False


def _x64_on() -> bool:
    """Read the x64 flag FRESH every time — it is runtime-togglable,
    and a stale cached True would run the int64 cumsum / f64 bitcast in
    32-bit and silently diverge from the host path."""
    try:
        import jax

        return bool(jax.config.jax_enable_x64)
    except Exception:  # noqa: BLE001
        return False


def active() -> bool:
    """Device decode usable in this process (knob + x64 + backend).
    x64 is what makes the int64 cumsum and f64 bitcast bit-identical to
    the host decoders."""
    return enabled() and _x64_on() and _backend_ok()


@functools.lru_cache(maxsize=1024)
def _gorilla_scan(payload: bytes, n: int):
    """Host structural scan of one gorilla XOR stream: the control bits
    are inherently sequential, so the host walks them ONCE per block
    (cached on the payload bytes the EncodedColumn retains anyway) and
    emits the per-value aux vectors the data-parallel device decode
    needs — bitpos (where each value's meaningful-bit window starts),
    mbits (its length; 0 marks a repeat), shift (its trailing-zero
    shift).  Value 0 is the raw 64-bit first value (mbits=64, shift=0).
    Returns (bitpos int32, mbits uint8, shift uint8, vals uint64) where
    vals[i] is the decoded bit pattern of value i (the cumulative XOR) —
    mesh shards slice mid-stream and seed the device XOR-scan with
    vals[lo-1].  Returns None when the stream is malformed (the caller
    falls back to the host decoder's error handling)."""
    nbits = len(payload) * 8

    def read(pos, k):
        b = payload[pos >> 3:(pos + k + 7) >> 3]
        v = int.from_bytes(b, "big")
        return (v >> (len(b) * 8 - (pos & 7) - k)) & ((1 << k) - 1)

    bitpos = np.zeros(n, np.int32)
    mbits = np.zeros(n, np.uint8)
    shift = np.zeros(n, np.uint8)
    vals = np.zeros(n, np.uint64)
    if n == 0:
        return bitpos, mbits, shift, vals
    if nbits < 64:
        return None
    mbits[0] = 64
    acc = read(0, 64)
    vals[0] = acc
    pos = 64
    lz = tz = 0
    for i in range(1, n):
        if pos + 1 > nbits:
            return None
        c = read(pos, 1)
        pos += 1
        if not c:
            vals[i] = acc
            continue  # repeat of prev: xor = 0, mbits stays 0
        if pos + 1 > nbits:
            return None
        f = read(pos, 1)
        pos += 1
        if f:
            if pos + 11 > nbits:
                return None
            lz = read(pos, 5)
            pos += 5
            mb = read(pos, 6) + 1
            pos += 6
            tz = 64 - lz - mb
            if tz < 0:
                return None
        mb = 64 - lz - tz
        if mb <= 0 or pos + mb > nbits:
            return None
        bitpos[i] = pos
        mbits[i] = mb
        shift[i] = tz
        acc ^= read(pos, mb) << tz
        vals[i] = acc
        pos += mb
    return bitpos, mbits, shift, vals


def _varint_ok(payload: bytes, n: int) -> bool:
    """Shape-validate a varint stream on the host (vectorized): exactly
    n terminator bytes, stream ends on one, and every varint is at most
    10 bytes (canonical uint64) so the device's 7*offset shifts stay in
    range."""
    b = np.frombuffer(payload, np.uint8)
    ends = np.flatnonzero((b & 0x80) == 0)
    if len(ends) != n or (n and ends[-1] != len(b) - 1):
        return False
    if n == 0:
        return len(b) == 0
    lens = np.diff(np.concatenate(([np.int64(-1)], ends)))
    return bool((lens <= 10).all())


def classify(blocks) -> list | None:
    """DeviceBlock views of every raw block buffer, or None when any
    block (or the block count) is not device-decodable — including
    kinds excluded by OGT_DEVICE_DECODE_CODECS and streams whose host
    structural validation fails."""
    if len(blocks) > _MAX_BLOCKS:
        return None
    allowed = codecs_enabled()
    out = []
    for buf in blocks:
        if isinstance(buf, encoding.DeviceBlock):
            db = buf  # pre-sliced mesh-shard block; knob still applies
        else:
            db = encoding.device_block(buf)
        if db is None or db.kind not in allowed:
            return None
        if db.kind == "gorilla":
            # sliced blocks carry their scan (aux); whole blocks scan here
            if db.aux is None and \
                    _gorilla_scan(bytes(db.payload), db.n) is None:
                return None
        elif db.kind == "varint":
            if not _varint_ok(bytes(db.payload), db.n):
                return None
        elif db.kind == "strdict" and len(db.payload) != db.n * db.width:
            return None
        out.append(db)
    return out


def _pack_blocks(dbs):
    """(sig, payload, scalars, aux32, aux8) of classified DeviceBlocks —
    THE block assembly every program entry point shares, so the jit
    cache key (sig) can never desynchronize from the shipped bytes.
    aux32/aux8 carry the gorilla structural-scan vectors (bitpos;
    interleaved mbits,shift) and are None when no block needs them."""
    sig = tuple((b.kind, b.n, b.width) for b in dbs)
    payload = np.frombuffer(
        b"".join(bytes(b.payload) for b in dbs), np.uint8)
    scalars = np.array([[b.first, b.step] for b in dbs],
                       np.int64).reshape(len(dbs), 2)
    aux32 = aux8 = None
    if any(b.kind == "gorilla" for b in dbs):
        p32, p8 = [], []
        for b in dbs:
            if b.kind != "gorilla":
                continue
            if b.aux is not None:
                bitpos, mbits, shift = b.aux
            else:
                bitpos, mbits, shift, _ = _gorilla_scan(
                    bytes(b.payload), b.n)
            p32.append(bitpos)
            p8.append(np.stack([mbits, shift], axis=1).reshape(-1))
        aux32 = np.concatenate(p32) if p32 else np.zeros(0, np.int32)
        aux8 = np.concatenate(p8) if p8 else np.zeros(0, np.uint8)
    return sig, payload, scalars, aux32, aux8


def _sig_has_aux(sig) -> bool:
    return any(kind == "gorilla" for kind, _, _ in sig)


def note_fallback(n: int = 1) -> None:
    """Count an eligible-looking encoded scan that ended up on the host
    decode path anyway (ineligible blocks, codec excluded by the knob,
    cost gate, knob off at freeze time) — the triage counter for "why
    didn't H2D drop"."""
    _STATS.incr("device", "decode_fallbacks_total", n)


# per-codec counter spellings (the label-free registry renders each as
# its own ogt_device_decode_*_total family; README documents the set)
_CODEC_KEYS = {
    "const": ("decode_blocks_const_total",
              "decode_payload_bytes_const_total"),
    "delta": ("decode_blocks_delta_total",
              "decode_payload_bytes_delta_total"),
    "raw64": ("decode_blocks_raw64_total",
              "decode_payload_bytes_raw64_total"),
    "gorilla": ("decode_blocks_gorilla_total",
                "decode_payload_bytes_gorilla_total"),
    "varint": ("decode_blocks_varint_total",
               "decode_payload_bytes_varint_total"),
    "strdict": ("decode_blocks_strdict_total",
                "decode_payload_bytes_strdict_total"),
}


def _payload_nbytes(kind: str, n: int, width: int) -> int:
    if kind == "const":
        return 0
    if kind == "delta":
        return (n - 1) * width if n else 0
    if kind == "raw64":
        return 8 * n
    if kind == "strdict":
        return n * width
    return width  # gorilla/varint: width IS the payload byte length


def _note_decode_stats(sig, rows: int) -> None:
    """The decode counters, split per codec so /debug/device shows which
    codecs actually ship encoded (the aggregates keep their pre-split
    spellings)."""
    _STATS.incr("device", "decode_blocks_total", len(sig))
    total = 0
    for kind, bn, width in sig:
        nb = _payload_nbytes(kind, bn, width)
        total += nb
        bkey, pkey = _CODEC_KEYS[kind]
        _STATS.incr("device", bkey)
        _STATS.incr("device", pkey, nb)
    _STATS.incr("device", "decode_payload_bytes_total", total)
    _STATS.incr("device", "decode_rows_total", rows)


class GridPlan:
    """Host-side inputs + static geometry of one fused decode->scatter->
    reduce program invocation.  The scatter slots travel either as an
    explicit int32 `flat` array (4 bytes/row) or — when every series run
    is constant-stride and the window arithmetic verifies on the host —
    as `runmeta` (rel0, stride, start_row) int64 triples plus one phase
    scalar (~24 bytes/RUN), reconstructed on device."""

    __slots__ = ("geom", "payload", "scalars", "aux32", "aux8",
                 "viewruns", "flat", "runmeta", "consts", "maskbits", "n")

    def __init__(self, geom, payload, scalars, aux32, aux8, viewruns,
                 flat, runmeta, consts, maskbits, n):
        self.geom = geom
        self.payload = payload
        self.scalars = scalars
        self.aux32 = aux32
        self.aux8 = aux8
        self.viewruns = viewruns
        self.flat = flat
        self.runmeta = runmeta
        self.consts = consts
        self.maskbits = maskbits
        self.n = n

    def transfer_nbytes(self) -> int:
        nb = int(self.payload.nbytes) + int(self.scalars.nbytes)
        for a in (self.aux32, self.aux8, self.viewruns, self.flat,
                  self.runmeta, self.consts, self.maskbits):
            if a is not None:
                nb += int(a.nbytes)
        return nb


def _affine_scatter(flat, rel, starts, every_ns, dt, k, w_pad):
    """(runmeta, consts) when the scatter slots are reconstructible
    on device from per-run scalars, else None.

    Requirements, each VERIFIED on the host against the actual arrays
    (vectorized int compares — far cheaper than the transfer they save):
    every run's times are affine (rel0 + j*stride), and the window
    ordinal follows one global phase: w == (rel - woff) // every.  Then
    the device recomputes flat = (rid*k + (rel - w*every)//dt)*w_pad + w
    exactly — any offset/edge subtlety just fails verification and the
    plan ships the explicit flat array instead."""
    n = len(rel)
    runs = len(starts)
    if n == 0 or runs == 0 or every_ns is None or not every_ns or not dt:
        return None
    lens = np.diff(np.append(starts, n))
    rel0 = rel[starts]
    stride = np.zeros(runs, np.int64)
    multi = lens > 1
    if multi.any():
        d = np.diff(rel)
        stride[multi] = d[starts[multi]]
    rid = np.repeat(np.arange(runs, dtype=np.int64), lens)
    j = np.arange(n, dtype=np.int64) - np.repeat(starts, lens)
    if not np.array_equal(rel0[rid] + j * stride[rid], rel):
        return None  # gaps / irregular spacing inside a run
    w = flat % w_pad
    # window phase: any valid woff satisfies woff + w*every <= rel <
    # woff + (w+1)*every for EVERY row; the supremum of that interval,
    # min(rel - w*every), is valid whenever any woff is — and the full
    # verification below rejects the rest
    woff = int((rel - w * every_ns).min())
    if not np.array_equal((rel - woff) // every_ns, w):
        return None
    r = (rel - w * every_ns) // dt
    if not np.array_equal((rid * k + r) * w_pad + w, flat):
        return None
    # (rel0, stride, start_row) per run — all DYNAMIC program inputs
    # (~24 bytes/run): baking row offsets in as program constants would
    # make every distinct series count a fresh multi-second compile
    runmeta = np.stack([rel0, stride, starts.astype(np.int64)], axis=1)
    return runmeta, np.array([woff], np.int64)


def combine_views(views):
    """Flatten per-column (blocks, segments, n_full) views into one
    block list plus the absolute row runs of the combined view over the
    combined decode (adjacent runs merged; None = identity).  Returns
    (blocks, runs|None, n_view, n_full)."""
    blocks: list = []
    runs = []
    base = 0
    n_view = 0
    for vb, segs, n_full in views:
        blocks.extend(vb)
        for a, b in np.asarray(segs, np.int64):
            a, b = int(a) + base, int(b) + base
            n_view += b - a
            if runs and runs[-1][1] == a:
                runs[-1][1] = b  # adjacent runs merge
            else:
                runs.append([a, b])
        base += int(n_full)
    if not runs or (len(runs) == 1 and runs[0] == [0, base]):
        return blocks, None, n_view, base  # identity (or empty) view
    return blocks, np.asarray(runs, np.int64), n_view, base


def build_grid_plan(views, flat, mask, shape, dtype, rel=None,
                    starts=None, every_ns=None, dt=None) -> GridPlan | None:
    """Plan the fused program for one frozen grid: `views` are the
    still-encoded value columns' (blocks, segments, n_full) triples in
    row order, `flat` the host-computed scatter slots (injective,
    < prod(shape)), `mask` the row validity.  `rel`/`starts`/
    `every_ns`/`dt` (the freeze's run layout) enable the per-run scatter
    reconstruction.  Returns None when the blocks are not
    device-decodable or the transfer would not beat the decoded grid —
    the caller host-decodes exactly as before."""
    if not active():
        return None
    blocks, viewruns, n_view, n_full = combine_views(views)
    dbs = classify(blocks)
    if dbs is None:
        note_fallback()
        return None
    if sum(b.n for b in dbs) != n_full or n_view != len(flat):
        note_fallback()
        return None  # defensive: blocks must cover the view exactly
    n = n_view
    sig, payload, scalars, aux32, aux8 = _pack_blocks(dbs)
    maskbits = None
    if mask is not None and not mask.all():
        maskbits = np.packbits(np.asarray(mask, np.bool_))
    affine = None
    if rel is not None and starts is not None:
        affine = _affine_scatter(flat, rel, np.asarray(starts),
                                 every_ns, dt, shape[1], shape[2])
    if affine is not None:
        runmeta, consts = affine
        flat32 = None
        nruns_affine = len(runmeta)
    else:
        runmeta, consts, nruns_affine = None, None, None
        flat32 = np.ascontiguousarray(flat, np.int32)
    geom = (sig, n, tuple(shape), np.dtype(dtype).str,
            maskbits is not None, nruns_affine,
            every_ns if nruns_affine else None,
            dt if nruns_affine else None,
            None if viewruns is None else len(viewruns))
    plan = GridPlan(geom, payload, scalars, aux32, aux8, viewruns,
                    flat32, runmeta, consts, maskbits, n)
    # cost gate, now the offload planner's zero-sample prior: with no
    # measured device samples this is the exact byte inequality (the
    # fused path must shrink the transfer below the decoded grid it
    # replaces — values + mask bytes per padded cell); once the planner
    # holds real wall samples for this geometry its decide() owns the
    # choice and the byte rule stands down
    if not offload.GLOBAL.gate_prior(
            "grid_decode", geom, plan.transfer_nbytes(),
            int(np.prod(shape)) * 9):
        note_fallback()
        return None
    return plan


def _plan_inputs(plan: GridPlan) -> list:
    """The program's positional inputs in the ONE canonical order shared
    with _grid_program: payload, scalars, [aux32, aux8], [viewruns],
    [flat | runmeta+consts], [maskbits]."""
    inputs = [plan.payload, plan.scalars]
    if plan.aux32 is not None:
        inputs.extend((plan.aux32, plan.aux8))
    if plan.viewruns is not None:
        inputs.append(plan.viewruns)
    if plan.flat is not None:
        inputs.append(plan.flat)
    else:
        inputs.extend((plan.runmeta, plan.consts))
    if plan.maskbits is not None:
        inputs.append(plan.maskbits)
    return inputs


def run_grid_plan(plan: GridPlan):
    """Execute the fused program: one H2D of the encoded inputs (site
    `device-decode`), then decode+scatter+reduce in a single jit program.
    Returns ({count,sum,mean,min,max} device arrays, vt, mt, flat) —
    vt/mt are the decoded grid buffers, ready for colcache device-tier
    retention and the ssd/selector kernels; flat is the device-resident
    scatter-slot vector (imat_from_flat builds the selector index grid
    from it without a host round-trip)."""
    import jax

    t0 = time.perf_counter_ns()
    inputs = _plan_inputs(plan)
    dev = [jax.device_put(a) for a in inputs]
    devobs.note_transfer("h2d", _XFER_SITE, plan.transfer_nbytes(),
                         (time.perf_counter_ns() - t0) / 1e9)
    _note_decode_stats(plan.geom[0], plan.n)
    geom = plan.geom
    pw_geo = (len(geom[0]), geom[1], geom[2], geom[3],
              geom[5] is not None)
    devobs.note_use("grid_decode_fused", pw_geo)
    offload.register_builder("grid_decode_fused", pw_geo,
                             lambda g=geom: _grid_program(g))
    fn = _grid_program(plan.geom)
    t = devobs.t0()
    stats, vt, mt, flat = fn(*dev)
    if t:
        devobs.note_exec(t)
    return stats, vt, mt, flat


class MeshGridPlan:
    """One fused-decode plan per mesh shard, plus the global geometry
    the assembly step needs.  Each shard's GridPlan is self-contained
    (its own blocks, scatter slots rebased to the shard's row origin,
    per-shard affine runs), so the per-shard programs are exactly the
    single-device fused program — sharding is pure input partitioning,
    zero collectives."""

    __slots__ = ("mesh", "shards", "shape", "dtype_str", "n")

    def __init__(self, mesh, shards, shape, dtype_str, n):
        self.mesh = mesh
        self.shards = shards
        self.shape = shape
        self.dtype_str = dtype_str
        self.n = n

    def transfer_nbytes(self) -> int:
        return sum(p.transfer_nbytes() for p in self.shards)


@functools.lru_cache(maxsize=1024)
def _varint_scan(payload: bytes, n: int):
    """Host byte-structure + values of one varint block (cached like
    the gorilla scan): (ends, vals) where ends[i] is the byte index of
    value i's terminator byte and vals[i] its decoded int64 — mesh
    shards slice the byte stream at ends and seed the device cumsum
    with vals[lo-1]."""
    b = np.frombuffer(payload, np.uint8)
    ends = np.flatnonzero((b & 0x80) == 0).astype(np.int64)
    vals = encoding.decode_ints(
        struct.pack("<BI", encoding._T_VARINT, n) + payload)
    return ends, np.asarray(vals, np.int64)


@functools.lru_cache(maxsize=1024)
def _delta_vals(payload: bytes, n: int, first: int, step: int,
                width: int):
    """Host-decoded int64 values of one FOR-delta block (the exact
    decode_ints arithmetic: zero-extend widen, +step, wrapping cumsum,
    +first) — mesh shards reseed a slice's `first` from vals[lo]."""
    dt = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[width]
    d = np.frombuffer(payload[:(n - 1) * width], dtype=dt).astype(
        np.int64)
    out = np.empty(n, np.int64)
    out[0] = first
    if n > 1:
        np.cumsum(d + step, out=out[1:])
        out[1:] += first
    return out


def _wrap_i64(v) -> int:
    v = int(v) & 0xFFFFFFFFFFFFFFFF
    return v - (1 << 64) if v >= (1 << 63) else v


def _slice_block(db, lo: int, hi: int):
    """A DeviceBlock covering values [lo, hi) of `db`, shipping ONLY the
    payload bytes those values need — what lets a mesh shard whose span
    ends mid-block avoid duplicating the whole stream.  Stateful codecs
    get their seed carried in `first` (gorilla: the decoded bit pattern
    of value lo-1, XORed into the device scan; varint: the int64 value
    of lo-1, added to the device cumsum) and gorilla slices attach their
    precomputed structural scan as `aux` (the control bits are stateful,
    so a mid-stream payload cannot be re-scanned).  Returns None when
    the codec cannot slice (the caller falls back)."""
    n = hi - lo
    if lo == 0 and hi == db.n:
        return db
    if db.kind == "const":
        return encoding.DeviceBlock(
            "const", n, _wrap_i64(db.first + db.step * lo), db.step)
    if db.kind == "raw64":
        return encoding.DeviceBlock(
            "raw64", n, payload=db.payload[8 * lo:8 * hi])
    if db.kind == "strdict":
        w = db.width
        return encoding.DeviceBlock(
            "strdict", n, width=w, payload=db.payload[w * lo:w * hi],
            table=db.table)
    if db.kind == "delta":
        vals = _delta_vals(bytes(db.payload), db.n, db.first, db.step,
                           db.width)
        # payload keeps deltas for slice indices 1..n-1 = global
        # lo+1..hi-1; delta j lives at payload[(j-1)*width:]
        return encoding.DeviceBlock(
            "delta", n, int(vals[lo]), db.step, db.width,
            db.payload[lo * db.width:(hi - 1) * db.width])
    if db.kind == "varint":
        ends, vals = _varint_scan(bytes(db.payload), db.n)
        b0 = 0 if lo == 0 else int(ends[lo - 1]) + 1
        sub = db.payload[b0:int(ends[hi - 1]) + 1]
        seed = 0 if lo == 0 else int(vals[lo - 1])
        return encoding.DeviceBlock(
            "varint", n, seed, width=len(sub), payload=sub)
    if db.kind == "gorilla":
        scan = _gorilla_scan(bytes(db.payload), db.n)
        if scan is None:
            return None
        bitpos, mbits, shift, vals = scan
        mb = mbits[lo:hi].astype(np.int32)
        sel = mb > 0
        if sel.any():
            bp = bitpos[lo:hi].astype(np.int64)
            b0 = int(bp[sel].min()) >> 3
            b1 = (int((bp[sel] + mb[sel]).max()) + 7) >> 3
            sub = db.payload[b0:b1]
            bp = np.where(sel, bp - 8 * b0, 0).astype(np.int32)
        else:  # pure repeat run: every value IS the seed
            sub = b""
            bp = np.zeros(n, np.int32)
        seed = 0 if lo == 0 else _wrap_i64(vals[lo - 1])
        return encoding.DeviceBlock(
            "gorilla", n, seed, width=len(sub), payload=sub,
            aux=(bp, mbits[lo:hi].copy(), shift[lo:hi].copy()))
    return None


def build_mesh_grid_plan(views, flat, mask, shape, dtype, mesh,
                         rel=None, starts=None, every_ns=None,
                         dt=None) -> MeshGridPlan | None:
    """Partition one fused grid-decode plan by output row shard.  The
    scatter row ids (flat // (k*W_pad)) are non-decreasing — series runs
    are emitted in row order — so each mesh shard owns one CONTIGUOUS
    span of data rows, and that span maps to a contiguous span of view
    rows, blocks, and payload bytes: every per-shard input is a slice +
    rebase of the global plan's, built through the same build_grid_plan
    (same verification, same per-shard cost gate).  Returns None when
    the rows cannot split cleanly or any shard refuses — the caller
    falls back to the host scatter + shard_leading_axis exactly as
    before."""
    if not active():
        return None
    S_pad, k, w_pad = shape
    nsh = int(mesh.size)
    if S_pad % nsh:
        return None
    rows_per = S_pad // nsh
    blocks, viewruns, n_view, n_full = combine_views(views)
    dbs = classify(blocks)
    if dbs is None or sum(b.n for b in dbs) != n_full \
            or n_view != len(flat):
        note_fallback()
        return None
    flat = np.asarray(flat, np.int64)
    row_of = flat // (k * w_pad)
    if len(row_of) and (np.diff(row_of) < 0).any():
        note_fallback()
        return None  # rows out of order: no contiguous shard spans
    cuts = np.concatenate((
        [0], np.searchsorted(row_of, np.arange(1, nsh) * rows_per),
        [n_view])).astype(np.int64)
    mask = None if mask is None else np.asarray(mask, bool)
    rel = None if rel is None else np.asarray(rel, np.int64)
    starts = None if starts is None else np.asarray(starts, np.int64)
    # block offsets in FULL (concatenated-decode) coordinates, and the
    # view runs as explicit [lo, hi) full-coordinate spans
    boffs = np.cumsum([0] + [b.n for b in dbs]).astype(np.int64)
    vruns = (np.array([[0, n_full]], np.int64) if viewruns is None
             else np.asarray(viewruns, np.int64))
    run_len = vruns[:, 1] - vruns[:, 0]
    run_end_v = np.cumsum(run_len)       # view-coordinate run ends
    run_start_v = run_end_v - run_len
    shards = []
    for s in range(nsh):
        a, b = int(cuts[s]), int(cuts[s + 1])
        sub_views: list = []
        if a < b:
            i0 = int(np.searchsorted(run_end_v, a, side="right"))
            i1 = int(np.searchsorted(run_start_v, b, side="left"))
            lo_f = vruns[i0:i1, 0] + np.maximum(a - run_start_v[i0:i1], 0)
            hi_f = vruns[i0:i1, 0] + np.minimum(b - run_start_v[i0:i1],
                                                run_len[i0:i1])
            span_lo, span_hi = int(lo_f[0]), int(hi_f[-1])
            jmin = int(np.searchsorted(boffs, span_lo,
                                       side="right")) - 1
            jmax = int(np.searchsorted(boffs, span_hi - 1,
                                       side="right")) - 1
            # slice boundary blocks at VALUE granularity — a block
            # spanning several shards must not ship whole to each (the
            # duplicated payload+aux would trip every shard's cost
            # gate); _slice_block reseeds the stateful codecs
            sub_blocks = []
            for j in range(jmin, jmax + 1):
                o = int(boffs[j])
                sb = _slice_block(dbs[j], max(span_lo - o, 0),
                                  min(span_hi, int(boffs[j + 1])) - o)
                if sb is None:
                    note_fallback()
                    return None
                sub_blocks.append(sb)
            segs = np.stack([lo_f - span_lo, hi_f - span_lo], axis=1)
            sub_views = [(sub_blocks, segs, span_hi - span_lo)]
        plan = build_grid_plan(
            sub_views, flat[a:b] - s * rows_per * k * w_pad,
            None if mask is None else mask[a:b],
            (rows_per, k, w_pad), dtype,
            rel=None if rel is None else rel[a:b],
            starts=None if starts is None else
            starts[(starts >= a) & (starts < b)] - a,
            every_ns=every_ns, dt=dt)
        if plan is None:
            note_fallback()
            return None
        shards.append(plan)
    return MeshGridPlan(mesh, shards, tuple(shape), np.dtype(dtype).str,
                        n_view)


def run_mesh_grid_plan(mplan: MeshGridPlan):
    """Execute the per-shard fused programs and assemble the results as
    NamedSharding global arrays partitioned on the row axis.  One
    explicit device_put per input per shard (each shard's encoded bytes
    land only on its own device — the explicit per-shard form of the
    row-sharded layout, no replicated intermediate), then the SAME
    cached per-geometry programs as the single-device path, then a
    zero-copy global-array assembly.  Returns (stats, vt, mt, None) —
    vt/mt ready for the mesh-aware colcache device tier and the GSPMD
    ssd/selector kernels."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = mplan.mesh
    devices = list(mesh.devices.flat)
    t0 = time.perf_counter_ns()
    nbytes = 0
    shard_in = []
    for plan, dev in zip(mplan.shards, devices):
        shard_in.append([jax.device_put(a, dev)
                         for a in _plan_inputs(plan)])
        nbytes += plan.transfer_nbytes()
    # every byte here is mesh-cold H2D a warm repeat must NOT pay (the
    # sharded colcache tier retains vt/mt) — the same warm-flat contract
    # shard_leading_axis's counter carries for the dense path
    _STATS.incr("device", "mesh_h2d_bytes", nbytes)
    devobs.note_transfer("h2d", _XFER_SITE, nbytes,
                         (time.perf_counter_ns() - t0) / 1e9, mesh=True)
    outs = []
    t = devobs.t0()
    for plan, ins in zip(mplan.shards, shard_in):
        _note_decode_stats(plan.geom[0], plan.n)
        outs.append(_grid_program(plan.geom)(*ins))
    if t:
        devobs.note_exec(t)
    ax = tuple(mesh.axis_names)

    def assemble(pieces):
        gshape = (mplan.shape[0],) + tuple(pieces[0].shape[1:])
        spec = PartitionSpec(ax, *([None] * (pieces[0].ndim - 1)))
        return jax.make_array_from_single_device_arrays(
            gshape, NamedSharding(mesh, spec), list(pieces))

    vt = assemble([o[1] for o in outs])
    mt = assemble([o[2] for o in outs])
    stats = {key: assemble([o[0][key] for o in outs])
             for key in outs[0][0]}
    return stats, vt, mt, None


def imat_from_flat(flat_dev, shape):
    """Selector index grid (sample ordinal per grid slot) from the
    device-resident scatter slots a fused decode left behind — replaces
    the host imat build + its full-grid transfer on the cold selector
    path."""
    return _imat_program(int(flat_dev.shape[0]), tuple(shape))(flat_dev)


@functools.lru_cache(maxsize=256)
def _imat_program(n: int, shape):
    import jax
    import jax.numpy as jnp

    devobs.note_compile("grid_decode_imat", (n, shape))
    cells = int(np.prod(shape))

    def run(flat):
        return jnp.zeros(cells, jnp.int32).at[flat].set(
            jnp.arange(n, dtype=jnp.int32),
            unique_indices=True).reshape(shape)

    return jax.jit(run)


def decode_to_device(blocks, dtype=None):
    """Standalone device decode of raw block buffers -> one device value
    vector (int64/float64, or `dtype` when given).  The non-fused entry
    point: tests assert bit-identity against the host decoders with it,
    and column-shaped consumers can device_put encoded bytes directly."""
    import jax

    dbs = classify(blocks)
    if dbs is None:
        raise ValueError("blocks are not device-decodable")
    out_dtype = np.dtype(dtype) if dtype is not None else (
        np.dtype(np.float64)
        if any(b.kind in ("raw64", "gorilla") for b in dbs)
        else np.dtype(np.int64))
    sig, payload, scalars, aux32, aux8 = _pack_blocks(dbs)
    host_in = [payload, scalars]
    if aux32 is not None:
        host_in.extend((aux32, aux8))
    t0 = time.perf_counter_ns()
    dev = [jax.device_put(a) for a in host_in]
    devobs.note_transfer(
        "h2d", _XFER_SITE, sum(int(a.nbytes) for a in host_in),
        (time.perf_counter_ns() - t0) / 1e9)
    return _decode_program(sig, out_dtype.str)(*dev)


def materialize_enc(enc) -> np.ndarray:
    """Host materialization of a (ftype, blocks, segments, slices)
    encoded-column descriptor into the concatenated f64 sample vector —
    the bit-identical fallback for consumers that need host values
    (dense prom kernels, mesh sharding)."""
    ftype, blocks, segments, slices = enc
    d = encoding.decode_value_blocks(ftype, list(blocks)).astype(
        np.float64)
    if segments is not None:
        d = (np.concatenate([d[a:b] for a, b in segments])
             if len(segments) else d[:0])
    if not slices:
        return np.empty(0, np.float64)
    if len(slices) == 1:
        lo, hi = slices[0]
        return d[lo:hi]
    return np.concatenate([d[lo:hi] for lo, hi in slices])


def decode_rows_matrix(enc, shape, dtype):
    """Decode raw blocks ON device and lay the per-series sample slices
    into a zero-padded (S, N) row matrix — the PromQL tiled kernels'
    value matrix without the padded-f64 H2D (the transfer is the raw
    payload + two ints per series).  `enc` is the (ftype, blocks,
    segments, slices) descriptor (slices in VIEW coordinates).  Returns
    the device matrix, or None when the blocks are not device-decodable
    (caller host-materializes, bit-identically)."""
    import jax

    if not active():
        return None
    ftype, blocks, segments, slices = enc
    dbs = classify(list(blocks))
    if dbs is None:
        note_fallback()
        return None
    n_full = sum(b.n for b in dbs)
    if segments is None:
        viewruns, n_view = None, n_full
    else:
        segments = np.asarray(segments, np.int64).reshape(-1, 2)
        viewruns = segments
        n_view = int((segments[:, 1] - segments[:, 0]).sum())
        if len(segments) and (segments[:, 0] < 0).any() \
                or len(segments) and (segments[:, 1] > n_full).any():
            note_fallback()
            return None
    S, N = shape
    lo = np.array([s[0] for s in slices], np.int64)
    ln = np.array([s[1] - s[0] for s in slices], np.int64)
    if len(slices) != S or (ln > N).any() or (lo < 0).any() \
            or (lo + ln > n_view).any():
        note_fallback()
        return None
    sig, payload, scalars, aux32, aux8 = _pack_blocks(dbs)
    host_in = [payload, scalars]
    if aux32 is not None:
        host_in.extend((aux32, aux8))
    host_in.extend((lo, ln))
    if viewruns is not None:
        host_in.append(viewruns)
    # cost gate (the encoded transfer must beat the padded value matrix
    # it replaces — whole-block payloads can exceed a heavily trimmed
    # view; raw64 floats have no width compression to amortize it),
    # serving as the offload planner's zero-sample prior: measured
    # device samples for this geometry retire the byte rule
    rows_geo = (len(sig), n_view, (S, N))
    if not offload.GLOBAL.gate_prior(
            "prom_decode_rows", rows_geo,
            sum(int(a.nbytes) for a in host_in),
            S * N * np.dtype(dtype).itemsize):
        note_fallback()
        return None
    t0 = time.perf_counter_ns()
    dev = [jax.device_put(a) for a in host_in]
    devobs.note_transfer(
        "h2d", _XFER_SITE, sum(int(a.nbytes) for a in host_in),
        (time.perf_counter_ns() - t0) / 1e9)
    _note_decode_stats(sig, n_view)
    devobs.note_use("prom_decode_rows", rows_geo)
    pw = (sig, n_view, (S, N), np.dtype(dtype).str,
          None if viewruns is None else len(viewruns))
    offload.register_builder("prom_decode_rows", rows_geo,
                             lambda a=pw: _rows_program(*a))
    fn = _rows_program(sig, n_view, (S, N), np.dtype(dtype).str,
                       None if viewruns is None else len(viewruns))
    t = devobs.t0()
    out = fn(*dev)
    if t:
        devobs.note_exec(t)
    return out


@functools.lru_cache(maxsize=256)
def _rows_program(sig, n: int, shape, dtype_str, nruns):
    import jax
    import jax.numpy as jnp

    devobs.note_compile("prom_decode_rows", (len(sig), n, shape))
    S, N = shape
    out_dt = jnp.dtype(dtype_str)
    decode = _decode_expr(sig, dtype_str)
    has_aux = _sig_has_aux(sig)

    def run(payload, scalars, *rest):
        if n == 0:
            return jnp.zeros((S, N), out_dt)
        if has_aux:
            aux32, aux8 = rest[0], rest[1]
            rest = rest[2:]
        else:
            aux32 = aux8 = None
        lo, ln = rest[0], rest[1]
        viewruns = rest[2] if len(rest) > 2 else None
        vals = decode(payload, scalars, aux32, aux8)
        if nruns is not None:
            vals = _view_gather(vals, viewruns, n)
        col = jnp.arange(N, dtype=jnp.int64)[None, :]
        idx = jnp.clip(lo[:, None] + col, 0, n - 1)
        m = col < ln[:, None]
        return jnp.where(m, vals[idx], jnp.zeros((), out_dt))

    return jax.jit(run)


# -- jit program construction -------------------------------------------------


def _view_gather(vals_full, viewruns, n_view: int):
    """Gather a column VIEW (absolute [lo, hi) row runs) out of the
    fully-decoded block concatenation, on device.  `viewruns` is the
    dynamic (k, 2) run array; `n_view` is static."""
    import jax.numpy as jnp

    run_len = viewruns[:, 1] - viewruns[:, 0]
    ends = jnp.cumsum(run_len)
    pos = jnp.arange(n_view, dtype=jnp.int64)
    rid = jnp.searchsorted(ends, pos, side="right")
    start_out = ends - run_len
    return vals_full[viewruns[rid, 0] + pos - start_out[rid]]


def _widen(raw, width: int, cnt: int):
    """(cnt*width,) LE bytes -> (cnt,) int64, matching the host
    frombuffer(...).astype(int64) exactly (zero-extend below 8 bytes,
    bit-reinterpretation at 8).  Width-1/2 blocks route through the
    Pallas widen kernel where the backend supports it."""
    import jax
    import jax.numpy as jnp

    if width in (1, 2) and _pallas_widen_ok():
        from opengemini_tpu.ops import pallas_segment as ps

        return ps.widen_packed(raw, width, cnt).astype(jnp.int64)
    if width == 1:
        return raw.astype(jnp.int64)
    if width == 8:
        # bitcast, not convert: uint64 values >= 2^63 must wrap to
        # negative int64 exactly like numpy's astype
        return jax.lax.bitcast_convert_type(
            raw.reshape(cnt, 8), jnp.int64)
    dt = {2: jnp.uint16, 4: jnp.uint32}[width]
    return jax.lax.bitcast_convert_type(
        raw.reshape(cnt, width), dt).astype(jnp.int64)


def _pallas_widen_ok() -> bool:
    from opengemini_tpu.ops import pallas_segment as ps

    return ps.use_pallas() and devobs.pallas_supported()[0]


def _unpack_bits(raw, nbytes: int):
    """(nbytes,) uint8 -> (nbytes*8,) int32 bits, MSB-first per byte —
    Pallas unpack_bits where the probe allows, jnp shift/mask fallback
    elsewhere (both match np.unpackbits exactly)."""
    import jax.numpy as jnp

    if _pallas_widen_ok():
        from opengemini_tpu.ops import pallas_segment as ps

        return ps.unpack_bits(raw, nbytes)
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    return ((raw[:, None] >> shifts) & jnp.uint8(1)).astype(
        jnp.int32).reshape(nbytes * 8)


def _gorilla_piece(raw, m: int, bitpos, mb_sh, bn: int, seed):
    """Data-parallel gorilla reconstruction from the payload bytes plus
    the host structural scan's aux vectors.  Each value's 64-bit window
    starting at bitpos is gathered from the unpacked bit vector; the top
    mbits of the window, shifted left by its trailing-zero count, is the
    value's XOR delta (repeats have mbits=0 -> delta 0; value 0 has
    mbits=64 -> its raw bits).  An associative XOR prefix scan, XORed
    with `seed` (the running value BEFORE this slice: 0 for whole
    blocks, vals[lo-1] for mesh-shard slices), then yields every decoded
    word in parallel — bit-identical to the host's sequential prev^delta
    walk, NaN/±0.0 included, because XOR carries no arithmetic."""
    import jax
    import jax.numpy as jnp

    if m == 0:
        # all-repeat slice: no meaningful bits shipped; every value is
        # the seed (the gather below reads only masked-out zeros)
        bits = jnp.zeros(64, jnp.int32)
    else:
        bits = jnp.concatenate(
            [_unpack_bits(raw, m), jnp.zeros(64, jnp.int32)])
    g = bitpos[:, None].astype(jnp.int32) + jnp.arange(
        64, dtype=jnp.int32)
    bv = bits[g].astype(jnp.uint64)  # (bn, 64)
    w64 = jnp.sum(bv << jnp.arange(63, -1, -1, dtype=jnp.uint64),
                  axis=1, dtype=jnp.uint64)
    pair = mb_sh.reshape(bn, 2)
    mb = pair[:, 0].astype(jnp.uint64)
    sh = pair[:, 1].astype(jnp.uint64)
    nz = mb > 0
    s1 = jnp.where(nz, jnp.uint64(64) - mb, jnp.uint64(0))
    xor = jnp.where(nz, (w64 >> s1) << sh, jnp.uint64(0))
    acc = jax.lax.associative_scan(jnp.bitwise_xor, xor)
    return jax.lax.bitcast_convert_type(acc ^ seed, jnp.float64)


def _varint_piece(raw, m: int, bn: int):
    """Data-parallel LEB128 delta+zigzag decode: terminator bytes (high
    bit clear) close each varint, so a cumulative count assigns every
    byte its value id; a segmented shift/or (the 7-bit groups occupy
    disjoint bit ranges, so scatter-add IS or) rebuilds each unsigned
    word; zigzag then a wrapping int64 cumsum reproduce the host's
    mod-2^64 arithmetic exactly (the first value is a delta from 0)."""
    import jax.numpy as jnp

    ends = (raw & jnp.uint8(0x80)) == 0
    e64 = ends.astype(jnp.int64)
    vid = jnp.cumsum(e64) - e64
    pos = jnp.arange(m, dtype=jnp.int64)
    is_start = jnp.concatenate([jnp.ones(1, bool), ends[:-1]])
    starts = jnp.zeros(bn, jnp.int64).at[vid].add(
        jnp.where(is_start, pos, 0), unique_indices=False)
    off7 = ((pos - starts[vid]) * 7).astype(jnp.uint64)
    groups = (raw.astype(jnp.uint64) & jnp.uint64(0x7F)) << off7
    u = jnp.zeros(bn, jnp.uint64).at[vid].add(groups)
    d = (u >> jnp.uint64(1)).astype(jnp.int64) \
        ^ -((u & jnp.uint64(1)).astype(jnp.int64))
    return jnp.cumsum(d)


def _decode_expr(sig, dtype_str):
    """The unrolled per-block decode, shared by the standalone and fused
    programs.  Returns a traced fn (payload, scalars, aux32, aux8) ->
    (n,) values in `dtype_str` (aux args are None unless the signature
    has gorilla blocks).  Offsets are static (they come from the
    signature), so every slice lowers to a static-slice."""
    import jax
    import jax.numpy as jnp

    out_dt = jnp.dtype(dtype_str)

    def decode(payload, scalars, aux32=None, aux8=None):
        pieces = []
        off = 0
        aoff = 0
        for i, (kind, bn, width) in enumerate(sig):
            if bn == 0:
                continue
            first = scalars[i, 0]
            step = scalars[i, 1]
            if kind == "const":
                piece = first + step * jnp.arange(bn, dtype=jnp.int64)
            elif kind == "delta":
                m = (bn - 1) * width
                raw = jax.lax.slice(payload, (off,), (off + m,))
                off += m
                d = _widen(raw, width, bn - 1) + step
                piece = jnp.concatenate(
                    [first[None], first + jnp.cumsum(d)])
            elif kind == "raw64":
                m = 8 * bn
                raw = jax.lax.slice(payload, (off,), (off + m,))
                off += m
                piece = jax.lax.bitcast_convert_type(
                    raw.reshape(bn, 8), jnp.float64)
            elif kind == "gorilla":
                m = width  # payload byte length rides in the signature
                raw = jax.lax.slice(payload, (off,), (off + m,))
                off += m
                bitpos = jax.lax.slice(aux32, (aoff,), (aoff + bn,))
                mb_sh = jax.lax.slice(
                    aux8, (2 * aoff,), (2 * (aoff + bn),))
                aoff += bn
                # scalar 0 carries the slice seed (decoded bit pattern
                # of the value preceding the slice; 0 for whole blocks)
                seed = jax.lax.bitcast_convert_type(first, jnp.uint64)
                piece = _gorilla_piece(raw, m, bitpos, mb_sh, bn, seed)
            elif kind == "varint":
                m = width
                raw = jax.lax.slice(payload, (off,), (off + m,))
                off += m
                # `first` seeds mid-stream slices (wrapping int64 add,
                # like the host's mod-2^64 walk); 0 for whole blocks
                piece = first + _varint_piece(raw, m, bn)
            else:  # strdict: min-width indices, table stays host-side
                m = bn * width
                raw = jax.lax.slice(payload, (off,), (off + m,))
                off += m
                piece = _widen(raw, width, bn)
            pieces.append(piece.astype(out_dt))
        if not pieces:
            return jnp.zeros((0,), out_dt)
        return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)

    return decode


@functools.lru_cache(maxsize=256)
def _decode_program(sig, dtype_str):
    import jax

    devobs.note_compile("device_decode",
                        (len(sig), sum(b[1] for b in sig), dtype_str))
    return jax.jit(_decode_expr(sig, dtype_str))


@functools.lru_cache(maxsize=256)
def _grid_program(geom):
    """One fused program per static geometry: decode the blocks, scatter
    values+mask into the padded grid, and reduce the basic window stats
    — the compressed-bytes->decode->group->reduce pipeline of the
    data-path-fusion literature as a single XLA program."""
    import jax
    import jax.numpy as jnp

    (sig, n, shape, dtype_str, has_mask, nruns_affine, every_ns, dt,
     nruns) = geom
    devobs.note_compile("grid_decode_fused",
                        (len(sig), n, shape, dtype_str,
                         nruns_affine is not None))
    out_dt = jnp.dtype(dtype_str)
    cells = int(np.prod(shape))
    k, w_pad = shape[1], shape[2]
    decode = _decode_expr(sig, dtype_str)
    has_aux = _sig_has_aux(sig)

    def scatter_slots(args):
        if nruns_affine is None:
            return args[0], args[1:]  # explicit flat
        # runmeta rows: (rel0, stride, start_row) — all dynamic, so the
        # program is free of run-count-sized constants
        runmeta, consts = args[0], args[1]
        starts_c = runmeta[:, 2]
        ar = jnp.arange(n, dtype=jnp.int64)
        rid = jnp.searchsorted(starts_c, ar, side="right") - 1
        j = ar - starts_c[rid]
        rel = runmeta[:, 0][rid] + j * runmeta[:, 1][rid]
        w = (rel - consts[0]) // every_ns
        r = (rel - w * every_ns) // dt
        return ((rid * k + r) * w_pad + w).astype(jnp.int32), args[2:]

    def run(payload, scalars, *rest):
        from opengemini_tpu.ops import segment as seg

        if has_aux:
            aux32, aux8 = rest[0], rest[1]
            rest = rest[2:]
        else:
            aux32 = aux8 = None
        vals = decode(payload, scalars, aux32, aux8)
        if nruns is not None:
            vals = _view_gather(vals, rest[0], n)
            rest = rest[1:]
        flat, rest2 = scatter_slots(rest)
        vt = jnp.zeros(cells, out_dt).at[flat].set(
            vals, unique_indices=True).reshape(shape)
        if has_mask:
            shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
            bits = (rest2[0][:, None] >> shifts) & jnp.uint8(1)
            mrow = bits.reshape(-1)[:n].astype(bool)
        else:
            mrow = jnp.ones((n,), bool)
        mt = jnp.zeros(cells, bool).at[flat].set(
            mrow, unique_indices=True).reshape(shape)
        stats = seg.grid_window_agg_t(vt, mt)
        return stats, vt, mt, flat

    return jax.jit(run)
