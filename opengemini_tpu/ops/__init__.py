"""Device kernels: segmented window reductions, prom stencils, pallas.

This package is the TPU-native replacement for the reference's generated
per-type reduce kernels (engine/series_agg_func.gen.go — 45 reduce/merge
functions, series_agg_reducer.gen.go — 148 functions) and its pluggable
CoProcessor/Reducer seam (engine/coprocessor.go:43-101): instead of scalar Go
loops per (type, agg) pair, every aggregate is a masked segmented reduction
over (series-group, time-window) segment ids, jitted once per plan template
and executed on the MXU/VPU.
"""

from opengemini_tpu.ops import segment, window  # noqa: F401
