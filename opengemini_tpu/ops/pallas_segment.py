"""Pallas TPU kernels for the aggregation hot loop.

The XLA paths in ``ops/segment.py`` / ``models/ragged.py`` express each
aggregate as separate masked reduces and rely on XLA fusion to keep the
batch in registers/VMEM. These Pallas kernels make that guarantee
explicit: one tile load from HBM into VMEM feeds EVERY statistic (count,
sum, mean, min, max, ssd — and for the selector variant the four
lexicographic (hi, lo) scans), so the batch crosses HBM exactly once per
kernel regardless of how many aggregates the query asked for.

This is the TPU replacement for the reference's generated per-(type, agg)
scalar reduce loops (engine/series_agg_func.gen.go:47 floatSumReduce and
the 45 sibling fns; series_agg_reducer.gen.go) — there the fusion is
hand-written per combination, here it is one kernel per *shape family*:

  - ``bucket_stats_basic``     — (G, W) dense bucket rows (models/ragged.py)
  - ``bucket_stats_selectors`` — same tiles, first/last/min/max row selection
  - ``grid_window_agg_t``      — (S, SPW, W) regular-grid window layout
                                 (ops/segment.grid_window_agg_t)

Measured on v5e-1 (full-output consumption so XLA cannot dead-code-
eliminate rows; interleaved best-of-4): the fused SELECTOR kernel beats
the XLA lex-scan chain ~1.5x (3.5-4.9 vs 2.2-2.4 G rows/s at (131072,
256)) because one tile residency feeds all four lexicographic scans, so
models/ragged routes selectors here on TPU. For the pure reductions
(basic/grid) XLA's own fusion wins (~28-55 vs ~22-48 G rows/s) — those
kernels are retained, tested, and directly callable as the explicit-
fusion alternate, but the routing keeps XLA for them: measurement beats
ideology.

Semantics match the XLA kernels exactly (same empty-segment identities:
count 0, sum 0, min +inf, max -inf, ssd 0) — ``tests/test_pallas.py``
asserts equality against them, and the routing layer (``use_pallas``)
only engages on a real TPU backend, falling back to the XLA path
elsewhere, so CPU-forced test runs and the virtual multichip dryrun are
unaffected.

Mask convention: callers pass bool masks; ``_as_i8`` widens to int8 at
the call boundary (TPU VMEM has no packed bool tiling) and kernels
compare ``!= 0``.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

_BIG_I32 = 2**31 - 1


# -- routing -----------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def use_pallas() -> bool:
    """True when the Pallas kernels should serve the hot path: a real TPU
    backend and not explicitly disabled. OGTPU_PALLAS=1 forces them on
    (interpret mode off-TPU is far slower than XLA — test-only), =0 off."""
    flag = os.environ.get("OGTPU_PALLAS")
    if flag is not None:
        return flag.strip().lower() not in ("0", "false", "off", "no", "")
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _interpret() -> bool:
    """Interpret mode whenever the default backend is not a TPU — keeps the
    kernels runnable (tests, forced-on CPU) without Mosaic."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _as_i8(mask) -> jax.Array:
    return jnp.asarray(mask).astype(jnp.int8)


def _tile_g(g: int, w: int) -> int:
    """Rows-per-block: amortize per-grid-step overhead while bounding the
    VMEM footprint (~4 MB of input tiles per step at the cap). G is pow2
    >= 8 (models/ragged.py _pow2_at_least) so any pow2 tile divides it."""
    cap = max(512 * 256 // max(w, 128), 128)
    return min(g, cap)


# -- (G, W) bucket stats: basic ---------------------------------------------


def _basic_kernel(v_ref, m_ref, cnt_ref, sum_ref, mean_ref, min_ref, max_ref, ssd_ref):
    v = v_ref[...]
    m = m_ref[...] != 0
    zero = jnp.zeros((), v.dtype)
    big = jnp.array(jnp.inf, v.dtype)
    vz = jnp.where(m, v, zero)
    # explicit int32 result: under x64 the interpret-mode lowering widens
    # integer reduces to int64, which an int32 out ref rejects ("Invalid
    # dtype for swap") — the breakage devobs.backend_capabilities probes
    cnt = jnp.sum(m.astype(jnp.int32), axis=1, keepdims=True).astype(jnp.int32)
    s = jnp.sum(vz, axis=1, keepdims=True)
    mean = s / jnp.maximum(cnt, 1).astype(v.dtype)
    dev = jnp.where(m, v - mean, zero)
    cnt_ref[...] = cnt
    sum_ref[...] = s
    mean_ref[...] = mean
    min_ref[...] = jnp.min(jnp.where(m, v, big), axis=1, keepdims=True)
    max_ref[...] = jnp.max(jnp.where(m, v, -big), axis=1, keepdims=True)
    ssd_ref[...] = jnp.sum(dev * dev, axis=1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bucket_basic_call(v, m_i8, *, interpret: bool):
    from jax.experimental import pallas as pl

    g, w = v.shape
    tg = _tile_g(g, w)
    if g % tg:  # trailing rows would be silently skipped by the floor grid
        raise ValueError(f"row count {g} must be a multiple of the tile {tg}")
    col = lambda dt: jax.ShapeDtypeStruct((g, 1), dt)  # noqa: E731
    in_spec = pl.BlockSpec((tg, w), lambda i: (i, 0))
    out_spec = pl.BlockSpec((tg, 1), lambda i: (i, 0))
    outs = pl.pallas_call(
        _basic_kernel,
        grid=(g // tg,),
        in_specs=[in_spec, in_spec],
        out_specs=[out_spec] * 6,
        out_shape=[
            col(jnp.int32), col(v.dtype), col(v.dtype),
            col(v.dtype), col(v.dtype), col(v.dtype),
        ],
        interpret=interpret,
    )(v, m_i8)
    names = ("count", "sum", "mean", "min", "max", "ssd")
    return {k: o[:, 0] for k, o in zip(names, outs)}


def bucket_stats_basic(v, hi, lo, idx, m):
    """Drop-in for models/ragged._stats_jit('basic'): fused single-pass
    count/sum/mean/min/max/ssd over (G, W) bucket rows. hi/lo/idx are
    accepted (same signature) and unused."""
    return _bucket_basic_call(jnp.asarray(v), _as_i8(m), interpret=_interpret())


# -- (G, W) bucket stats: selectors ------------------------------------------


def _masked(vals, cand_i32, fill):
    """where(cand, vals, fill) in pure i32 arithmetic — Mosaic (the Pallas
    TPU compiler) rejects relayouts of combined i1 mask vectors
    ("non-singleton dimension replicated"), so candidate masks stay i32
    0/1 end-to-end and never materialize as vector<i1>."""
    return vals * cand_i32 + fill * (1 - cand_i32)


def _lex_col(hi, lo, cand, latest):
    """Column index of the lexicographically (hi, lo) extreme candidate per
    row; ties break by column order. Mirrors models/ragged._lex_col.
    ``cand`` is i32 0/1; returns i32 columns (big-valued rows = no
    candidate)."""
    big = _BIG_I32
    col = jax.lax.broadcasted_iota(jnp.int32, hi.shape, dimension=1)
    bcast = lambda x: jnp.broadcast_to(x, hi.shape)  # noqa: E731
    if latest:
        hi_ext = jnp.max(_masked(hi, cand, -big), axis=1, keepdims=True)
        c2 = cand * (hi == bcast(hi_ext)).astype(jnp.int32)
        lo_ext = jnp.max(_masked(lo, c2, -big), axis=1, keepdims=True)
        c3 = c2 * (lo == bcast(lo_ext)).astype(jnp.int32)
        return jnp.max(_masked(col, c3, -big), axis=1)
    hi_ext = jnp.min(_masked(hi, cand, big), axis=1, keepdims=True)
    c2 = cand * (hi == bcast(hi_ext)).astype(jnp.int32)
    lo_ext = jnp.min(_masked(lo, c2, big), axis=1, keepdims=True)
    c3 = c2 * (lo == bcast(lo_ext)).astype(jnp.int32)
    return jnp.min(_masked(col, c3, big), axis=1)


def _first_last_col(v, hi, lo, cand, latest):
    """first/last column pick: extreme (hi, lo) time, then exact-time ties
    take the LARGER VALUE (reference agg_func.go FirstReduce/LastReduce),
    then column order."""
    big = _BIG_I32
    col = jax.lax.broadcasted_iota(jnp.int32, hi.shape, dimension=1)
    bcast = lambda x: jnp.broadcast_to(x, hi.shape)  # noqa: E731
    if latest:
        hi_ext = jnp.max(_masked(hi, cand, -big), axis=1, keepdims=True)
        c2 = cand * (hi == bcast(hi_ext)).astype(jnp.int32)
        lo_ext = jnp.max(_masked(lo, c2, -big), axis=1, keepdims=True)
        c3 = c2 * (lo == bcast(lo_ext)).astype(jnp.int32)
    else:
        hi_ext = jnp.min(_masked(hi, cand, big), axis=1, keepdims=True)
        c2 = cand * (hi == bcast(hi_ext)).astype(jnp.int32)
        lo_ext = jnp.min(_masked(lo, c2, big), axis=1, keepdims=True)
        c3 = c2 * (lo == bcast(lo_ext)).astype(jnp.int32)
    fbig = jnp.array(jnp.inf, v.dtype)
    v_ext = jnp.max(jnp.where(c3 != 0, v, -fbig), axis=1, keepdims=True)
    c4 = c3 * (v == bcast(v_ext)).astype(jnp.int32)
    return jnp.min(_masked(col, c4, big), axis=1)


def _sel_kernel(v_ref, hi_ref, lo_ref, idx_ref, m_ref,
                first_ref, last_ref, sf_ref, sl_ref, smin_ref, smax_ref):
    v = v_ref[...]
    hi = hi_ref[...]
    lo = lo_ref[...]
    idx = idx_ref[...]
    m = m_ref[...] != 0  # direct load-compare i1 is fine; combining isn't
    m32 = m_ref[...].astype(jnp.int32)
    big = jnp.array(jnp.inf, v.dtype)
    mn = jnp.broadcast_to(
        jnp.min(jnp.where(m, v, big), axis=1, keepdims=True), v.shape
    )
    mx = jnp.broadcast_to(
        jnp.max(jnp.where(m, v, -big), axis=1, keepdims=True), v.shape
    )
    wlim = v.shape[1] - 1
    clip = lambda c: jnp.clip(c, 0, wlim)  # noqa: E731
    cf = clip(_first_last_col(v, hi, lo, m32, latest=False))
    cl = clip(_first_last_col(v, hi, lo, m32, latest=True))
    cmin = clip(_lex_col(hi, lo, m32 * (v == mn).astype(jnp.int32), latest=False))
    cmax = clip(_lex_col(hi, lo, m32 * (v == mx).astype(jnp.int32), latest=False))

    def take(mat, cols):
        # one-hot lane select: (TG, W) -> (TG, 1) without gather (TPU-
        # friendly; W <= 1024 so the one-hot mask is one VREG row set).
        # where (not multiply): a NaN value off-lane must not leak into
        # the sum; the fresh same-shape compare is a layout-safe i1.
        oh = jax.lax.broadcasted_iota(jnp.int32, mat.shape, 1) == jnp.broadcast_to(
            cols[:, None], mat.shape
        )
        # keep the reduce at the ref dtype: x64 interpret mode widens
        # integer sums to int64, which the int32 out refs reject
        return jnp.sum(jnp.where(oh, mat, jnp.zeros((), mat.dtype)),
                       axis=1, keepdims=True).astype(mat.dtype)

    first_ref[...] = take(v, cf)
    last_ref[...] = take(v, cl)
    sf_ref[...] = take(idx, cf)
    sl_ref[...] = take(idx, cl)
    smin_ref[...] = take(idx, cmin)
    smax_ref[...] = take(idx, cmax)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bucket_sel_call(v, hi, lo, idx, m_i8, *, interpret: bool):
    from jax.experimental import pallas as pl

    g, w = v.shape
    tg = _tile_g(g, w)
    if g % tg:  # trailing rows would be silently skipped by the floor grid
        raise ValueError(f"row count {g} must be a multiple of the tile {tg}")
    col = lambda dt: jax.ShapeDtypeStruct((g, 1), dt)  # noqa: E731
    in_spec = pl.BlockSpec((tg, w), lambda i: (i, 0))
    out_spec = pl.BlockSpec((tg, 1), lambda i: (i, 0))
    outs = pl.pallas_call(
        _sel_kernel,
        grid=(g // tg,),
        in_specs=[in_spec] * 5,
        out_specs=[out_spec] * 6,
        out_shape=[
            col(v.dtype), col(v.dtype), col(jnp.int32),
            col(jnp.int32), col(jnp.int32), col(jnp.int32),
        ],
        interpret=interpret,
    )(v, hi, lo, idx, m_i8)
    names = ("first", "last", "sel_first", "sel_last", "sel_min", "sel_max")
    return {k: o[:, 0] for k, o in zip(names, outs)}


def bucket_stats_selectors(v, hi, lo, idx, m):
    """Drop-in for models/ragged._stats_jit('selectors'): fused first/last
    values + first/last/min/max row-index selection in one tile pass."""
    return _bucket_sel_call(
        jnp.asarray(v), jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(idx),
        _as_i8(m), interpret=_interpret(),
    )


# -- (S, SPW, W) regular-grid window aggregation -----------------------------


def _grid_kernel(v_ref, m_ref, cnt_ref, sum_ref, mean_ref, min_ref, max_ref):
    v = v_ref[...]  # (TS, SPW, TW)
    m = m_ref[...] != 0
    zero = jnp.zeros((), v.dtype)
    big = jnp.array(jnp.inf, v.dtype)
    vz = jnp.where(m, v, zero)
    # int32 ref store under x64 interpret mode needs the explicit cast
    cnt = jnp.sum(m.astype(jnp.int32), axis=1).astype(jnp.int32)
    s = jnp.sum(vz, axis=1)
    cnt_ref[...] = cnt
    sum_ref[...] = s
    mean_ref[...] = s / jnp.maximum(cnt, 1).astype(v.dtype)
    min_ref[...] = jnp.min(jnp.where(m, v, big), axis=1)
    max_ref[...] = jnp.max(jnp.where(m, v, -big), axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _grid_call(v_t, m_i8, *, interpret: bool):
    from jax.experimental import pallas as pl

    s_dim, spw, w = v_t.shape
    ts = 8 if s_dim % 8 == 0 else 1
    tw = 512 if w % 512 == 0 else w
    grid = (s_dim // ts, w // tw)
    in_spec = pl.BlockSpec((ts, spw, tw), lambda i, j: (i, 0, j))
    out_spec = pl.BlockSpec((ts, tw), lambda i, j: (i, j))
    mat = lambda dt: jax.ShapeDtypeStruct((s_dim, w), dt)  # noqa: E731
    outs = pl.pallas_call(
        _grid_kernel,
        grid=grid,
        in_specs=[in_spec, in_spec],
        out_specs=[out_spec] * 5,
        out_shape=[
            mat(jnp.int32), mat(v_t.dtype), mat(v_t.dtype),
            mat(v_t.dtype), mat(v_t.dtype),
        ],
        interpret=interpret,
    )(v_t, m_i8)
    names = ("count", "sum", "mean", "min", "max")
    return dict(zip(names, outs))


def grid_window_agg_t(values_t, mask_t):
    """Pallas variant of ops/segment.grid_window_agg_t: same (S, SPW, W)
    windows-on-lanes layout, all five stats from one VMEM residency."""
    return _grid_call(jnp.asarray(values_t), _as_i8(mask_t), interpret=_interpret())


# -- packed-delta widen (device decode, ops/device_decode.py) ----------------


def _widen_kernel(b_ref, out_ref):
    """(cnt, width) LE bytes -> (cnt, 1) int32 little-endian combine.
    int32 is exact for the width-1/2 blocks routed here; the explicit
    astype keeps x64 interpret mode off int64 (the int32-ref rule)."""
    b = b_ref[...]
    acc = b[:, 0].astype(jnp.int32)
    for j in range(1, b.shape[1]):
        acc = acc + (b[:, j].astype(jnp.int32) << (8 * j))
    out_ref[...] = acc[:, None].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("width", "cnt", "interpret"))
def _widen_call(raw, *, width: int, cnt: int, interpret: bool):
    from jax.experimental import pallas as pl

    out = pl.pallas_call(
        _widen_kernel,
        out_shape=jax.ShapeDtypeStruct((cnt, 1), jnp.int32),
        interpret=interpret,
    )(raw.reshape(cnt, width))
    return out[:, 0]


def widen_packed(raw, width: int, cnt: int):
    """Widen `cnt` packed little-endian `width`-byte unsigned values to
    int32 — the byte-combine step of the device-side FOR-delta decode
    (ops/device_decode.py), as an explicit VMEM tile pass.  Callers
    guarantee width in (1, 2) so int32 is exact."""
    return _widen_call(jnp.asarray(raw), width=width, cnt=cnt,
                       interpret=_interpret())


# -- bit unpack (gorilla device decode, ops/device_decode.py) ----------------


def _unpack_bits_kernel(b_ref, out_ref):
    """(nbytes,) uint8 -> (nbytes, 8) int32 bits, MSB-first within each
    byte (np.unpackbits order — the gorilla stream's bit order).  int32
    out keeps x64 interpret mode off int64 (the int32-ref rule)."""
    b = b_ref[...].astype(jnp.int32)
    shifts = jnp.arange(7, -1, -1, dtype=jnp.int32)
    out_ref[...] = ((b[:, None] >> shifts) & 1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("nbytes", "interpret"))
def _unpack_bits_call(raw, *, nbytes: int, interpret: bool):
    from jax.experimental import pallas as pl

    out = pl.pallas_call(
        _unpack_bits_kernel,
        out_shape=jax.ShapeDtypeStruct((nbytes, 8), jnp.int32),
        interpret=interpret,
    )(raw)
    return out.reshape(nbytes * 8)


def unpack_bits(raw, nbytes: int):
    """Unpack `nbytes` payload bytes into a flat (nbytes*8,) int32 bit
    vector, MSB-first per byte — the bit-addressing substrate of the
    device-side gorilla decode (templated on the same probed pallas
    routing as widen_packed; ops/device_decode.py carries the jnp
    shift/mask fallback where the probe fails)."""
    return _unpack_bits_call(jnp.asarray(raw), nbytes=nbytes,
                             interpret=_interpret())
