"""PromQL range-vector functions as dense device kernels.

Reference: the store-side prom cursors + reducers
(engine/prom_range_vector_cursor.go, prom_function_reducers.go:633) which
walk samples per series per step. TPU-native design: per series the
samples live in a padded (num_series, max_samples) matrix; every step
window is resolved to [first_idx, last_idx] sample indices with a
vmap'd searchsorted, and rate/increase/delta become GATHERS + arithmetic
over the (num_series, num_steps) grid — overlapping windows cost O(1)
each via per-series prefix sums of counter-reset corrections, instead of
re-walking samples (no data duplication across steps).

Semantics follow Prometheus exactly (promql/functions.go extrapolatedRate):
  - counter resets: correction[i] = v[i-1] if v[i] < v[i-1]
  - extrapolation to window bounds, limited to 1.1x average sample
    interval, and clamped to zero-crossing for counters.

All timestamps here are int64 milliseconds (prom's unit) on the HOST;
the device sees float64/float32 seconds relative to the window start —
callers produce them via `prepare_matrix`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def prepare_matrix(series_samples: list[tuple[np.ndarray, np.ndarray]], dtype=np.float32):
    """[(times_ms int64, values f64)] -> padded matrices.

    Returns (times_s f64-as-dtype relative to base, values, counts, base_ms).
    Times must be sorted ascending per series.
    """
    S = len(series_samples)
    n_max = max((len(t) for t, _v in series_samples), default=0)
    n_max = max(n_max, 1)
    base_ms = min((int(t[0]) for t, _v in series_samples if len(t)), default=0)
    times = np.zeros((S, n_max), dtype=np.float64)
    values = np.zeros((S, n_max), dtype=dtype)
    counts = np.zeros(S, dtype=np.int32)
    for i, (t, v) in enumerate(series_samples):
        k = len(t)
        counts[i] = k
        times[i, :k] = (t - base_ms) / 1000.0
        values[i, :k] = v
        if k:  # pad tail with a huge time so searchsorted never picks it
            times[i, k:] = np.inf
        else:
            times[i, :] = np.inf
    return times, values, counts, base_ms


def prepare_matrix_runs(t_ms_all, v_all, lens, dtype=np.float32):
    """prepare_matrix over run-encoded input: one concatenated (times_ms,
    values) pair with per-series lengths, filled by ONE flat scatter — no
    per-series Python loop (the loop dominated 1M-series instant queries,
    BASELINE.md config #5)."""
    lens = np.asarray(lens, np.int64)
    S = len(lens)
    n_max = max(1, int(lens.max()) if S else 1)
    times = np.full((S, n_max), np.inf, dtype=np.float64)
    values = np.zeros((S, n_max), dtype=dtype)
    total = int(lens.sum())
    starts = np.cumsum(lens) - lens
    base_ms = 0
    if total:
        # times are ascending per series, so the global min is the min of
        # each non-empty series' first sample
        base_ms = int(t_ms_all[starts[lens > 0]].min())
        rows = np.repeat(np.arange(S, dtype=np.int64), lens)
        cols = np.arange(total, dtype=np.int64) - np.repeat(starts, lens)
        flat = rows * n_max + cols
        times.reshape(-1)[flat] = (np.asarray(t_ms_all) - base_ms) / 1000.0
        values.reshape(-1)[flat] = v_all
    return times, values, lens.astype(np.int32), base_ms


def window_bounds(times, counts, step_starts, step_ends):
    """Per (series, step) first/last sample indices inside (start, end].

    times: (S, N) seconds; step_starts/step_ends: (K,) seconds.
    Returns (first_idx, last_idx, has_samples) each (S, K).
    Prom windows are left-OPEN right-CLOSED: (t-w, t].
    """
    first_idx = _vmap_searchsorted(times, step_starts, "right")
    last_idx = _vmap_searchsorted(times, step_ends, "right") - 1
    has = (last_idx >= first_idx) & (first_idx < counts[:, None])
    return first_idx, last_idx, has


def _vmap_searchsorted(times, keys, side):
    import jax

    return jax.vmap(lambda row: jnp.searchsorted(row, keys, side=side))(times)


def _gather_rows(mat, idx):
    return jnp.take_along_axis(mat, idx, axis=1)


def reset_corrections(values, counts):
    """Per-series prefix sum of counter-reset corrections:
    C[i] = sum_{j<=i} (v[j-1] if v[j] < v[j-1] else 0). (S, N)."""
    prev = jnp.concatenate([values[:, :1], values[:, :-1]], axis=1)
    drop = jnp.where(values < prev, prev, jnp.zeros((), values.dtype))
    drop = drop.at[:, 0].set(0)
    n = values.shape[1]
    valid = jnp.arange(n)[None, :] < counts[:, None]
    return jnp.cumsum(jnp.where(valid, drop, 0), axis=1)


def extrapolated_rate(
    times, values, counts, step_starts, step_ends,
    window_s: float, is_counter: bool, is_rate: bool,
):
    """Prometheus extrapolatedRate for every (series, step).

    Returns (out (S, K), valid (S, K)); valid requires >= 2 samples in the
    window (prom semantics).
    """
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    safe_first = jnp.clip(first_idx, 0, times.shape[1] - 1)
    safe_last = jnp.clip(last_idx, 0, times.shape[1] - 1)
    t_first = _gather_rows(times, safe_first)
    t_last = _gather_rows(times, safe_last)
    v_first = _gather_rows(values, safe_first)
    v_last = _gather_rows(values, safe_last)
    n_samples = last_idx - first_idx + 1
    valid = has & (n_samples >= 2)

    delta = v_last - v_first
    if is_counter:
        cum = reset_corrections(values, counts)
        c_first = _gather_rows(cum, safe_first)
        c_last = _gather_rows(cum, safe_last)
        delta = delta + (c_last - c_first)

    # prom extrapolation (promql/functions.go extrapolatedRate)
    sampled_interval = t_last - t_first
    sampled_interval = jnp.where(sampled_interval <= 0, 1.0, sampled_interval)
    avg_interval = sampled_interval / jnp.maximum(n_samples - 1, 1).astype(times.dtype)
    dur_to_start = t_first - step_starts[None, :]
    dur_to_end = step_ends[None, :] - t_last
    extrap_threshold = avg_interval * 1.1
    dur_to_start = jnp.where(dur_to_start > extrap_threshold, avg_interval / 2, dur_to_start)
    dur_to_end = jnp.where(dur_to_end > extrap_threshold, avg_interval / 2, dur_to_end)
    if is_counter:
        # a counter cannot extrapolate below zero (prom applies this only
        # for delta > 0 AND v_first >= 0, promql/functions.go)
        dur_zero = jnp.where(
            (delta > 0) & (v_first >= 0),
            sampled_interval * (v_first / jnp.maximum(delta, 1e-30)),
            jnp.inf,
        )
        dur_to_start = jnp.minimum(dur_to_start, dur_zero)
    extrapolated = sampled_interval + dur_to_start + dur_to_end
    out = delta.astype(times.dtype) * (extrapolated / sampled_interval)
    if is_rate:
        out = out / window_s
    return out, valid


def over_time(times, values, counts, step_starts, step_ends, func: str):
    """xxx_over_time functions: avg/min/max/sum/count/last. (S, K).

    sum/avg/count/last use the O(S*K) prefix-sum+gather scheme (no dense
    (S, K, N) tensor). min/max have no prefix form; they use a dense
    window-membership tensor computed in step CHUNKS so peak memory stays
    bounded at S * 256 * N booleans.
    """
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    n = times.shape[1]
    if func in ("sum", "avg", "count", "last"):
        if func == "last":
            safe_last = jnp.clip(last_idx, 0, n - 1)
            return _gather_rows(values, safe_last), has
        valid_cols = jnp.arange(n)[None, :] < counts[:, None]
        csum = jnp.cumsum(jnp.where(valid_cols, values, 0), axis=1)
        csum = jnp.concatenate([jnp.zeros_like(csum[:, :1]), csum], axis=1)  # (S, N+1)
        safe_f = jnp.clip(first_idx, 0, n)
        safe_l1 = jnp.clip(last_idx + 1, 0, n)
        wsum = _gather_rows(csum, safe_l1) - _gather_rows(csum, safe_f)
        wcnt = (last_idx - first_idx + 1).astype(values.dtype)
        wcnt = jnp.where(has, wcnt, 0)
        if func == "count":
            return wcnt, has
        if func == "sum":
            return jnp.where(has, wsum, 0), has
        return jnp.where(has, wsum, 0) / jnp.maximum(wcnt, 1), has
    if func in ("stddev", "stdvar"):
        # population variance over window samples (prom funcStddevOverTime)
        # via prefix sums. Variance is shift-invariant, so values are
        # centered on the per-series mean FIRST: raw v^2 prefix sums over
        # a long series of large-magnitude samples (e.g. ~1.7e9 unix-
        # timestamp gauges) reach ~3e22 and the window difference loses
        # every significant digit (verified: naive form returned -4e5
        # where the true variance was 0.65)
        valid_cols = jnp.arange(n)[None, :] < counts[:, None]
        vz_raw = jnp.where(valid_cols, values, 0)
        series_n = jnp.maximum(counts, 1).astype(values.dtype)[:, None]
        center = vz_raw.sum(axis=1, keepdims=True) / series_n
        vz = jnp.where(valid_cols, values - center, 0)
        c1 = jnp.cumsum(vz, axis=1)
        c2 = jnp.cumsum(vz * vz, axis=1)
        zcol = jnp.zeros_like(c1[:, :1])
        c1 = jnp.concatenate([zcol, c1], axis=1)
        c2 = jnp.concatenate([zcol, c2], axis=1)
        safe_f = jnp.clip(first_idx, 0, n)
        safe_l1 = jnp.clip(last_idx + 1, 0, n)
        ws = _gather_rows(c1, safe_l1) - _gather_rows(c1, safe_f)
        wss = _gather_rows(c2, safe_l1) - _gather_rows(c2, safe_f)
        wcnt = jnp.where(has, (last_idx - first_idx + 1), 0).astype(values.dtype)
        denom = jnp.maximum(wcnt, 1)
        mean = ws / denom
        var = jnp.maximum(wss / denom - mean * mean, 0)
        out = var if func == "stdvar" else jnp.sqrt(var)
        return jnp.where(has, out, 0), has
    if func == "present":
        return jnp.where(has, 1.0, 0.0).astype(values.dtype), has
    if func in ("min", "max"):
        k = step_starts.shape[0]
        chunk = 256
        outs = []
        fill = jnp.inf if func == "min" else -jnp.inf
        for c0 in range(0, k, chunk):
            in_win, v = _window_tensor(times, values, counts, first_idx,
                                       last_idx, c0, chunk)
            if func == "min":
                outs.append(jnp.where(in_win, v, fill).min(axis=2))
            else:
                outs.append(jnp.where(in_win, v, fill).max(axis=2))
        return jnp.concatenate(outs, axis=1), has
    raise ValueError(f"unsupported over_time func {func!r}")


def _window_tensor(times, values, counts, first_idx, last_idx, c0, chunk):
    """Masked (S, C, N) membership view for one step chunk: (in_win, v)."""
    n = values.shape[1]
    fi = first_idx[:, c0 : c0 + chunk, None]
    li = last_idx[:, c0 : c0 + chunk, None]
    col = jnp.arange(n)[None, None, :]
    in_win = (col >= fi) & (col <= li) & (col < counts[:, None, None])
    return in_win, values[:, None, :]


def quantile_over_time(times, values, counts, step_starts, step_ends, q: float):
    """phi-quantile with linear interpolation over window samples (prom
    funcQuantileOverTime). Dense chunked like min/max; NaN-padded windows
    + nanquantile keep the masked samples out."""
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    k = step_starts.shape[0]
    chunk = 256
    outs = []
    for c0 in range(0, k, chunk):
        in_win, v = _window_tensor(times, values, counts, first_idx, last_idx, c0, chunk)
        vw = jnp.where(in_win, v, jnp.nan)
        outs.append(jnp.nanquantile(vw, jnp.clip(q, 0.0, 1.0), axis=2))
    out = jnp.concatenate(outs, axis=1)
    if q < 0:
        out = jnp.full_like(out, -jnp.inf)
    elif q > 1:
        out = jnp.full_like(out, jnp.inf)
    return out, has


def mad_over_time(times, values, counts, step_starts, step_ends):
    """median(|v - median(v)|) over window samples (prom mad_over_time)."""
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    k = step_starts.shape[0]
    chunk = 128  # two dense passes live at once
    outs = []
    for c0 in range(0, k, chunk):
        in_win, v = _window_tensor(times, values, counts, first_idx, last_idx, c0, chunk)
        vw = jnp.where(in_win, v, jnp.nan)
        med = jnp.nanmedian(vw, axis=2, keepdims=True)
        outs.append(jnp.nanmedian(jnp.abs(vw - med), axis=2))
    return jnp.concatenate(outs, axis=1), has


def linear_regression(times, values, counts, step_starts, step_ends):
    """Per-(series, step) least-squares over window samples, centered at
    the window END (the prom eval time): returns (slope per second,
    intercept at eval time, has_2plus). deriv() is the slope;
    predict_linear(v, d) = intercept + slope * d
    (prom promql/functions.go linearRegression)."""
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    k = step_starts.shape[0]
    chunk = 128
    slopes, intercepts = [], []
    for c0 in range(0, k, chunk):
        in_win, v = _window_tensor(times, values, counts, first_idx, last_idx, c0, chunk)
        t_rel = times[:, None, :] - step_ends[None, c0 : c0 + chunk, None]
        tw = jnp.where(in_win, t_rel, 0.0)
        vw = jnp.where(in_win, v, 0.0)
        cnt = in_win.sum(axis=2).astype(values.dtype)
        denom_n = jnp.maximum(cnt, 1)
        st = tw.sum(axis=2)
        sv = vw.sum(axis=2)
        stt = (tw * tw).sum(axis=2)
        stv = (tw * vw).sum(axis=2)
        cov = stv - st * sv / denom_n
        var = stt - st * st / denom_n
        slope = cov / jnp.where(var == 0, 1.0, var)
        slope = jnp.where(var == 0, 0.0, slope)
        intercept = sv / denom_n - slope * (st / denom_n)
        slopes.append(slope)
        intercepts.append(intercept)
    first_t = _gather_rows(times, jnp.clip(first_idx, 0, times.shape[1] - 1))
    last_t = _gather_rows(times, jnp.clip(last_idx, 0, times.shape[1] - 1))
    has2 = has & (last_t > first_t)
    return (jnp.concatenate(slopes, axis=1), jnp.concatenate(intercepts, axis=1),
            has2)


def holt_winters_window(times, values, counts, step_starts, step_ends,
                        sf: float, tf: float):
    """Prom double exponential smoothing per window
    (funcHoltWinters/double_exponential_smoothing): sequential over the
    window's samples — a lax.scan across the sample axis carrying
    (level, trend) per (series, step), masked to each window's members.
    Windows with <2 samples yield no result."""
    from jax import lax

    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    vj = jnp.asarray(values)  # dynamic scan indexing needs a jax array
    n = values.shape[1]
    k = step_starts.shape[0]
    chunk = 128
    outs, valids = [], []
    for c0 in range(0, k, chunk):
        in_win, _v = _window_tensor(times, values, counts, first_idx, last_idx,
                                    c0, chunk)
        shape = in_win[:, :, 0].shape  # (S, C)

        def body(carry, i):
            # prom recurrence (funcDoubleExponentialSmoothing): sample 0
            # seeds the level; sample 1 seeds the trend then smooths with
            # it; sample j>=2 first updates the trend from the two
            # PREVIOUS levels, then smooths. Result = final level.
            s_prev, s_curr, b, seen = carry
            x = jnp.broadcast_to(vj[:, i][:, None], shape)
            m = in_win[:, :, i]
            is_first = m & (seen == 0)
            is_second = m & (seen == 1)
            later = m & (seen >= 2)
            b_new = jnp.where(later, tf * (s_curr - s_prev) + (1 - tf) * b, b)
            b_new = jnp.where(is_second, x - s_curr, b_new)
            smooth = sf * x + (1 - sf) * (s_curr + b_new)
            upd = is_second | later
            new_s_prev = jnp.where(upd, s_curr, s_prev)
            new_s_curr = jnp.where(upd, smooth, jnp.where(is_first, x, s_curr))
            return (new_s_prev, new_s_curr, b_new,
                    seen + m.astype(jnp.int32)), None

        z = jnp.zeros(shape, values.dtype)
        (s_prev, s_curr, b, seen), _ = lax.scan(
            body, (z, z, z, jnp.zeros(shape, jnp.int32)), jnp.arange(n)
        )
        outs.append(s_curr)
        valids.append(seen >= 2)
    return (jnp.concatenate(outs, axis=1),
            has & jnp.concatenate(valids, axis=1))


def changes_resets(times, values, counts, step_starts, step_ends, kind: str):
    """changes()/resets() per (series, step): transitions between
    consecutive in-window samples, via prefix sums of per-pair indicators
    (prom promql/functions.go funcChanges/funcResets)."""
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    n = values.shape[1]
    prev = jnp.concatenate([values[:, :1], values[:, :-1]], axis=1)
    if kind == "changes":
        ind = (values != prev).astype(values.dtype)
    else:  # resets
        ind = (values < prev).astype(values.dtype)
    ind = ind.at[:, 0].set(0)
    valid_cols = jnp.arange(n)[None, :] < counts[:, None]
    cum = jnp.cumsum(jnp.where(valid_cols, ind, 0), axis=1)
    cum = jnp.concatenate([jnp.zeros_like(cum[:, :1]), cum], axis=1)  # (S, N+1)
    safe_f = jnp.clip(first_idx + 1, 0, n)  # pairs with i in (first, last]
    safe_l1 = jnp.clip(last_idx + 1, 0, n)
    out = _gather_rows(cum, safe_l1) - _gather_rows(cum, safe_f)
    valid = has & (last_idx >= first_idx)
    return jnp.where(valid, out, 0), valid


def instant_values(times, values, counts, eval_times, lookback_s: float = 300.0):
    """Instant vector selection: latest sample within [t - lookback, t].
    Returns (vals (S, K), valid (S, K)) — prom staleness semantics (without
    explicit staleness markers, which the influx data model doesn't carry).
    """
    idx = _vmap_searchsorted(times, eval_times, "right") - 1
    safe = jnp.clip(idx, 0, times.shape[1] - 1)
    t_at = _gather_rows(times, safe)
    v_at = _gather_rows(values, safe)
    valid = (idx >= 0) & (t_at >= eval_times[None, :] - lookback_s) & (
        idx < counts[:, None]
    )
    return v_at, valid
