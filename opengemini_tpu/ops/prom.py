"""PromQL range-vector functions: tiled interval reductions + dense kernels.

Reference: the store-side prom cursors + reducers
(engine/prom_range_vector_cursor.go, prom_function_reducers.go:633) which
walk samples per series per step.

Two generations live here:

  * The TILED engine (TilePlan / TiledPrepared, bottom of the module —
    the production path): time-interval-centric batch operators in the
    TiLT style (arXiv:2301.12030).  Window edges define a ms tile
    lattice, samples bucket by integer arithmetic, and every
    (series, step) window answers from cumulative tile prefixes plus two
    boundary refinements — O(1) per window, no searchsorted, no dense
    membership tensors.  One xp-generic code path runs as host numpy,
    eager jax.numpy, or traced under jit (the bench harness compiles
    it; the engine's accelerator path is eager today).

  * The DENSE kernels (top of the module): padded (num_series,
    max_samples) matrices, vmap'd searchsorted window bounds, chunked
    (S, chunk, N) membership tensors for the non-prefix-able forms.
    They remain as the fallback for window grids the tile lattice cannot
    express (sub-ms edges, over-budget tile counts) and for
    quantile/mad/holt_winters, and as the in-bench/test reference the
    tiled engine is equality-gated against.

Semantics follow Prometheus exactly (promql/functions.go extrapolatedRate):
  - counter resets: correction[i] = v[i-1] if v[i] < v[i-1], restricted
    to sample pairs fully inside the window
  - extrapolation to window bounds, limited to 1.1x average sample
    interval, and clamped to zero-crossing for counters.

All timestamps here are int64 milliseconds (prom's unit) on the HOST;
kernels see float seconds relative to a base — callers produce them via
`prepare_matrix_runs` (dense) or `prepare_tiled`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def prepare_matrix(series_samples: list[tuple[np.ndarray, np.ndarray]], dtype=np.float32):
    """[(times_ms int64, values f64)] -> padded matrices.

    Returns (times_s f64-as-dtype relative to base, values, counts, base_ms).
    Times must be sorted ascending per series.
    """
    S = len(series_samples)
    n_max = max((len(t) for t, _v in series_samples), default=0)
    n_max = max(n_max, 1)
    base_ms = min((int(t[0]) for t, _v in series_samples if len(t)), default=0)
    times = np.zeros((S, n_max), dtype=np.float64)
    values = np.zeros((S, n_max), dtype=dtype)
    counts = np.zeros(S, dtype=np.int32)
    for i, (t, v) in enumerate(series_samples):
        k = len(t)
        counts[i] = k
        times[i, :k] = (t - base_ms) / 1000.0
        values[i, :k] = v
        if k:  # pad tail with a huge time so searchsorted never picks it
            times[i, k:] = np.inf
        else:
            times[i, :] = np.inf
    return times, values, counts, base_ms


def prepare_matrix_runs(t_ms_all, v_all, lens, dtype=np.float32):
    """prepare_matrix over run-encoded input: one concatenated (times_ms,
    values) pair with per-series lengths, filled by ONE flat scatter — no
    per-series Python loop (the loop dominated 1M-series instant queries,
    BASELINE.md config #5)."""
    lens = np.asarray(lens, np.int64)
    S = len(lens)
    n_max = max(1, int(lens.max()) if S else 1)
    times = np.full((S, n_max), np.inf, dtype=np.float64)
    # v_all None = still-encoded values (TiledPrepared enc mode): only
    # the time/count structure is prepared; the value matrix fills
    # lazily (host fallback) or decodes on device (ops/device_decode)
    values = None if v_all is None else np.zeros((S, n_max), dtype=dtype)
    total = int(lens.sum())
    starts = np.cumsum(lens) - lens
    base_ms = 0
    if total:
        # times are ascending per series, so the global min is the min of
        # each non-empty series' first sample
        base_ms = int(t_ms_all[starts[lens > 0]].min())
        rows = np.repeat(np.arange(S, dtype=np.int64), lens)
        cols = np.arange(total, dtype=np.int64) - np.repeat(starts, lens)
        flat = rows * n_max + cols
        times.reshape(-1)[flat] = (np.asarray(t_ms_all) - base_ms) / 1000.0
        if values is not None:
            values.reshape(-1)[flat] = v_all
    return times, values, lens.astype(np.int32), base_ms


def window_bounds(times, counts, step_starts, step_ends):
    """Per (series, step) first/last sample indices inside (start, end].

    times: (S, N) seconds; step_starts/step_ends: (K,) seconds.
    Returns (first_idx, last_idx, has_samples) each (S, K).
    Prom windows are left-OPEN right-CLOSED: (t-w, t].
    """
    first_idx = _vmap_searchsorted(times, step_starts, "right")
    last_idx = _vmap_searchsorted(times, step_ends, "right") - 1
    has = (last_idx >= first_idx) & (first_idx < counts[:, None])
    return first_idx, last_idx, has


def _vmap_searchsorted(times, keys, side):
    import jax

    return jax.vmap(lambda row: jnp.searchsorted(row, keys, side=side))(times)


def _gather_rows(mat, idx):
    return jnp.take_along_axis(mat, idx, axis=1)


def reset_corrections(values, counts):
    """Per-series prefix sum of counter-reset corrections:
    C[i] = sum_{j<=i} (v[j-1] if v[j] < v[j-1] else 0). (S, N)."""
    prev = jnp.concatenate([values[:, :1], values[:, :-1]], axis=1)
    drop = jnp.where(values < prev, prev, jnp.zeros((), values.dtype))
    drop = drop.at[:, 0].set(0)
    n = values.shape[1]
    valid = jnp.arange(n)[None, :] < counts[:, None]
    return jnp.cumsum(jnp.where(valid, drop, 0), axis=1)


def extrapolated_rate(
    times, values, counts, step_starts, step_ends,
    window_s: float, is_counter: bool, is_rate: bool,
):
    """Prometheus extrapolatedRate for every (series, step).

    Returns (out (S, K), valid (S, K)); valid requires >= 2 samples in the
    window (prom semantics).
    """
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    safe_first = jnp.clip(first_idx, 0, times.shape[1] - 1)
    safe_last = jnp.clip(last_idx, 0, times.shape[1] - 1)
    t_first = _gather_rows(times, safe_first)
    t_last = _gather_rows(times, safe_last)
    v_first = _gather_rows(values, safe_first)
    v_last = _gather_rows(values, safe_last)
    n_samples = last_idx - first_idx + 1
    valid = has & (n_samples >= 2)

    delta = v_last - v_first
    if is_counter:
        cum = reset_corrections(values, counts)
        c_first = _gather_rows(cum, safe_first)
        c_last = _gather_rows(cum, safe_last)
        delta = delta + (c_last - c_first)

    # prom extrapolation (promql/functions.go extrapolatedRate)
    sampled_interval = t_last - t_first
    sampled_interval = jnp.where(sampled_interval <= 0, 1.0, sampled_interval)
    avg_interval = sampled_interval / jnp.maximum(n_samples - 1, 1).astype(times.dtype)
    dur_to_start = t_first - step_starts[None, :]
    dur_to_end = step_ends[None, :] - t_last
    extrap_threshold = avg_interval * 1.1
    dur_to_start = jnp.where(dur_to_start > extrap_threshold, avg_interval / 2, dur_to_start)
    dur_to_end = jnp.where(dur_to_end > extrap_threshold, avg_interval / 2, dur_to_end)
    if is_counter:
        # a counter cannot extrapolate below zero (prom applies this only
        # for delta > 0 AND v_first >= 0, promql/functions.go)
        dur_zero = jnp.where(
            (delta > 0) & (v_first >= 0),
            sampled_interval * (v_first / jnp.maximum(delta, 1e-30)),
            jnp.inf,
        )
        dur_to_start = jnp.minimum(dur_to_start, dur_zero)
    extrapolated = sampled_interval + dur_to_start + dur_to_end
    out = delta.astype(times.dtype) * (extrapolated / sampled_interval)
    if is_rate:
        out = out / window_s
    return out, valid


def over_time(times, values, counts, step_starts, step_ends, func: str):
    """xxx_over_time functions: avg/min/max/sum/count/last. (S, K).

    sum/avg/count/last use the O(S*K) prefix-sum+gather scheme (no dense
    (S, K, N) tensor). min/max have no prefix form; they use a dense
    window-membership tensor computed in step CHUNKS so peak memory stays
    bounded at S * 256 * N booleans.
    """
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    n = times.shape[1]
    if func in ("sum", "avg", "count", "last"):
        if func == "last":
            safe_last = jnp.clip(last_idx, 0, n - 1)
            return _gather_rows(values, safe_last), has
        valid_cols = jnp.arange(n)[None, :] < counts[:, None]
        csum = jnp.cumsum(jnp.where(valid_cols, values, 0), axis=1)
        csum = jnp.concatenate([jnp.zeros_like(csum[:, :1]), csum], axis=1)  # (S, N+1)
        safe_f = jnp.clip(first_idx, 0, n)
        safe_l1 = jnp.clip(last_idx + 1, 0, n)
        wsum = _gather_rows(csum, safe_l1) - _gather_rows(csum, safe_f)
        wcnt = (last_idx - first_idx + 1).astype(values.dtype)
        wcnt = jnp.where(has, wcnt, 0)
        if func == "count":
            return wcnt, has
        if func == "sum":
            return jnp.where(has, wsum, 0), has
        return jnp.where(has, wsum, 0) / jnp.maximum(wcnt, 1), has
    if func in ("stddev", "stdvar"):
        # population variance over window samples (prom funcStddevOverTime)
        # via prefix sums. Variance is shift-invariant, so values are
        # centered on the per-series mean FIRST: raw v^2 prefix sums over
        # a long series of large-magnitude samples (e.g. ~1.7e9 unix-
        # timestamp gauges) reach ~3e22 and the window difference loses
        # every significant digit (verified: naive form returned -4e5
        # where the true variance was 0.65)
        valid_cols = jnp.arange(n)[None, :] < counts[:, None]
        vz_raw = jnp.where(valid_cols, values, 0)
        series_n = jnp.maximum(counts, 1).astype(values.dtype)[:, None]
        center = vz_raw.sum(axis=1, keepdims=True) / series_n
        vz = jnp.where(valid_cols, values - center, 0)
        c1 = jnp.cumsum(vz, axis=1)
        c2 = jnp.cumsum(vz * vz, axis=1)
        zcol = jnp.zeros_like(c1[:, :1])
        c1 = jnp.concatenate([zcol, c1], axis=1)
        c2 = jnp.concatenate([zcol, c2], axis=1)
        safe_f = jnp.clip(first_idx, 0, n)
        safe_l1 = jnp.clip(last_idx + 1, 0, n)
        ws = _gather_rows(c1, safe_l1) - _gather_rows(c1, safe_f)
        wss = _gather_rows(c2, safe_l1) - _gather_rows(c2, safe_f)
        wcnt = jnp.where(has, (last_idx - first_idx + 1), 0).astype(values.dtype)
        denom = jnp.maximum(wcnt, 1)
        mean = ws / denom
        var = jnp.maximum(wss / denom - mean * mean, 0)
        out = var if func == "stdvar" else jnp.sqrt(var)
        return jnp.where(has, out, 0), has
    if func == "present":
        return jnp.where(has, 1.0, 0.0).astype(values.dtype), has
    if func in ("min", "max"):
        k = step_starts.shape[0]
        chunk = 256
        outs = []
        fill = jnp.inf if func == "min" else -jnp.inf
        for c0 in range(0, k, chunk):
            in_win, v = _window_tensor(times, values, counts, first_idx,
                                       last_idx, c0, chunk)
            if func == "min":
                outs.append(jnp.where(in_win, v, fill).min(axis=2))
            else:
                outs.append(jnp.where(in_win, v, fill).max(axis=2))
        return jnp.concatenate(outs, axis=1), has
    raise ValueError(f"unsupported over_time func {func!r}")


def _window_tensor(times, values, counts, first_idx, last_idx, c0, chunk):
    """Masked (S, C, N) membership view for one step chunk: (in_win, v)."""
    n = values.shape[1]
    fi = first_idx[:, c0 : c0 + chunk, None]
    li = last_idx[:, c0 : c0 + chunk, None]
    col = jnp.arange(n)[None, None, :]
    in_win = (col >= fi) & (col <= li) & (col < counts[:, None, None])
    return in_win, values[:, None, :]


def quantile_over_time(times, values, counts, step_starts, step_ends, q: float):
    """phi-quantile with linear interpolation over window samples (prom
    funcQuantileOverTime). Dense chunked like min/max; NaN-padded windows
    + nanquantile keep the masked samples out."""
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    k = step_starts.shape[0]
    chunk = 256
    outs = []
    for c0 in range(0, k, chunk):
        in_win, v = _window_tensor(times, values, counts, first_idx, last_idx, c0, chunk)
        vw = jnp.where(in_win, v, jnp.nan)
        outs.append(jnp.nanquantile(vw, jnp.clip(q, 0.0, 1.0), axis=2))
    out = jnp.concatenate(outs, axis=1)
    if q < 0:
        out = jnp.full_like(out, -jnp.inf)
    elif q > 1:
        out = jnp.full_like(out, jnp.inf)
    return out, has


def mad_over_time(times, values, counts, step_starts, step_ends):
    """median(|v - median(v)|) over window samples (prom mad_over_time)."""
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    k = step_starts.shape[0]
    chunk = 128  # two dense passes live at once
    outs = []
    for c0 in range(0, k, chunk):
        in_win, v = _window_tensor(times, values, counts, first_idx, last_idx, c0, chunk)
        vw = jnp.where(in_win, v, jnp.nan)
        med = jnp.nanmedian(vw, axis=2, keepdims=True)
        outs.append(jnp.nanmedian(jnp.abs(vw - med), axis=2))
    return jnp.concatenate(outs, axis=1), has


def linear_regression(times, values, counts, step_starts, step_ends):
    """Per-(series, step) least-squares over window samples, centered at
    the window END (the prom eval time): returns (slope per second,
    intercept at eval time, has_2plus). deriv() is the slope;
    predict_linear(v, d) = intercept + slope * d
    (prom promql/functions.go linearRegression)."""
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    k = step_starts.shape[0]
    chunk = 128
    slopes, intercepts = [], []
    for c0 in range(0, k, chunk):
        in_win, v = _window_tensor(times, values, counts, first_idx, last_idx, c0, chunk)
        t_rel = times[:, None, :] - step_ends[None, c0 : c0 + chunk, None]
        tw = jnp.where(in_win, t_rel, 0.0)
        vw = jnp.where(in_win, v, 0.0)
        cnt = in_win.sum(axis=2).astype(values.dtype)
        denom_n = jnp.maximum(cnt, 1)
        st = tw.sum(axis=2)
        sv = vw.sum(axis=2)
        stt = (tw * tw).sum(axis=2)
        stv = (tw * vw).sum(axis=2)
        cov = stv - st * sv / denom_n
        var = stt - st * st / denom_n
        slope = cov / jnp.where(var == 0, 1.0, var)
        slope = jnp.where(var == 0, 0.0, slope)
        intercept = sv / denom_n - slope * (st / denom_n)
        slopes.append(slope)
        intercepts.append(intercept)
    first_t = _gather_rows(times, jnp.clip(first_idx, 0, times.shape[1] - 1))
    last_t = _gather_rows(times, jnp.clip(last_idx, 0, times.shape[1] - 1))
    has2 = has & (last_t > first_t)
    return (jnp.concatenate(slopes, axis=1), jnp.concatenate(intercepts, axis=1),
            has2)


def holt_winters_window(times, values, counts, step_starts, step_ends,
                        sf: float, tf: float):
    """Prom double exponential smoothing per window
    (funcHoltWinters/double_exponential_smoothing): sequential over the
    window's samples — a lax.scan across the sample axis carrying
    (level, trend) per (series, step), masked to each window's members.
    Windows with <2 samples yield no result."""
    from jax import lax

    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    vj = jnp.asarray(values)  # dynamic scan indexing needs a jax array
    n = values.shape[1]
    k = step_starts.shape[0]
    chunk = 128
    outs, valids = [], []
    for c0 in range(0, k, chunk):
        in_win, _v = _window_tensor(times, values, counts, first_idx, last_idx,
                                    c0, chunk)
        shape = in_win[:, :, 0].shape  # (S, C)

        def body(carry, i):
            # prom recurrence (funcDoubleExponentialSmoothing): sample 0
            # seeds the level; sample 1 seeds the trend then smooths with
            # it; sample j>=2 first updates the trend from the two
            # PREVIOUS levels, then smooths. Result = final level.
            s_prev, s_curr, b, seen = carry
            x = jnp.broadcast_to(vj[:, i][:, None], shape)
            m = in_win[:, :, i]
            is_first = m & (seen == 0)
            is_second = m & (seen == 1)
            later = m & (seen >= 2)
            b_new = jnp.where(later, tf * (s_curr - s_prev) + (1 - tf) * b, b)
            b_new = jnp.where(is_second, x - s_curr, b_new)
            smooth = sf * x + (1 - sf) * (s_curr + b_new)
            upd = is_second | later
            new_s_prev = jnp.where(upd, s_curr, s_prev)
            new_s_curr = jnp.where(upd, smooth, jnp.where(is_first, x, s_curr))
            return (new_s_prev, new_s_curr, b_new,
                    seen + m.astype(jnp.int32)), None

        z = jnp.zeros(shape, values.dtype)
        (s_prev, s_curr, b, seen), _ = lax.scan(
            body, (z, z, z, jnp.zeros(shape, jnp.int32)), jnp.arange(n)
        )
        outs.append(s_curr)
        valids.append(seen >= 2)
    return (jnp.concatenate(outs, axis=1),
            has & jnp.concatenate(valids, axis=1))


def changes_resets(times, values, counts, step_starts, step_ends, kind: str):
    """changes()/resets() per (series, step): transitions between
    consecutive in-window samples, via prefix sums of per-pair indicators
    (prom promql/functions.go funcChanges/funcResets)."""
    first_idx, last_idx, has = window_bounds(times, counts, step_starts, step_ends)
    n = values.shape[1]
    prev = jnp.concatenate([values[:, :1], values[:, :-1]], axis=1)
    if kind == "changes":
        ind = (values != prev).astype(values.dtype)
    else:  # resets
        ind = (values < prev).astype(values.dtype)
    ind = ind.at[:, 0].set(0)
    valid_cols = jnp.arange(n)[None, :] < counts[:, None]
    cum = jnp.cumsum(jnp.where(valid_cols, ind, 0), axis=1)
    cum = jnp.concatenate([jnp.zeros_like(cum[:, :1]), cum], axis=1)  # (S, N+1)
    safe_f = jnp.clip(first_idx + 1, 0, n)  # pairs with i in (first, last]
    safe_l1 = jnp.clip(last_idx + 1, 0, n)
    out = _gather_rows(cum, safe_l1) - _gather_rows(cum, safe_f)
    valid = has & (last_idx >= first_idx)
    return jnp.where(valid, out, 0), valid


def instant_rate(times, values, counts, starts, ends, per_second: bool):
    """irate/idelta from the last two samples in each (series, step)
    window (prom funcIrate/funcIdelta).  Dense fallback form (searchsorted
    bounds); the tiled form lives on TiledPrepared.instant_rate."""
    first_idx, last_idx, has = window_bounds(times, counts, starts, ends)
    n = times.shape[1]
    prev_idx = jnp.clip(last_idx - 1, 0, n - 1)
    safe_last = jnp.clip(last_idx, 0, n - 1)
    valid = has & (last_idx - first_idx >= 1)
    v_last = _gather_rows(values, safe_last)
    v_prev = _gather_rows(values, prev_idx)
    t_last = _gather_rows(times, safe_last)
    t_prev = _gather_rows(times, prev_idx)
    dv = v_last - v_prev
    if per_second:
        dv = jnp.where(dv < 0, v_last, dv)  # counter reset
        dt = jnp.maximum(t_last - t_prev, 1e-9)
        return dv / dt, valid
    return dv, valid


def instant_values(times, values, counts, eval_times, lookback_s: float = 300.0):
    """Instant vector selection: latest sample within [t - lookback, t].
    Returns (vals (S, K), valid (S, K)) — prom staleness semantics (without
    explicit staleness markers, which the influx data model doesn't carry).
    """
    idx = _vmap_searchsorted(times, eval_times, "right") - 1
    safe = jnp.clip(idx, 0, times.shape[1] - 1)
    t_at = _gather_rows(times, safe)
    v_at = _gather_rows(values, safe)
    valid = (idx >= 0) & (t_at >= eval_times[None, :] - lookback_s) & (
        idx < counts[:, None]
    )
    return v_at, valid


# ---------------------------------------------------------------------------
# Time-centric tiled range-vector engine (TiLT, arXiv:2301.12030).
#
# The kernels above resolve every (series, step) window with a vmap'd
# searchsorted and, for min/max, dense (S, 256, N) membership tensors —
# per-series/per-sample lookups that lose an order of magnitude on every
# backend (the measured prom_rate_10k 50x hole).  The tiled engine replaces
# them with time-interval-centric batch operators:
#
#   1. All window edges of one range query live on a millisecond lattice;
#      g = gcd of the edge spacings defines a fixed grid of
#      left-open/right-closed time tiles (t0 + i*g, t0 + (i+1)*g], so every
#      window (s, e] is an EXACT union of w/g consecutive tiles — no
#      boundary sample ever straddles a window edge's tile.
#   2. Samples bucket onto tiles by integer arithmetic on their ms
#      timestamps ((t - t0 - 1) // g — no searchsorted anywhere), giving
#      per-(series, tile) sample-count prefixes; the first/last sample
#      index of ANY window is a prefix lookup at its edge tiles.
#   3. Per-(series, tile) partials (sum, sum-of-squares, min, max,
#      counter-reset drops, change/reset pair indicators) are masked
#      reductions over a compact gather of ONLY the tiles any window
#      covers (the want_sel-pruning idea from the grid path: a
#      step>window range query touches a fraction of the samples).
#   4. Every window then answers from cumulative tile prefixes
#      (ops/segment.py tile_window_sums / tile_sliding_extreme) plus two
#      boundary refinements: the pair quantities (counter resets, changes)
#      subtract the one pair that straddles the window start, and
#      first/last values gather at the prefix-resolved sample indices.
#
# The same code answers in numpy (host path — CPU backends skip jax
# dispatch and per-shape compiles entirely) or traces under jit with
# xp=jax.numpy (device path), so host/device parity holds by construction.
# ---------------------------------------------------------------------------

_MS_PER_S = 1000


class TilePlan:
    """Time-tile grid for one range query: all window edges on the
    anchor + i*g_ms lattice.  Built host-side by plan_tiles (None when the
    query is ineligible and must take the dense fallback path)."""

    __slots__ = ("g_ms", "anchor_ms", "num_tiles", "a_idx", "b_idx",
                 "win_tiles", "cov", "tile2c", "ca", "cb", "window_s")

    def __init__(self, g_ms, anchor_ms, num_tiles, a_idx, b_idx, win_tiles,
                 cov, tile2c, ca, cb, window_s):
        self.g_ms = g_ms
        self.anchor_ms = anchor_ms
        self.num_tiles = num_tiles
        self.a_idx = a_idx      # (K,) start-edge tile index per window
        self.b_idx = b_idx      # (K,) end-edge tile index per window
        self.win_tiles = win_tiles  # tiles per window (w == win_tiles * g)
        self.cov = cov          # sorted covered tile ids, (C,)
        self.tile2c = tile2c    # tile id -> compact position (or -1)
        self.ca = ca            # (K,) compact start position per window
        self.cb = cb            # (K,) compact end position (exclusive)
        self.window_s = window_s


def plan_tiles(starts_s, ends_s, tmin_ms: int, tmax_ms: int,
               max_tiles: int) -> "TilePlan | None":
    """Tile grid for windows (starts_s[k], ends_s[k]] (seconds, shared
    width).  Returns None when ineligible: edges off the ms lattice,
    non-constant width, or a grid larger than max_tiles (the dense path
    stays correct for those)."""
    starts_s = np.asarray(starts_s, np.float64)
    ends_s = np.asarray(ends_s, np.float64)
    if starts_s.size == 0 or not (
            np.isfinite(starts_s).all() and np.isfinite(ends_s).all()):
        return None
    s_ms = np.rint(starts_s * _MS_PER_S)
    e_ms = np.rint(ends_s * _MS_PER_S)
    # edges must be exactly on the ms lattice (sub-ms windows keep the
    # float-comparison fallback: quantizing them would MOVE a boundary)
    if (np.abs(s_ms - starts_s * _MS_PER_S).max() > 1e-6
            or np.abs(e_ms - ends_s * _MS_PER_S).max() > 1e-6):
        return None
    s_ms = s_ms.astype(np.int64)
    e_ms = e_ms.astype(np.int64)
    w_ms = e_ms - s_ms
    if (w_ms != w_ms[0]).any() or w_ms[0] <= 0:
        return None
    edges = np.unique(np.concatenate([s_ms, e_ms]))
    g_ms = int(np.gcd.reduce(np.diff(edges))) if len(edges) > 1 else int(w_ms[0])
    anchor_ms = int(edges[0])
    if tmin_ms <= anchor_ms:
        # every sample must land at tile index >= 0: pull the anchor back
        # onto the lattice point strictly below the earliest sample
        anchor_ms -= ((anchor_ms - tmin_ms) // g_ms + 1) * g_ms
    a_idx = ((s_ms - anchor_ms) // g_ms).astype(np.int64)
    b_idx = ((e_ms - anchor_ms) // g_ms).astype(np.int64)
    num_tiles = int(max(int(b_idx.max()),
                        (max(tmax_ms, anchor_ms + 1) - anchor_ms - 1) // g_ms + 1)) + 1
    if num_tiles > max_tiles:
        return None
    win_tiles = int(w_ms[0]) // g_ms
    # covered-tile union by interval marking — O(num_tiles), never
    # materializing per-window tile lists (K * win_tiles could dwarf the
    # grid itself for overlapping windows)
    mark = np.zeros(num_tiles + 1, np.int64)
    np.add.at(mark, a_idx, 1)
    np.add.at(mark, b_idx, -1)
    cov = np.flatnonzero(np.cumsum(mark[:-1]) > 0)
    tile2c = np.full(num_tiles + 1, -1, np.int64)
    tile2c[cov] = np.arange(len(cov))
    ca = tile2c[a_idx]
    cb = tile2c[b_idx - 1] + 1
    return TilePlan(g_ms, anchor_ms, num_tiles, a_idx, b_idx, win_tiles,
                    cov, tile2c, ca.astype(np.int32), cb.astype(np.int32),
                    float(w_ms[0]) / _MS_PER_S)


class TiledPrepared:
    """Prepared tiled state for one (series set, window grid) pair.

    Built once per query on the host from run-encoded samples (integer ms
    timestamps); every kernel method then answers all (series, step)
    windows in O(1) per window.  `xp` selects numpy (host) or jax.numpy
    (device); `values`/`value_shift` let callers re-run the value-dependent
    part with fresh values against the same prepared time structure (the
    bench harness and the device jit path)."""

    def __init__(self, plan: TilePlan, t_ms_all, v_all, lens,
                 dtype=np.float64, max_gather_cols: int | None = None,
                 lane_quantum: int = 1, enc=None):
        lens = np.asarray(lens, np.int64)
        t_ms_all = np.asarray(t_ms_all, np.int64)
        self.plan = plan
        # enc = (ftype, blocks, segments, slices): the value column is
        # on-disk encoded blocks (device-decode cold path) — v_all may
        # then be None and the (S, N) value matrix decodes on the DEVICE
        # (_values_for -> ops/device_decode.decode_rows_matrix) or
        # materializes lazily on the host (_host_values, bit-identical)
        self._enc = enc if v_all is None else None
        self.dtype = np.dtype(dtype)
        S = len(lens)
        N = max(1, int(lens.max()) if S else 1)
        self.S, self.N = S, N
        self.K = len(plan.a_idx)
        # backend-aware lane padding (models/grid.py quantum): the window
        # axis is the lane axis of every (S, K) output — pad it by
        # repeating the last window so device reduces tile cleanly, and
        # callers slice [:, :k_real]
        self.k_real = self.K
        if lane_quantum > 1 and self.K % lane_quantum:
            pad_k = (-self.K) % lane_quantum
            plan = TilePlan(
                plan.g_ms, plan.anchor_ms, plan.num_tiles,
                np.concatenate([plan.a_idx, np.repeat(plan.a_idx[-1:], pad_k)]),
                np.concatenate([plan.b_idx, np.repeat(plan.b_idx[-1:], pad_k)]),
                plan.win_tiles, plan.cov, plan.tile2c,
                np.concatenate([plan.ca, np.repeat(plan.ca[-1:], pad_k)]),
                np.concatenate([plan.cb, np.repeat(plan.cb[-1:], pad_k)]),
                plan.window_s)
            self.plan = plan
            self.K = len(plan.a_idx)
        total = int(lens.sum())
        # padded (S, N) matrices: the one flat-scatter fill shared with
        # the dense path (same +inf/zero padding and base_ms contract)
        self.times, self.values, self.counts, self.base_ms = (
            prepare_matrix_runs(t_ms_all, v_all, lens, dtype=self.dtype))

        # -- integer-arithmetic tile bucketing (no searchsorted) --
        from opengemini_tpu.ops.window import tile_index

        T = plan.num_tiles
        tid = np.clip(tile_index(t_ms_all, plan.anchor_ms, plan.g_ms),
                      0, T - 1)
        if total:
            rows = np.repeat(np.arange(S, dtype=np.int64), lens)
            # int32 throughout: counts and prefixes are bounded by N <
            # 2^31, and these (S, T) arrays are the prepare path's
            # dominant allocation
            cnt = np.bincount(rows * T + tid,
                              minlength=S * T).reshape(S, T).astype(np.int32)
        else:
            cnt = np.zeros((S, T), np.int32)
        tile_cum = np.zeros((S, T + 1), np.int32)
        np.cumsum(cnt, axis=1, out=tile_cum[:, 1:])
        # first/last sample index per window: prefix lookups at edge tiles
        first_idx = tile_cum[:, plan.a_idx]
        last_idx = tile_cum[:, plan.b_idx] - 1
        self.first_idx = first_idx.astype(np.int64)
        self.last_idx = last_idx.astype(np.int64)
        n_samp = last_idx - first_idx + 1
        self.has1 = n_samp >= 1
        self.has2 = n_samp >= 2
        self.n_samp = n_samp.astype(self.dtype)
        lim = np.maximum(lens, 1)[:, None] - 1
        self.safe_f = np.clip(first_idx, 0, lim).astype(np.int32)
        self.safe_l = np.clip(last_idx, 0, lim).astype(np.int32)
        self.safe_fm1 = np.clip(first_idx - 1, 0, lim).astype(np.int32)
        self.safe_lm1 = np.clip(last_idx - 1, 0, lim).astype(np.int32)
        self.fmask = first_idx >= 1  # the straddling boundary pair exists
        self.t_first = np.take_along_axis(
            self.times, self.safe_f, axis=1).astype(self.dtype)
        self.t_last = np.take_along_axis(
            self.times, self.safe_l, axis=1).astype(self.dtype)
        self.t_lm1 = np.take_along_axis(
            self.times, self.safe_lm1, axis=1).astype(self.dtype)

        # -- compact covered-tile gather layout --
        cov = plan.cov
        C = len(cov)
        cnt_cov = cnt[:, cov]
        pmax = int(cnt_cov.max()) if total else 0
        self.occupancy = pmax
        budget = max_gather_cols if max_gather_cols is not None else 8 * N + 64
        if C * (pmax + 1) > max(budget, 64):
            raise TileBudgetExceeded(
                f"gather layout {C}x{pmax + 1} over budget {budget}")
        # slot 0 = the sample BEFORE the tile's first (any tile — pair
        # quantities need the previous sample wherever it lives); slots
        # 1..pmax = the tile's own samples
        tile_start = tile_cum[:, cov]  # (S, C) first sample ordinal in tile
        gidx_local = tile_start[:, :, None] + np.arange(-1, pmax)[None, None, :]
        own_valid = (np.arange(pmax)[None, None, :] < cnt_cov[:, :, None])
        prev_valid = tile_start > 0
        self.gmask = np.concatenate(
            [prev_valid[:, :, None], own_valid], axis=2)
        gidx_local = np.clip(gidx_local, 0, lim[:, :, None])
        self.gidx = (np.arange(S, dtype=np.int64)[:, None, None] * N
                     + gidx_local).astype(np.int64)
        # row-LOCAL gather columns (gidx minus its row offset): the mesh
        # path gathers per series row so GSPMD can shard the series axis
        # without collectives; None until shard_tiled derives it
        self.gidx_col = None
        self.C, self.pmax = C, pmax
        # (1, K): take_along_axis broadcasts the non-gather dim, so the
        # per-series copy would be S redundant rows of the same indices
        self.ca2 = plan.ca[None, :].astype(np.int32)
        self.cb2 = plan.cb[None, :].astype(np.int32)
        self.pairmask = self.gmask[:, :, 1:] & self.gmask[:, :, :-1]
        self.ownmask = self.gmask[:, :, 1:]
        # window edges, base-relative seconds, kernel dtype
        self.starts_rel = ((np.rint(np.asarray(plan.a_idx) * plan.g_ms
                                    + plan.anchor_ms) - self.base_ms)
                           / 1000.0).astype(self.dtype)
        self.ends_rel = ((np.rint(np.asarray(plan.b_idx) * plan.g_ms
                                  + plan.anchor_ms) - self.base_ms)
                         / 1000.0).astype(self.dtype)

    # -- kernel building blocks ------------------------------------------

    def _host_values(self):
        """The (S, N) value matrix on the host, materializing a
        still-encoded column lazily (decode + the same flat scatter
        prepare_matrix_runs does — bit-identical to the eager path)."""
        if self.values is None:
            from opengemini_tpu.ops import device_decode

            v_all = device_decode.materialize_enc(self._enc)
            values = np.zeros((self.S, self.N), dtype=self.dtype)
            lens = np.asarray(self.counts, np.int64)
            starts = np.cumsum(lens) - lens
            rows = np.repeat(np.arange(self.S, dtype=np.int64), lens)
            cols = np.arange(int(lens.sum()), dtype=np.int64) \
                - np.repeat(starts, lens)
            values.reshape(-1)[rows * self.N + cols] = v_all
            self.values = values
        return self.values

    def _values_for(self, xp):
        """The prepared value matrix in xp's array type (one cached device
        copy for the traced path, so gathers run on device).  A
        still-encoded column decodes ON the device for the traced path —
        the H2D carries the raw block payloads instead of the padded f64
        matrix."""
        if xp is np:
            return self._host_values()
        dev = getattr(self, "_dev_values", None)
        if dev is None:
            import time as _time

            from opengemini_tpu.utils import devobs

            if self.values is None:
                from opengemini_tpu.ops import device_decode

                dev = device_decode.decode_rows_matrix(
                    self._enc, (self.S, self.N), self.dtype)
                if dev is not None:
                    devobs.LEDGER.register(
                        "prom_dev_values", int(dev.nbytes),
                        label="tiled-values-decoded", anchor=self)
                    self._dev_values = dev
                    return dev
            mat = self._host_values()
            t0 = _time.perf_counter_ns()
            dev = xp.asarray(mat)
            devobs.note_transfer(
                "h2d", "prom-values", int(mat.nbytes),
                (_time.perf_counter_ns() - t0) / 1e9)
            devobs.LEDGER.register(
                "prom_dev_values", int(mat.nbytes),
                label="tiled-values", anchor=self)
            self._dev_values = dev
        return dev

    def _vals(self, xp, values, value_shift):
        v = self._values_for(xp) if values is None else values
        vg = self._gather_tiles(xp, v)
        v_first = xp.take_along_axis(v, self.safe_f, axis=1)
        v_last = xp.take_along_axis(v, self.safe_l, axis=1)
        if value_shift is not None:
            vg = vg + value_shift
            v_first = v_first + value_shift
            v_last = v_last + value_shift
        return v, vg, v_first, v_last

    def _gather_tiles(self, xp, mat):
        """(S, C, pmax+1) covered-tile gather of a (S, N) matrix. The flat
        form is one big take on the host; the row-local form (gidx_col)
        keeps every gather inside its own series row, which is what lets
        the mesh path shard the series axis with zero collectives."""
        if self.gidx_col is not None:
            return xp.take_along_axis(mat[:, None, :], self.gidx_col, axis=2)
        return mat.reshape(-1)[self.gidx]

    def _window_sums(self, xp, tile_vals):
        from opengemini_tpu.ops import segment as seg

        return seg.tile_window_sums(tile_vals, self.ca2, self.cb2, xp=xp)

    def _gather1(self, xp, v, idx, value_shift):
        out = xp.take_along_axis(v, idx, axis=1)
        return out if value_shift is None else out + value_shift

    # -- kernels ----------------------------------------------------------

    def rate(self, xp=np, values=None, value_shift=None, *,
             is_counter: bool, is_rate: bool):
        """rate/increase/delta over every (series, step) window:
        tile-prefix counter-reset corrections + first/last gathers,
        prom extrapolatedRate semantics (identical formulas to
        extrapolated_rate above)."""
        v, vg, v_first, v_last = self._vals(xp, values, value_shift)
        delta = v_last - v_first
        if is_counter:
            drop = xp.where((vg[:, :, 1:] < vg[:, :, :-1]) & self.pairmask,
                            vg[:, :, :-1], xp.zeros((), vg.dtype))
            corr = self._window_sums(xp, drop.sum(axis=2))
            # boundary refinement: the tile diff counts the one pair that
            # straddles the window start (its earlier sample sits at
            # first_idx - 1, OUTSIDE the window) — subtract it
            v_fm1 = self._gather1(xp, v, self.safe_fm1, value_shift)
            drop_f = xp.where((v_first < v_fm1) & self.fmask, v_fm1,
                              xp.zeros((), v_first.dtype))
            delta = delta + (corr - drop_f)
        valid = self.has2
        sampled = self.t_last - self.t_first
        sampled = xp.where(sampled <= 0, 1.0, sampled)
        avg_int = sampled / xp.maximum(self.n_samp - 1, 1)
        d2s = self.t_first - self.starts_rel[None, :]
        d2e = self.ends_rel[None, :] - self.t_last
        thr = avg_int * 1.1
        d2s = xp.where(d2s > thr, avg_int / 2, d2s)
        d2e = xp.where(d2e > thr, avg_int / 2, d2e)
        if is_counter:
            dz = xp.where((delta > 0) & (v_first >= 0),
                          sampled * (v_first / xp.maximum(delta, 1e-30)),
                          xp.asarray(np.inf, dtype=sampled.dtype)
                          if xp is np else jnp.inf)
            d2s = xp.minimum(d2s, dz)
        out = delta * ((sampled + d2s + d2e) / sampled)
        if is_rate:
            out = out / self.plan.window_s
        return out, valid

    def instant_rate(self, xp=np, values=None, value_shift=None, *,
                     per_second: bool):
        """irate/idelta: last two samples per window, prefix-resolved."""
        v = self._values_for(xp) if values is None else values
        v_last = self._gather1(xp, v, self.safe_l, value_shift)
        v_prev = self._gather1(xp, v, self.safe_lm1, value_shift)
        valid = self.has2
        dv = v_last - v_prev
        if per_second:
            dv = xp.where(dv < 0, v_last, dv)  # counter reset
            dt = xp.maximum(self.t_last - self.t_lm1, 1e-9)
            return dv / dt, valid
        return dv, valid

    def over_time(self, xp=np, values=None, value_shift=None, *, func: str):
        """sum/count/avg/last/present/stddev/stdvar/min/max _over_time.

        Prefix-able forms answer from cumulative tile sums; min/max from
        the fixed-length sliding-extreme over tile partials — no dense
        (S, chunk, N) membership tensor anywhere."""
        has = self.has1
        wcnt = xp.where(has, self.n_samp, xp.zeros((), self.n_samp.dtype))
        if func == "count":
            return wcnt, has
        if func == "present":
            one = np.ones((), self.dtype) if xp is np else jnp.ones((), self.dtype)
            return xp.where(has, one, 0), has
        if func == "last":
            v = self._values_for(xp) if values is None else values
            return self._gather1(xp, v, self.safe_l, value_shift), has
        v, vg, _vf, _vl = self._vals(xp, values, value_shift)
        if func in ("sum", "avg"):
            vz = xp.where(self.ownmask, vg[:, :, 1:], xp.zeros((), vg.dtype))
            wsum = self._window_sums(xp, vz.sum(axis=2))
            if func == "sum":
                return xp.where(has, wsum, xp.zeros((), wsum.dtype)), has
            return xp.where(has, wsum, xp.zeros((), wsum.dtype)) / xp.maximum(wcnt, 1), has
        if func in ("stddev", "stdvar"):
            # center on the per-series mean first (see over_time above: raw
            # v^2 prefixes cancel catastrophically for large magnitudes)
            valid_cols = xp.arange(self.N)[None, :] < self.counts[:, None]
            series_n = xp.maximum(self.counts, 1).astype(self.dtype)[:, None]
            vz_raw = xp.where(valid_cols, v, xp.zeros((), v.dtype))
            center = vz_raw.sum(axis=1, keepdims=True) / series_n
            vc = xp.where(self.ownmask, vg[:, :, 1:] - center[:, :, None],
                          xp.zeros((), vg.dtype))
            ws = self._window_sums(xp, vc.sum(axis=2))
            wss = self._window_sums(xp, (vc * vc).sum(axis=2))
            denom = xp.maximum(wcnt, 1)
            mean = ws / denom
            var = xp.maximum(wss / denom - mean * mean, 0)
            out = var if func == "stdvar" else xp.sqrt(var)
            return xp.where(has, out, xp.zeros((), out.dtype)), has
        if func in ("min", "max"):
            from opengemini_tpu.ops import segment as seg

            want_min = func == "min"
            fill = self.dtype.type(np.inf if want_min else -np.inf)
            if self.pmax == 0:  # no samples in any covered tile
                tile_ext = xp.full((self.S, self.C), fill, dtype=self.dtype)
            elif want_min:
                tile_ext = xp.where(self.ownmask, vg[:, :, 1:], fill).min(axis=2)
            else:
                tile_ext = xp.where(self.ownmask, vg[:, :, 1:], fill).max(axis=2)
            out = seg.tile_sliding_extreme(
                tile_ext, self.plan.win_tiles, self.ca2, want_min, xp=xp)
            return out, has
        raise ValueError(f"unsupported over_time func {func!r}")

    def changes_resets(self, xp=np, values=None, value_shift=None, *, kind: str):
        """changes()/resets(): pair-indicator tile sums + the straddling
        boundary-pair refinement (same shape as the rate correction)."""
        v, vg, v_first, _vl = self._vals(xp, values, value_shift)
        cur, prev = vg[:, :, 1:], vg[:, :, :-1]
        if kind == "changes":
            ind = (cur != prev) & self.pairmask
        else:
            ind = (cur < prev) & self.pairmask
        wind = self._window_sums(xp, ind.astype(self.dtype).sum(axis=2))
        v_fm1 = self._gather1(xp, v, self.safe_fm1, value_shift)
        if kind == "changes":
            bnd = (v_first != v_fm1) & self.fmask
        else:
            bnd = (v_first < v_fm1) & self.fmask
        out = wind - bnd.astype(self.dtype)
        valid = self.has1
        return xp.where(valid, out, xp.zeros((), out.dtype)), valid

    def linear_regression(self, xp=np, values=None, value_shift=None):
        """Least-squares slope/intercept per window centered at the window
        end (prom linearRegression), from tile partials of {v, t, t^2, tv}
        — the O(S*chunk*N) dense pass becomes four prefix lookups."""
        v, vg, _vf, _vl = self._vals(xp, values, value_shift)
        tg = self._gather_tiles(xp, self.times)[:, :, 1:].astype(self.dtype)
        z = xp.zeros((), vg.dtype)
        vz = xp.where(self.ownmask, vg[:, :, 1:], z)
        tz = xp.where(self.ownmask, tg, z)
        sv = self._window_sums(xp, vz.sum(axis=2))
        st_abs = self._window_sums(xp, tz.sum(axis=2))
        stt_abs = self._window_sums(xp, (tz * tz).sum(axis=2))
        stv_abs = self._window_sums(xp, (tz * vz).sum(axis=2))
        e = self.ends_rel[None, :]
        cnt = xp.where(self.has1, self.n_samp, 0)
        denom_n = xp.maximum(cnt, 1)
        st = st_abs - e * cnt
        stt = stt_abs - 2 * e * st_abs + e * e * cnt
        stv = stv_abs - e * sv
        cov = stv - st * sv / denom_n
        var = stt - st * st / denom_n
        slope = cov / xp.where(var == 0, 1.0, var)
        slope = xp.where(var == 0, 0.0, slope)
        intercept = sv / denom_n - slope * (st / denom_n)
        has2 = self.has2 & (self.t_last > self.t_first)
        return slope, intercept, has2


    def sharded(self, mesh) -> "ShardedTiled":
        """The mesh view of this prepared state (cached per mesh object:
        one sharding transfer per query however many kernels run)."""
        cached = getattr(self, "_sharded_view", None)
        if cached is not None and cached[0] is mesh:
            return cached[1]
        view = ShardedTiled(self, mesh)
        self._sharded_view = (mesh, view)
        return view


# ---------------------------------------------------------------------------
# Multi-chip tiled kernels: series-axis sharding over a device mesh.
#
# Every TiledPrepared tensor is either per-series (leading axis S: the
# values/times matrices, the covered-tile gather and its masks, the
# per-window prefix lookups and boundary-refinement gathers) or per-window
# (the compact range positions ca/cb and the window edges). Series are
# independent — no kernel ever combines two series rows — so sharding the
# S axis partitions the WHOLE program with zero collectives, exactly the
# GSPMD style of distributed.shard_leading_axis for the grid layout. The
# boundary refinements (the straddling pair subtraction, first/last value
# gathers) are row-local gathers and stay per-shard by construction once
# the flat covered-tile gather is rewritten row-locally (gidx_col).
# ---------------------------------------------------------------------------

# per-series tensors (leading axis S — sharded over every mesh axis)
_TILED_SHARD_ATTRS = (
    "values", "counts", "times", "ownmask", "pairmask", "fmask",
    "has1", "has2", "n_samp", "safe_f", "safe_l", "safe_fm1", "safe_lm1",
    "t_first", "t_last", "t_lm1",
)
# per-window tensors (replicated: every shard answers all K windows for
# its own series rows)
_TILED_REPL_ATTRS = ("ca2", "cb2", "starts_rel", "ends_rel")


class _TiledShardView(TiledPrepared):
    """TiledPrepared stand-in rebuilt inside the jit trace: tensor
    attributes are traced (sharded) arrays, statics are Python scalars.
    The kernel methods run unmodified against it."""

    def __init__(self):  # attrs are assigned by the trace, not prepared
        pass


class _PlanView:
    __slots__ = ("win_tiles", "window_s")

    def __init__(self, win_tiles: int, window_s: float):
        self.win_tiles = win_tiles
        self.window_s = window_s


import functools as _functools  # noqa: E402  (kernel-cache only)


@_functools.lru_cache(maxsize=128)
def _sharded_tiled_jit(kernel: str, opts: tuple, meta: tuple):
    """One compiled sharded program per (kernel, static opts, geometry).
    Tensors arrive as a pytree argument (never closed over — constants
    would be baked into the program) and carry their NamedSharding in;
    GSPMD propagates it through every op."""
    import jax

    from opengemini_tpu.utils import devobs

    devobs.note_compile("prom_" + kernel, (opts, meta))
    s_pad, n_cols, k_win, c_cov, pmax, dtype_str, win_tiles, window_s = meta
    kwargs = dict(opts)

    def fn(arrays):
        view = _TiledShardView()
        view.__dict__.update(arrays)
        view.gidx = None  # force the row-local gather form
        view.S, view.N, view.K = s_pad, n_cols, k_win
        view.C, view.pmax = c_cov, pmax
        view.dtype = np.dtype(dtype_str)
        view.plan = _PlanView(win_tiles, window_s)
        view._dev_values = arrays["values"]
        return getattr(TiledPrepared, kernel)(view, jnp, **kwargs)

    return jax.jit(fn)


class ShardedTiled:
    """Mesh execution of one TiledPrepared: per-series tensors device_put
    with the series axis sharded (explicit NamedSharding, rows padded to a
    multiple of mesh.size — padding rows carry all-False masks so they
    answer as empty windows and are sliced off by the caller), per-window
    tensors replicated. Kernel methods mirror TiledPrepared's but run as
    one sharded jit program each; outputs are (S_pad, K)-sharded arrays
    the caller slices to [:prep.S, :prep.k_real]."""

    def __init__(self, prep: TiledPrepared, mesh):
        import jax

        from opengemini_tpu.parallel import distributed as dist

        self.prep = prep
        self.mesh = mesh
        n_dev = mesh.size
        self.S_pad = max(1, (prep.S + n_dev - 1) // n_dev * n_dev)
        # row-local covered-tile gather: flat gidx minus its row offset
        rows = (np.arange(prep.S, dtype=np.int64) * prep.N)[:, None, None]
        gidx_col = (prep.gidx - rows).astype(np.int32)
        series = {name: (prep._host_values() if name == "values"
                         else getattr(prep, name))
                  for name in _TILED_SHARD_ATTRS}
        series["gidx_col"] = gidx_col
        sharded = dist.shard_leading_axis(mesh, *series.values(),
                                          xfer_site="prom-shard")
        self.arrays = dict(zip(series.keys(), sharded))
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(mesh, P())
        for name in _TILED_REPL_ATTRS:
            self.arrays[name] = jax.device_put(
                np.asarray(getattr(prep, name)), repl)
        self._meta = (self.S_pad, prep.N, prep.K, prep.C, prep.pmax,
                      str(prep.dtype), prep.plan.win_tiles,
                      float(prep.plan.window_s))
        from opengemini_tpu.utils import devobs
        from opengemini_tpu.parallel import runtime as _prt

        devobs.LEDGER.register(
            "prom_sharded",
            sum(int(a.nbytes) for a in self.arrays.values()),
            mesh_epoch=_prt.mesh_epoch(), label="sharded-tiled",
            anchor=self)

    def _run(self, kernel: str, **opts):
        from opengemini_tpu.query import offload
        from opengemini_tpu.utils import devobs

        opts_t = tuple(sorted(opts.items()))
        devobs.note_use("prom_" + kernel, (opts_t, self._meta))
        offload.register_builder(
            "prom_" + kernel, (opts_t, self._meta),
            lambda k=kernel, o=opts_t, m=self._meta:
                _sharded_tiled_jit(k, o, m))
        fn = _sharded_tiled_jit(kernel, opts_t, self._meta)
        t0 = devobs.t0()
        out = fn(self.arrays)
        if t0:
            devobs.note_exec(t0)
        return out

    def rate(self, *, is_counter: bool, is_rate: bool):
        return self._run("rate", is_counter=is_counter, is_rate=is_rate)

    def instant_rate(self, *, per_second: bool):
        return self._run("instant_rate", per_second=per_second)

    def over_time(self, *, func: str):
        return self._run("over_time", func=func)

    def changes_resets(self, *, kind: str):
        return self._run("changes_resets", kind=kind)

    def linear_regression(self):
        return self._run("linear_regression")


class TileBudgetExceeded(ValueError):
    """Raised by TiledPrepared when the compact gather layout would exceed
    its memory budget (pathological occupancy skew); callers fall back to
    the dense kernels."""


def prepare_tiled(plan: TilePlan, t_ms_all, v_all, lens, dtype=np.float64,
                  max_gather_cols: int | None = None, lane_quantum: int = 1,
                  enc=None):
    """TiledPrepared or None (budget exceeded -> dense fallback)."""
    try:
        return TiledPrepared(plan, t_ms_all, v_all, lens, dtype=dtype,
                             max_gather_cols=max_gather_cols,
                             lane_quantum=lane_quantum, enc=enc)
    except TileBudgetExceeded:
        return None

# -- incremental tile-state tier (promql/rules.py) ----------------------------
#
# The continuous rule engine maintains PER-TILE partials as durable-ish
# STATE between ticks instead of recomputing them per query: each tile of
# the group's ms lattice carries one mergeable record per series, the
# ingest path dirties tiles, and a tick refolds only the dirtied tiles
# (fold_tile_partials) before answering every rule window from a
# left-to-right merge of its covering tiles (merge_tile_partials +
# partials_answer).  The record is the TiLT partial (arXiv:2301.12030)
# the batch engine above computes transiently, plus the boundary-pair
# inputs (first/last sample) that let cross-tile merges reconstruct the
# straddling reset/change corrections exactly.
#
# All arithmetic here is HOST numpy float64 on purpose: the rule engine's
# acceptance contract is BITWISE identity between the incremental leg
# (merge cached + refolded tiles) and the from-scratch leg (fold every
# tile off one full-window scan, merge identically), which holds only
# under a deterministic reduction order.  Device/mesh routing still
# happens per group — for the matcher probe (label tier) and for the
# full-rescan fallback leg, which evaluates through the ordinary planner-
# routed engine kernels.

# field -> fill value for an EMPTY (series, tile) cell; merge order is
# the tuple order
TILE_PARTIAL_FIELDS = (
    ("n", 0.0), ("sum", 0.0), ("sumsq", 0.0),
    ("mn", np.inf), ("mx", -np.inf),
    ("t_first", 0.0), ("v_first", 0.0), ("t_last", 0.0), ("v_last", 0.0),
    ("drop", 0.0), ("changes", 0.0), ("resets", 0.0),
)

# range-vector functions the partial record answers exactly (everything
# else takes the rule engine's full-rescan fallback through the engine)
PARTIAL_RATE_FUNCS = frozenset({"rate", "increase", "delta"})
PARTIAL_OVER_TIME = frozenset({
    "sum", "count", "avg", "min", "max", "stddev", "stdvar", "last",
    "present"})
PARTIAL_PAIR_FUNCS = frozenset({"changes", "resets"})


def empty_tile_partials(n_series: int) -> dict:
    """One tile's record columns for `n_series` series, all empty."""
    return {f: np.full(n_series, fill, np.float64)
            for f, fill in TILE_PARTIAL_FIELDS}


def fold_tile_partials(t_ms_all, v_all, lens, anchor_ms: int, g_ms: int,
                       lo_tile: int, hi_tile: int) -> dict[int, dict]:
    """Fold run-encoded samples into per-tile partial records.

    Input is the engine's run-encoded collection (concatenated int64 ms
    timestamps + float64 values with per-series lengths, ascending per
    series); only samples landing in lattice tiles [lo_tile, hi_tile)
    contribute.  Returns {tile_idx: {field: (S,) float64}} holding ONLY
    tiles that received at least one sample — absent means empty, so the
    caller can overlay the result onto cached state.

    Pair quantities (drop/changes/resets) count sample pairs fully INSIDE
    one tile; pairs straddling tiles are reconstructed at merge time from
    (v_last, v_first) of consecutive non-empty tiles, which is exact
    because tiles partition the time axis and samples are time-ordered.
    """
    from opengemini_tpu.ops.window import tile_index

    lens = np.asarray(lens, np.int64)
    S = len(lens)
    t_ms_all = np.asarray(t_ms_all, np.int64)
    v_all = np.asarray(v_all, np.float64)
    if t_ms_all.size == 0:
        return {}
    tid = tile_index(t_ms_all, anchor_ms, g_ms)
    rows = np.repeat(np.arange(S, dtype=np.int64), lens)
    keep = (tid >= lo_tile) & (tid < hi_tile)
    span = hi_tile - lo_tile
    # rows are blockwise-ascending and t (hence tid) ascends per series,
    # so key is globally non-decreasing: segment reductions are plain
    # reduceat over change points — no sort, no hashing
    key = rows * span + (tid - lo_tile)
    # pair columns BEFORE masking: a pair exists when sample i-1 and i
    # share a (series, tile) cell
    same = np.zeros(len(key), bool)
    if len(key) > 1:
        same[1:] = key[1:] == key[:-1]
    prev_v = np.empty_like(v_all)
    prev_v[0] = 0.0
    prev_v[1:] = v_all[:-1]
    p_reset = same & (v_all < prev_v)
    p_drop = np.where(p_reset, prev_v, 0.0)
    p_change = (same & (v_all != prev_v)).astype(np.float64)
    if not keep.all():
        key = key[keep]
        t_k = t_ms_all[keep]
        v_k = v_all[keep]
        p_drop = p_drop[keep]
        p_change = p_change[keep]
        p_resets = p_reset[keep].astype(np.float64)
    else:
        t_k = t_ms_all
        v_k = v_all
        p_resets = p_reset.astype(np.float64)
    if key.size == 0:
        return {}
    starts = np.flatnonzero(np.diff(key)) + 1
    starts = np.concatenate([[0], starts])
    seg_key = key[starts]
    seg_n = np.diff(np.concatenate([starts, [key.size]]))
    seg_sum = np.add.reduceat(v_k, starts)
    seg_sumsq = np.add.reduceat(v_k * v_k, starts)
    seg_mn = np.minimum.reduceat(v_k, starts)
    seg_mx = np.maximum.reduceat(v_k, starts)
    seg_drop = np.add.reduceat(p_drop, starts)
    seg_changes = np.add.reduceat(p_change, starts)
    seg_resets = np.add.reduceat(p_resets, starts)
    ends = starts + seg_n - 1
    out: dict[int, dict] = {}
    seg_row = seg_key // span
    seg_tile = seg_key % span + lo_tile
    for tile in np.unique(seg_tile):
        sel = seg_tile == tile
        r = seg_row[sel]
        rec = empty_tile_partials(S)
        rec["n"][r] = seg_n[sel]
        rec["sum"][r] = seg_sum[sel]
        rec["sumsq"][r] = seg_sumsq[sel]
        rec["mn"][r] = seg_mn[sel]
        rec["mx"][r] = seg_mx[sel]
        rec["t_first"][r] = t_k[starts[sel]]
        rec["v_first"][r] = v_k[starts[sel]]
        rec["t_last"][r] = t_k[ends[sel]]
        rec["v_last"][r] = v_k[ends[sel]]
        rec["drop"][r] = seg_drop[sel]
        rec["changes"][r] = seg_changes[sel]
        rec["resets"][r] = seg_resets[sel]
        out[int(tile)] = rec
    return out


def merge_tile_partials(tiles: list[dict | None], n_series: int) -> dict:
    """Left-to-right merge of per-tile records into one window record.

    `tiles` lists the window's covering tiles in time order (None =
    empty tile).  Boundary pairs between consecutive NON-EMPTY tiles add
    the straddling reset/change corrections the per-tile fold could not
    see.  Deterministic (same tile order -> same bits), which is the
    incremental-vs-rescan identity contract."""
    m = empty_tile_partials(n_series)
    for rec in tiles:
        if rec is None:
            continue
        t_has = rec["n"] > 0
        if not t_has.any():
            continue
        m_has = m["n"] > 0
        both = m_has & t_has
        bd_reset = both & (rec["v_first"] < m["v_last"])
        m["drop"] += np.where(bd_reset, m["v_last"], 0.0) \
            + np.where(t_has, rec["drop"], 0.0)
        m["resets"] += bd_reset + np.where(t_has, rec["resets"], 0.0)
        m["changes"] += (both & (rec["v_first"] != m["v_last"])) \
            + np.where(t_has, rec["changes"], 0.0)
        m["n"] += np.where(t_has, rec["n"], 0.0)
        m["sum"] += np.where(t_has, rec["sum"], 0.0)
        m["sumsq"] += np.where(t_has, rec["sumsq"], 0.0)
        m["mn"] = np.where(t_has, np.minimum(m["mn"], rec["mn"]), m["mn"])
        m["mx"] = np.where(t_has, np.maximum(m["mx"], rec["mx"]), m["mx"])
        first = t_has & ~m_has
        m["t_first"] = np.where(first, rec["t_first"], m["t_first"])
        m["v_first"] = np.where(first, rec["v_first"], m["v_first"])
        m["t_last"] = np.where(t_has, rec["t_last"], m["t_last"])
        m["v_last"] = np.where(t_has, rec["v_last"], m["v_last"])
    return m


def partials_answer(m: dict, func: str, ws_ms: int, we_ms: int):
    """(values, valid) for one rule window from a merged record.

    Same semantics as the batch kernels above: extrapolatedRate with the
    1.1x-average-interval clamp and counter zero-crossing for
    rate/increase/delta, pair counts for changes/resets, moment algebra
    for the *_over_time forms (stddev/stdvar from sum/sumsq — adequate
    for monitoring magnitudes; the engine's per-query centered form is
    not reachable from mergeable per-tile state)."""
    n = m["n"]
    has1 = n >= 1
    if func == "count":
        return np.where(has1, n, 0.0), has1
    if func == "present":
        return np.where(has1, 1.0, 0.0), has1
    if func == "last":
        return m["v_last"], has1
    if func == "sum":
        return np.where(has1, m["sum"], 0.0), has1
    if func == "avg":
        return m["sum"] / np.maximum(n, 1.0), has1
    if func == "min":
        return m["mn"], has1
    if func == "max":
        return m["mx"], has1
    if func in ("stddev", "stdvar"):
        denom = np.maximum(n, 1.0)
        mean = m["sum"] / denom
        var = np.maximum(m["sumsq"] / denom - mean * mean, 0.0)
        return (var if func == "stdvar" else np.sqrt(var)), has1
    if func in ("changes", "resets"):
        out = m["changes"] if func == "changes" else m["resets"]
        return np.where(has1, out, 0.0), has1
    if func in PARTIAL_RATE_FUNCS:
        is_counter = func in ("rate", "increase")
        valid = n >= 2
        delta = m["v_last"] - m["v_first"]
        if is_counter:
            delta = delta + m["drop"]
        # int64 ms differences -> exact float seconds (the batch path's
        # base-relative precision argument, with the window start as base)
        sampled = (m["t_last"] - m["t_first"]) / 1000.0
        sampled = np.where(sampled <= 0, 1.0, sampled)
        avg_int = sampled / np.maximum(n - 1, 1.0)
        d2s = (m["t_first"] - ws_ms) / 1000.0
        d2e = (we_ms - m["t_last"]) / 1000.0
        thr = avg_int * 1.1
        d2s = np.where(d2s > thr, avg_int / 2, d2s)
        d2e = np.where(d2e > thr, avg_int / 2, d2e)
        if is_counter:
            dz = np.where((delta > 0) & (m["v_first"] >= 0),
                          sampled * (m["v_first"] / np.maximum(delta, 1e-30)),
                          np.inf)
            d2s = np.minimum(d2s, dz)
        out = delta * ((sampled + d2s + d2e) / sampled)
        if func == "rate":
            out = out / ((we_ms - ws_ms) / 1000.0)
        return out, valid
    raise ValueError(f"unsupported partials func {func!r}")
