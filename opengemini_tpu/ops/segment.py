"""Masked segmented reductions — the device hot loop.

Each aggregate over (series-group, time-window) segments is a masked
segmented reduction with segment id ``group_id * num_windows + window_id``.
Rows arrive series-major and time-sorted within a series, so segment ids are
sorted within each series run — ``indices_are_sorted`` is still False
globally (multiple series interleave). These scatter-based forms are the
general fallback; the hot paths are the dense layouts (``grid_window_agg_t``
here, bucket matrices in ``models/ragged.py``), whose fused Pallas tile
kernels live in ``ops/pallas_segment.py`` and engage on TPU backends.

This replaces the reference's generated scalar reduce loops
(engine/series_agg_func.gen.go: floatSumReduce:47 etc., 45 fns;
series_agg_reducer.gen.go, 148 fns): one masked-segment-reduce per aggregate
instead of one hand-written loop per (type, agg).

All functions are pure and jit-traceable; ``num_segments`` must be static.
Null semantics: ``mask`` False rows contribute nothing; empty segments
produce count==0 and the executor renders them as null/fill values
(reference nil-bitmap semantics, lib/record/column.go:30).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# plain int (not jnp scalar): keeps module import free of backend init
_BIG_I32 = 2**31 - 1


def seg_sum(values, seg_ids, num_segments: int, mask):
    data = jnp.where(mask, values, jnp.zeros((), values.dtype))
    return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)


def seg_count(seg_ids, num_segments: int, mask):
    data = mask.astype(jnp.int32)
    return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)


def seg_min(values, seg_ids, num_segments: int, mask):
    big = _type_max(values.dtype)
    data = jnp.where(mask, values, big)
    return jax.ops.segment_min(data, seg_ids, num_segments=num_segments)


def seg_max(values, seg_ids, num_segments: int, mask):
    small = _type_min(values.dtype)
    data = jnp.where(mask, values, small)
    return jax.ops.segment_max(data, seg_ids, num_segments=num_segments)


def seg_mean(values, seg_ids, num_segments: int, mask):
    s = seg_sum(values, seg_ids, num_segments, mask)
    c = seg_count(seg_ids, num_segments, mask)
    return s / jnp.maximum(c, 1).astype(s.dtype)


def seg_sumsq(values, seg_ids, num_segments: int, mask):
    data = jnp.where(mask, values * values, jnp.zeros((), values.dtype))
    return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)


def seg_stddev(values, seg_ids, num_segments: int, mask):
    """Sample stddev, n-1 denominator (influx stddev semantics, reference
    engine/series_agg_func.gen.go float stddev reducers).

    Two-pass (mean, then squared deviations): the one-pass sum-of-squares
    formula cancels catastrophically for large means, especially in f32 on
    TPU. Cost is still two segment-sums — same shape on device.
    """
    mean = seg_mean(values, seg_ids, num_segments, mask)
    dev = values - mean[seg_ids]
    ssd = jax.ops.segment_sum(
        jnp.where(mask, dev * dev, jnp.zeros((), values.dtype)),
        seg_ids,
        num_segments=num_segments,
    )
    c = seg_count(seg_ids, num_segments, mask).astype(values.dtype)
    var = ssd / jnp.maximum(c - 1, 1)
    return jnp.sqrt(jnp.maximum(var, 0))


def seg_first(values, rel_hi, rel_lo, seg_ids, num_segments: int, mask):
    """(value, row_idx) of the earliest valid row per segment.

    Timestamps arrive as an EXACT lexicographic int32 pair
    (rel_hi = rel_ns >> 30, rel_lo = rel_ns & (2^30-1)) so ns-precision
    ordering survives on devices without int64. True ns ties pick the
    LARGER VALUE — the reference first/last rule (engine/executor/
    agg_func.go FirstReduce: `times == && v > firstValue`,
    TestServer_Query_Aggregates_IdenticalTime); value ties then fall to
    scan order."""
    return _seg_extreme_by_time(
        values, rel_hi, rel_lo, seg_ids, num_segments, mask, latest=False
    )


def seg_last(values, rel_hi, rel_lo, seg_ids, num_segments: int, mask):
    return _seg_extreme_by_time(
        values, rel_hi, rel_lo, seg_ids, num_segments, mask, latest=True
    )


def _seg_extreme_by_time(values, rel_hi, rel_lo, seg_ids, num_segments, mask, latest):
    n = values.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    smax = lambda d: jax.ops.segment_max(d, seg_ids, num_segments=num_segments)  # noqa: E731
    smin = lambda d: jax.ops.segment_min(d, seg_ids, num_segments=num_segments)  # noqa: E731
    if latest:
        hi_ext = smax(jnp.where(mask, rel_hi, -_BIG_I32))
        cand = mask & (rel_hi == hi_ext[seg_ids])
        lo_ext = smax(jnp.where(cand, rel_lo, -_BIG_I32))
        cand &= rel_lo == lo_ext[seg_ids]
    else:
        hi_ext = smin(jnp.where(mask, rel_hi, _BIG_I32))
        cand = mask & (rel_hi == hi_ext[seg_ids])
        lo_ext = smin(jnp.where(cand, rel_lo, _BIG_I32))
        cand &= rel_lo == lo_ext[seg_ids]
    # exact-time ties: larger value wins (reference FirstReduce/LastReduce)
    v_ext = smax(jnp.where(cand, values, _type_min(values.dtype)))
    cand &= values == v_ext[seg_ids]
    sel = smin(jnp.where(cand, idx, _BIG_I32))
    safe = jnp.clip(sel, 0, n - 1)
    return values[safe], sel


def seg_min_selector(values, rel_hi, rel_lo, seg_ids, num_segments: int, mask):
    """min() as a *selector*: also returns the row index of the selected
    row — InfluxQL bare-selector queries return the point's own time
    (reference MinReduce keeps the row, series_agg_func.gen.go); the host
    resolves the index against its exact int64 ns times. Value ties break
    by EARLIEST TIMESTAMP (then scan order), matching the reference's
    time-ordered merge — batch scan order alone is series-major, not
    time-ordered, across series in one group."""
    return _seg_extreme_by_value(
        values, rel_hi, rel_lo, seg_ids, num_segments, mask, want_max=False
    )


def seg_max_selector(values, rel_hi, rel_lo, seg_ids, num_segments: int, mask):
    return _seg_extreme_by_value(
        values, rel_hi, rel_lo, seg_ids, num_segments, mask, want_max=True
    )


def _seg_extreme_by_value(values, rel_hi, rel_lo, seg_ids, num_segments, mask, want_max):
    n = values.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    smin = lambda d: jax.ops.segment_min(d, seg_ids, num_segments=num_segments)  # noqa: E731
    if want_max:
        v_ext = seg_max(values, seg_ids, num_segments, mask)
    else:
        v_ext = seg_min(values, seg_ids, num_segments, mask)
    cand = mask & (values == v_ext[seg_ids])
    hi_best = smin(jnp.where(cand, rel_hi, _BIG_I32))
    cand &= rel_hi == hi_best[seg_ids]
    lo_best = smin(jnp.where(cand, rel_lo, _BIG_I32))
    cand &= rel_lo == lo_best[seg_ids]
    sel = smin(jnp.where(cand, idx, _BIG_I32))
    return v_ext, sel


def _sort_by_segment(values, seg_ids, num_segments, mask):
    """Shared prologue for rank-based aggregates: rows sorted by
    (segment, value) with invalid rows pushed into a trailing dummy segment.
    Returns (sorted_values, sorted_seg, counts, starts)."""
    sort_seg = jnp.where(mask, seg_ids, num_segments)
    order = jnp.lexsort((values, sort_seg))
    counts = seg_count(seg_ids, num_segments, mask)
    starts = jnp.cumsum(counts) - counts
    return values[order], sort_seg[order], counts, starts


def seg_percentile(values, seg_ids, num_segments: int, mask, q: float):
    """Nearest-rank percentile per segment (InfluxQL percentile(): returns
    an actual sample, rank = floor(n*q/100 + 0.5) — the lifted influx rule
    (FloatPercentileReduceSlice); reference engine/executor/agg_func.go
    percentile processors)."""
    n = values.shape[0]
    sorted_vals, _, counts, starts = _sort_by_segment(values, seg_ids, num_segments, mask)
    rank = jnp.floor(q / 100.0 * counts + 0.5).astype(jnp.int32)
    rank = jnp.clip(rank - 1, 0, jnp.maximum(counts - 1, 0))
    sel = jnp.clip(starts + rank, 0, n - 1)
    return sorted_vals[sel]


def seg_median(values, seg_ids, num_segments: int, mask):
    """InfluxQL median(): middle value, or mean of the two middles for even
    counts (reference agg_func.go median handling)."""
    n = values.shape[0]
    sorted_vals, _, counts, starts = _sort_by_segment(values, seg_ids, num_segments, mask)
    lo = starts + jnp.maximum((counts - 1) // 2, 0)
    hi = starts + jnp.maximum(counts // 2, 0)
    lo_v = sorted_vals[jnp.clip(lo, 0, n - 1)]
    hi_v = sorted_vals[jnp.clip(hi, 0, n - 1)]
    return (lo_v + hi_v) / 2


def seg_count_distinct(values, seg_ids, num_segments: int, mask):
    """count(distinct(field)) — sort by (seg, value), count run heads."""
    sv, ss, _, _ = _sort_by_segment(values, seg_ids, num_segments, mask)
    head = jnp.ones_like(ss, dtype=jnp.int32)
    same = (ss[1:] == ss[:-1]) & (sv[1:] == sv[:-1])
    head = head.at[1:].set(jnp.where(same, 0, 1))
    head = jnp.where(ss < num_segments, head, 0)
    return jax.ops.segment_sum(head, jnp.clip(ss, 0, num_segments - 1), num_segments=num_segments)


def grid_window_agg(values, mask, windows_per_series: int):
    """Regular-grid fast path: when a chunk's timestamps are a constant
    stride (the TSF encoder already detects this — storage/encoding.py
    _T_CONST blocks) and windows divide the grid evenly, windowed
    aggregation is a pure dense reshape-reduce: (S, R) -> (S, W, R/W) ->
    reduce. No scatter; memory-bound optimal on TPU (VPU/MXU friendly,
    XLA fuses the mask). This replaces the reference's pre-aggregation
    block skipping *and* its per-row interval loop for the regular case
    (engine/immutable/pre_aggregation.go, aggregate_cursor.go:343).

    values, mask: (num_series, rows_per_series); rows_per_series must be a
    multiple of windows_per_series. Returns dict of (S, W) arrays.
    """
    s_dim, r = values.shape
    w = windows_per_series
    k = r // w
    v = values.reshape(s_dim, w, k)
    m = mask.reshape(s_dim, w, k)
    vz = jnp.where(m, v, jnp.zeros((), values.dtype))
    cnt = m.sum(axis=-1, dtype=jnp.int32)
    s = vz.sum(axis=-1)
    mn = jnp.where(m, v, _type_max(values.dtype)).min(axis=-1)
    mx = jnp.where(m, v, _type_min(values.dtype)).max(axis=-1)
    mean = s / jnp.maximum(cnt, 1).astype(s.dtype)
    return {"sum": s, "count": cnt, "mean": mean, "min": mn, "max": mx}


def grid_window_agg_t(values_t, mask_t):
    """Regular-grid fast path in the TPU-native layout: values_t is
    (num_series, samples_per_window, num_windows) — windows on the LANE
    axis, within-window samples on sublanes, so every per-window stat is a
    sublane-axis reduce. Measured ~9x faster than the last-axis layout on
    v5e (164 vs 18 G rows/s): the reduce streams at near HBM bandwidth.
    Production wiring: models/grid.py GridBatch assembles scanned chunks
    directly in this layout when the data is stride-regular (pick_batch
    routes GROUP BY time() aggregates there); bench.py measures the same
    kernel standalone.

    Returns dict of (num_series, num_windows) arrays.
    """
    vz = jnp.where(mask_t, values_t, jnp.zeros((), values_t.dtype))
    cnt = mask_t.sum(axis=1, dtype=jnp.int32)
    s = vz.sum(axis=1)
    mn = jnp.where(mask_t, values_t, _type_max(values_t.dtype)).min(axis=1)
    mx = jnp.where(mask_t, values_t, _type_min(values_t.dtype)).max(axis=1)
    mean = s / jnp.maximum(cnt, 1).astype(s.dtype)
    return {"sum": s, "count": cnt, "mean": mean, "min": mn, "max": mx}


# ---------------------------------------------------------------------------
# Tiled interval reductions (time-centric batch operators, TiLT
# arXiv:2301.12030): per-(series, tile) partials answered per window from
# cumulative tile prefixes.  Shared by the PromQL range-vector engine
# (ops/prom.py TiledPrepared): every window is an exact union of
# left-open/right-closed time tiles, so these helpers replace the per-window
# sample walks (vmap'd searchsorted + dense membership tensors) with O(1)
# prefix lookups.  `xp` is numpy or jax.numpy — the host path answers in
# numpy (no dispatch/compile cost on CPU backends), the device path traces
# the identical code under jit.
# ---------------------------------------------------------------------------


def tile_window_sums(tile_vals, ca, cb, xp=None):
    """Per-window sums over contiguous compact-tile ranges [ca, cb) from
    ONE cumulative pass over the tile partials.

    tile_vals: (S, C) per-(series, tile) partial sums; ca/cb: (S, K) int
    compact positions (cb exclusive).  Returns (S, K)."""
    if xp is None:
        xp = jnp
    s_dim = tile_vals.shape[0]
    cc = xp.cumsum(tile_vals, axis=1)
    cc = xp.concatenate(
        [xp.zeros((s_dim, 1), dtype=tile_vals.dtype), cc], axis=1)
    return (xp.take_along_axis(cc, cb, axis=1)
            - xp.take_along_axis(cc, ca, axis=1))


def _accumulate_extreme(x, axis, want_min: bool, reverse: bool, xp):
    if xp is not jnp:  # numpy host path
        import numpy as _np

        op = _np.minimum if want_min else _np.maximum
        if reverse:
            x = _np.flip(x, axis=axis)
        out = op.accumulate(x, axis=axis)
        return _np.flip(out, axis=axis) if reverse else out
    from jax import lax

    fn = lax.cummin if want_min else lax.cummax
    return fn(x, axis=axis, reverse=reverse)


def tile_sliding_extreme(tile_vals, win_tiles: int, start_pos, want_min: bool,
                         xp=None):
    """min/max over EXACTLY win_tiles consecutive tiles starting at compact
    position start_pos (S, K): the fixed-length sliding-extreme trick —
    block the tile axis at the window length, scan each block prefix-from-
    left and suffix-from-right, and any length-L range [i, i+L) spans at
    most two blocks, so its extreme is suffix_at(i) combined with
    prefix_at(i+L-1).  O(C) build, O(1) per window — no dense membership
    tensor, no per-sample rescan (the old chunked (S, 256, N) path)."""
    if xp is None:
        xp = jnp
    import numpy as _np

    s_dim, c_dim = tile_vals.shape
    # identity element computed with numpy dtype logic: the host path must
    # not touch a jax backend just to pick +/-inf
    ndt = _np.dtype(str(tile_vals.dtype))
    if _np.issubdtype(ndt, _np.floating):
        fill = ndt.type(_np.inf if want_min else -_np.inf)
    else:
        info = _np.iinfo(ndt)
        fill = ndt.type(info.max if want_min else info.min)
    ln = max(int(win_tiles), 1)
    blocks = (c_dim + ln - 1) // ln
    pad = blocks * ln - c_dim
    x = xp.concatenate(
        [tile_vals, xp.full((s_dim, pad), fill, dtype=tile_vals.dtype)],
        axis=1) if pad else tile_vals
    x3 = x.reshape(s_dim, blocks, ln)
    suf = _accumulate_extreme(x3, 2, want_min, reverse=True, xp=xp)
    pre = _accumulate_extreme(x3, 2, want_min, reverse=False, xp=xp)
    suf = suf.reshape(s_dim, blocks * ln)
    pre = pre.reshape(s_dim, blocks * ln)
    hi = xp.clip(start_pos + (ln - 1), 0, blocks * ln - 1)
    lo = xp.clip(start_pos, 0, blocks * ln - 1)
    a = xp.take_along_axis(suf, lo, axis=1)
    b = xp.take_along_axis(pre, hi, axis=1)
    return xp.minimum(a, b) if want_min else xp.maximum(a, b)


def _type_max(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype)


def _type_min(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).min, dtype)
