"""Aggregate function registry: InfluxQL call name -> device reduction.

The declarative replacement for the reference's call-processor dispatch
(engine/executor/call_processor.go + agg_func.go): each entry knows how to
compute per-segment outputs from a masked device batch and how the executor
should render results.

Contract: fn(values, rel_hi, rel_lo, seg_ids, num_segments, mask, *params)
    -> (out_values, sel_idx | None)
(rel_hi, rel_lo) is the exact int32 pair encoding of the row's ns time
relative to the batch base (rel >> 30, rel & (2^30-1)) used for device-side
ordering; `sel_idx` (selectors only) is the batch row index of the selected
point, which the executor resolves against its host-side int64 ns times for
exact output timestamps.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from opengemini_tpu.ops import segment as seg


@dataclass(frozen=True)
class AggSpec:
    name: str
    fn: Callable
    is_selector: bool = False  # returns the selected point's own row index
    int_output: bool = False  # count-like: always rendered as int
    params: tuple = field(default_factory=tuple)  # e.g. percentile q


def _wrap_plain(f):
    def run(values, rel_hi, rel_lo, seg_ids, num_segments, mask, *params):
        return f(values, seg_ids, num_segments, mask, *params), None

    return run


def _count(values, rel_hi, rel_lo, seg_ids, n, mask):
    return seg.seg_count(seg_ids, n, mask), None


def _spread(values, rel_hi, rel_lo, seg_ids, n, mask):
    mx = seg.seg_max(values, seg_ids, n, mask)
    mn = seg.seg_min(values, seg_ids, n, mask)
    return mx - mn, None


def _min_sel(values, rel_hi, rel_lo, seg_ids, n, mask):
    return seg.seg_min_selector(values, rel_hi, rel_lo, seg_ids, n, mask)


def _max_sel(values, rel_hi, rel_lo, seg_ids, n, mask):
    return seg.seg_max_selector(values, rel_hi, rel_lo, seg_ids, n, mask)


def _first(values, rel_hi, rel_lo, seg_ids, n, mask):
    return seg.seg_first(values, rel_hi, rel_lo, seg_ids, n, mask)


def _last(values, rel_hi, rel_lo, seg_ids, n, mask):
    return seg.seg_last(values, rel_hi, rel_lo, seg_ids, n, mask)


REGISTRY: dict[str, AggSpec] = {
    "count": AggSpec("count", _count, int_output=True),
    "sum": AggSpec("sum", _wrap_plain(seg.seg_sum)),
    "mean": AggSpec("mean", _wrap_plain(seg.seg_mean)),
    "min": AggSpec("min", _min_sel, is_selector=True),
    "max": AggSpec("max", _max_sel, is_selector=True),
    "first": AggSpec("first", _first, is_selector=True),
    "last": AggSpec("last", _last, is_selector=True),
    "spread": AggSpec("spread", _spread),
    "stddev": AggSpec("stddev", _wrap_plain(seg.seg_stddev)),
    "median": AggSpec("median", _wrap_plain(seg.seg_median)),
    "percentile": AggSpec("percentile", _wrap_plain(seg.seg_percentile)),
    "count_distinct": AggSpec(
        "count_distinct", _wrap_plain(seg.seg_count_distinct), int_output=True
    ),
}


def get(name: str) -> AggSpec:
    spec = REGISTRY.get(name.lower())
    if spec is None:
        raise KeyError(f"unsupported aggregate function: {name}")
    return spec


def supported() -> list[str]:
    return sorted(REGISTRY)
