"""Aggregate function registry: InfluxQL call name -> device reduction.

The declarative replacement for the reference's call-processor dispatch
(engine/executor/call_processor.go + agg_func.go): each entry knows how to
compute per-segment outputs from a masked device batch and how the executor
should render results (selector timestamps, integer vs float output).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from opengemini_tpu.ops import segment as seg


@dataclass(frozen=True)
class AggSpec:
    name: str
    # fn(values, rel_t, seg_ids, num_segments, mask, *params)
    #   -> (out_values, out_rel_t | None)
    fn: Callable
    is_selector: bool = False  # returns the selected point's own timestamp
    int_output: bool = False  # count-like: render as int
    needs_time: bool = False
    params: tuple = field(default_factory=tuple)  # e.g. percentile q


def _wrap_plain(f):
    def run(values, rel_t, seg_ids, num_segments, mask, *params):
        return f(values, seg_ids, num_segments, mask, *params), None

    return run


def _count(values, rel_t, seg_ids, n, mask):
    return seg.seg_count(seg_ids, n, mask), None


def _spread(values, rel_t, seg_ids, n, mask):
    mx = seg.seg_max(values, seg_ids, n, mask)
    mn = seg.seg_min(values, seg_ids, n, mask)
    return mx - mn, None


def _min_sel(values, rel_t, seg_ids, n, mask):
    v, t, _ = seg.seg_min_selector(values, rel_t, seg_ids, n, mask)
    return v, t


def _max_sel(values, rel_t, seg_ids, n, mask):
    v, t, _ = seg.seg_max_selector(values, rel_t, seg_ids, n, mask)
    return v, t


def _first(values, rel_t, seg_ids, n, mask):
    v, t, _ = seg.seg_first(values, rel_t, seg_ids, n, mask)
    return v, t


def _last(values, rel_t, seg_ids, n, mask):
    v, t, _ = seg.seg_last(values, rel_t, seg_ids, n, mask)
    return v, t


REGISTRY: dict[str, AggSpec] = {
    "count": AggSpec("count", _count, int_output=True),
    "sum": AggSpec("sum", _wrap_plain(seg.seg_sum)),
    "mean": AggSpec("mean", _wrap_plain(seg.seg_mean)),
    "min": AggSpec("min", _min_sel, is_selector=True, needs_time=True),
    "max": AggSpec("max", _max_sel, is_selector=True, needs_time=True),
    "first": AggSpec("first", _first, is_selector=True, needs_time=True),
    "last": AggSpec("last", _last, is_selector=True, needs_time=True),
    "spread": AggSpec("spread", _spread),
    "stddev": AggSpec("stddev", _wrap_plain(seg.seg_stddev)),
    "median": AggSpec("median", _wrap_plain(seg.seg_median)),
    "percentile": AggSpec("percentile", _wrap_plain(seg.seg_percentile)),
    "count_distinct": AggSpec(
        "count_distinct", _wrap_plain(seg.seg_count_distinct), int_output=True
    ),
}


def get(name: str) -> AggSpec:
    spec = REGISTRY.get(name.lower())
    if spec is None:
        raise KeyError(f"unsupported aggregate function: {name}")
    return spec


def supported() -> list[str]:
    return sorted(REGISTRY)
