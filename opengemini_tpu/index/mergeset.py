"""ctypes binding for the C++ mergeset series index
(native/seriesindex.cpp) — the high-cardinality replacement for the
dict-based SeriesIndex, same API.

Role of the reference's tsi mergeset index
(engine/index/tsi/mergeset_index.go over lib/util/lifted/vm/mergeset):
sorted immutable posting runs on disk (mmap, binary search) + a
WAL-backed memtable, merged inline — million-series indexes open in
seconds with bounded RSS instead of rebuilding Python dicts from a JSON
log. Regex matching stays in Python (re semantics) over the C-side
distinct tag-value enumeration; everything exact runs native.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import re
import struct
import subprocess
import threading
from opengemini_tpu.utils import lockdep

import numpy as np

from opengemini_tpu.ingest.line_protocol import series_key

_LIB = None
_TRIED = False


def _lib_path() -> str:
    return os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "native",
        "libogtseriesindex.so"))


def load():
    """The loaded library or None. Never raises."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        _build()
    if not os.path.exists(path):
        return None
    _LIB = _load_at(path)
    if _LIB is None:
        # a stale .so from before a symbol was added: rebuild once and
        # retry — refusing to open existing mergeset dirs over a fixable
        # build is much worse than one make invocation
        _build()
        _LIB = _load_at(path)
    return _LIB


def _load_at(path: str):
    try:
        lib = ctypes.CDLL(path)
        u64 = ctypes.c_uint64
        p = ctypes.c_void_p
        cp = ctypes.c_char_p
        u64p = ctypes.POINTER(u64)
        for name, res, args in [
            ("msi_open", p, [cp]),
            ("msi_close", None, [p]),
            ("msi_free", None, [p]),
            ("msi_insert", u64, [p, cp, u64, u64]),
            ("msi_insert_keys", u64, [p, cp, u64, u64, u64p]),
            ("msi_lookup", u64, [p, cp, u64]),
            ("msi_has_live", ctypes.c_int, [p, cp, u64]),
            ("msi_series_ids", p, [p, cp, u64, u64p]),
            ("msi_match_eq", p, [p, cp, u64, cp, u64, cp, u64, u64p]),
            ("msi_enum_field", p, [p, ctypes.c_char, cp, u64,
                                   ctypes.c_uint32, u64p, u64p]),
            ("msi_key_of", p, [p, u64, u64p]),
            ("msi_keys_of", p, [p, u64p, u64, u64p]),
            ("msi_remove_sids", None, [p, u64p, u64]),
            ("msi_flush", None, [p]),
            ("msi_compact", None, [p]),
            ("msi_stats", None, [p, u64p, u64p, u64p, u64p]),
        ]:
            fn = getattr(lib, name)
            fn.restype = res
            fn.argtypes = args
        return lib
    except (OSError, AttributeError):
        return None


def _build() -> None:
    d = os.path.dirname(_lib_path())
    try:
        subprocess.run(["make", "-C", d, "libogtseriesindex.so"],
                       check=True, capture_output=True)
    except (OSError, subprocess.CalledProcessError):
        pass


def _field(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


def _pack_series(key: str, mst: str, tags: tuple) -> bytes:
    out = [_field(key.encode()), _field(mst.encode()),
           struct.pack("<I", len(tags))]
    for k, v in tags:
        out.append(_field(k.encode()))
        out.append(_field(v.encode()))
    return b"".join(out)


def _unpack_series(blob: bytes):
    off = 0

    def field():
        nonlocal off
        (n,) = struct.unpack_from("<I", blob, off)
        off += 4
        f = blob[off : off + n]
        off += n
        return f

    key = field().decode()
    mst = field().decode()
    (ntags,) = struct.unpack_from("<I", blob, off)
    off += 4
    tags = tuple(
        (field().decode(), field().decode()) for _ in range(ntags)
    )
    return key, mst, tags


_TAGS_CACHE_MAX = 200_000


class MergesetIndex:
    """Drop-in for index.inverted.SeriesIndex backed by the native
    mergeset engine. `path` is a DIRECTORY (runs + wal live inside)."""

    def __init__(self, path: str):
        lib = load()
        if lib is None:
            raise OSError("native series index library unavailable")
        self._lib = lib
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._h = lib.msi_open(path.encode())
        if not self._h:
            raise OSError(f"msi_open failed for {path!r}")
        self._lock = lockdep.RLock()
        # sid -> (mst, tags): bounded decode cache for the render path
        self._tags_cache: dict[int, tuple] = {}
        # series key -> sid: the ingest hot path is overwhelmingly repeat
        # series; skip the native call for those
        self._key_cache: dict[str, int] = {}
        # label-engine invalidation protocol: per-measurement insert
        # generation + index-wide removal epoch (index.labels snapshots
        # and the tag_values cache key off label_gen())
        self._label_gens: dict[str, int] = {}
        self._label_epoch = 0
        # (measurement, key) -> (label_gen, sorted values)
        self._tagvals_cache: dict[tuple, tuple] = {}

    @contextlib.contextmanager
    def _native(self):
        """Serialized access to the live native handle. A closed index
        raises a clean OSError; holding the (reentrant) lock for the
        call's duration means a racing close() can never free the handle
        under a reader (use-after-free -> process crash)."""
        with self._lock:
            if not self._h:
                raise OSError("series index is closed")
            yield self._h

    def label_gen(self, measurement: str) -> tuple:
        return (self._label_epoch, self._label_gens.get(measurement, 0))

    def _label_bump(self, measurement: str) -> None:
        self._label_gens[measurement] = \
            self._label_gens.get(measurement, 0) + 1

    # -- write side ---------------------------------------------------------

    def get_or_create(self, measurement: str, tags: tuple) -> int:
        key = series_key(measurement, tags)
        sid = self._key_cache.get(key)
        if sid is not None:
            return sid
        return self._insert_series(key, measurement, tags)

    def get_or_create_by_key(self, key: str) -> int:
        """Canonical-key ingest path (native parser output); repeat series
        never reconstruct tags."""
        sid = self._key_cache.get(key)
        if sid is not None:
            return sid
        from opengemini_tpu.index.inverted import parse_series_key

        measurement, tags = parse_series_key(key)
        return self._insert_series(key, measurement, tags)

    def _insert_series(self, key: str, measurement: str, tags: tuple) -> int:
        blob = _pack_series(key, measurement, tags)
        with self._native() as h:
            sid = int(self._lib.msi_insert(h, blob, len(blob), 0))
        self._label_bump(measurement)
        if len(self._key_cache) >= _TAGS_CACHE_MAX:
            self._key_cache.clear()
        self._key_cache[key] = sid
        return sid

    def get_or_create_bulk(self, keys: list[str]) -> list[int]:
        """Batched canonical-key ingest: ONE native call parses and
        inserts every escape-free new key (the per-key Python parse +
        pack + ctypes crossing dominated 1M-series ingest). Keys with
        backslash escapes keep the exact per-key path."""
        out = [0] * len(keys)
        plain_i: list[int] = []
        parts: list[bytes] = []
        cache = self._key_cache
        for i, key in enumerate(keys):
            sid = cache.get(key)
            if sid is not None:
                out[i] = sid
            elif "\\" in key:
                out[i] = self.get_or_create_by_key(key)
            else:
                kb = key.encode()
                parts.append(struct.pack("<I", len(kb)) + kb)
                plain_i.append(i)
        if plain_i:
            if len(cache) + len(plain_i) >= _TAGS_CACHE_MAX:
                cache.clear()
            # chunked native calls: one giant batch would hold the index
            # mutex for the whole 1M-series insert and stall every
            # concurrent reader (lookup/match share the same lock)
            CHUNK = 32_768
            for lo in range(0, len(plain_i), CHUNK):
                idxs = plain_i[lo:lo + CHUNK]
                blob = b"".join(parts[lo:lo + CHUNK])
                sids = (ctypes.c_uint64 * len(idxs))()
                with self._native() as h:
                    done = int(self._lib.msi_insert_keys(
                        h, blob, len(blob), len(idxs), sids))
                if done != len(idxs):
                    raise OSError("series index batch insert failed")
                for i, sid in zip(idxs, sids):
                    out[i] = int(sid)
                    cache[keys[i]] = int(sid)
                    # plain keys carry no escapes, so the measurement is
                    # exactly the prefix before the first comma
                    self._label_bump(keys[i].split(",", 1)[0])
        return out

    def flush(self) -> None:
        with self._native() as h:
            self._lib.msi_flush(h)

    def compact(self) -> None:
        with self._native() as h:
            self._lib.msi_compact(h)

    def close(self) -> None:
        with self._lock:
            if self._h:
                self._lib.msi_close(self._h)
                self._h = None

    # -- read side ----------------------------------------------------------

    def _sid_buf(self, ptr, n: int) -> set[int]:
        try:
            if not n:
                return set()
            raw = ctypes.string_at(ptr, n * 8)
            return set(map(int, np.frombuffer(raw, "<u8")))
        finally:
            self._lib.msi_free(ptr)

    def series_ids(self, measurement: str) -> set[int]:
        m = measurement.encode()
        n = ctypes.c_uint64()
        with self._native() as h:
            ptr = self._lib.msi_series_ids(h, m, len(m), ctypes.byref(n))
        return self._sid_buf(ptr, int(n.value))

    def _match_eq_raw(self, measurement: str, key: str,
                      value: str) -> set[int]:
        m, k, v = measurement.encode(), key.encode(), value.encode()
        n = ctypes.c_uint64()
        with self._native() as h:
            ptr = self._lib.msi_match_eq(
                h, m, len(m), k, len(k), v, len(v), ctypes.byref(n))
        return self._sid_buf(ptr, int(n.value))

    def _with_key(self, measurement: str, key: str) -> set[int]:
        """Series carrying the tag key at all (any value — including an
        EXPLICIT empty value, hence the raw match: the ''-special
        match_eq would recurse). Only empty-value match paths pay
        this union."""
        out: set[int] = set()
        for v in self.tag_values(measurement, key):
            out |= self._match_eq_raw(measurement, key, v)
        return out

    def _match_eq_walk(self, measurement: str, key: str,
                       value: str) -> set[int]:
        """The pre-tier mergeset walk — the oracle the columnar tier is
        fuzzed against (tests/test_labels.py)."""
        if value == "":
            # influx: a missing tag equals the empty string; an explicit
            # '' value stored in the index matches too (raw lookup)
            return (self.series_ids(measurement)
                    - self._with_key(measurement, key)) | \
                self._match_eq_raw(measurement, key, "")
        return self._match_eq_raw(measurement, key, value)

    def _match_neq_walk(self, measurement: str, key: str,
                        value: str) -> set[int]:
        return self.series_ids(measurement) - self._match_eq_walk(
            measurement, key, value)

    def _tier_match(self, op: str, measurement: str, key: str,
                    value: str) -> set[int] | None:
        """Columnar-tier answer as a set (the index API's type), or None
        when the tier is knob-disabled."""
        from opengemini_tpu.index import labels

        tier = labels.tier_for(self)
        if tier is None:
            return None
        arr = labels.match_tier(tier.snapshot(measurement), op, key, value)
        return None if arr is None else set(arr.tolist())

    def match_eq(self, measurement: str, key: str, value: str) -> set[int]:
        if value == "":
            # the empty-value walk pays one cgo match_eq per distinct
            # value (_with_key) — one posting-tier mask replaces it
            got = self._tier_match("=", measurement, key, value)
            if got is not None:
                return got
        return self._match_eq_walk(measurement, key, value)

    def match_neq(self, measurement: str, key: str, value: str) -> set[int]:
        # the walk rebuilds the full series_ids set to subtract from
        got = self._tier_match("!=", measurement, key, value)
        if got is not None:
            return got
        return self._match_neq_walk(measurement, key, value)

    def _enum(self, kind: bytes, pfx: bytes, idx: int) -> list[str]:
        n = ctypes.c_uint64()
        blen = ctypes.c_uint64()
        with self._native() as h:
            ptr = self._lib.msi_enum_field(
                h, kind, pfx, len(pfx), idx, ctypes.byref(n),
                ctypes.byref(blen))
        try:
            raw = ctypes.string_at(ptr, blen.value)
        finally:
            self._lib.msi_free(ptr)
        out = []
        off = 0
        for _ in range(n.value):
            (ln,) = struct.unpack_from("<I", raw, off)
            off += 4
            out.append(raw[off : off + ln].decode())
            off += ln
        return out

    def tag_keys(self, measurement: str) -> list[str]:
        return sorted(self._enum(b"P", _field(measurement.encode()), 1))

    _TAGVALS_CACHE_MAX = 4096

    def tag_values(self, measurement: str, key: str) -> list[str]:
        # generation-keyed cache: match_regex re-enumerated (and
        # re-sorted) the whole value list through cgo on EVERY call —
        # twice per query for empty-matching selectors. Callers get the
        # cached list itself; the meta/match paths never mutate it.
        gen = self.label_gen(measurement)
        got = self._tagvals_cache.get((measurement, key))
        if got is not None and got[0] == gen:
            return got[1]
        pfx = _field(measurement.encode()) + _field(key.encode())
        vals = sorted(self._enum(b"P", pfx, 2))
        if len(self._tagvals_cache) >= self._TAGVALS_CACHE_MAX:
            self._tagvals_cache.clear()
        self._tagvals_cache[(measurement, key)] = (gen, vals)
        return vals

    def match_regex(self, measurement: str, key: str, pattern: str,
                    negate: bool = False) -> set[int]:
        got = self._tier_match("!~" if negate else "=~",
                               measurement, key, pattern)
        if got is not None:
            return got
        return self._match_regex_walk(measurement, key, pattern, negate)

    def _match_regex_walk(self, measurement: str, key: str, pattern: str,
                          negate: bool = False) -> set[int]:
        rx = re.compile(pattern)
        hit: set[int] = set()
        empty_matches = bool(rx.search(""))  # missing tag is "" (influx)
        with_key: set[int] = set()
        for v in self.tag_values(measurement, key):
            if rx.search(v):
                got = self._match_eq_raw(measurement, key, v)
                hit |= got
                if empty_matches:
                    with_key |= got
            elif empty_matches:
                with_key |= self._match_eq_raw(measurement, key, v)
        if empty_matches:
            hit |= self.series_ids(measurement) - with_key
        if negate:
            return self.series_ids(measurement) - hit
        return hit

    def tags_of(self, sid: int) -> dict[str, str]:
        got = self._tags_cache.get(sid)
        if got is None:
            n = ctypes.c_uint64()
            with self._native() as h:
                ptr = self._lib.msi_key_of(h, sid, ctypes.byref(n))
            try:
                raw = ctypes.string_at(ptr, n.value)
            finally:
                self._lib.msi_free(ptr)
            if not raw:
                raise KeyError(sid)
            _key, mst, tags = _unpack_series(raw)
            if len(self._tags_cache) >= _TAGS_CACHE_MAX:
                self._tags_cache.clear()
            got = self._tags_cache[sid] = (mst, tags)
        return dict(got[1])

    def series_entry(self, sid: int) -> tuple[str, tuple]:
        self.tags_of(sid)  # populate the cache
        mst, tags = self._tags_cache[sid]
        return mst, tags

    def entries_bulk(self, sids,
                     cache: bool = True) -> list[tuple[str, tuple] | None]:
        """Batch series_entry: ONE native call for all sids (the per-sid
        ctypes round-trip dominates high-cardinality label assembly).
        Missing sids yield None. ``cache=False`` skips populating the
        shared tags cache — million-row label-tier builds must not evict
        the render path's working set (or balloon it past the bound)."""
        import numpy as _np

        sids = [int(s) for s in _np.asarray(sids, dtype=_np.uint64).tolist()]
        # results assemble into a local map FIRST: evicting the shared
        # cache must never drop answers for already-cached sids in this
        # very request
        local = {s: self._tags_cache[s] for s in sids if s in self._tags_cache}
        missing = [s for s in sids if s not in local]
        if missing:
            arr = (ctypes.c_uint64 * len(missing))(*missing)
            n = ctypes.c_uint64()
            with self._native() as h:
                ptr = self._lib.msi_keys_of(h, arr, len(missing), ctypes.byref(n))
            try:
                raw = ctypes.string_at(ptr, n.value)
            finally:
                self._lib.msi_free(ptr)
            off = 0
            for sid in missing:
                (ln,) = struct.unpack_from("<I", raw, off)
                off += 4
                if ln:
                    _key, mst, tags = _unpack_series(raw[off:off + ln])
                    local[sid] = (mst, tags)
                off += ln
            if cache:
                if len(self._tags_cache) + len(missing) >= _TAGS_CACHE_MAX:
                    self._tags_cache.clear()
                self._tags_cache.update(local)
        return [local.get(s) for s in sids]

    def iter_series_entries(self):
        for m in self.measurements():
            for sid in sorted(self.series_ids(m)):
                yield self.series_entry(sid)

    def measurements(self) -> list[str]:
        # a measurement whose every series was removed must not list:
        # membership postings are tombstone-filtered, 'M' items are not.
        # msi_has_live early-exits — never decodes whole posting sets
        out = []
        for m in self._enum(b"M", b"", 0):
            mb = m.encode()
            with self._native() as h:
                if self._lib.msi_has_live(h, mb, len(mb)):
                    out.append(m)
        return sorted(out)

    # -- deletion ------------------------------------------------------------

    def remove_sids(self, sids: set[int]) -> None:
        if not sids:
            return
        arr = (ctypes.c_uint64 * len(sids))(*sorted(sids))
        with self._native() as h:
            self._lib.msi_remove_sids(h, arr, len(sids))
        for sid in sids:
            self._tags_cache.pop(sid, None)
        self._key_cache.clear()  # deletes are rare; a full drop is fine
        # removals don't know their measurements: the index-wide epoch
        # invalidates every label-tier snapshot and tag_values entry
        self._label_epoch += 1
        self._tagvals_cache.clear()

    def stats(self) -> dict:
        a, b, c, d = (ctypes.c_uint64() for _ in range(4))
        with self._native() as h:
            self._lib.msi_stats(h, *(ctypes.byref(x) for x in (a, b, c, d)))
        return {"mem_items": a.value, "runs": b.value,
                "run_items": c.value, "next_sid": d.value}


def open_series_index(shard_path: str):
    """Index factory for a shard directory: the native mergeset engine
    when available, migrating any legacy series.log once; the dict
    SeriesIndex otherwise."""
    from opengemini_tpu.index.inverted import SeriesIndex

    legacy_log = os.path.join(shard_path, "series.log")
    msi_dir = os.path.join(shard_path, "seriesidx")
    if load() is None:
        if os.path.isdir(msi_dir) and os.listdir(msi_dir):
            # the shard's series live ONLY in the mergeset dir: a silent
            # dict fallback would restart sid numbering at 1 and alias
            # unrelated series onto existing TSF chunks
            raise OSError(
                f"native series index library unavailable but {msi_dir!r} "
                "holds this shard's index — rebuild native/ (make -C native)"
            )
        return SeriesIndex(legacy_log)
    idx = MergesetIndex(msi_dir)
    if os.path.exists(legacy_log):
        legacy = SeriesIndex(legacy_log)
        for sid, (mst, tags) in sorted(legacy.sid_to_series.items()):
            blob = _pack_series(series_key(mst, tags), mst, tags)
            idx._lib.msi_insert(idx._h, blob, len(blob), sid)
        legacy.close()
        idx.compact()
        idx.flush()
        os.replace(legacy_log, legacy_log + ".migrated")
    return idx
