"""Series indexing (reference: engine/index/tsi mergeset inverted index)."""
