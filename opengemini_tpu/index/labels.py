"""Columnar label engine: per-measurement dictionary-encoded tag
columns + sorted int64 posting arrays over the durable series index.

Role of the reference's high-cardinality matcher path (tsi mergeset
search.go): answer label selectors over millions of series without one
index round-trip per distinct value. The durable index (mergeset or
dict) stays the source of truth; this tier is a lazily-built, cache-like
projection of one measurement's series:

  sids   sorted int64 array of the measurement's live series ids;
         row i of every column describes series sids[i]
  cols   tag key -> _KeyCol: the key's distinct values dictionary-
         encoded (sorted list + value->vid map) and one int32 vid per
         row, -1 where the series lacks the key

Matching is vectorized over those arrays:
  =  / != dictionary lookup + posting slice / column mask
  =~ / !~ the compiled regex runs ONCE per DISTINCT value over the
          dictionary, producing a boolean LUT; one gather of the LUT
          through the vid column yields the row mask (optionally routed
          to the device — or hash-sharded over a configured mesh — as a
          scan->filter kernel via the offload planner)
All results are SORTED unique int64 sid arrays, so matcher composition
is np.intersect1d/union1d/setdiff1d instead of Python set algebra.

Consistency: the base index bumps a per-measurement generation counter
on insert and an index-wide epoch on removal (label_gen()); a snapshot
records the generation it was built from and rebuilds lazily when it
goes stale. Results are bit-identical to the set-returning index walk
(the oracle — tests/test_labels.py fuzzes the equivalence), including
the influx missing-tag-equals-"" rule. `OGT_LABEL_INDEX=0` disables the
tier entirely and every caller falls back to the walk.
"""

from __future__ import annotations

import os
import re
import threading
import time

import numpy as np

from opengemini_tpu.utils import lockdep
from opengemini_tpu.utils.stats import GLOBAL as _STATS

EMPTY_SIDS = np.empty(0, np.int64)

# below this row count the LUT gather is memcpy-bound on the host and
# the device round-trip can never win — don't even ask the planner
_DEVICE_MIN_ROWS = 65_536

_FNV = np.uint64(0x9E3779B97F4A7C15)  # Fibonacci hashing multiplier


def enabled() -> bool:
    return os.environ.get("OGT_LABEL_INDEX", "1") != "0"


def _device_mode() -> str:
    """'' auto (planner decides, static host), '0' host-only,
    '1' device/mesh static."""
    return os.environ.get("OGT_LABEL_INDEX_DEVICE", "")


def tier_for(index) -> "LabelTier | None":
    """The index's columnar tier, or None when the knob is off or the
    index lacks the label_gen generation protocol (remote/meta proxies
    keep the set walk)."""
    if not enabled():
        return None
    tier = getattr(index, "_label_tier", None)
    if tier is None:
        if not hasattr(index, "label_gen"):
            return None
        tier = index._label_tier = LabelTier(index)
    return tier


class _KeyCol:
    """One tag key's dictionary-encoded column: sorted distinct values,
    value->vid map, and an int32 vid per snapshot row (-1 = series has
    no such tag). Posting arrays derive lazily from ONE stable argsort
    of the column — postings(vid) slices are sorted row indices, hence
    sorted sid arrays after gathering through the snapshot's sids."""

    __slots__ = ("values", "vid_map", "col", "n_present",
                 "_rows_sorted", "_bounds", "_values_u")

    def __init__(self, values: list[str], vid_map: dict, col: np.ndarray,
                 n_present: int):
        self.values = values
        self.vid_map = vid_map
        self.col = col
        self.n_present = n_present
        self._rows_sorted = None
        self._bounds = None
        self._values_u = None

    def values_u(self) -> np.ndarray:
        """The distinct values as a numpy unicode array (lazy; feeds the
        vectorized np.char substring prefilter for regex matchers)."""
        if self._values_u is None:
            self._values_u = np.asarray(self.values, dtype=np.str_)
        return self._values_u

    def _postings(self):
        if self._rows_sorted is None:
            pres = np.flatnonzero(self.col >= 0)
            vids = self.col[pres]
            order = np.argsort(vids, kind="stable")
            self._rows_sorted = pres[order]
            self._bounds = np.searchsorted(
                vids[order], np.arange(len(self.values) + 1))
        return self._rows_sorted, self._bounds

    def counts(self) -> np.ndarray:
        _, bounds = self._postings()
        return np.diff(bounds)

    def posting_rows(self, vid: int) -> np.ndarray:
        rows, bounds = self._postings()
        return rows[bounds[vid]:bounds[vid + 1]]


_RX_SPECIALS = frozenset("([{.*+?\\^$)|")
_RX_QUANTS = frozenset("*+?{")
_PREFILTER_MIN_VALUES = 4096  # below this a plain LUT pass is cheaper


def _literal_head(pattern: str) -> str:
    """The pattern's leading literal run — a MANDATORY substring of any
    re.search hit (the match starts by consuming it), so it can gate a
    vectorized substring prefilter over the distinct values. Returns ''
    when no safe literal exists: any alternation may bypass the head
    (`abc|x`), and a quantifier makes the preceding char optional."""
    if "|" in pattern:
        return ""
    if pattern.startswith("^"):
        pattern = pattern[1:]
    out: list[str] = []
    for ch in pattern:
        if ch in _RX_SPECIALS:
            if ch in _RX_QUANTS and out:
                out.pop()  # `ab*`: the b is optional
            break
        out.append(ch)
    return "".join(out)


class _Snapshot:
    """One measurement's columnar view at a recorded generation. All
    match_* methods return sorted unique int64 sid arrays."""

    __slots__ = ("gen", "measurement", "sids", "cols", "n", "_mesh_parts",
                 "_rx_luts")

    def __init__(self, gen, measurement: str, sids: np.ndarray, cols: dict):
        self.gen = gen
        self.measurement = measurement
        self.sids = sids
        self.cols = cols
        self.n = len(sids)
        self._mesh_parts = None  # (epoch, nparts, [row arrays])
        # (key, pattern) -> bool LUT over distinct values; the snapshot
        # is immutable per generation, so entries never go stale —
        # repeated dashboard selectors skip the automaton entirely
        self._rx_luts: dict = {}

    # -- matchers -------------------------------------------------------

    def match_eq(self, key: str, value: str) -> np.ndarray:
        kc = self.cols.get(key)
        if value == "":
            # influx: a missing tag equals the empty string; an explicit
            # '' value stored in the index matches too
            if kc is None:
                return self.sids
            mask = kc.col < 0
            vid = kc.vid_map.get("")
            if vid is not None:
                mask = mask | (kc.col == vid)
            return self.sids[mask]
        if kc is None:
            return EMPTY_SIDS
        vid = kc.vid_map.get(value)
        if vid is None:
            return EMPTY_SIDS
        return self.sids[kc.posting_rows(vid)]

    def match_neq(self, key: str, value: str) -> np.ndarray:
        kc = self.cols.get(key)
        if value == "":
            if kc is None:
                return EMPTY_SIDS
            mask = kc.col >= 0
            vid = kc.vid_map.get("")
            if vid is not None:
                mask = mask & (kc.col != vid)
            return self.sids[mask]
        if kc is None:
            return self.sids
        vid = kc.vid_map.get(value)
        if vid is None:
            return self.sids
        return self.sids[kc.col != vid]  # -1 (missing) != vid matches

    def match_regex(self, key: str, pattern: str, negate: bool = False,
                    head: "str | None" = None) -> np.ndarray:
        """`head` is an optional mandatory-substring hint for callers
        that wrap the user pattern (promql anchors as ^(?:p)$, hiding
        the literal run from _literal_head); default derives it from
        `pattern` itself (influx search semantics)."""
        rx = re.compile(pattern)
        empty_matches = bool(rx.search(""))  # missing tag is "" (influx)
        kc = self.cols.get(key)
        if kc is None:
            hit = empty_matches != negate
            return self.sids if hit else EMPTY_SIDS
        nvals = len(kc.values)
        lut = self._rx_luts.get((key, pattern))
        if lut is None:
            _STATS.incr("index", "regex_values_total", nvals)
            if head is None:
                head = _literal_head(pattern)
            if len(head) >= 2 and nvals >= _PREFILTER_MIN_VALUES:
                # any search hit must contain the leading literal run:
                # vectorized substring scan bounds the automaton to the
                # candidate values only (high-distinct keys like pod=)
                cand = np.flatnonzero(
                    np.char.find(kc.values_u(), head) >= 0)
                lut = np.zeros(nvals, np.bool_)
                if cand.size:
                    vals = kc.values
                    lut[cand] = np.fromiter(
                        (bool(rx.search(vals[i])) for i in cand.tolist()),
                        np.bool_, cand.size)
                _STATS.incr("index", "regex_prefilter_skipped_total",
                            nvals - int(cand.size))
            else:
                lut = np.fromiter((bool(rx.search(v)) for v in kc.values),
                                  np.bool_, nvals)
            if len(self._rx_luts) >= 128:
                self._rx_luts.clear()
            self._rx_luts[(key, pattern)] = lut
        else:
            _STATS.incr("index", "regex_lut_hits_total")
        # missing rows gather slot nvals: the empty-string verdict
        lut_ext = np.append(lut, np.bool_(empty_matches))
        mask = self._lut_gather(kc, lut_ext)
        if negate:
            mask = ~mask
        return self.sids[mask]

    def match_tag_compare(self, key_a: str, key_b: str,
                          want_equal: bool) -> np.ndarray:
        """tag = tag / tag != tag leaves: two series tags compare equal
        when both are missing or both hold the same value (the per-sid
        tags_of walk's `tags.get(a) == tags.get(b)`), vectorized over
        the two columns."""
        if key_a == key_b:
            return self.sids if want_equal else EMPTY_SIDS
        ca, cb = self.cols.get(key_a), self.cols.get(key_b)
        if ca is None and cb is None:
            eq = np.ones(self.n, np.bool_)
        elif ca is None:
            eq = cb.col < 0
        elif cb is None:
            eq = ca.col < 0
        else:
            eq = _materialized(ca) == _materialized(cb)
        return self.sids[eq if want_equal else ~eq]

    def estimate(self, op: str, key: str, value) -> int:
        """Posting-length selectivity estimate for matcher ordering.
        Regexes are unknown until the automaton runs: worst case."""
        kc = self.cols.get(key)
        if op == "=":
            if value == "":
                miss = self.n - (0 if kc is None else kc.n_present)
                if kc is not None:
                    vid = kc.vid_map.get("")
                    if vid is not None:
                        miss += int(kc.counts()[vid])
                return miss
            if kc is None:
                return 0
            vid = kc.vid_map.get(value)
            return 0 if vid is None else int(kc.counts()[vid])
        if op == "!=":
            return self.n - self.estimate("=", key, value)
        return self.n

    # -- the LUT gather (host / device / mesh) --------------------------

    def _lut_gather(self, kc: _KeyCol, lut_ext: np.ndarray) -> np.ndarray:
        nvals = len(kc.values)
        col_idx = np.where(kc.col < 0, np.int32(nvals), kc.col)
        route = _route_gather(self.n, nvals)
        if route == "host":
            return lut_ext[col_idx]
        t0 = time.perf_counter()
        try:
            if route == "mesh":
                mask = self._gather_mesh(col_idx, lut_ext)
            else:
                mask = _gather_device(col_idx, lut_ext)
        except Exception:
            # any device failure keeps the query correct on the host;
            # the planner never hears about the broken route's wall
            _STATS.incr("index", "gather_fallback_total")
            return lut_ext[col_idx]
        _observe_gather(self.n, nvals, route, time.perf_counter() - t0)
        return mask

    def _gather_mesh(self, col_idx: np.ndarray,
                     lut_ext: np.ndarray) -> np.ndarray:
        """Hash-partition rows by series id over the mesh devices and
        gather each partition on its device — the same series-axis
        sharding the scan kernels use, applied to index probes. The
        scattered-back mask is bit-identical to the host gather."""
        import jax
        import jax.numpy as jnp

        from opengemini_tpu.parallel import runtime as prt
        from opengemini_tpu.utils import devobs

        mesh = prt.get_mesh()
        if mesh is None:
            return _gather_device(col_idx, lut_ext)
        devs = list(mesh.devices.flat)
        parts = self._hash_parts(len(devs))
        mask = np.empty(self.n, np.bool_)
        shipped = 0
        outs = []
        for rows, dev in zip(parts, devs):
            if not len(rows):
                outs.append(None)
                continue
            sub = jax.device_put(col_idx[rows], dev)
            lutd = jax.device_put(lut_ext, dev)
            shipped += int(sub.nbytes) + int(lutd.nbytes)
            outs.append(jnp.take(lutd, sub, mode="clip"))
        devobs.note_transfer("h2d", "label-match", shipped, mesh=True)
        got = 0
        for rows, out in zip(parts, outs):
            if out is None:
                continue
            res = np.asarray(out)
            got += res.nbytes
            mask[rows] = res
        devobs.note_transfer("d2h", "label-match", got, mesh=True)
        return mask

    def _hash_parts(self, nparts: int) -> list:
        from opengemini_tpu.parallel import runtime as prt

        epoch = prt.mesh_epoch()
        cached = self._mesh_parts
        if cached is not None and cached[0] == epoch and cached[1] == nparts:
            return cached[2]
        h = (self.sids.astype(np.uint64) * _FNV) >> np.uint64(33)
        part = (h % np.uint64(nparts)).astype(np.int64)
        rows = [np.flatnonzero(part == p) for p in range(nparts)]
        self._mesh_parts = (epoch, nparts, rows)
        return rows


def _materialized(kc: _KeyCol) -> np.ndarray:
    """The column as an object array of value strings, None where the
    series lacks the key (matches dict.get semantics)."""
    ext = np.empty(len(kc.values) + 1, object)
    ext[:len(kc.values)] = kc.values
    ext[len(kc.values)] = None
    idx = np.where(kc.col < 0, len(kc.values), kc.col)
    return ext[idx]


def _gather_device(col_idx: np.ndarray, lut_ext: np.ndarray) -> np.ndarray:
    import jax
    import jax.numpy as jnp

    from opengemini_tpu.utils import devobs

    cd = jax.device_put(col_idx)
    ld = jax.device_put(lut_ext)
    devobs.note_transfer("h2d", "label-match",
                         int(cd.nbytes) + int(ld.nbytes))
    out = np.asarray(jnp.take(ld, cd, mode="clip"))
    devobs.note_transfer("d2h", "label-match", out.nbytes)
    return out


def _route_gather(n_rows: int, n_vals: int) -> str:
    mode = _device_mode()
    if mode == "0" or n_rows < _DEVICE_MIN_ROWS:
        return "host"
    try:
        from opengemini_tpu.parallel import runtime as prt
        from opengemini_tpu.query import offload

        mesh = prt.get_mesh()
        candidates = ["host", "device"]
        if mesh is not None:
            candidates.append("mesh")
        static = "host"
        if mode == "1":
            static = "mesh" if mesh is not None else "device"
        return offload.GLOBAL.decide(
            "label_match", (n_rows, n_vals), candidates, static,
            stage="label-match",
            bytes_hint={"device": n_rows * 4 + n_vals + 1,
                        "mesh": n_rows * 4 + n_vals + 1})
    except Exception:
        return "host"


def _observe_gather(n_rows: int, n_vals: int, route: str,
                    seconds: float) -> None:
    try:
        from opengemini_tpu.query import offload

        offload.GLOBAL.observe("label_match", (n_rows, n_vals), route,
                               seconds)
    except Exception:
        # a failed telemetry feed must never fail the query; the count
        # keeps the loss visible in /debug/vars
        _STATS.incr("index", "gather_observe_errors_total")


def _build_snapshot(index, measurement: str, gen) -> _Snapshot:
    sid_set = index.series_ids(measurement)
    if not sid_set:
        return _Snapshot(gen, measurement, EMPTY_SIDS, {})
    sids = np.fromiter(sid_set, np.int64, len(sid_set))
    sids.sort()
    if hasattr(index, "entries_bulk"):
        try:
            entries = index.entries_bulk(sids, cache=False)
        except TypeError:  # duck-typed index without the cache knob
            entries = index.entries_bulk(sids)
    else:
        entries = [index.series_entry(int(s)) for s in sids]
    n = len(sids)
    per_key: dict[str, tuple] = {}  # key -> (rows, vals)
    for row, entry in enumerate(entries):
        if entry is None:
            continue
        for k, v in entry[1]:
            bucket = per_key.get(k)
            if bucket is None:
                bucket = per_key[k] = ([], [])
            bucket[0].append(row)
            bucket[1].append(v)
    cols: dict[str, _KeyCol] = {}
    for k, (rows, vals) in per_key.items():
        distinct = sorted(set(vals))
        vid_map = {v: i for i, v in enumerate(distinct)}
        col = np.full(n, -1, np.int32)
        col[np.asarray(rows, np.int64)] = np.fromiter(
            (vid_map[v] for v in vals), np.int32, len(vals))
        kc = cols[k] = _KeyCol(distinct, vid_map, col, len(vals))
        if len(distinct) >= _PREFILTER_MIN_VALUES:
            kc.values_u()  # pay the U-array conversion here, not on the
            # first regex probe — high-distinct keys are the ones whose
            # matchers need the vectorized substring prefilter
    return _Snapshot(gen, measurement, sids, cols)


class LabelTier:
    """Lazily-built columnar snapshots per measurement, LRU-bounded.
    Builds run OUTSIDE the tier lock (entries_bulk takes the index's own
    lock; tier lock -> index lock nesting never happens), so a racing
    insert mid-build at worst yields a snapshot already stale on arrival
    — the recorded pre-build generation forces the next probe to
    rebuild. Builds are SINGLE-FLIGHT per measurement: when a
    generation bump invalidates a hot snapshot, concurrent probes wait
    on the in-progress build instead of each re-walking the index (the
    churn thundering herd: N readers x an O(series) build per churn)."""

    MAX_SNAPSHOTS = 64

    def __init__(self, index):
        self._index = index
        self._lock = lockdep.Lock()
        self._snaps: dict[str, _Snapshot] = {}
        self._building: dict = {}  # measurement -> (gen, Event)

    def snapshot(self, measurement: str) -> _Snapshot:
        while True:
            gen = self._index.label_gen(measurement)
            with self._lock:
                snap = self._snaps.get(measurement)
                if snap is not None:
                    if snap.gen == gen:
                        # move-to-end: dict order is the LRU order
                        self._snaps.pop(measurement)
                        self._snaps[measurement] = snap
                        _STATS.incr("index", "tier_hits_total")
                        return snap
                    _STATS.incr("index", "tier_stale_total")
                pending = self._building.get(measurement)
                if pending is None or pending[0] != gen:
                    ev = threading.Event()
                    self._building[measurement] = (gen, ev)
                    break  # this thread owns the build for `gen`
                ev = pending[1]
            # another probe is building this generation: wait for it and
            # re-check the cache (timeout so a failed builder can't park
            # waiters forever; the loop then claims the build itself)
            ev.wait(timeout=30.0)
            _STATS.incr("index", "tier_build_waits_total")
        try:
            snap = _build_snapshot(self._index, measurement, gen)
            _STATS.incr("index", "tier_builds_total")
            with self._lock:
                self._snaps.pop(measurement, None)
                self._snaps[measurement] = snap
                while len(self._snaps) > self.MAX_SNAPSHOTS:
                    self._snaps.pop(next(iter(self._snaps)))
        finally:
            with self._lock:
                cur = self._building.get(measurement)
                if cur is not None and cur[1] is ev:
                    del self._building[measurement]
            ev.set()
        return snap


def match_tier(snap: _Snapshot, op: str, key: str, value: str):
    """Operator dispatch over one snapshot; returns a sorted int64 sid
    array, or None for an operator the tier does not handle."""
    if op == "=":
        return snap.match_eq(key, value)
    if op in ("!=", "<>"):
        return snap.match_neq(key, value)
    if op == "=~":
        return snap.match_regex(key, value)
    if op == "!~":
        return snap.match_regex(key, value, negate=True)
    return None
