"""Inverted tag index: tag postings -> series ids.

The role of the reference's mergeset-based tsi index
(engine/index/tsi/mergeset_index.go, search.go): map tag filters to series
id sets, series ids back to (measurement, tags). In-memory dict postings
with an append-only on-disk log for durability; high-cardinality scaling
later moves the postings into the C++ side, the API stays.

Persistence format (series.log): one JSON array per line,
    [sid, measurement, [[k, v], ...]]
appended on series creation and replayed on open — JSON so arbitrary tag
values (commas, tabs, '=') can never corrupt the log. Writes are buffered
by the shard's WAL-sync cadence.
"""

from __future__ import annotations

import json
import os
import re

from opengemini_tpu.ingest.line_protocol import series_key


def parse_series_key(key: str) -> tuple[str, tuple]:
    """Inverse of line_protocol.series_key: canonical key ->
    (measurement, tags tuple). Components unescape with the parser's own
    helpers so the round-trip is exact."""
    from opengemini_tpu.ingest.line_protocol import _split_escaped, _unescape

    segs = _split_escaped(key, ",")
    mst = _unescape(segs[0])
    tags = []
    for seg in segs[1:]:
        kv = _split_escaped(seg, "=")
        tags.append((_unescape(kv[0]), _unescape(kv[1])))
    return mst, tuple(tags)


class SeriesIndex:
    def __init__(self, path: str | None = None):
        self.path = path
        self.key_to_sid: dict[str, int] = {}
        self.sid_to_series: dict[int, tuple[str, tuple]] = {}
        # measurement -> set[sid]
        self.mst_sids: dict[str, set[int]] = {}
        # (measurement, tag_key, tag_value) -> set[sid]
        self.postings: dict[tuple[str, str, str], set[int]] = {}
        self._next_sid = 1
        # label-engine invalidation protocol (see index.labels): bumped
        # per measurement on insert, index-wide on removal
        self._label_gens: dict[str, int] = {}
        self._label_epoch = 0
        self._log = None
        if path is not None:
            self._replay()
            self._log = open(path, "a", encoding="utf-8")

    # -- write side ---------------------------------------------------------

    def get_or_create(self, measurement: str, tags: tuple) -> int:
        key = series_key(measurement, tags)
        sid = self.key_to_sid.get(key)
        if sid is not None:
            return sid
        return self._insert_logged(measurement, tags, key)

    def get_or_create_by_key(self, key: str) -> int:
        """Canonical-key ingest path (the native parser hands keys, not
        tag tuples); repeat series skip the tag reconstruction entirely."""
        sid = self.key_to_sid.get(key)
        if sid is not None:
            return sid
        measurement, tags = parse_series_key(key)
        return self._insert_logged(measurement, tags, key)

    def _insert_logged(self, measurement: str, tags: tuple, key: str) -> int:
        sid = self._insert(measurement, tags, key)
        if self._log is not None:
            self._log.write(
                json.dumps([sid, measurement, [list(t) for t in tags]]) + "\n"
            )
        return sid

    def _insert(self, measurement: str, tags: tuple, key: str, sid: int | None = None) -> int:
        if sid is None:
            sid = self._next_sid
        self._next_sid = max(self._next_sid, sid + 1)
        self.key_to_sid[key] = sid
        self.sid_to_series[sid] = (measurement, tags)
        self.mst_sids.setdefault(measurement, set()).add(sid)
        for k, v in tags:
            self.postings.setdefault((measurement, k, v), set()).add(sid)
        self._label_gens[measurement] = \
            self._label_gens.get(measurement, 0) + 1
        return sid

    def label_gen(self, measurement: str) -> tuple:
        return (self._label_epoch, self._label_gens.get(measurement, 0))

    def flush(self) -> None:
        if self._log is not None:
            self._log.flush()
            os.fsync(self._log.fileno())

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None

    def _replay(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                try:
                    sid, measurement, tag_list = json.loads(line)
                except ValueError:
                    continue  # torn tail from a crash mid-append
                tags = tuple((k, v) for k, v in tag_list)
                self._insert(measurement, tags, series_key(measurement, tags), sid)

    # -- read side ----------------------------------------------------------

    def series_ids(self, measurement: str) -> set[int]:
        return set(self.mst_sids.get(measurement, ()))

    def tag_values(self, measurement: str, key: str) -> list[str]:
        vals = {
            v
            for (m, k, v) in self.postings
            if m == measurement and k == key
        }
        return sorted(vals)

    def tag_keys(self, measurement: str) -> list[str]:
        return sorted({k for (m, k, _v) in self.postings if m == measurement})

    def _with_key(self, measurement: str, key: str) -> set[int]:
        out: set[int] = set()
        for (m, k, _v), sids in self.postings.items():
            if m == measurement and k == key:
                out |= sids
        return out

    def match_eq(self, measurement: str, key: str, value: str) -> set[int]:
        if value == "":
            # influx: a missing tag equals the empty string
            # (server_test.go With_EmptyTags 'where empty tag'); an
            # explicit '' posting matches too
            return (self.series_ids(measurement)
                    - self._with_key(measurement, key)) | set(
                self.postings.get((measurement, key, ""), ()))
        return set(self.postings.get((measurement, key, value), ()))

    def match_neq(self, measurement: str, key: str, value: str) -> set[int]:
        return self.series_ids(measurement) - self.match_eq(measurement, key, value)

    def match_regex(self, measurement: str, key: str, pattern: str, negate: bool = False) -> set[int]:
        rx = re.compile(pattern)
        hit: set[int] = set()
        for (m, k, v), sids in self.postings.items():
            if m == measurement and k == key and rx.search(v):
                hit |= sids
        if rx.search(""):
            # the missing tag is "" and it matches: series without the
            # key match the pattern too
            hit |= self.series_ids(measurement) - self._with_key(
                measurement, key)
        if negate:
            return self.series_ids(measurement) - hit
        return hit

    def tags_of(self, sid: int) -> dict[str, str]:
        return dict(self.sid_to_series[sid][1])

    def series_entry(self, sid: int) -> tuple[str, tuple]:
        return self.sid_to_series[sid]

    def iter_series_entries(self):
        yield from self.sid_to_series.values()

    def measurements(self) -> list[str]:
        return sorted(self.mst_sids)

    # -- deletion ------------------------------------------------------------

    def remove_sids(self, sids: set[int]) -> None:
        """Drop series from the index and rewrite the log (reference: tsi
        DeleteSeries / DropMeasurement index paths)."""
        for sid in sids:
            entry = self.sid_to_series.pop(sid, None)
            if entry is None:
                continue
            mst, tags = entry
            self.key_to_sid.pop(series_key(mst, tags), None)
            bucket = self.mst_sids.get(mst)
            if bucket is not None:
                bucket.discard(sid)
                if not bucket:
                    del self.mst_sids[mst]
            for k, v in tags:
                post = self.postings.get((mst, k, v))
                if post is not None:
                    post.discard(sid)
                    if not post:
                        del self.postings[(mst, k, v)]
        self._label_epoch += 1
        self._rewrite_log()

    def _rewrite_log(self) -> None:
        if self.path is None:
            return
        if self._log is not None:
            self._log.close()
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for sid, (mst, tags) in sorted(self.sid_to_series.items()):
                f.write(json.dumps([sid, mst, [list(t) for t in tags]]) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._log = open(self.path, "a", encoding="utf-8")
