"""Stable error-code taxonomy (reference: lib/errno — module/code
constants, code.go's ~390 codes + error.go's Node/Module typing).

The reference threads typed errno values through every raise site; here
the taxonomy layers over the existing exception types instead: each
exception CLASS (and a few message patterns) maps to a stable
(module, code) pair, raise sites can pin an explicit code by setting
``exc.og_errno``, and the HTTP surface + service loggers attach the code to
what they emit. Codes are stable API: fleet log triage greps them, so
values never get reused or renumbered — add new ones at the end of their
module block.
"""

from __future__ import annotations

from enum import IntEnum


class Module(IntEnum):
    UNKNOWN = 0
    QUERY = 1
    WRITE = 2
    INDEX = 3
    META = 4
    META_RAFT = 5
    NETWORK = 6
    COMPACT = 7
    STORAGE = 8
    HA = 9
    HTTP = 10
    WAL = 11
    DOWNSAMPLE = 12
    CASTOR = 13
    STREAM = 14
    LOGSTORE = 15
    AUTH = 16


# -- code blocks (1000 per module, reference code.go style) ------------------

# query (1xxx)
QUERY_PARSE = 1001
QUERY_UNSUPPORTED = 1002
QUERY_BAD_ARGUMENT = 1003
QUERY_KILLED = 1004
QUERY_TOO_MANY_BUCKETS = 1005
QUERY_MEASUREMENT_NOT_FOUND = 1006

# write (2xxx)
WRITE_PARSE = 2001
WRITE_FIELD_CONFLICT = 2002
WRITE_DISABLED = 2003
WRITE_DB_NOT_FOUND = 2004
WRITE_RP_NOT_FOUND = 2005

# meta (4xxx)
META_NOT_LEADER = 4001
META_NO_QUORUM = 4002
META_DB_NOT_FOUND = 4003

# network / cluster (6xxx)
NET_NODE_UNREACHABLE = 6001
NET_PARTIALS_RETRY = 6002
NET_PARTIALS_UNAVAILABLE = 6003

# auth (16xxx block stays 3-digit-suffixed for grep stability)
AUTH_DENIED = 16001

# catch-alls (9xxx)
INTERNAL_ERROR = 9001


def classify(exc: BaseException) -> tuple[int, Module]:
    """-> (stable code, module) for any exception. Explicit wins: a raise
    site may set ``exc.og_errno`` (int) and optionally ``exc.og_module``
    (NOT ``errno`` — OSError's built-in errno attribute would hijack the
    pin and report raw OS codes as taxonomy codes)."""
    explicit = getattr(exc, "og_errno", None)
    if isinstance(explicit, int):
        mod = getattr(exc, "og_module", None)
        return explicit, mod if isinstance(mod, Module) else Module.UNKNOWN

    # imports are local: errno must be importable from anywhere without
    # dragging the query/storage stacks in
    from opengemini_tpu.ingest.line_protocol import ParseError
    from opengemini_tpu.meta.users import AuthError
    from opengemini_tpu.query.qhelpers import QueryError
    from opengemini_tpu.record import FieldTypeConflict
    from opengemini_tpu.storage.engine import DatabaseNotFound, WriteError
    from opengemini_tpu.utils.querytracker import QueryKilled

    if isinstance(exc, QueryKilled):
        return QUERY_KILLED, Module.QUERY
    if isinstance(exc, AuthError):
        return AUTH_DENIED, Module.AUTH
    if isinstance(exc, ParseError):
        return WRITE_PARSE, Module.WRITE
    if isinstance(exc, FieldTypeConflict):
        return WRITE_FIELD_CONFLICT, Module.WRITE
    if isinstance(exc, DatabaseNotFound):
        return WRITE_DB_NOT_FOUND, Module.WRITE
    if isinstance(exc, WriteError):
        msg = str(exc)
        if "disabled" in msg:
            return WRITE_DISABLED, Module.WRITE
        if "retention policy" in msg:
            return WRITE_RP_NOT_FOUND, Module.WRITE
        return WRITE_PARSE, Module.WRITE
    try:
        from opengemini_tpu.parallel.cluster import (
            PartialsRetry, PartialsUnavailable, RemoteScanError,
        )

        if isinstance(exc, PartialsRetry):
            return NET_PARTIALS_RETRY, Module.NETWORK
        if isinstance(exc, PartialsUnavailable):
            return NET_PARTIALS_UNAVAILABLE, Module.NETWORK
        if isinstance(exc, RemoteScanError):
            return NET_NODE_UNREACHABLE, Module.NETWORK
    except ImportError:  # pragma: no cover
        pass
    if isinstance(exc, QueryError):
        msg = str(exc)
        if "not the meta leader" in msg or "leader" in msg and "redirect" in msg:
            return META_NOT_LEADER, Module.META
        if "no quorum" in msg:
            return META_NO_QUORUM, Module.META
        if "measurement not found" in msg:
            return QUERY_MEASUREMENT_NOT_FOUND, Module.QUERY
        if "max-select-buckets" in msg or "too large" in msg:
            return QUERY_TOO_MANY_BUCKETS, Module.QUERY
        if "unsupported" in msg or "not supported" in msg:
            return QUERY_UNSUPPORTED, Module.QUERY
        if "error parsing" in msg or "expected" in msg:
            return QUERY_PARSE, Module.QUERY
        return QUERY_BAD_ARGUMENT, Module.QUERY
    if isinstance(exc, OSError):
        return NET_NODE_UNREACHABLE, Module.NETWORK
    return INTERNAL_ERROR, Module.UNKNOWN


def tag(exc: BaseException) -> str:
    """Log/wire form: 'errno=<code> module=<name>'."""
    code, mod = classify(exc)
    return f"errno={code} module={mod.name.lower()}"
