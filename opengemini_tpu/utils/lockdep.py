"""Runtime lock-order validation (the Linux lockdep analogue).

Nine PRs of concurrency work left the load-bearing lock invariants in
comments: `_flush_lock -> _lock` (storage/shard.py), "fsync runs off
the shard lock", "no blocking call under a hot lock".  Each was at some
point violated and fixed by hand (the PR 3 compact/flush ordering, the
PR 7 fsync-under-manager-lock stall).  This module enforces them
mechanically, the way Linux lockdep proves lock-class ordering: armed
via ``OGT_LOCKDEP=1``, every ``lockdep.Lock()``/``RLock()``/
``Condition()`` in the tree becomes a tracked wrapper; unset, the names
are plain CLASS ALIASES for ``threading.Lock``/``RLock``/``Condition``
— zero per-acquisition work, asserted by tests/test_lockdep.py and
measured by ``bench.py lockdep_overhead``.

What the armed mode proves, per process:

- **Order-graph cycles.**  Locks are grouped into CLASSES by their
  construction site (every per-shard ``_lock`` is one class), like
  lockdep's lock classes.  Acquiring B while holding A records the edge
  A -> B with one representative acquisition stack per side; a new edge
  that closes a cycle (B already reaches A) is a potential deadlock and
  is reported with BOTH stack pairs — the classic "possible circular
  locking dependency" report — even if the two threads never actually
  collided in this run.  Same-class nesting (two shards' locks) is
  ignored: instance order within a class is the engine's sorted-
  iteration business, not a class-order fact.
- **Blocking under a hot lock.**  ``os.fsync``, ``time.sleep``,
  ``subprocess.Popen`` and socket connect/send/recv are patched (armed
  mode only) to flag execution while the thread holds a HOT lock class
  (``mark_hot``: the shard lock, the engine lock, the rollup manager
  lock).  Audited exceptions wrap the call in
  ``with lockdep.allow_blocking("why"):`` — e.g. the WAL rotate fsync,
  which MUST run under the shard lock because that lock is what fences
  concurrent appends.
- **Hold-time budgets.**  ``OGT_LOCKDEP_HOLD_MS=<ms>`` (0/unset = off)
  records any single hold of a tracked lock longer than the budget into
  ``hold_reports()`` — advisory (a GIL-starved CI box makes wall-clock
  holds noisy), never part of ``check()``.

Violations are recorded process-globally (``violations()``) and printed
to stderr once per unique report; ``check()`` raises ``LockdepError``
with every report attached.  The tier-1 conftest calls ``check()`` at
session end when armed, so the ENTIRE existing concurrency suite — plus
``tools/torture.py --quick`` and ``tools/cluster_torture.py --quick``,
whose children inherit ``OGT_LOCKDEP`` — doubles as a deadlock
regression test.  A ``lockdep`` stats section (violations/edges/
classes) rides /debug/vars via utils/stats.py so the cluster harness
can assert zero findings on live nodes.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

__all__ = [
    "Lock", "RLock", "Condition", "LockdepError", "enabled", "mark_hot",
    "name_class", "held_classes", "allow_blocking", "violations",
    "hold_reports", "check", "reset", "stats_snapshot",
    "RETIRED_EXEMPTIONS",
]

_ARMED = os.environ.get("OGT_LOCKDEP", "") not in ("", "0")
HOLD_BUDGET_MS = float(os.environ.get("OGT_LOCKDEP_HOLD_MS", "0") or 0)


class LockdepError(RuntimeError):
    """Raised by check(): at least one ordering/blocking violation."""


# Exemption reasons that USED to be audited and were then eliminated by
# restructuring the code (the off-lock compaction rework moved every
# compaction merge/fsync off the hot shard lock).  Re-registering one is
# a regression — the invariant is now "compaction never blocks under the
# shard lock", and it is enforced here in BOTH modes (armed and not) so
# the cheap unarmed tree still refuses the exemption at the call site.
RETIRED_EXEMPTIONS = frozenset({
    "compact merge under shard lock",
    "level-compact merge under shard lock",
    "out-of-order compact merge under shard lock",
})


def _check_retired(reason: str) -> None:
    if reason in RETIRED_EXEMPTIONS:
        raise LockdepError(
            f"lockdep exemption {reason!r} is retired: compaction must "
            "merge/fsync OFF the shard lock (snapshot -> off-lock merge "
            "-> revalidated swap), not under an audited exemption")


def enabled() -> bool:
    return _ARMED


if not _ARMED:
    # Pass-through: plain aliases, NOT shims — the unarmed tree pays
    # zero per-acquisition (and zero per-construction) work.  Asserted
    # identity (`lockdep.Lock is threading.Lock`) in tests and bench.
    Lock = threading.Lock
    RLock = threading.RLock
    Condition = threading.Condition

    def mark_hot(lock, name: str):
        return lock

    def name_class(lock, name: str):
        return lock

    def held_classes() -> list:
        return []

    class _NullCtx:
        __slots__ = ()

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    _NULL_CTX = _NullCtx()

    def allow_blocking(reason: str = ""):
        _check_retired(reason)
        return _NULL_CTX

    def violations() -> list:
        return []

    def hold_reports() -> list:
        return []

    def check() -> None:
        return None

    def reset() -> None:
        return None

    def stats_snapshot() -> dict:
        return {}

else:
    _THIS_FILE = os.path.abspath(__file__)

    # -- process-global order graph (all guarded by _STATE_LOCK) ------
    _STATE_LOCK = threading.Lock()
    _CLASSES: dict[tuple, "_LockClass"] = {}   # site -> class
    _SUCC: dict[object, set] = {}              # class -> set(class)
    _EDGES: dict[tuple, tuple] = {}            # (a, b) -> (stack_a, stack_b)
    _VIOLATIONS: list[str] = []
    _HOLDS: list[str] = []
    _SEEN: set = set()                         # dedupe keys for reports
    _STACK_MEMO: dict[tuple, str] = {}         # (class, site) -> stack text

    _TLS = threading.local()

    class _LockClass:
        """One lock CLASS: every lock constructed at one code site."""

        __slots__ = ("site", "name", "hot")

        def __init__(self, site: tuple):
            self.site = site          # (filename, lineno)
            self.name = f"{_short(site[0])}:{site[1]}"
            self.hot = False

        def __repr__(self):
            return self.name

    def _short(path: str) -> str:
        for mark in ("opengemini_tpu", "tools", "tests"):
            i = path.find(os.sep + mark + os.sep)
            if i >= 0:
                return path[i + 1:]
        return os.path.basename(path)

    def _held():
        h = getattr(_TLS, "held", None)
        if h is None:
            h = _TLS.held = []
        return h

    def _caller_site() -> tuple:
        f = sys._getframe(1)
        while f is not None and f.f_code.co_filename == _THIS_FILE:
            f = f.f_back
        if f is None:  # pragma: no cover - interpreter teardown
            return ("<unknown>", 0)
        return (f.f_code.co_filename, f.f_lineno)

    def _site_stack(cls: "_LockClass", site: tuple) -> str:
        """One REPRESENTATIVE formatted stack per (class, acquire-site).
        Captured on the first acquisition through that site and memoized
        — steady-state acquire cost is a dict hit, not a stack walk."""
        key = (cls, site)
        st = _STACK_MEMO.get(key)
        if st is None:
            frames = [f for f in traceback.extract_stack()
                      if f.filename != _THIS_FILE]
            st = "".join(traceback.format_list(frames[-12:]))
            with _STATE_LOCK:
                st = _STACK_MEMO.setdefault(key, st)
        return st

    def _report(kind: str, key: tuple, text: str) -> None:
        with _STATE_LOCK:
            if key in _SEEN:
                return
            _SEEN.add(key)
            _VIOLATIONS.append(text)
        sys.stderr.write(text + "\n")

    def _reaches(src, dst) -> bool:
        """True when dst is reachable from src in the edge graph.
        Caller holds _STATE_LOCK."""
        seen = {src}
        stack = [src]
        while stack:
            node = stack.pop()
            if node is dst:
                return True
            for nxt in _SUCC.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return False

    def _cycle_path(src, dst) -> list:
        """One src ~> dst edge path (caller holds _STATE_LOCK)."""
        prev = {src: None}
        queue = [src]
        while queue:
            node = queue.pop(0)
            if node is dst:
                path = [node]
                while prev[node] is not None:
                    node = prev[node]
                    path.append(node)
                return list(reversed(path))
            for nxt in _SUCC.get(node, ()):
                if nxt not in prev:
                    prev[nxt] = node
                    queue.append(nxt)
        return [src, dst]

    def _add_edge(a_hold, b_cls, b_stack: str) -> None:
        a_cls = a_hold.cls
        pair = (a_cls, b_cls)
        if pair in _EDGES:  # fast path: dependency already proven
            return
        with _STATE_LOCK:
            if pair in _EDGES:
                return
            cycle = _reaches(b_cls, a_cls)
            path = _cycle_path(b_cls, a_cls) if cycle else None
            _EDGES[pair] = (a_hold.stack, b_stack)
            _SUCC.setdefault(a_cls, set()).add(b_cls)
        if not cycle:
            return
        # the lockdep report: the edge that closed the cycle, plus the
        # previously witnessed reverse chain — both stack pairs
        lines = [
            "LOCKDEP: possible circular locking dependency",
            f"  new dependency: {a_cls} -> {b_cls}",
            f"  while holding {a_cls}, acquired at:",
            _indent(a_hold.stack),
            f"  acquiring {b_cls} at:",
            _indent(b_stack),
            f"  but the inverse chain {' -> '.join(map(str, path))} "
            "was already witnessed:",
        ]
        for i in range(len(path) - 1):
            e = _EDGES.get((path[i], path[i + 1]))
            if not e:
                continue
            lines.append(f"  edge {path[i]} -> {path[i + 1]}: "
                         f"{path[i]} held at:")
            lines.append(_indent(e[0]))
            lines.append(f"  {path[i + 1]} acquired at:")
            lines.append(_indent(e[1]))
        _report("cycle", ("cycle",) + tuple(sorted((a_cls.name, b_cls.name))),
                "\n".join(lines))

    def _indent(text: str) -> str:
        return "\n".join("    " + ln for ln in text.rstrip().splitlines())

    class _Hold:
        __slots__ = ("lock", "cls", "stack", "site", "t0", "depth")

        def __init__(self, lock, cls, stack, site):
            self.lock = lock
            self.cls = cls
            self.stack = stack
            self.site = site
            self.t0 = time.perf_counter()
            self.depth = 1

    class _TrackedBase:
        """Shared acquire/release bookkeeping for Lock/RLock wrappers."""

        __slots__ = ("_inner", "_cls")

        def __init__(self):
            site = _caller_site()
            with _STATE_LOCK:
                cls = _CLASSES.get(site)
                if cls is None:
                    cls = _CLASSES[site] = _LockClass(site)
            self._cls = cls

        def _note_acquire(self) -> None:
            held = _held()
            for h in held:
                if h.lock is self:   # reentrant re-acquire: depth only
                    h.depth += 1
                    return
            site = _caller_site()
            stack = _site_stack(self._cls, site)
            for h in held:
                if h.cls is not self._cls:
                    _add_edge(h, self._cls, stack)
            held.append(_Hold(self, self._cls, stack, site))

        def _note_release(self) -> int:
            """Returns remaining depth (0 = fully released)."""
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                h = held[i]
                if h.lock is self:
                    if h.depth > 1:
                        h.depth -= 1
                        return h.depth
                    del held[i]
                    if HOLD_BUDGET_MS > 0:
                        ms = (time.perf_counter() - h.t0) * 1e3
                        if ms >= HOLD_BUDGET_MS:
                            _note_hold(h, ms)
                    return 0
            return 0  # release of a lock acquired pre-tracking: ignore

        def _untrack_for_wait(self) -> int:
            """Condition-wait release: drop the hold entirely, return
            its depth so _retrack_after_wait can restore it."""
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i].lock is self:
                    depth = held[i].depth
                    del held[i]
                    return depth
            return 1

        def _retrack_after_wait(self, depth: int) -> None:
            # reacquire after wait: the original acquire already
            # recorded this class's edges; no new dependency fact
            h = _Hold(self, self._cls, _site_stack(self._cls, self._cls.site),
                      self._cls.site)
            h.depth = depth
            _held().append(h)

        def locked(self):
            return self._inner.locked()

        def __repr__(self):
            return f"<lockdep {type(self).__name__} {self._cls.name}>"

    def _note_hold(h: "_Hold", ms: float) -> None:
        key = ("hold", h.cls, h.site)
        with _STATE_LOCK:
            if key in _SEEN:
                return
            _SEEN.add(key)
            _HOLDS.append(
                f"LOCKDEP: {h.cls} held {ms:.1f}ms "
                f"(budget {HOLD_BUDGET_MS:.0f}ms), acquired at:\n"
                + _indent(h.stack))

    class Lock(_TrackedBase):
        __slots__ = ()

        def __init__(self):
            super().__init__()
            self._inner = threading.Lock()

        def acquire(self, blocking: bool = True, timeout: float = -1):
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                self._note_acquire()
            return ok

        def release(self):
            self._note_release()
            self._inner.release()

        def __enter__(self):
            return self.acquire()

        def __exit__(self, *exc):
            self.release()
            return False

        # threading.Condition protocol (wait releases the lock: the
        # tracker must see it leave and re-enter the held set)
        def _release_save(self):
            self._untrack_for_wait()
            self._inner.release()
            return 1

        def _acquire_restore(self, depth):
            self._inner.acquire()
            self._retrack_after_wait(depth or 1)

        def _is_owned(self):
            if self._inner.acquire(False):
                self._inner.release()
                return False
            return True

    class RLock(_TrackedBase):
        __slots__ = ()

        def __init__(self):
            super().__init__()
            self._inner = threading.RLock()

        def acquire(self, blocking: bool = True, timeout: float = -1):
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                self._note_acquire()
            return ok

        def release(self):
            self._note_release()
            self._inner.release()

        def __enter__(self):
            return self.acquire()

        def __exit__(self, *exc):
            self.release()
            return False

        def _release_save(self):
            depth = self._untrack_for_wait()
            return (self._inner._release_save(), depth)

        def _acquire_restore(self, state):
            inner_state, depth = state
            self._inner._acquire_restore(inner_state)
            self._retrack_after_wait(depth)

        def _is_owned(self):
            return self._inner._is_owned()

        def locked(self):  # RLock has no locked() before 3.12
            if self._inner.acquire(False):
                self._inner.release()
                return False
            return True

    class Condition(threading.Condition):
        """threading.Condition over a tracked lock: wait() routes
        through the wrapper's _release_save/_acquire_restore, so the
        held-set stays truthful across the release/reacquire."""

        def __init__(self, lock=None):
            if lock is None:
                lock = RLock()
            super().__init__(lock)

    def mark_hot(lock, name: str):
        """Name a lock's CLASS and mark it hot: blocking calls (fsync/
        sleep/socket/subprocess) while holding it are violations unless
        inside allow_blocking().  Returns the lock (assignment chains)."""
        cls = getattr(lock, "_cls", None)
        if cls is not None:
            cls.name = name
            cls.hot = True
        return lock

    def name_class(lock, name: str):
        """Friendly class name in reports, without the hot marking."""
        cls = getattr(lock, "_cls", None)
        if cls is not None:
            cls.name = name
        return lock

    def held_classes() -> list[str]:
        """Class names the CURRENT thread holds right now (tests)."""
        return [h.cls.name for h in getattr(_TLS, "held", ())]

    class _AllowCtx:
        __slots__ = ("reason",)

        def __init__(self, reason: str):
            self.reason = reason

        def __enter__(self):
            _TLS.allow = getattr(_TLS, "allow", 0) + 1
            return self

        def __exit__(self, *exc):
            _TLS.allow -= 1
            return False

    def allow_blocking(reason: str = ""):
        """Annotate an AUDITED blocking call under a hot lock (e.g. the
        WAL rotate fsync, fenced by the shard lock by design)."""
        _check_retired(reason)
        return _AllowCtx(reason)

    def _check_blocking(kind: str) -> None:
        held = getattr(_TLS, "held", None)
        if not held or getattr(_TLS, "allow", 0):
            return
        for h in held:
            if h.cls.hot:
                site = _caller_site()
                frames = [f for f in traceback.extract_stack()
                          if f.filename != _THIS_FILE]
                here = "".join(traceback.format_list(frames[-12:]))
                _report(
                    "blocking", ("blocking", kind, h.cls, site),
                    f"LOCKDEP: blocking call {kind} while holding hot "
                    f"lock {h.cls}\n  {h.cls} acquired at:\n"
                    + _indent(h.stack)
                    + f"\n  {kind} called at:\n" + _indent(here))
                return

    # -- blocking-call tripwires (armed process only) -----------------
    _orig_fsync = os.fsync
    _orig_sleep = time.sleep

    def _fsync(fd):
        _check_blocking("os.fsync")
        return _orig_fsync(fd)

    def _sleep(secs):
        _check_blocking("time.sleep")
        return _orig_sleep(secs)

    os.fsync = _fsync
    time.sleep = _sleep

    import socket as _socket_mod
    import subprocess as _subprocess_mod

    _orig_popen_init = _subprocess_mod.Popen.__init__

    def _popen_init(self, *a, **kw):
        _check_blocking("subprocess.Popen")
        return _orig_popen_init(self, *a, **kw)

    _subprocess_mod.Popen.__init__ = _popen_init

    def _patch_sock(name: str):
        orig = getattr(_socket_mod.socket, name, None)
        if orig is None:  # pragma: no cover - platform variance
            return

        def wrapper(self, *a, __orig=orig, __kind="socket." + name, **kw):
            _check_blocking(__kind)
            return __orig(self, *a, **kw)

        wrapper.__name__ = name
        setattr(_socket_mod.socket, name, wrapper)

    for _n in ("connect", "sendall", "recv", "recv_into", "accept"):
        _patch_sock(_n)
    del _n

    # -- reporting API ------------------------------------------------
    def violations() -> list[str]:
        with _STATE_LOCK:
            return list(_VIOLATIONS)

    def hold_reports() -> list[str]:
        with _STATE_LOCK:
            return list(_HOLDS)

    def check() -> None:
        """Raise LockdepError when any cycle/blocking violation was
        recorded (hold-budget reports are advisory, not failures)."""
        v = violations()
        if v:
            raise LockdepError(
                f"{len(v)} lockdep violation(s):\n\n" + "\n\n".join(v))

    def reset() -> None:
        """Forget the graph and every report (tests only)."""
        with _STATE_LOCK:
            _CLASSES.clear()
            _SUCC.clear()
            _EDGES.clear()
            _VIOLATIONS.clear()
            _HOLDS.clear()
            _SEEN.clear()
            _STACK_MEMO.clear()

    def stats_snapshot() -> dict:
        """`lockdep` gauge section for /debug/vars: the cluster torture
        harness asserts violations == 0 on every live node."""
        with _STATE_LOCK:
            return {
                "violations": len(_VIOLATIONS),
                "hold_reports": len(_HOLDS),
                "edges": len(_EDGES),
                "classes": len(_CLASSES),
            }
