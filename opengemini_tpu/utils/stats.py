"""Self-monitoring statistics registry.

Reference: lib/statisticsPusher (~40 statistic modules accumulated and
pushed to file/http/_internal). Here: a process-wide registry of named
counters, exposed at /debug/vars (the influxdb expvar convention) and
pushable into an `_internal` database by the monitor service.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class Statistics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, dict[str, int]] = defaultdict(lambda: defaultdict(int))
        # computed gauge sections: module -> [fn() -> {name: int}].
        # Providers are evaluated at snapshot time (live state — e.g. the
        # per-shard durability ledgers aggregate, failpoint hit counts)
        # and their values must be ints: the monitor service pushes every
        # snapshot field into `_internal` as INT points.
        self._providers: dict[str, list] = defaultdict(list)
        self.started_at = time.time()

    def incr(self, module: str, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[module][name] += delta

    def set(self, module: str, name: str, value: int) -> None:
        with self._lock:
            self._counters[module][name] = value

    def register_provider(self, module: str, fn) -> None:
        """Attach a live gauge section to every snapshot(). Multiple
        providers of one module merge by summing shared keys (several
        engines in one process report process-wide totals)."""
        with self._lock:
            self._providers[module].append(fn)

    def unregister_provider(self, module: str, fn) -> None:
        with self._lock:
            fns = self._providers.get(module)
            if fns and fn in fns:
                fns.remove(fn)
            if fns is not None and not fns:
                del self._providers[module]

    def counters(self, module: str) -> dict:
        """One module's RAW counter section — no gauge providers run.
        Hot paths (the executor reads colcache counters twice per query)
        must not pay the providers' engine/shard-lock sweeps just to
        read a plain counter dict."""
        with self._lock:
            return dict(self._counters.get(module, ()))

    def snapshot(self) -> dict:
        with self._lock:
            out = {m: dict(vals) for m, vals in self._counters.items()}
            providers = [(m, fn) for m, fns in self._providers.items()
                         for fn in fns]
        for module, fn in providers:  # outside the lock: providers lock
            try:                      # their own structures (shard locks)
                vals = fn()
            except Exception:  # noqa: BLE001 — a dying provider (e.g. a
                continue       # closed engine) must not break /debug/vars
            if not vals:
                continue  # keep empty sections out of pushed snapshots
            sect = out.setdefault(module, {})
            for k, v in vals.items():
                sect[k] = sect.get(k, 0) + int(v)
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


# process-wide registry (the reference's statistics singletons)
GLOBAL = Statistics()


def _failpoint_hits() -> dict:
    from opengemini_tpu.utils import failpoint

    return failpoint.all_hits()


# failpoint hit counts ride every stats snapshot (/debug/vars): the
# torture harness and operators can see WHICH armed sites actually fired
GLOBAL.register_provider("failpoints", _failpoint_hits)


def _governor_gauges() -> dict:
    from opengemini_tpu.utils import governor

    return governor.GOVERNOR.gauges()


# governor ledger/admission gauges ride /debug/vars when the governor is
# enabled (OGT_MEM_BUDGET_MB set); the provider answers {} pass-through
GLOBAL.register_provider("governor", _governor_gauges)
