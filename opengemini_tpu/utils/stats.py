"""Self-monitoring statistics registry + latency histograms + the
Prometheus text-format renderer.

Reference: lib/statisticsPusher (~40 statistic modules accumulated and
pushed to file/http/_internal). Here: a process-wide registry of named
counters, exposed at /debug/vars (the influxdb expvar convention) and
pushable into an `_internal` database by the monitor service; plus
fixed-log-bucket Histograms (HTTP endpoints, query stages, per-peer
RPCs, WAL fsync, flush, rollup folds) exported — together with every
counter/gauge — at GET /metrics under the `ogt_*` naming scheme.
"""

from __future__ import annotations

import os
import re
import threading
from opengemini_tpu.utils import lockdep
import time
from collections import defaultdict


class Statistics:
    def __init__(self) -> None:
        self._lock = lockdep.Lock()
        self._counters: dict[str, dict[str, int]] = defaultdict(lambda: defaultdict(int))
        # computed gauge sections: module -> [fn() -> {name: int}].
        # Providers are evaluated at snapshot time (live state — e.g. the
        # per-shard durability ledgers aggregate, failpoint hit counts)
        # and their values must be ints: the monitor service pushes every
        # snapshot field into `_internal` as INT points.
        self._providers: dict[str, list] = defaultdict(list)
        # uptime is a DURATION: perf_counter, not wall clock (an NTP
        # step mid-run would bend every scraped ogt_uptime_seconds)
        self.started_pc = time.perf_counter()

    def incr(self, module: str, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[module][name] += delta

    def set(self, module: str, name: str, value: int) -> None:
        with self._lock:
            self._counters[module][name] = value

    def register_provider(self, module: str, fn) -> None:
        """Attach a live gauge section to every snapshot(). Multiple
        providers of one module merge by summing shared keys (several
        engines in one process report process-wide totals)."""
        with self._lock:
            self._providers[module].append(fn)

    def unregister_provider(self, module: str, fn) -> None:
        with self._lock:
            fns = self._providers.get(module)
            if fns and fn in fns:
                fns.remove(fn)
            if fns is not None and not fns:
                del self._providers[module]

    def counters(self, module: str) -> dict:
        """One module's RAW counter section — no gauge providers run.
        Hot paths (the executor reads colcache counters twice per query)
        must not pay the providers' engine/shard-lock sweeps just to
        read a plain counter dict."""
        with self._lock:
            return dict(self._counters.get(module, ()))

    def snapshot(self) -> dict:
        with self._lock:
            out = {m: dict(vals) for m, vals in self._counters.items()}
            providers = [(m, fn) for m, fns in self._providers.items()
                         for fn in fns]
        for module, fn in providers:  # outside the lock: providers lock
            try:                      # their own structures (shard locks)
                vals = fn()
            except Exception:  # noqa: BLE001 — a dying provider (e.g. a
                continue       # closed engine) must not break /debug/vars
            if not vals:
                continue  # keep empty sections out of pushed snapshots
            sect = out.setdefault(module, {})
            for k, v in vals.items():
                sect[k] = sect.get(k, 0) + int(v)
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


# process-wide registry (the reference's statistics singletons)
GLOBAL = Statistics()


def _failpoint_hits() -> dict:
    from opengemini_tpu.utils import failpoint

    return failpoint.all_hits()


# failpoint hit counts ride every stats snapshot (/debug/vars): the
# torture harness and operators can see WHICH armed sites actually fired
GLOBAL.register_provider("failpoints", _failpoint_hits)


def _governor_gauges() -> dict:
    from opengemini_tpu.utils import governor

    return governor.GOVERNOR.gauges()


# governor ledger/admission gauges ride /debug/vars when the governor is
# enabled (OGT_MEM_BUDGET_MB set); the provider answers {} pass-through
GLOBAL.register_provider("governor", _governor_gauges)

# lock-order validator findings (OGT_LOCKDEP=1 only): the torture
# harnesses assert violations == 0 on live nodes via /debug/vars
if lockdep.enabled():
    GLOBAL.register_provider("lockdep", lockdep.stats_snapshot)


# -- latency histograms ------------------------------------------------------
# Fixed log2 buckets over nanoseconds: bounds 2^10 ns (~1µs) .. 2^35 ns
# (~34s), 26 finite buckets + overflow.  The fixed layout makes every
# histogram of a family mergeable by plain element-wise addition (the
# concurrency/merge-exactness contract the tests assert) and keeps the
# Prometheus export cumulative-bucket math trivial.

_H_LO = 10                      # first bound: 2^10 ns
_NBOUNDS = 26                   # bounds 2^10 .. 2^35
_BOUNDS_NS = [1 << (_H_LO + i) for i in range(_NBOUNDS)]
_BOUNDS_S = [b / 1e9 for b in _BOUNDS_NS]

# histogram arming: OGT_TRACE=0 short-circuits every observe() to one
# global read — the bench's disabled arm.  Unset/1 = armed (a default
# /metrics scrape sees live latency data without any knob).
_OBS_ON = os.environ.get("OGT_TRACE", "") != "0"


def obs_enabled() -> bool:
    return _OBS_ON


def set_obs_enabled(on: bool) -> None:
    global _OBS_ON
    _OBS_ON = bool(on)


class Histogram:
    """Lock-cheap fixed-bucket latency histogram.  observe_ns computes
    the bucket outside the lock and holds it for three int updates; the
    lock is what makes concurrent counts EXACT (a bare `counts[i] += 1`
    loses increments across bytecode boundaries under threads).

    ``unit`` selects how the fixed 2^10..2^35 bounds export: "seconds"
    (values are nanoseconds, le bounds and sum scale by 1e-9 — every
    latency family) or "bytes" (values are raw bytes, bounds 1KiB..32GiB
    export unscaled — the devobs transfer-size families)."""

    __slots__ = ("name", "labels", "_lock", "counts", "count", "sum_ns",
                 "unit")

    def __init__(self, name: str, labels: tuple = (),
                 unit: str = "seconds"):
        self.name = name
        self.labels = labels  # sorted ((k, v), ...) — family identity
        self.unit = unit
        self._lock = lockdep.Lock()
        self.counts = [0] * (_NBOUNDS + 1)  # [+Inf] last
        self.count = 0
        self.sum_ns = 0

    def observe_ns(self, ns: int) -> None:
        if not _OBS_ON:
            return
        ns = int(ns)
        if ns < 0:
            ns = 0
        # smallest bound >= ns: (ns-1).bit_length() rounds exact powers
        # of two DOWN into their own bucket (le is inclusive)
        idx = (ns - 1).bit_length() - _H_LO
        if idx < 0:
            idx = 0
        elif idx > _NBOUNDS:
            idx = _NBOUNDS
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum_ns += ns

    def merge(self, other: "Histogram") -> None:
        """Element-wise fold of `other` into self (exact: fixed shared
        bucket layout)."""
        with other._lock:
            oc = list(other.counts)
            ocount, osum = other.count, other.sum_ns
        with self._lock:
            for i, c in enumerate(oc):
                self.counts[i] += c
            self.count += ocount
            self.sum_ns += osum

    def snapshot(self) -> dict:
        with self._lock:
            return {"counts": list(self.counts), "count": self.count,
                    "sum_ns": self.sum_ns, "unit": self.unit}

    def percentile_s(self, q: float) -> float:
        return snapshot_percentile_s(self.snapshot(), q)


def snapshot_percentile_s(hsnap: dict, q: float) -> float:
    """Approximate quantile in SECONDS from a Histogram.snapshot(): the
    upper bound of the bucket holding the rank (overflow reports the
    last finite bound doubled).  Good to one log2 bucket — what the
    monitor service self-writes as p50/p99."""
    return snapshot_percentile(dict(hsnap, unit="seconds"), q)


def snapshot_percentile(hsnap: dict, q: float) -> float:
    """Quantile in the histogram's own unit (seconds for latency
    families, raw bytes for the devobs transfer-size families)."""
    bounds = _BOUNDS_S if hsnap.get("unit", "seconds") == "seconds" \
        else _BOUNDS_NS
    total = hsnap["count"]
    if total <= 0:
        return 0.0
    rank = max(1, int(q / 100.0 * total + 0.5))
    acc = 0
    for i, c in enumerate(hsnap["counts"]):
        acc += c
        if acc >= rank:
            return bounds[i] if i < _NBOUNDS else bounds[-1] * 2
    return bounds[-1] * 2


_HIST_LOCK = lockdep.Lock()
_HISTOGRAMS: dict[tuple, Histogram] = {}


def histogram(name: str, unit: str = "seconds", **labels) -> Histogram:
    """Get-or-create the process-wide histogram for (name, labels).
    Call sites with fixed labels should cache the returned object —
    observe_ns() itself is the hot path, not this lookup.  ``unit`` is
    fixed at first creation (a family never changes units)."""
    key = (name, tuple(sorted(labels.items())))
    h = _HISTOGRAMS.get(key)
    if h is None:
        with _HIST_LOCK:
            h = _HISTOGRAMS.get(key)
            if h is None:
                h = Histogram(name, key[1], unit=unit)
                _HISTOGRAMS[key] = h
    return h


def observe_ns(name: str, ns: int, **labels) -> None:
    if not _OBS_ON:
        return
    histogram(name, **labels).observe_ns(ns)


def histograms_snapshot() -> list[tuple[str, tuple, dict]]:
    """Every registered histogram as (name, labels, snapshot), grouped
    by family name (stable export order)."""
    with _HIST_LOCK:
        items = sorted(_HISTOGRAMS.items())
    return [(name, labels, h.snapshot()) for (name, labels), h in items]


def reset_histograms() -> None:
    with _HIST_LOCK:
        _HISTOGRAMS.clear()


# -- Prometheus text-format export (GET /metrics) ----------------------------
# The statisticsPusher analogue: every counter/gauge section of the
# registry plus the histograms, under `ogt_*` names, text format 0.0.4.

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

# registry sections whose metric already reads naturally as a Prometheus
# name get explicit stable spellings; everything else derives
# mechanically as ogt_<module>_<key>
_RENAMES = {
    ("write", "points"): ("ogt_write_rows_total", "counter"),
}


def _san(name: str) -> str:
    name = _NAME_OK.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _esc_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{_san(str(k))}="{_esc_label(str(v))}"'
                     for k, v in labels)
    return "{" + inner + "}"


def _fmt_val(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def render_prometheus(version: str = "") -> str:
    lines: list[str] = []
    if version:
        lines.append("# HELP ogt_build_info build metadata")
        lines.append("# TYPE ogt_build_info gauge")
        lines.append(
            f'ogt_build_info{{version="{_esc_label(version)}"}} 1')
    lines.append("# HELP ogt_uptime_seconds process uptime")
    lines.append("# TYPE ogt_uptime_seconds gauge")
    lines.append(
        f"ogt_uptime_seconds "
        f"{_fmt_val(time.perf_counter() - GLOBAL.started_pc)}")

    # counters + provider gauges, one family per (module, key).  Two
    # distinct registry keys can sanitize to one family name (e.g.
    # failpoint sites differing only by '-' vs '_'): the first wins —
    # a duplicate TYPE line would fail any strict scraper
    seen: set[str] = {"ogt_build_info", "ogt_uptime_seconds"}
    snap = GLOBAL.snapshot()
    for module in sorted(snap):
        sect = snap[module]
        for key in sorted(sect):
            val = sect[key]
            if not isinstance(val, (int, float)):
                continue
            renamed = _RENAMES.get((module, key))
            if renamed:
                fam, typ = renamed
            else:
                fam = _san(f"ogt_{module}_{key}")
                typ = "counter" if key.endswith("_total") else "gauge"
            if fam in seen:
                continue
            seen.add(fam)
            lines.append(f"# TYPE {fam} {typ}")
            lines.append(f"{fam} {_fmt_val(val)}")

    # histograms: families share one TYPE header across label sets
    prev_fam = None
    skip_fam = None
    for name, labels, hsnap in histograms_snapshot():
        fam = _san(f"ogt_{name}")
        if fam == skip_fam:
            continue
        if fam != prev_fam:
            if fam in seen:  # name collision with a scalar family
                skip_fam = fam
                continue
            seen.add(fam)
            lines.append(f"# TYPE {fam} histogram")
            prev_fam = fam
        seconds = hsnap.get("unit", "seconds") == "seconds"
        bounds = _BOUNDS_S if seconds else _BOUNDS_NS
        acc = 0
        for i, c in enumerate(hsnap["counts"]):
            acc += c
            le = ("+Inf" if i == _NBOUNDS
                  else repr(bounds[i]) if seconds else str(bounds[i]))
            lab = _fmt_labels(tuple(labels) + (("le", le),))
            lines.append(f"{fam}_bucket{lab} {acc}")
        lab = _fmt_labels(labels)
        total = hsnap["sum_ns"] / 1e9 if seconds else hsnap["sum_ns"]
        lines.append(f"{fam}_sum{lab} {_fmt_val(total)}")
        lines.append(f"{fam}_count{lab} {hsnap['count']}")
    return "\n".join(lines) + "\n"
