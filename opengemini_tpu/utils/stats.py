"""Self-monitoring statistics registry.

Reference: lib/statisticsPusher (~40 statistic modules accumulated and
pushed to file/http/_internal). Here: a process-wide registry of named
counters, exposed at /debug/vars (the influxdb expvar convention) and
pushable into an `_internal` database by the monitor service.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class Statistics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, dict[str, int]] = defaultdict(lambda: defaultdict(int))
        self.started_at = time.time()

    def incr(self, module: str, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[module][name] += delta

    def set(self, module: str, name: str, value: int) -> None:
        with self._lock:
            self._counters[module][name] = value

    def snapshot(self) -> dict:
        with self._lock:
            return {
                m: dict(vals) for m, vals in self._counters.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


# process-wide registry (the reference's statistics singletons)
GLOBAL = Statistics()
