"""Running-query registry with kill support.

Reference: the query task manager (lib/util/lifted/influx/query
executor.go task manager + app/ts-store/transport/query/manager.go:130
Kill): every executing query is registered with an id; SHOW QUERIES lists
them, KILL QUERY marks one killed and execution aborts at the next
cancellation point (scan loops check between series).
"""

from __future__ import annotations

import re
import threading
from opengemini_tpu.utils import lockdep
import time

# redact password literals before storing query text (the reference
# renders [REDACTED] in SHOW QUERIES/logs for these statements)
_PASSWORD_RE = re.compile(
    r"(?i)(WITH\s+PASSWORD\s+|SET\s+PASSWORD\s+FOR\s+[^=]+=\s*)'(?:[^'\\]|\\.)*'"
)


def redact(text: str) -> str:
    return _PASSWORD_RE.sub(lambda m: m.group(1) + "'[REDACTED]'", text)


class QueryKilled(Exception):
    def __init__(self, qid: int):
        super().__init__(f"query {qid} killed")
        self.qid = qid


class QueryTracker:
    def __init__(self) -> None:
        self._lock = lockdep.Lock()
        self._next = 1
        self._running: dict[int, dict] = {}
        self._killed: set[int] = set()
        self._local = threading.local()
        # optional () -> dict hook (engine.durability_snapshot): the
        # monitoring view pairs in-flight queries with the live
        # acked-vs-durable ledger so an operator sees loss the moment a
        # query would observe it (PR 4)
        self._durability_provider = None
        # optional () -> dict hook (governor.admission_snapshot): pairs
        # the running queries with the admission queue/slot state (PR 5)
        self._admission_provider = None

    def register(self, text: str, db: str) -> int:
        with self._lock:
            qid = self._next
            self._next += 1
            self._running[qid] = {
                "query": redact(text), "database": db,
                "started": time.monotonic(),
            }
        self._local.qid = qid
        return qid

    def unregister(self, qid: int) -> None:
        with self._lock:
            self._running.pop(qid, None)
            self._killed.discard(qid)
        self._local.qid = None

    def kill(self, qid: int) -> bool:
        with self._lock:
            if qid not in self._running:
                return False
            self._killed.add(qid)
            return True

    def check(self) -> None:
        """Cancellation point: raises when the CURRENT thread's query was
        killed. Cheap (one set lookup), called between scan units."""
        self.raise_if_killed(self.current_qid())

    def current_qid(self) -> int | None:
        """The query id bound to the calling thread (None off-query)."""
        return getattr(self._local, "qid", None)

    def bind(self, qid: int | None) -> None:
        """Adopt a query id on a helper thread (scan-pool / prefetch
        workers) so check() fires there too. Helper threads bind fresh
        per task; the binding dies with the thread's next bind."""
        self._local.qid = qid

    def is_killed(self, qid: int | None) -> bool:
        return qid is not None and qid in self._killed

    def set_trace(self, qid: int | None, trace) -> None:
        """Bind a live span tree (utils/tracing.Trace) to a running
        query: /debug/queries renders it in place and /debug/trace?qid=
        serves it before the query finishes."""
        if qid is None:
            return
        with self._lock:
            info = self._running.get(qid)
            if info is not None:
                info["trace"] = trace

    def trace_of(self, qid: int | None):
        if qid is None:
            return None
        with self._lock:
            info = self._running.get(qid)
            return info.get("trace") if info else None

    def stages_of(self, qid: int | None) -> dict:
        """Copy of the per-stage ns attribution for one running query
        (the slow-log grabs it just before unregister)."""
        if qid is None:
            return {}
        with self._lock:
            info = self._running.get(qid)
            return dict(info.get("stages", ())) if info else {}

    def add_stage_ns(self, qid: int | None, name: str, ns: int) -> None:
        """Attribute stage time (e.g. the decoded-column cache's lookup /
        fill work, storage/colcache.py) to a running query so SHOW
        QUERIES-style snapshots expose where a long query spends its
        time.  No-op off-query or after the query unregistered; helper
        threads (scan pool) bind the owning qid per task."""
        if qid is None or ns <= 0:
            return
        with self._lock:
            info = self._running.get(qid)
            if info is not None:
                stages = info.setdefault("stages", {})
                stages[name] = stages.get(name, 0) + ns

    def note_route(self, qid: int | None, stage: str, route: str) -> None:
        """Record the offload planner's chosen route (host/device/mesh)
        for one stage of a running query — /debug/queries shows WHERE a
        query ran next to where it spent its time.  No-op off-query."""
        if qid is None:
            return
        with self._lock:
            info = self._running.get(qid)
            if info is not None:
                info.setdefault("routes", {})[stage] = route

    def raise_if_killed(self, qid: int | None) -> None:
        """check() for threads that carry the qid explicitly instead of
        thread-locally (scan-pool decode workers)."""
        if self.is_killed(qid):
            raise QueryKilled(qid)

    def snapshot(self) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            out = []
            for qid, info in sorted(self._running.items()):
                entry = {
                    "qid": qid,
                    "query": info["query"],
                    "database": info["database"],
                    "duration_ms": int((now - info["started"]) * 1000),
                    "status": "killed" if qid in self._killed else "running",
                    # per-stage attribution (colcache etc.), ms
                    "stages": {
                        name: ns // 1_000_000
                        for name, ns in info.get("stages", {}).items()
                    },
                }
                routes = info.get("routes")
                if routes:
                    # offload planner route per stage (query/offload.py)
                    entry["routes"] = dict(routes)
                trace = info.get("trace")
                if trace is not None:
                    # the stitched (so-far) span tree, rendered in place:
                    # /debug/queries is where an operator first looks
                    # when a cluster query is slow RIGHT NOW
                    entry["trace_id"] = trace.trace_id
                    entry["trace"] = trace.render()
                out.append(entry)
            return out

    def set_durability_provider(self, fn) -> None:
        """fn() -> engine.durability_snapshot()-shaped dict (None to
        detach — e.g. the owning engine closed)."""
        self._durability_provider = fn

    def detach_durability_provider(self, fn) -> None:
        """Detach ONLY if `fn` is still the attached provider — a closed
        engine must not yank a newer engine's hook (bound-method equality
        compares __self__ and __func__)."""
        if self._durability_provider == fn:
            self._durability_provider = None

    def set_admission_provider(self, fn) -> None:
        """fn() -> governor.admission_snapshot()-shaped dict (None to
        detach)."""
        self._admission_provider = fn

    def full_snapshot(self) -> dict:
        """Monitoring snapshot: running queries plus `durability` and
        `admission` sections from the registered providers (empty dicts
        when unattached or failing — monitoring must never raise)."""
        durability: dict = {}
        fn = self._durability_provider
        if fn is not None:
            try:
                durability = fn()
            except Exception:  # noqa: BLE001 — see docstring
                durability = {}
        admission: dict = {}
        fn = self._admission_provider
        if fn is not None:
            try:
                admission = fn()
            except Exception:  # noqa: BLE001 — see docstring
                admission = {}
        return {"queries": self.snapshot(), "durability": durability,
                "admission": admission}


# process-wide tracker (like the reference's per-node query manager)
GLOBAL = QueryTracker()
