"""Cross-cutting utilities: tracing, statistics, errors."""
