"""Bloom filter (reference: lib/bloomfilter — used to reject
absent keys before touching per-file metadata/postings).

Double hashing over blake2b: h_i(x) = h1 + i*h2 (Kirsch-Mitzenmacher),
bits in a numpy uint8 array. Sized for a target false-positive rate at
build time; lookups are O(k) with no allocation.
"""

from __future__ import annotations

import hashlib
import math

import numpy as np


def _hash_pair(key: bytes) -> tuple[int, int]:
    d = hashlib.blake2b(key, digest_size=16).digest()
    return int.from_bytes(d[:8], "little"), int.from_bytes(d[8:], "little") | 1


class BloomFilter:
    def __init__(self, capacity: int, fp_rate: float = 0.01):
        capacity = max(1, capacity)
        m = max(8, int(-capacity * math.log(fp_rate) / (math.log(2) ** 2)))
        self.m = (m + 7) // 8 * 8
        self.k = max(1, round(self.m / capacity * math.log(2)))
        self.bits = np.zeros(self.m // 8, dtype=np.uint8)

    @staticmethod
    def _key(item) -> bytes:
        if isinstance(item, bytes):
            return item
        if isinstance(item, str):
            return item.encode("utf-8")
        return int(item).to_bytes(8, "little", signed=True)

    def add(self, item) -> None:
        h1, h2 = _hash_pair(self._key(item))
        for i in range(self.k):
            bit = (h1 + i * h2) % self.m
            self.bits[bit >> 3] |= 1 << (bit & 7)

    def might_contain(self, item) -> bool:
        h1, h2 = _hash_pair(self._key(item))
        for i in range(self.k):
            bit = (h1 + i * h2) % self.m
            if not (self.bits[bit >> 3] >> (bit & 7)) & 1:
                return False
        return True

    def __contains__(self, item) -> bool:
        return self.might_contain(item)
