"""Query-execution trace tree (reference: lib/tracing — Trace/Span
span.go:31 with StartPP/EndPP wall-time measurement and fields; serialized
back to the client by EXPLAIN ANALYZE, statement_executor.go:943).
"""

from __future__ import annotations

import time
from contextlib import contextmanager


class Span:
    __slots__ = ("name", "fields", "children", "_t0", "elapsed_ns")

    def __init__(self, name: str):
        self.name = name
        self.fields: list[tuple[str, object]] = []
        self.children: list[Span] = []
        self._t0 = time.perf_counter_ns()
        self.elapsed_ns = 0

    def add_field(self, key: str, value) -> None:
        self.fields.append((key, value))

    def finish(self) -> None:
        self.elapsed_ns = time.perf_counter_ns() - self._t0


class Trace:
    def __init__(self, name: str):
        self.root = Span(name)
        self._stack = [self.root]

    @contextmanager
    def span(self, name: str):
        s = Span(name)
        self._stack[-1].children.append(s)
        self._stack.append(s)
        try:
            yield s
        finally:
            s.finish()
            self._stack.pop()
            _record_stage(name, s.elapsed_ns)

    def add_field(self, key: str, value) -> None:
        self._stack[-1].add_field(key, value)

    def finish(self) -> None:
        self.root.finish()

    def render(self) -> list[str]:
        """Indented tree lines (the EXPLAIN ANALYZE payload)."""
        lines: list[str] = []

        def walk(span: Span, depth: int):
            pad = "    " * depth
            lines.append(f"{pad}{span.name}: {_fmt_ns(span.elapsed_ns)}")
            for k, v in span.fields:
                lines.append(f"{pad}    {k}: {v}")
            for c in span.children:
                walk(c, depth + 1)

        walk(self.root, 0)
        return lines


def record_stage(name: str, elapsed_ns: int) -> None:
    """Cumulative per-stage timings in the statistics registry — the
    operator-facing counterpart of EXPLAIN ANALYZE (reference:
    executor_statistics.go per-transform counters).  Public: stages that
    happen OUTSIDE a live trace (the governor's admission wait precedes
    statement execution) record through here so /debug/vars carries them
    alongside the span-recorded stages."""
    from opengemini_tpu.utils.stats import GLOBAL as STATS

    STATS.incr("query_stages", f"{name}_ns", elapsed_ns)
    STATS.incr("query_stages", f"{name}_count")


_record_stage = record_stage  # internal alias (span finish path)


class NoopTrace:
    """Near-zero-cost stand-in when tracing is off: the executor calls
    trace methods unconditionally. Stage TIMINGS still accumulate in the
    stats registry (a perf_counter pair per stage, ~1us — negligible
    against any real stage) so /debug/vars shows them for every query,
    not just EXPLAIN ANALYZE."""

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            yield _NOOP_SPAN
        finally:
            _record_stage(name, time.perf_counter_ns() - t0)

    def add_field(self, key: str, value) -> None:
        pass

    def finish(self) -> None:
        pass


class _NoopSpan:
    def add_field(self, key: str, value) -> None:
        pass


_NOOP_SPAN = _NoopSpan()
NOOP = NoopTrace()


def _fmt_ns(ns: int) -> str:
    if ns >= 1_000_000_000:
        return f"{ns / 1e9:.3f}s"
    if ns >= 1_000_000:
        return f"{ns / 1e6:.3f}ms"
    if ns >= 1_000:
        return f"{ns / 1e3:.1f}µs"
    return f"{ns}ns"
