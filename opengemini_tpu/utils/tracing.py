"""Hierarchical query tracing with cross-node span propagation.

Reference: lib/tracing — Trace/Span (span.go:31) with StartPP/EndPP
wall-time measurement and fields, serialized back to the client by
EXPLAIN ANALYZE (statement_executor.go:943); the reference additionally
ships spans across the MPP executor's RPC boundary so the coordinator
renders one tree spanning every store node.

Here a Trace is a tree of Spans, each carrying (trace_id, span_id,
parent_id, node, start wall-ns, elapsed perf-ns).  The coordinator
attaches `ctx()` — {trace_id, span_id} of its innermost open span — to
/internal/* RPC bodies; the replica executes under a child Trace built
by `start_remote()` and returns `to_dict()` in its response payload;
the coordinator `graft()`s the subtree back under the span that issued
the RPC, yielding one stitched tree with correct cross-node parentage.

Cost model: with OGT_TRACE unset/0 queries run under NoopTrace exactly
as before — no Span objects, no ids, two perf_counter reads per stage
for the cumulative stats channel.  OGT_TRACE=1 arms per-query trees
(`/debug/trace?qid=`, slow-log capture); the arming check is one module
global read per query.
"""

from __future__ import annotations

import os
import random
import threading
from opengemini_tpu.utils import lockdep
import time
from contextlib import contextmanager

# per-query span-tree capture (OGT_TRACE=1).  Mutable at runtime via
# /debug/ctrl?mod=obs — read through trace_enabled(), never directly.
_TRACE_ON = os.environ.get("OGT_TRACE", "") in ("1", "true")

# finished traces kept for /debug/trace?qid= (bounded; newest wins)
_RECENT_MAX = 256
_RECENT: dict[object, dict] = {}
_RECENT_LOCK = lockdep.Lock()

_ACTIVE = threading.local()


def trace_enabled() -> bool:
    return _TRACE_ON


def set_trace_enabled(on: bool) -> None:
    global _TRACE_ON
    _TRACE_ON = bool(on)


def _new_id() -> str:
    # span/trace ids need uniqueness across NODES (replica subtrees are
    # grafted into coordinator trees), so a per-process counter is not
    # enough; 64 random bits at ~100ns/span only when tracing is armed
    return f"{random.getrandbits(64):016x}"


class Span:
    __slots__ = ("name", "span_id", "parent_id", "node", "fields",
                 "children", "start_ns", "elapsed_ns", "_t0")

    def __init__(self, name: str, span_id: str, parent_id: str,
                 node: str = ""):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.node = node
        self.fields: list[tuple[str, object]] = []
        self.children: list[Span] = []
        self.start_ns = time.time_ns()  # wall: cross-node alignment
        self._t0 = time.perf_counter_ns()
        self.elapsed_ns = 0

    def add_field(self, key: str, value) -> None:
        self.fields.append((key, value))

    def finish(self) -> None:
        self.elapsed_ns = time.perf_counter_ns() - self._t0

    def to_dict(self) -> dict:
        return {
            "name": self.name, "span_id": self.span_id,
            "parent_id": self.parent_id, "node": self.node,
            "start_ns": self.start_ns, "elapsed_ns": self.elapsed_ns,
            "fields": [[k, v] for k, v in self.fields],
            "children": [c.to_dict() for c in self.children],
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "Span":
        s = cls.__new__(cls)
        s.name = str(doc.get("name", ""))
        s.span_id = str(doc.get("span_id", ""))
        s.parent_id = str(doc.get("parent_id", ""))
        s.node = str(doc.get("node", ""))
        s.fields = [(k, v) for k, v in doc.get("fields", ())]
        s.start_ns = int(doc.get("start_ns", 0))
        s._t0 = 0
        s.elapsed_ns = int(doc.get("elapsed_ns", 0))
        s.children = [cls.from_dict(c) for c in doc.get("children", ())]
        return s


class Trace:
    def __init__(self, name: str, trace_id: str | None = None,
                 parent_span_id: str = "", node: str = ""):
        self.trace_id = trace_id or _new_id()
        self.node = node
        self.root = Span(name, _new_id(), parent_span_id, node)
        self._stack = [self.root]

    @contextmanager
    def span(self, name: str):
        s = Span(name, _new_id(), self._stack[-1].span_id, self.node)
        self._stack[-1].children.append(s)
        self._stack.append(s)
        try:
            yield s
        finally:
            s.finish()
            self._stack.pop()
            _record_stage(name, s.elapsed_ns)

    def add_field(self, key: str, value) -> None:
        self._stack[-1].add_field(key, value)

    def ctx(self) -> dict:
        """Wire context of the innermost open span — attached to
        /internal/* RPC bodies so the replica's subtree parents here."""
        return {"trace_id": self.trace_id,
                "span_id": self._stack[-1].span_id}

    def graft(self, subtree: dict | None) -> None:
        """Attach a remote subtree (a Trace.to_dict() from a replica's
        response payload) under the innermost open span.  The subtree
        root's recorded parent_id is the ctx span the coordinator sent;
        a mismatched or trace-less payload is ignored, never an error —
        stitching is best-effort observability."""
        if not subtree or not isinstance(subtree, dict):
            return
        root = subtree.get("root")
        if not isinstance(root, dict):
            return
        try:
            self._stack[-1].children.append(Span.from_dict(root))
        except (TypeError, ValueError):
            pass

    def finish(self) -> None:
        self.root.finish()

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "node": self.node,
                "root": self.root.to_dict()}

    def render(self) -> list[str]:
        """Indented tree lines (the EXPLAIN ANALYZE payload)."""
        lines: list[str] = []

        def walk(span: Span, depth: int):
            pad = "    " * depth
            where = f" [{span.node}]" if span.node else ""
            lines.append(
                f"{pad}{span.name}{where}: {_fmt_ns(span.elapsed_ns)}")
            for k, v in span.fields:
                lines.append(f"{pad}    {k}: {v}")
            for c in span.children:
                walk(c, depth + 1)

        walk(self.root, 0)
        return lines


def start_remote(name: str, ctx: dict | None, node: str = "") -> Trace | None:
    """Replica side: a child Trace parented at the coordinator's wire
    ctx.  None when the ctx is absent/malformed (untraced caller)."""
    if not isinstance(ctx, dict):
        return None
    tid, sid = ctx.get("trace_id"), ctx.get("span_id")
    if not tid or not sid:
        return None
    return Trace(name, trace_id=str(tid), parent_span_id=str(sid),
                 node=node)


def start_remote_activated(name: str, ctx: dict | None, node: str = ""):
    """The whole replica-side entry protocol in one call: (trace | None,
    activation context manager) — a nullcontext when the caller is
    untraced, so handlers write `t, cm = ...; with cm: work()`
    unconditionally.  Pair with ship_subtree(t) on the way out."""
    import contextlib

    t = start_remote(name, ctx, node=node)
    return t, (activate(t) if t is not None else contextlib.nullcontext())


def ship_subtree(trace: Trace | None) -> dict | None:
    """Replica-side exit protocol: finish the child trace and hand back
    the wire subtree for the response payload (None when untraced).
    The obs-before-span-ship failpoint arms the computed-but-unshipped
    window here for every shipping site."""
    if trace is None:
        return None
    from opengemini_tpu.utils.failpoint import inject as _fp

    _fp("obs-before-span-ship")
    trace.finish()
    return trace.to_dict()


# -- thread-local activation -------------------------------------------------
# The executor binds its per-query Trace here so deep callees (cluster
# RPC fan-out, the partials serializer) reach it without threading a
# trace parameter through every signature.  Worker threads (scan pool,
# RPC fan-out) never inherit the binding — ctx is captured on the query
# thread before dispatch.


@contextmanager
def activate(trace):
    prev = getattr(_ACTIVE, "trace", None)
    _ACTIVE.trace = trace
    try:
        yield trace
    finally:
        _ACTIVE.trace = prev


def current():
    """The calling thread's active Trace, or NOOP."""
    t = getattr(_ACTIVE, "trace", None)
    return t if t is not None else NOOP


def current_ctx() -> dict | None:
    """Wire ctx of the active trace (None when untraced) — what RPC
    bodies carry."""
    t = getattr(_ACTIVE, "trace", None)
    return t.ctx() if isinstance(t, Trace) else None


# -- finished-trace ring (/debug/trace) --------------------------------------


def note_finished(qid, trace: Trace, meta: dict | None = None) -> None:
    """Retain a finished trace for /debug/trace?qid= (bounded ring,
    oldest evicted).  `qid` may be None (e.g. routed writes) — the
    entry is then addressable by trace_id only."""
    doc = {"qid": qid, "trace_id": trace.trace_id,
           "name": trace.root.name,
           "elapsed_ms": round(trace.root.elapsed_ns / 1e6, 3),
           "trace": trace.to_dict()}
    if meta:
        doc.update(meta)
    key = qid if qid is not None else trace.trace_id
    with _RECENT_LOCK:
        _RECENT.pop(key, None)
        _RECENT[key] = doc
        while len(_RECENT) > _RECENT_MAX:
            _RECENT.pop(next(iter(_RECENT)))


def recent_traces() -> list[dict]:
    """Newest-first summaries (no tree) of the retained traces."""
    with _RECENT_LOCK:
        docs = list(_RECENT.values())
    return [
        {k: v for k, v in d.items() if k != "trace"}
        for d in reversed(docs)
    ]


def get_trace(qid=None, trace_id: str | None = None) -> dict | None:
    with _RECENT_LOCK:
        if qid is not None:
            return _RECENT.get(qid)
        if trace_id is not None:
            for d in _RECENT.values():
                if d["trace_id"] == trace_id:
                    return d
    return None


def clear_recent() -> None:
    with _RECENT_LOCK:
        _RECENT.clear()


# -- cumulative stage statistics ---------------------------------------------


def record_stage(name: str, elapsed_ns: int) -> None:
    """Cumulative per-stage timings in the statistics registry — the
    operator-facing counterpart of EXPLAIN ANALYZE (reference:
    executor_statistics.go per-transform counters).  Public: stages that
    happen OUTSIDE a live trace (the governor's admission wait precedes
    statement execution) record through here so /debug/vars carries them
    alongside the span-recorded stages."""
    from opengemini_tpu.utils.stats import GLOBAL as STATS
    from opengemini_tpu.utils.stats import observe_ns

    STATS.incr("query_stages", f"{name}_ns", elapsed_ns)
    STATS.incr("query_stages", f"{name}_count")
    # latency histogram per stage — only for the FIXED stage vocabulary
    # (scan/device_compute/render/...); dynamic names ("select: <mst>")
    # would leak label cardinality into /metrics
    if " " not in name:
        observe_ns("query_stage_seconds", elapsed_ns, stage=name)


_record_stage = record_stage  # internal alias (span finish path)


class NoopTrace:
    """Near-zero-cost stand-in when tracing is off: the executor calls
    trace methods unconditionally. Stage TIMINGS still accumulate in the
    stats registry (a perf_counter pair per stage, ~1us — negligible
    against any real stage) so /debug/vars shows them for every query,
    not just EXPLAIN ANALYZE."""

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            yield _NOOP_SPAN
        finally:
            _record_stage(name, time.perf_counter_ns() - t0)

    def add_field(self, key: str, value) -> None:
        pass

    def ctx(self) -> None:
        return None

    def graft(self, subtree) -> None:
        pass

    def finish(self) -> None:
        pass


class _NoopSpan:
    def add_field(self, key: str, value) -> None:
        pass


_NOOP_SPAN = _NoopSpan()
NOOP = NoopTrace()


def _fmt_ns(ns: int) -> str:
    if ns >= 1_000_000_000:
        return f"{ns / 1e9:.3f}s"
    if ns >= 1_000_000:
        return f"{ns / 1e6:.3f}ms"
    if ns >= 1_000:
        return f"{ns / 1e3:.1f}µs"
    return f"{ns}ns"
