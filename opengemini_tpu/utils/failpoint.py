"""Failpoint-style fault injection.

Reference: pingcap/failpoint sites in the WAL/flush/compaction paths
(engine/shard.go:457, engine/wal.go:391, enabled via gofail in
Makefile.common:26-27).  Sites are free at runtime when no failpoint is
armed (one dict lookup on an empty dict).

Arming:
  - code:      failpoint.enable("shard-flush-before-publish", "error")
  - env:       OGTPU_FAILPOINTS="wal-before-sync=error;flush=sleep:0.5"
  - syscontrol: POST /debug/ctrl?mod=failpoint&name=...&action=...

Actions: "error" (raise FailpointError), "panic" (os._exit(13): a hard
crash the recovery paths must survive), "sleep:<seconds>", or a callable
registered via enable().  Counts are recorded for assertions.
"""

from __future__ import annotations

import os
import threading
import time

_lock = threading.Lock()
_active: dict[str, object] = {}
_hits: dict[str, int] = {}


class FailpointError(RuntimeError):
    def __init__(self, name: str):
        super().__init__(f"failpoint {name!r} injected error")
        self.name = name


def _load_env() -> None:
    spec = os.environ.get("OGTPU_FAILPOINTS", "")
    for part in spec.split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, action = part.partition("=")
        _active[name.strip()] = action.strip()


_load_env()


def enable(name: str, action) -> None:
    with _lock:
        _active[name] = action


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)


def disable_all() -> None:
    with _lock:
        _active.clear()
        _hits.clear()


def active() -> dict:
    with _lock:
        return dict(_active)


def hits(name: str) -> int:
    with _lock:
        return _hits.get(name, 0)


def inject(name: str) -> None:
    """The site hook. No-op unless `name` is armed."""
    if not _active:  # fast path: nothing armed anywhere
        return
    with _lock:
        action = _active.get(name)
        if action is None:
            return
        _hits[name] = _hits.get(name, 0) + 1
    if callable(action):
        action()
        return
    if action == "error":
        raise FailpointError(name)
    if action == "panic":
        os._exit(13)
    if isinstance(action, str) and action.startswith("sleep:"):
        time.sleep(float(action.split(":", 1)[1]))
        return
    if action == "off":
        return
    raise ValueError(f"unknown failpoint action {action!r}")
