"""Failpoint-style fault injection.

Reference: pingcap/failpoint sites in the WAL/flush/compaction paths
(engine/shard.go:457, engine/wal.go:391, enabled via gofail in
Makefile.common:26-27).  Sites are free at runtime when no failpoint is
armed (one dict lookup on an empty dict).

Arming:
  - code:      failpoint.enable("shard-flush-before-publish", "error")
  - env:       OGTPU_FAILPOINTS="wal-before-sync=error;flush=sleep:0.5"
  - syscontrol: POST /debug/ctrl?mod=failpoint&name=...&action=...

Actions:
  - "error"            raise FailpointError
  - "panic"            os._exit(13): a hard crash the recovery paths must
                       survive (the torture harness's in-process kill)
  - "sleep:<seconds>"  schedule perturbation: widen a race window
  - "wait:<event>"     block until another site (or the test) fires
                       "set:<event>" — deterministic schedule replay.
                       Waits are bounded (WAIT_TIMEOUT_S) and raise on
                       timeout so a mis-paired schedule surfaces as a
                       failure, never a hang.
  - "set:<event>"      release every waiter of <event> (idempotent)
  - "barrier:<n>"      rendezvous of n hits across threads (the site name
                       scopes the barrier); bounded like wait
  - "off"              disarm (counts hits only)
  - callable           registered via enable(); return value ignored
Any action may carry a "#<k>" suffix: fire only on the k-th hit of the
site (1-based) and count hits otherwise — "panic#3" crashes the third
time the site is reached, which is how the torture harness randomizes
kill points along one code path.

Beyond the storage lock-handoff sites (PR 4), every resource-governor
decision edge is a site (utils/governor.py): governor-admit,
governor-queue, governor-shed, governor-overdraft-kill,
governor-backpressure-on, governor-backpressure-off — arm "wait:"
actions there to pin admission/shed interleavings deterministically
(catalogued with the storage sites in README.md).

Counts are recorded per site for assertions, and every hit of an ARMED
site (plus every site when record_all(True)) is appended to a global
ordering log — (seq, site, thread) — so schedule tests can assert WHICH
interleaving actually ran.
"""

from __future__ import annotations

import os
import threading
from opengemini_tpu.utils import lockdep
import time

_lock = lockdep.Lock()
_active: dict[str, object] = {}
_hits: dict[str, int] = {}
_events: dict[str, threading.Event] = {}
# site -> [arrival count, Condition, poisoned]; poisoned releases every
# parked waiter (disable_all teardown must never leave a product thread
# blocked at a barrier for the full wait timeout)
_barriers: dict[str, list] = {}
_hit_log: list[tuple[int, str, str]] = []
_record_all = False
_LOG_MAX = 8192  # bounded: schedule assertions read the prefix

# a mis-paired wait:/barrier: must fail the test, not hang the suite
WAIT_TIMEOUT_S = float(os.environ.get("OGTPU_FAILPOINT_WAIT_S", "30"))


class FailpointError(RuntimeError):
    def __init__(self, name: str):
        super().__init__(f"failpoint {name!r} injected error")
        self.name = name


def _load_env() -> None:
    spec = os.environ.get("OGTPU_FAILPOINTS", "")
    for part in spec.split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, action = part.partition("=")
        _active[name.strip()] = action.strip()


_load_env()


def enable(name: str, action) -> None:
    with _lock:
        _active[name] = action


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)


def disable_all() -> None:
    global _record_all
    with _lock:
        _active.clear()
        _hits.clear()
        _hit_log.clear()
        for st in _barriers.values():
            st[2] = True  # poison: parked waiters wake and proceed
            st[1].notify_all()
        _barriers.clear()
        for ev in _events.values():
            ev.set()  # release stranded waiters before forgetting them
        _events.clear()
        _record_all = False


def active() -> dict:
    with _lock:
        return dict(_active)


def hits(name: str) -> int:
    with _lock:
        return _hits.get(name, 0)


def all_hits() -> dict[str, int]:
    """Per-site hit counts (exported at /debug/vars)."""
    with _lock:
        return dict(_hits)


def record_all(on: bool = True) -> None:
    """Log EVERY site reached (not just armed ones) into the ordering
    log — schedule tests use this to assert the interleaving that ran."""
    global _record_all
    with _lock:
        _record_all = on


def hit_log() -> list[tuple[int, str, str]]:
    """Ordered (seq, site, thread-name) hits recorded so far."""
    with _lock:
        return list(_hit_log)


def set_event(event: str) -> None:
    """Release every "wait:<event>" site (and future ones)."""
    _event(event).set()


def clear_event(event: str) -> None:
    _event(event).clear()


def _event(name: str) -> threading.Event:
    with _lock:
        ev = _events.get(name)
        if ev is None:
            ev = _events[name] = threading.Event()
        return ev


def _barrier_wait(site: str, parties: int) -> None:
    with _lock:
        st = _barriers.get(site)
        if st is None:
            st = _barriers[site] = [0, lockdep.Condition(_lock), False]
        st[0] += 1
        cond = st[1]
        if st[0] % parties == 0:
            cond.notify_all()
            return
        gen = st[0] // parties
        deadline = time.monotonic() + WAIT_TIMEOUT_S
        while (not st[2] and st[0] // parties <= gen
               and st[0] % parties != 0):
            left = deadline - time.monotonic()
            if left <= 0 or not cond.wait(left):
                raise RuntimeError(
                    f"failpoint barrier {site!r} timed out "
                    f"({st[0] % parties}/{parties} arrived)")


def inject(name: str) -> None:
    """The site hook. No-op unless `name` is armed (or record_all)."""
    if not _active and not _record_all:  # fast path: nothing armed
        return
    with _lock:
        action = _active.get(name)
        if action is None and not _record_all:
            return
        _hits[name] = _hits.get(name, 0) + 1
        if len(_hit_log) < _LOG_MAX:
            _hit_log.append(
                (len(_hit_log) + 1, name, threading.current_thread().name))
        if action is None:
            return
        count = _hits[name]
    if isinstance(action, str) and "#" in action:
        base, _, nth = action.rpartition("#")
        if nth.isdigit():  # a non-numeric tail is part of the action
            if count != int(nth):
                return
            action = base
    if callable(action):
        action()
        return
    if action == "error":
        raise FailpointError(name)
    if action == "panic":
        os._exit(13)
    if isinstance(action, str):
        if action.startswith("sleep:"):
            # audited blocking: a sleep: action exists to WIDEN race
            # windows, deliberately also under hot locks
            with lockdep.allow_blocking("failpoint sleep action"):
                time.sleep(float(action.split(":", 1)[1]))
            return
        if action.startswith("wait:"):
            ev = _event(action.split(":", 1)[1])
            if not ev.wait(WAIT_TIMEOUT_S):
                raise RuntimeError(
                    f"failpoint {name!r} wait on {action!r} timed out")
            return
        if action.startswith("set:"):
            _event(action.split(":", 1)[1]).set()
            return
        if action.startswith("barrier:"):
            _barrier_wait(name, max(2, int(action.split(":", 1)[1])))
            return
        if action == "off":
            return
    raise ValueError(f"unknown failpoint action {action!r}")
