"""Device-runtime telemetry: compile, transfer, and device-memory
accounting for the accelerator tier.

PR 8 made the HOST side observable (stitched traces, log2 histograms,
/metrics, slow log); this module does the same for the device tier the
multi-chip work built — jit compiles (models/templates.py, models/
grid.py, models/ragged.py, ops/prom.py ShardedTiled, parallel/
distributed.py), host<->device transfers (colcache fills, grid/bucket
sharding, donate-resharding, result fetches), and retained device
buffers (the colcache device tier, frozen-batch mesh arrays, the
ShardedTiled caches).  Offload engines live or die by knowing exactly
what transfer, compile, and residency cost each query pays (the
GPU-offloading OLAP literature, arXiv:2601.19911); this is the
instrumentation floor the decode-on-device roadmap item is judged
against.

Four concerns, one arming model (the PR 8 idiom — `OGT_DEVOBS=1`, or
`/debug/ctrl?mod=devobs&arm=1` at runtime; results are bit-identical
armed or not):

  compile accounting   every jit lowering site calls note_compile() on
      a program-cache miss.  ALWAYS cheap-counted (compiles are rare —
      counters, the per-(kernel, geometry, mesh-epoch) inventory, the
      bounded recent-compile ring, and the recompile TRIPWIRE run even
      disarmed, replacing the old bare `device/compile_cache_misses`).
      Armed additionally: backend compile WALL TIME via the
      jax.monitoring duration events, attributed to the kernel label
      and to the running query's `device_compile` stage.

      The tripwire: mark_warm() (bench warm loops, or the ctrl op)
      snapshots "everything is compiled now"; ANY lowering-site miss
      after the mark increments `recompiles_after_warm_total` and flags
      the ring entry — the classic silent 10x regression in jit systems
      (shape churn, unstable cache keys, evicted programs).  Repeat
      compiles of an already-seen (kernel, geometry, mesh-epoch) triple
      are counted separately (`repeat_compiles_total`) with no mark
      needed: the same program lowering twice always means a cache lost
      an entry.

  transfer accounting  note_transfer(direction, site, nbytes, seconds)
      is the single chokepoint for h2d / d2h / reshard byte accounting
      (it owns the `device/{h2d,d2h,reshard}_bytes` counters the ad-hoc
      sites used to bump inline).  Armed additionally: per-site
      `ogt_device_{h2d,d2h,reshard}_{bytes,seconds}` histograms and
      `device_transfer` stage attribution.  fetch_np() wraps the
      device->host materialization (np.asarray of a jax array) so
      result fetches are labeled `result-fetch` — disarmed it is one
      isinstance check over a plain np.asarray.

  device-memory ledger every RETAINED device buffer registers (owner,
      nbytes, mesh-epoch): the colcache device tier, grid `mesh_arrays`
      / ragged `_Bucket._mesh_arrays` sharded copies, the ShardedTiled
      per-query caches and TiledPrepared device values.  Entries anchor
      to their holder via weakref.finalize, so a dropped batch can
      never leak a ledger row; /debug/device answers "what is resident
      and who owns it" by owner, and /metrics exports the gauges
      (cross-checked against jax per-device memory_stats() where the
      backend reports them — CPU does not).  Armed-only: register sites
      check enabled(), so arm BEFORE the workload you want inventoried.

  capability probes    backend_capabilities() answers what this jax
      backend can actually run — today: Pallas support (probed by
      executing a tiny real kernel from ops/pallas_segment.py).  The
      tier-1 pallas suite skips-with-reason on backends where the probe
      fails instead of reporting 12 undiagnosable failures, and fails
      for real where it succeeds.

An on-demand `jax.profiler` capture (start_profile / /debug/ctrl
op=profile&seconds=N) rounds out the ops surface — single-capture
guarded, writing a TensorBoard-loadable trace directory.

Knobs (README "Device observability"): OGT_DEVOBS (1 = armed),
OGT_DEVOBS_RING (recent-compile ring bound, default 256).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from contextlib import contextmanager

import numpy as _np

from opengemini_tpu.utils import lockdep
from opengemini_tpu.utils.stats import GLOBAL as _STATS

_ON = os.environ.get("OGT_DEVOBS", "") in ("1", "true")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


_RING_MAX = max(16, _env_int("OGT_DEVOBS_RING", 256))

# geometry-inventory bound per kernel: past this only the count grows
# (a kernel compiling thousands of distinct geometries IS the finding)
_GEOMETRIES_MAX = 512

_lock = lockdep.Lock()
_ring: deque = deque(maxlen=_RING_MAX)
_inventory: dict[str, dict] = {}   # kernel -> {compiles, geometries: {},
#                                    geometry_overflow, repeats}
_warm_marked = False
_compiles_since_warm = 0
_compile_wall_ns = 0               # armed-only accumulation
_started_pc = time.perf_counter()

# thread-local label of the most recently built kernel: the backend
# compile duration event fires on the SAME thread during the program's
# first invocation, immediately after the lowering-site miss, so "last
# built label on this thread" attributes it correctly for every
# instrumented site (un-instrumented compiles attribute to "other")
_tls = threading.local()

_listener_registered = False


def enabled() -> bool:
    return _ON


def set_enabled(on: bool) -> None:
    global _ON
    _ON = bool(on)
    if _ON:
        _ensure_listener()


def _ensure_listener() -> None:
    """Register the jax.monitoring compile-duration listener once (at
    first arming — registration itself is idempotent-guarded here)."""
    global _listener_registered
    if _listener_registered:
        return
    _listener_registered = True
    try:
        import jax.monitoring as _mon

        _mon.register_event_duration_secs_listener(_on_jax_duration)
    except Exception:  # noqa: BLE001 — observability must not raise
        pass


_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_jax_duration(event: str, duration_s: float, **_kw) -> None:
    if not _ON or event != _COMPILE_EVENT:
        return
    global _compile_wall_ns
    ns = int(duration_s * 1e9)
    kernel = getattr(_tls, "kernel", None) or "other"
    with _lock:
        _compile_wall_ns += ns
        ent = getattr(_tls, "ring_entry", None)
        if ent is not None and ent.get("kernel") == kernel:
            ent["wall_ms"] = round(ent.get("wall_ms", 0.0) + ns / 1e6, 3)
        # last-compile wall on the INVENTORY entry too: the offload
        # planner's compile-cost prior (query/offload.py) reads it from
        # inventory() per (kernel, geometry), not from the bounded ring
        geo = getattr(_tls, "geo_entry", None)
        if geo is not None:
            geo["wall_ms"] = round(geo.get("wall_ms", 0.0) + ns / 1e6, 3)
    from opengemini_tpu.utils.stats import observe_ns

    observe_ns("device_compile_seconds", ns, kernel=kernel)
    _note_stage("device_compile", ns)


def _note_stage(name: str, ns: int) -> None:
    """Attribute device time to the running query (tracker stages ->
    /debug/queries + slow-log stages_ms) and the cumulative stage stats
    (query_stages + the query_stage_seconds histogram)."""
    from opengemini_tpu.utils import tracing
    from opengemini_tpu.utils.querytracker import GLOBAL as _TRACKER

    tracing.record_stage(name, ns)
    _TRACKER.add_stage_ns(_TRACKER.current_qid(), name, ns)


# per-(family, site) histogram cache: note_transfer is on the armed hot
# path (every fetch/put), and the registry's get-or-create does a
# sorted-tuple key build per call — cache the objects like every other
# fixed-label call site does
_hist_cache: dict[tuple, object] = {}


def _hist(family: str, site: str, unit: str, mesh: bool = False):
    key = (family, site, mesh)
    h = _hist_cache.get(key)
    if h is None:
        from opengemini_tpu.utils.stats import histogram

        labels = {"site": site}
        if mesh:
            # the mesh dimension only appears on sharded transfers, so
            # every pre-existing site keeps its exact label set
            labels["mesh"] = "on"
        h = _hist_cache[key] = histogram(family, unit=unit, **labels)
    return h


# -- compile accounting -------------------------------------------------------


def _mesh_epoch() -> int:
    from opengemini_tpu.parallel import runtime as _prt

    return _prt.mesh_epoch()


def note_compile(kernel: str, geometry=()) -> None:
    """Record one jit lowering-site program-cache MISS.  Called at every
    site that builds a device program (templates._jitted_build, the grid
    and bucket stat kernels, the ShardedTiled program cache, the mesh
    batch-agg and reshard programs).  Always-on: compiles are rare, and
    the inventory/tripwire is precisely the thing you need when the
    system is misbehaving and nobody thought to arm anything."""
    global _compiles_since_warm
    geo = str(geometry)
    epoch = _mesh_epoch()
    _STATS.incr("device", "compiles_total")
    _STATS.incr("device", "compile_cache_misses")  # pre-PR-14 spelling
    entry = {
        "kernel": kernel, "geometry": geo, "mesh_epoch": epoch,
        "uptime_s": round(time.perf_counter() - _started_pc, 3),
    }
    with _lock:
        geo_ent = _geo_entry_locked(kernel, geo, epoch)
        inv = _inventory[kernel]
        inv["compiles"] += 1
        if geo_ent is not None:
            if geo_ent["compiles"]:
                inv["repeats"] += 1
                entry["repeat"] = True
                _STATS.incr("device", "repeat_compiles_total")
            geo_ent["compiles"] += 1
        if _warm_marked:
            _compiles_since_warm += 1
            entry["after_warm"] = True
            _STATS.incr("device", "recompiles_after_warm_total")
        _ring.append(entry)
        _tls.kernel = kernel
        _tls.ring_entry = entry
        _tls.geo_entry = geo_ent


def _geo_entry_locked(kernel: str, geo: str, epoch) -> dict | None:
    """The per-(geometry, mesh-epoch) inventory record for one kernel
    (created on first sight, None past the per-kernel bound — the
    overflow count is the finding then).  Caller holds _lock."""
    inv = _inventory.get(kernel)
    if inv is None:
        inv = _inventory[kernel] = {
            "compiles": 0, "geometries": OrderedDict(),
            "geometry_overflow": 0, "repeats": 0}
    key = (geo, epoch)
    ent = inv["geometries"].get(key)
    if ent is None:
        if len(inv["geometries"]) >= _GEOMETRIES_MAX:
            inv["geometry_overflow"] += 1
            return None
        ent = inv["geometries"][key] = {
            "compiles": 0, "hits": 0, "wall_ms": 0.0}
    return ent


def note_use(kernel: str, geometry=()) -> None:
    """Record one WARM dispatch of an already-compiled (kernel,
    geometry) program — the shape-recurrence signal the offload
    planner's amortization (query/offload.py) and the pre-warmer's
    top-K ranking feed on.  Always-on and cheap (two dict lookups under
    the lock, once per kernel launch)."""
    with _lock:
        ent = _geo_entry_locked(kernel, str(geometry), _mesh_epoch())
        if ent is not None:
            ent["hits"] += 1


def mark_warm() -> None:
    """Arm the recompile tripwire: everything needed is compiled NOW;
    any lowering-site miss from here on is a flagged recompile.  Bench
    warm loops call this after their compile warmup; operators via
    /debug/ctrl?mod=devobs&op=mark_warm once a service is warm."""
    global _warm_marked, _compiles_since_warm
    with _lock:
        _warm_marked = True
        _compiles_since_warm = 0


def clear_warm() -> None:
    global _warm_marked, _compiles_since_warm
    with _lock:
        _warm_marked = False
        _compiles_since_warm = 0


def compiles_since_warm() -> int:
    """Lowering-site misses since mark_warm() (0 when never marked)."""
    with _lock:
        return _compiles_since_warm


def jit_inventory() -> dict:
    """Per-kernel program-cache view: compile counts, distinct
    geometries (per mesh epoch), repeat compiles."""
    with _lock:
        return {
            k: {
                "compiles": v["compiles"],
                # use-only records (note_use before any compile) are not
                # compiled geometries; the pre-PR counting stands
                "distinct_geometries": sum(
                    1 for e in v["geometries"].values() if e["compiles"]),
                "geometry_overflow": v["geometry_overflow"],
                "repeat_compiles": v["repeats"],
            }
            for k, v in sorted(_inventory.items())
        }


def inventory() -> dict:
    """Structured per-(kernel, geometry) snapshot for the offload
    planner's cost model (query/offload.py): each kernel maps to its
    aggregate counts plus one record per (geometry, mesh-epoch) carrying
    the compile count, the warm-dispatch hit count (note_use), and the
    accumulated backend compile wall for that geometry — the
    recurrence + compile-cost inputs the amortization math needs.
    jit_inventory() stays the render-only aggregate view."""
    with _lock:
        return {
            k: {
                "compiles": v["compiles"],
                "repeat_compiles": v["repeats"],
                "geometry_overflow": v["geometry_overflow"],
                "geometries": [
                    {"geometry": geo, "mesh_epoch": epoch,
                     "compiles": e["compiles"], "hits": e["hits"],
                     "wall_ms": e["wall_ms"]}
                    for (geo, epoch), e in v["geometries"].items()
                ],
            }
            for k, v in sorted(_inventory.items())
        }


def recent_compiles() -> list[dict]:
    """Newest-first bounded ring of recent compiles with shapes."""
    with _lock:
        return [dict(e) for e in reversed(_ring)]


# -- transfer accounting ------------------------------------------------------


def note_transfer(direction: str, site: str, nbytes: int,
                  seconds: float | None = None,
                  mesh: bool = False) -> None:
    """The single chokepoint for device transfer accounting.  Always
    owns the `device/{h2d,d2h,reshard}_bytes` counters; armed it adds
    the per-site byte/latency histograms and attributes the wall to the
    running query's `device_transfer` stage.  ``mesh=True`` marks a
    transfer made under a configured device mesh (a `mesh="on"` label on
    the site's histograms — the sharded-decode H2D is distinguishable
    from the single-device one at the same site)."""
    nbytes = int(nbytes)
    # counter spelled *_total so the unlabeled family name stays free
    # for the per-site histogram of the same quantity
    _STATS.incr("device", direction + "_bytes_total", nbytes)
    if not _ON:
        return
    _hist("device_" + direction + "_bytes", site, "bytes",
          mesh).observe_ns(nbytes)
    if seconds is not None:
        ns = int(seconds * 1e9)
        _hist("device_" + direction + "_seconds", site, "seconds",
              mesh).observe_ns(ns)
        _note_stage("device_transfer", ns)


def fetch_np(x, site: str = "result-fetch"):
    """np.asarray with d2h accounting: device arrays count bytes (and,
    armed, fetch wall time); host arrays pass straight through."""
    import jax

    if not isinstance(x, jax.Array):
        return _np.asarray(x)
    if not _ON:
        a = _np.asarray(x)
        note_transfer("d2h", site, a.nbytes)
        return a
    t0 = time.perf_counter_ns()
    a = _np.asarray(x)
    note_transfer("d2h", site, a.nbytes,
                  (time.perf_counter_ns() - t0) / 1e9)
    return a


def t0() -> int:
    """perf_counter_ns when armed, 0 disarmed — the one-branch guard
    for exec-time attribution at kernel dispatch sites:

        t = devobs.t0()
        out = fn(*arrays)
        if t:
            devobs.note_exec(t)
    """
    return time.perf_counter_ns() if _ON else 0


def note_exec(t0_ns: int) -> None:
    """Attribute device-exec wall (dispatch + any blocking wait) since
    ``t0_ns`` to the running query's `device_exec` stage."""
    _note_stage("device_exec", time.perf_counter_ns() - t0_ns)


def span_snapshot() -> dict:
    """Cheap counters-only snapshot for per-span delta attribution (the
    executor's device_compute span fields) and the bench device
    blocks."""
    snap = _STATS.counters("device")
    with _lock:
        wall = _compile_wall_ns
    return {
        "compiles": snap.get("compiles_total", 0),
        "compile_wall_ms": round(wall / 1e6, 3),
        "h2d_bytes": snap.get("h2d_bytes_total", 0),
        "d2h_bytes": snap.get("d2h_bytes_total", 0),
        "reshard_bytes": snap.get("reshard_bytes_total", 0),
        "recompiles_after_warm": snap.get("recompiles_after_warm_total", 0),
    }


# -- device-memory ledger -----------------------------------------------------


class DeviceLedger:
    """Registry of retained device buffers: (owner, nbytes, mesh_epoch)
    per entry.  Entries registered with an ``anchor`` drop automatically
    when the anchor is collected — a per-query batch that dies
    mid-flight can never leak a row.  The finalizer does NOT take the
    ledger lock (a GC pass can fire finalizers inside a ledger method
    that already holds it — dict mutation allocates); it appends the
    handle to a lock-free deque drained at the next ledger operation.
    Armed-only by the register sites' enabled() guard; register()
    itself returns None disarmed so holders store-and-forget the
    handle."""

    def __init__(self) -> None:
        self._lock = lockdep.Lock()
        self._next = 1
        self._entries: dict[int, dict] = {}
        # GC-finalizer drop queue: deque.append is atomic and takes no
        # lock, so it is safe to run at ANY allocation point
        self._pending_drops: deque = deque()

    def _drain_locked(self) -> None:
        while True:
            try:
                handle = self._pending_drops.popleft()
            except IndexError:
                return
            self._entries.pop(handle, None)

    def register(self, owner: str, nbytes: int, mesh_epoch=None,
                 label: str = "", anchor=None) -> int | None:
        if not _ON:
            return None
        with self._lock:
            self._drain_locked()
            handle = self._next
            self._next += 1
            self._entries[handle] = {
                "owner": owner, "nbytes": int(nbytes),
                "mesh_epoch": mesh_epoch, "label": label,
            }
        if anchor is not None:
            weakref.finalize(anchor, self._pending_drops.append, handle)
        return handle

    def update(self, handle: int | None, nbytes: int | None = None,
               mesh_epoch=...) -> None:
        if handle is None:
            return
        with self._lock:
            self._drain_locked()
            ent = self._entries.get(handle)
            if ent is None:
                return
            if nbytes is not None:
                ent["nbytes"] = int(nbytes)
            if mesh_epoch is not ...:
                ent["mesh_epoch"] = mesh_epoch

    def drop(self, handle: int | None) -> None:
        if handle is None:
            return
        with self._lock:
            self._drain_locked()
            self._entries.pop(handle, None)

    def total_bytes(self) -> int:
        with self._lock:
            self._drain_locked()
            return sum(e["nbytes"] for e in self._entries.values())

    def by_owner(self) -> dict:
        """{owner: {bytes, entries, stale_epoch_entries}} — the
        /debug/device residency answer.  An entry is stale when its
        recorded mesh epoch no longer matches the live one (a buffer
        laid out for a dead mesh, pending reshard or eviction)."""
        live = _mesh_epoch()
        out: dict[str, dict] = {}
        with self._lock:
            self._drain_locked()
            for e in self._entries.values():
                o = out.setdefault(e["owner"], {
                    "bytes": 0, "entries": 0, "stale_epoch_entries": 0})
                o["bytes"] += e["nbytes"]
                o["entries"] += 1
                if e["mesh_epoch"] is not None and e["mesh_epoch"] != live:
                    o["stale_epoch_entries"] += 1
        return out

    def entries(self, limit: int = 256) -> list[dict]:
        with self._lock:
            self._drain_locked()
            rows = sorted(self._entries.values(),
                          key=lambda e: -e["nbytes"])[:limit]
            return [dict(e) for e in rows]

    def clear(self) -> None:
        with self._lock:
            self._drain_locked()
            self._entries.clear()


LEDGER = DeviceLedger()


def _ledger_gauges() -> dict:
    """Stats provider: ledger residency gauges ride /debug/vars and
    /metrics (module `device` -> ogt_device_ledger_* families) when
    armed; {} pass-through disarmed, the governor-provider idiom."""
    if not _ON:
        return {}
    out = {"ledger_bytes": LEDGER.total_bytes()}
    for owner, doc in LEDGER.by_owner().items():
        safe = "".join(c if c.isalnum() else "_" for c in owner.lower())
        out["ledger_" + safe + "_bytes"] = doc["bytes"]
        out["ledger_" + safe + "_entries"] = doc["entries"]
    return out


_STATS.register_provider("device", _ledger_gauges)


# -- backend capabilities -----------------------------------------------------

_caps_lock = lockdep.Lock()
_caps: dict | None = None


def backend_capabilities(probe: bool = True) -> dict:
    """What this jax backend can actually run, probed once per process.
    `pallas`: executes a tiny SELF-CONTAINED pallas_call (interpret mode
    off-TPU, Mosaic on TPU) exercising the same backend capability the
    product kernels need — an int-typed masked reduce stored into an
    int32 out ref (exactly what breaks in interpret mode under x64 on
    some jax versions).  Deliberately NOT one of the product kernels:
    a regression in ops/pallas_segment.py must fail its tests, not
    convert them into skips.

    ``probe=False`` answers from the cache only (the /debug/device
    handler must never run a compile inline on a serving thread)."""
    global _caps
    with _caps_lock:
        if _caps is not None:
            return _caps
    if not probe:
        return {"probed": False, "pallas": {
            "supported": None,
            "reason": "unprobed (pallas_supported() runs the probe)"}}
    caps: dict = {"probed": True}
    try:
        import jax

        caps["backend"] = jax.default_backend()
        caps["device_count"] = len(jax.devices())
    except Exception as e:  # noqa: BLE001 — a dead backend is an answer
        caps["backend"] = None
        caps["error"] = f"{type(e).__name__}: {e}"
    ok, why = _probe_pallas()
    caps["pallas"] = {"supported": ok, "reason": why}
    with _caps_lock:
        _caps = caps
    return caps


def _probe_pallas() -> tuple[bool, str]:
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kern(m_ref, cnt_ref):
            # the product kernels' idiom: a masked integer reduce with an
            # EXPLICIT int32 result stored into an int32 ref.  The
            # explicit cast is load-bearing — x64 interpret mode widens
            # bare integer reduces to int64, which int32 refs reject —
            # so the kernels in ops/pallas_segment.py cast the same way,
            # and the probe passes wherever they can actually run.
            cnt_ref[...] = ((m_ref[...] != 0)
                            .sum(axis=1, keepdims=True)
                            .astype(jnp.int32))

        m = _np.ones((8, 8), _np.int8)
        out = pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((8, 1), jnp.int32),
            interpret=jax.default_backend() != "tpu",
        )(m)
        if int(_np.asarray(out)[0, 0]) != 8:
            return False, "pallas probe kernel computed a wrong count"
        return True, ""
    except Exception as e:  # noqa: BLE001 — any failure = unsupported
        return False, (f"pallas probe failed on this backend: "
                       f"{type(e).__name__}: {e}")


def pallas_supported() -> tuple[bool, str]:
    """(supported, reason) — what tests/test_pallas.py gates on."""
    cap = backend_capabilities()["pallas"]
    return cap["supported"], cap["reason"]


# -- on-demand profiler capture ----------------------------------------------

_profile_lock = lockdep.Lock()
_profile = {"active": False, "dir": None, "started_uptime_s": None,
            "seconds": None, "last": None}


def start_profile(seconds: float, logdir: str | None = None) -> dict:
    """Start a single-capture-guarded jax.profiler trace for
    ``seconds`` (clamped to [0.05, 120]); a background thread stops it.
    Raises RuntimeError while a capture is already active.  Returns the
    status dict (dir included) immediately — the trace directory is
    TensorBoard / XProf loadable once `active` goes false."""
    import tempfile

    seconds = min(max(float(seconds), 0.05), 120.0)
    with _profile_lock:
        if _profile["active"]:
            raise RuntimeError(
                f"profiler capture already active in {_profile['dir']}")
        if logdir is None:
            logdir = tempfile.mkdtemp(prefix="ogt-devobs-profile-")
        _profile.update(active=True, dir=logdir, seconds=seconds,
                        started_uptime_s=round(
                            time.perf_counter() - _started_pc, 3))
    import jax

    try:
        jax.profiler.start_trace(logdir)
    except Exception as e:  # noqa: BLE001 — surface, don't wedge the guard
        with _profile_lock:
            _profile.update(active=False,
                            last={"dir": logdir, "ok": False,
                                  "error": f"{type(e).__name__}: {e}"})
        raise RuntimeError(f"profiler start failed: {e}") from e

    def _stop():
        time.sleep(seconds)
        doc = {"dir": logdir, "seconds": seconds, "ok": True}
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            doc = {"dir": logdir, "seconds": seconds, "ok": False,
                   "error": f"{type(e).__name__}: {e}"}
        with _profile_lock:
            _profile.update(active=False, last=doc)

    threading.Thread(target=_stop, name="devobs-profile-stop",
                     daemon=True).start()
    return profile_status()


def profile_status() -> dict:
    with _profile_lock:
        return dict(_profile)


# -- /debug/device ------------------------------------------------------------


def device_table() -> list[dict]:
    """One row per jax device, with per-device memory stats where the
    backend reports them (TPU/GPU; CPU answers null) — the cross-check
    against the ledger's own residency accounting."""
    try:
        import jax

        devs = jax.devices()
    except Exception as e:  # noqa: BLE001
        return [{"error": f"{type(e).__name__}: {e}"}]
    out = []
    for d in devs:
        row = {"id": d.id, "platform": d.platform,
               "device_kind": getattr(d, "device_kind", "")}
        try:
            row["memory_stats"] = d.memory_stats()
        except Exception:  # noqa: BLE001 — optional per backend
            row["memory_stats"] = None
        out.append(row)
    return out


def debug_doc() -> dict:
    """The GET /debug/device payload."""
    from opengemini_tpu.parallel import runtime as _prt

    mesh = _prt.get_mesh()
    with _lock:
        warm = {"marked": _warm_marked,
                "compiles_since_warm": _compiles_since_warm}
        wall_ms = round(_compile_wall_ns / 1e6, 3)
    return {
        "enabled": _ON,
        # cache-only: the first debug scrape must never run the probe's
        # kernel compile inline on a serving thread
        "capabilities": backend_capabilities(probe=False),
        "devices": device_table(),
        "mesh": {"configured": mesh is not None,
                 "size": getattr(mesh, "size", None),
                 "epoch": _prt.mesh_epoch()},
        "counters": _STATS.counters("device"),
        "compile_wall_ms": wall_ms,
        "jit_cache": jit_inventory(),
        "recent_compiles": recent_compiles(),
        "warm": warm,
        "ledger": {
            "total_bytes": LEDGER.total_bytes(),
            "by_owner": LEDGER.by_owner(),
            "entries": LEDGER.entries(),
        },
        "profile": profile_status(),
    }


def reset() -> None:
    """Test/bench hygiene: clear the ring, inventory, warm mark, and
    compile-wall accumulation (counters in the stats registry are the
    registry's to reset)."""
    global _compile_wall_ns
    with _lock:
        _ring.clear()
        _inventory.clear()
        _compile_wall_ns = 0
    clear_warm()


@contextmanager
def armed(on: bool = True):
    """Scoped arm/disarm (tests, bench A/B legs)."""
    prev = _ON
    set_enabled(on)
    try:
        yield
    finally:
        set_enabled(prev)


if _ON:
    _ensure_listener()
