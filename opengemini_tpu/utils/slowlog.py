"""Slow-query capture: a bounded ring of the most recent queries that
crossed the OGT_SLOW_QUERY_MS threshold, each record carrying enough to
answer "which node/stage ate the time" after the fact — the statement,
database/tenant, per-stage timings, the stitched cross-node span tree
(when tracing is armed), and the governor ledger at completion.

Reference: the query-manager slow-log + lib/statisticsPusher slow-query
statistics.  Served at /debug/slow, tuned via /debug/ctrl?mod=obs,
embedded in sherlock diagnostic dumps.

Pass-through: with OGT_SLOW_QUERY_MS unset, note() is one attribute
check per query.
"""

from __future__ import annotations

import os
import threading
from opengemini_tpu.utils import lockdep
import time
from collections import deque


def _env_float(name: str):
    v = os.environ.get(name, "")
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


class SlowLog:
    def __init__(self) -> None:
        self._lock = lockdep.Lock()
        self.threshold_ms = _env_float("OGT_SLOW_QUERY_MS")  # None = off
        try:
            self.max_records = max(
                1, int(os.environ.get("OGT_SLOW_LOG_MAX", "") or 64))
        except ValueError:
            self.max_records = 64
        self._ring: deque[dict] = deque(maxlen=self.max_records)
        self.captured = 0  # total ever captured (ring evicts oldest)

    def enabled(self) -> bool:
        return self.threshold_ms is not None

    def configure(self, slow_ms: float | None = ...,
                  slow_max: int | None = None) -> None:
        """Runtime tuning (/debug/ctrl?mod=obs).  slow_ms=None disables;
        the ... sentinel leaves the threshold untouched.  Shrinking
        slow_max drops the OLDEST records (deque maxlen semantics)."""
        with self._lock:
            if slow_ms is not ...:
                self.threshold_ms = slow_ms
            if slow_max is not None and slow_max >= 1:
                if slow_max != self.max_records:
                    self.max_records = slow_max
                    self._ring = deque(self._ring, maxlen=slow_max)

    def note(self, qid, text: str, db: str, duration_ms: float,
             trace=None, stages: dict | None = None,
             extra: dict | None = None) -> bool:
        """Record one finished query if it crossed the threshold.
        `trace` is the (finished) tracing.Trace or None; `stages` the
        querytracker per-stage ns map (colcache/rollup/admission_wait
        attribution rides along even with span trees off)."""
        thresh = self.threshold_ms
        if thresh is None or duration_ms < thresh:
            return False
        from opengemini_tpu.utils.querytracker import redact

        rec = {
            "qid": qid,
            "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
            "duration_ms": round(duration_ms, 3),
            "statement": redact(text),
            "database": db,
            "tenant": db,  # the governor's tenant identity is the db
            "stages_ms": {
                name: round(ns / 1e6, 3)
                for name, ns in (stages or {}).items()
            },
            "trace": trace.to_dict() if trace is not None else None,
        }
        try:
            # the ledger at completion: which component held the memory
            # while this query was slow (empty dict pass-through when
            # the governor is disabled)
            from opengemini_tpu.utils.governor import GOVERNOR

            if GOVERNOR.enabled():
                rec["governor"] = GOVERNOR.describe()
        except Exception:  # noqa: BLE001 — observability must not raise
            pass
        if extra:
            rec.update(extra)
        with self._lock:
            self._ring.append(rec)
            self.captured += 1
        from opengemini_tpu.utils.stats import GLOBAL as STATS

        STATS.incr("slowlog", "captured")
        return True

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "threshold_ms": self.threshold_ms,
                "max_records": self.max_records,
                "captured": self.captured,
                "records": list(self._ring),
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


GLOBAL = SlowLog()
