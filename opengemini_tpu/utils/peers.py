"""Cluster-internal HTTP scheme + TLS client context.

The reference exposes TLS options in lib/config (sql.go https-enabled /
certificate/private-key) applied to the httpd listener and to
inter-node transports; here one process-wide switch flips every peer
call site (raft messages, /internal/* data-plane, /cluster/* control)
to https with a shared ssl.SSLContext. Server-side wrapping lives in
server/http.py (HttpService tls=...); this module is the CLIENT half —
call sites build URLs with url() and open them with urlopen() so none
of them hard-code a scheme.
"""

from __future__ import annotations

import ssl
import urllib.request

_scheme = "http"
_context: ssl.SSLContext | None = None


def configure_tls(ca_file: str | None = None,
                  skip_verify: bool = False) -> None:
    """Switch peer traffic to https. `ca_file` trusts a private CA (the
    usual cluster deployment); `skip_verify` disables verification for
    self-signed lab setups (reference: insecure-skip-verify)."""
    global _scheme, _context
    ctx = ssl.create_default_context(cafile=ca_file)
    if skip_verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    _scheme = "https"
    _context = ctx


def reset() -> None:
    """Back to plain http (tests)."""
    global _scheme, _context
    _scheme = "http"
    _context = None


def url(addr: str, path: str) -> str:
    """Peer URL under the configured scheme. `path` starts with '/'."""
    return f"{_scheme}://{addr}{path}"


def urlopen(req, timeout: float | None = None):
    """urllib.request.urlopen with the peer TLS context applied."""
    if timeout is None:
        return urllib.request.urlopen(req, context=_context)
    return urllib.request.urlopen(req, timeout=timeout, context=_context)
