"""Process-wide resource governor: unified memory ledger, query admission
control, write-path backpressure, and background throttling.

Reference: the dedicated resource-control layer of the reference engine —
lib/resourceallocator (per-resource allocators with seat counts),
the query manager's concurrency/memory limits (app/ts-store/transport/
query/manager.go), and lib/iodetector feeding load decisions.  This
reproduction grew four independent byte budgets (`OGT_SCAN_INFLIGHT_MB`,
`OGT_ENCODE_INFLIGHT_MB`, `OGT_COLCACHE_MB`, memtable flush thresholds)
with no process-wide ledger and nothing that sheds load instead of
OOMing; the governor closes that gap.

Four cooperating pieces, all pass-through when `OGT_MEM_BUDGET_MB` is
unset (every existing code path is bit-identical — each hook checks
`enabled()` first and does nothing):

  Unified memory ledger
      Every budget holder registers a live byte provider under one
      ceiling: memtables+WAL backlog across shards (storage/engine.py),
      decoded-column cache host+device tiers (storage/colcache.py),
      scanpool/encodepool in-flight bytes, plus per-query working-set
      RESERVATIONS estimated from chunk metadata before scan dispatch
      (query/executor.py).  The ledger is observational (providers) +
      transactional (reservations); `/debug/vars` exposes per-component
      bytes.

  Query admission control
      Priority classes (interactive HTTP/Flight queries > background
      compaction/downsample/stream/CQ work), concurrency slots
      (`OGT_MAX_CONCURRENT_QUERIES`), and a bounded FIFO wait queue with
      a deadline (`OGT_ADMIT_QUEUE`, `OGT_ADMIT_TIMEOUT_MS`).  A full
      queue or an expired deadline sheds with `AdmissionRejected`, which
      the HTTP layer maps to 503 + `Retry-After` (flight maps to
      UNAVAILABLE).  A reservation that would overdraw the ledger past
      `OGT_OVERDRAFT_PCT` kills the query through the existing
      QueryTracker cancellation points (a clean query error, never an
      OOM).

  Write-path backpressure
      When the memtable+WAL backlog crosses the high watermark
      (`OGT_WRITE_HIWAT_PCT` of the budget), `/write` answers 429 +
      `Retry-After` until the backlog drains below `OGT_WRITE_LOWAT_PCT`
      (a failpoint-visible hysteresis band: `governor-backpressure-on` /
      `governor-backpressure-off`).

  Background throttling
      Governed services (compaction/downsample/stream) acquire a
      low-priority token per tick and pause while interactive occupancy
      is high (`OGT_BG_PAUSE_PCT` of the slots) or an IO alarm is recent
      (services/iodetector.py calls `note_io_alarm`).

Failpoint sites at every decision edge (armed via OGTPU_FAILPOINTS or
POST /debug/ctrl?mod=failpoint, catalogued in README.md):
  governor-admit            every admission attempt (granted or not)
  governor-queue            a query entered the wait queue
  governor-shed             a request was shed (queue full / timeout /
                            write backpressure)
  governor-overdraft-kill   a reservation overdraft killed a query
  governor-backpressure-on  backlog crossed the high watermark
  governor-backpressure-off backlog drained below the low watermark

Observability: gauges + counters ride /debug/vars (utils/stats provider),
an admission section rides /debug/queries (querytracker provider),
admission wait time lands in the query_stages stats and on the waiting
query's stage attribution, and POST /debug/ctrl?mod=governor tunes every
knob at runtime.  A shed/kill burst triggers a rate-limited diagnostic
hook (services/sherlock.py registers its dump).
"""

from __future__ import annotations

import contextlib
import os
import threading
from opengemini_tpu.utils import lockdep
import time
from collections import deque

from opengemini_tpu.utils.failpoint import inject as _fp

_INTERACTIVE = "interactive"
_BACKGROUND = "background"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class AdmissionRejected(Exception):
    """A query was shed by admission control (HTTP 503 + Retry-After)."""

    def __init__(self, reason: str, retry_after_s: int):
        super().__init__(f"query shed: {reason}")
        self.reason = reason
        self.retry_after_s = retry_after_s


class _NoopToken:
    """Admission token of the disabled (pass-through) governor."""

    __slots__ = ()
    waited_ns = 0
    kind = _INTERACTIVE

    def release(self) -> None:
        pass


_NOOP_TOKEN = _NoopToken()


class _AdmitToken:
    __slots__ = ("_gov", "kind", "waited_ns", "_released", "_nested")

    def __init__(self, gov: "ResourceGovernor", kind: str, waited_ns: int,
                 nested: bool = False):
        self._gov = gov
        self.kind = kind
        self.waited_ns = waited_ns
        self._released = False
        self._nested = nested

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._gov._release_token(self)


class _BgToken:
    """Low-priority background token: marks the holding thread's query
    class as background (queries it runs classify accordingly) and rides
    the bg occupancy gauge."""

    __slots__ = ("_gov", "name", "_prev_kind", "_released")

    def __init__(self, gov: "ResourceGovernor", name: str):
        self._gov = gov
        self.name = name
        self._prev_kind = getattr(gov._local, "kind", None)
        gov._local.kind = _BACKGROUND
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._gov._local.kind = self._prev_kind
        with self._gov._cond:
            self._gov._bg_tokens = max(0, self._gov._bg_tokens - 1)


class ResourceGovernor:
    def __init__(self) -> None:
        self._lock = lockdep.Lock()
        self._cond = lockdep.Condition(self._lock)
        self._local = threading.local()
        # -- config (runtime-tunable via configure()) --
        self._budget = _env_int("OGT_MEM_BUDGET_MB", 0) << 20
        self._max_concurrent = max(1, _env_int("OGT_MAX_CONCURRENT_QUERIES", 16))
        self._queue_max = max(0, _env_int("OGT_ADMIT_QUEUE", 64))
        self._timeout_s = max(0.0, _env_int("OGT_ADMIT_TIMEOUT_MS", 3000) / 1000.0)
        self._hiwat_pct = max(1, _env_int("OGT_WRITE_HIWAT_PCT", 85))
        self._lowat_pct = max(0, _env_int("OGT_WRITE_LOWAT_PCT", 60))
        self._normalize_watermarks()
        self._overdraft_pct = _env_int("OGT_OVERDRAFT_PCT", 150)
        self._bg_pause_pct = _env_int("OGT_BG_PAUSE_PCT", 50)
        # anti-starvation bound on a background pause: sustained
        # interactive saturation must not stall compaction/downsample
        # forever (file counts and read amplification grow exactly when
        # the system is busiest) — after this many seconds a paused tick
        # is granted anyway.  0 = pause indefinitely.
        self._bg_max_pause_s = float(max(0, _env_int("OGT_BG_MAX_PAUSE_S", 30)))
        self._retry_after_s = max(1, _env_int("OGT_RETRY_AFTER_S", 1))
        # -- ledger --
        self._components: dict[str, list] = {}
        self._reserved = 0
        self._res_by_qid: dict[int, int] = {}
        # -- admission --
        self._active = {_INTERACTIVE: 0, _BACKGROUND: 0}
        # FIFO entries [event, kind, enqueued_monotonic]; interactive
        # waiters are granted before background ones, FIFO within a class
        self._waiting: deque = deque()
        self._bg_tokens = 0
        # -- backpressure hysteresis --
        self._bp_active = False
        # backlog sweep TTL: every governed write otherwise walks each
        # shard's memtable parts under the engine lock (O(shards) per
        # request on the hot ingest path).  0 = sweep every request.
        self._bp_cache_s = max(0, _env_int("OGT_WRITE_BP_CACHE_MS", 50)) / 1000.0
        self._bp_backlog_cached = 0
        self._bp_backlog_at = float("-inf")
        self._io_alarm_until = 0.0
        self._io_pause_s = max(0, _env_int("OGT_BG_IO_PAUSE_S", 30))
        # -- counters (ints; exported at /debug/vars) --
        self._counters = {
            "admitted": 0, "queued": 0, "sheds_queue_full": 0,
            "sheds_timeout": 0, "sheds_backpressure": 0, "kills": 0,
            "bp_on": 0, "bp_off": 0, "bg_pauses": 0, "bg_forced": 0,
            "io_alarms": 0,
        }
        # -- per-tenant (database) accounting: background maintenance
        # work charged to its owner (rollup folds, sheds) — surfaced in
        # gauges()/describe() so a hostile tenant's churn is attributable
        self._tenants: dict[str, dict[str, int]] = {}
        # -- shed/kill burst -> diagnostic hook (sherlock) --
        self._hook = None
        self._shed_times: deque = deque()
        self._burst_n = max(1, _env_int("OGT_SHED_BURST", 25))
        self._burst_window_s = 10.0
        self._hook_cooldown_s = max(0, _env_int("OGT_SHED_BURST_COOLDOWN_S", 120))
        self._last_hook = float("-inf")

    # -- config --------------------------------------------------------------

    def enabled(self) -> bool:
        return self._budget > 0

    def configure(self, budget_mb: int | None = None,
                  max_concurrent: int | None = None,
                  queue: int | None = None,
                  timeout_ms: int | None = None,
                  hiwat_pct: int | None = None,
                  lowat_pct: int | None = None,
                  overdraft_pct: int | None = None,
                  bg_pause_pct: int | None = None,
                  bg_max_pause_s: float | None = None,
                  bp_cache_ms: int | None = None) -> None:
        """Runtime tuning (POST /debug/ctrl?mod=governor). Each knob
        changes only when passed; growing the slot count grants waiters
        immediately; setting budget_mb=0 disables (pass-through)."""
        with self._cond:
            if budget_mb is not None:
                self._budget = max(0, int(budget_mb)) << 20
            if max_concurrent is not None:
                self._max_concurrent = max(1, int(max_concurrent))
            if queue is not None:
                self._queue_max = max(0, int(queue))
            if timeout_ms is not None:
                self._timeout_s = max(0.0, int(timeout_ms) / 1000.0)
            if hiwat_pct is not None:
                self._hiwat_pct = max(1, int(hiwat_pct))
            if lowat_pct is not None:
                self._lowat_pct = max(0, int(lowat_pct))
            self._normalize_watermarks()
            if overdraft_pct is not None:
                self._overdraft_pct = max(100, int(overdraft_pct))
            if bg_pause_pct is not None:
                self._bg_pause_pct = max(1, int(bg_pause_pct))
            if bg_max_pause_s is not None:
                self._bg_max_pause_s = max(0.0, float(bg_max_pause_s))
            if bp_cache_ms is not None:
                self._bp_cache_s = max(0, int(bp_cache_ms)) / 1000.0
                self._bp_backlog_at = float("-inf")  # take effect now
            self._grant_waiters_locked()
            self._cond.notify_all()

    def _normalize_watermarks(self) -> None:
        """The hysteresis band requires lowat STRICTLY below hiwat — an
        inverted band would flip backpressure on/off per request, which
        is exactly the oscillation the band exists to prevent.  Clamp
        rather than reject: /debug/ctrl sets knobs one at a time and a
        transient inversion mid-tuning must not error out."""
        if self._lowat_pct >= self._hiwat_pct:
            self._lowat_pct = self._hiwat_pct - 1

    def config(self) -> dict:
        return {
            "budget_mb": self._budget >> 20,
            "max_concurrent": self._max_concurrent,
            "queue": self._queue_max,
            "timeout_ms": int(self._timeout_s * 1000),
            "hiwat_pct": self._hiwat_pct,
            "lowat_pct": self._lowat_pct,
            "overdraft_pct": self._overdraft_pct,
            "bg_pause_pct": self._bg_pause_pct,
            "bg_max_pause_s": self._bg_max_pause_s,
            "bp_cache_ms": int(self._bp_cache_s * 1000),
        }

    def reset(self) -> None:
        """Zero counters and transient state (tests / operator reset).
        Only safe while no queries are in flight — held tokens released
        after a reset guard against going negative but their slot
        accounting is forfeited."""
        with self._cond:
            for k in self._counters:
                self._counters[k] = 0
            self._active = {_INTERACTIVE: 0, _BACKGROUND: 0}
            for entry in self._waiting:
                entry[0].set()  # never strand a parked waiter
            self._waiting.clear()
            self._reserved = 0
            self._res_by_qid.clear()
            self._bp_active = False
            self._bp_backlog_at = float("-inf")
            self._io_alarm_until = 0.0
            self._bg_tokens = 0
            self._tenants.clear()
            self._shed_times.clear()
            self._last_hook = float("-inf")
            self._cond.notify_all()

    # -- unified memory ledger ----------------------------------------------

    def register_component(self, name: str, fn) -> None:
        """Attach a live byte provider (fn() -> int). Multiple providers
        of one name sum (several engines report one memtable total)."""
        with self._lock:
            self._components.setdefault(name, []).append(fn)

    def unregister_component(self, name: str, fn) -> None:
        with self._lock:
            fns = self._components.get(name)
            if fns and fn in fns:
                fns.remove(fn)
            if fns is not None and not fns:
                del self._components[name]

    def _component_bytes(self, name: str) -> int:
        with self._lock:
            fns = list(self._components.get(name, ()))
        total = 0
        for fn in fns:  # outside the lock: providers lock their own state
            try:
                total += int(fn())
            except Exception:  # noqa: BLE001 — a dying provider (closed
                continue       # engine) must not break governance
        return total

    def ledger(self) -> dict:
        """Per-component live bytes + reservations (ints)."""
        with self._lock:
            names = list(self._components)
            reserved = self._reserved
        out = {name: self._component_bytes(name) for name in names}
        out["reserved"] = reserved
        return out

    def ledger_total(self) -> int:
        led = self.ledger()
        return sum(led.values())

    @contextlib.contextmanager
    def scan_reservation(self, qid: int | None, est_bytes: int):
        """Reserve a query's estimated working set (from chunk metadata)
        for the duration of its scan.  A reservation that would overdraw
        the ledger past the kill threshold cancels the query through the
        QueryTracker — the next cancellation point raises QueryKilled,
        which surfaces as a clean query error.

        The reservation stays charged at its full estimate while the scan
        runs, so bytes the query has already materialized are counted
        TWICE (once here, once by the scanpool/colcache gauges).  This is
        deliberate: the estimate cannot be decayed safely without knowing
        which gauge bytes belong to which query, and over-counting sheds
        a query early instead of OOMing late — size OGT_OVERDRAFT_PCT
        with that headroom in mind."""
        if self._budget <= 0 or est_bytes <= 0:
            yield
            return
        est_bytes = int(est_bytes)
        kill_at = self._budget * self._overdraft_pct // 100
        # charge FIRST, then check: each concurrent reservation sees the
        # others' charge in the ledger, so N queries reserving at once
        # cannot jointly blow past the kill threshold through a
        # read-then-charge race (the cost is killing one query too many
        # under a genuine race — shed early beats OOM late)
        with self._lock:
            self._reserved += est_bytes
            if qid is not None:
                self._res_by_qid[qid] = self._res_by_qid.get(qid, 0) + est_bytes
        if qid is not None and self.ledger_total() > kill_at:
            from opengemini_tpu.utils.querytracker import GLOBAL as _TRACKER

            self._release_reservation(qid, est_bytes)
            with self._lock:
                self._counters["kills"] += 1
            self._note_shed("overdraft kill")
            _fp("governor-overdraft-kill")
            _TRACKER.kill(qid)
            _TRACKER.raise_if_killed(qid)
        try:
            yield
        finally:
            self._release_reservation(qid, est_bytes)

    def _release_reservation(self, qid: int | None, est_bytes: int) -> None:
        with self._lock:
            self._reserved = max(0, self._reserved - est_bytes)
            if qid is not None:
                left = self._res_by_qid.get(qid, 0) - est_bytes
                if left > 0:
                    self._res_by_qid[qid] = left
                else:
                    self._res_by_qid.pop(qid, None)

    # -- admission control ---------------------------------------------------

    def current_kind(self) -> str:
        return getattr(self._local, "kind", None) or _INTERACTIVE

    def admit(self, kind: str | None = None):
        """Admit one query; returns a token to release() when the query
        finishes.  Raises AdmissionRejected (queue full / deadline) —
        the HTTP layer maps it to 503 + Retry-After.  Reentrant: a query
        executed from within an admitted query (logstore, CQ re-entry)
        rides the outer slot."""
        if self._budget <= 0:
            return _NOOP_TOKEN
        depth = getattr(self._local, "admit_depth", 0)
        if depth > 0:
            self._local.admit_depth = depth + 1
            return _AdmitToken(self, self.current_kind(), 0, nested=True)
        if kind is None:
            kind = self.current_kind()
        _fp("governor-admit")
        entry = None
        t0 = time.monotonic()
        with self._cond:
            if self._can_admit_locked(kind):
                self._active[kind] += 1
                self._counters["admitted"] += 1
                self._local.admit_depth = 1
                return _AdmitToken(self, kind, 0)
            if len(self._waiting) >= self._queue_max:
                self._counters["sheds_queue_full"] += 1
            else:
                entry = [threading.Event(), kind, t0]
                self._waiting.append(entry)
                self._counters["queued"] += 1
        if entry is None:
            self._note_shed("admission queue full")
            _fp("governor-shed")
            raise AdmissionRejected("admission queue full",
                                    self._retry_after())
        _fp("governor-queue")
        granted = entry[0].wait(self._timeout_s)
        if not granted:
            with self._cond:
                # re-check under the lock: a grant can race the timeout
                if entry[0].is_set():
                    granted = True
                else:
                    try:
                        self._waiting.remove(entry)
                    except ValueError:
                        pass
                    self._counters["sheds_timeout"] += 1
        waited_ns = int((time.monotonic() - t0) * 1e9)
        if not granted:
            self._note_shed("admission wait deadline")
            _fp("governor-shed")
            raise AdmissionRejected(
                f"admission wait exceeded {int(self._timeout_s * 1000)}ms",
                self._retry_after())
        self._local.admit_depth = 1
        return _AdmitToken(self, kind, waited_ns)

    @contextlib.contextmanager
    def admitted(self, kind: str | None = None):
        """Context-manager form of admit()/release() for call sites that
        wrap a single scan (the PromQL read surface)."""
        token = self.admit(kind)
        try:
            yield token
        finally:
            token.release()

    def _can_admit_locked(self, kind: str) -> bool:
        free = (self._active[_INTERACTIVE] + self._active[_BACKGROUND]
                < self._max_concurrent)
        if not free:
            return False
        if kind == _INTERACTIVE:
            # strict FIFO among interactive waiters; background waiters
            # never block an interactive grant (priority)
            return not any(e[1] == _INTERACTIVE for e in self._waiting)
        return not self._waiting

    def _grant_waiters_locked(self) -> None:
        while self._waiting and (
            self._active[_INTERACTIVE] + self._active[_BACKGROUND]
            < self._max_concurrent
        ):
            entry = next((e for e in self._waiting if e[1] == _INTERACTIVE),
                         self._waiting[0])
            self._waiting.remove(entry)
            self._active[entry[1]] += 1
            self._counters["admitted"] += 1
            entry[0].set()

    def _release_token(self, token: "_AdmitToken") -> None:
        depth = getattr(self._local, "admit_depth", 0)
        if depth > 1 or token._nested:
            self._local.admit_depth = max(0, depth - 1)
            return
        self._local.admit_depth = 0
        with self._cond:
            self._active[token.kind] = max(0, self._active[token.kind] - 1)
            self._grant_waiters_locked()
            self._cond.notify_all()

    def _retry_after(self) -> int:
        return max(self._retry_after_s, int(self._timeout_s))

    # -- write-path backpressure ---------------------------------------------

    def _backlog_bytes_cached(self) -> int:
        """Memtable+WAL backlog for the watermark check, swept at most
        once per OGT_WRITE_BP_CACHE_MS (bp_cache_ms=0 disables caching —
        tests pin it so a provider change is visible on the very next
        write).  A ≤TTL-stale reading only delays a watermark flip by
        that much; the hysteresis band already tolerates far more."""
        ttl = self._bp_cache_s
        if ttl <= 0:
            return self._component_bytes("memtable")
        now = time.monotonic()
        with self._lock:
            if now - self._bp_backlog_at < ttl:
                return self._bp_backlog_cached
        backlog = self._component_bytes("memtable")
        with self._lock:
            self._bp_backlog_cached = backlog
            self._bp_backlog_at = now
        return backlog

    def write_backpressure(self) -> int | None:
        """Retry-After seconds when the memtable+WAL backlog is over the
        high watermark (429 the write instead of growing RSS), None to
        admit the write.  Hysteresis: once active, sheds until the
        backlog drains below the LOW watermark."""
        if self._budget <= 0:
            return None
        backlog = self._backlog_bytes_cached()
        hi = self._budget * self._hiwat_pct // 100
        lo = self._budget * self._lowat_pct // 100
        flipped_on = flipped_off = False
        with self._lock:
            if self._bp_active:
                if backlog <= lo:
                    self._bp_active = False
                    self._counters["bp_off"] += 1
                    flipped_off = True
            elif backlog >= hi:
                self._bp_active = True
                self._counters["bp_on"] += 1
                flipped_on = True
            active = self._bp_active
            if active:
                self._counters["sheds_backpressure"] += 1
        if flipped_on:
            _fp("governor-backpressure-on")
        if flipped_off:
            _fp("governor-backpressure-off")
        if active:
            self._note_shed("write backpressure")
            _fp("governor-shed")
            return self._retry_after_s
        return None

    # -- background throttling -----------------------------------------------

    def note_io_alarm(self) -> None:
        """iodetector hook: a hung-disk alarm pauses background work for
        OGT_BG_IO_PAUSE_S so interactive traffic and flushes get the
        recovering volume first."""
        with self._cond:
            self._counters["io_alarms"] += 1
            self._io_alarm_until = time.monotonic() + self._io_pause_s
            # no notify: the pause only ever delays background waiters

    def background_allowed(self) -> bool:
        if self._budget <= 0:
            return True
        with self._lock:  # Condition wraps this same lock
            return self._background_allowed_locked()

    def acquire_background(self, name: str, stop=None,
                           timeout_s: float | None = None):
        """Low-priority token for one background tick (compaction,
        downsample, stream).  Blocks while interactive occupancy is high
        or an IO alarm is recent; returns None when `stop` (an Event)
        was set — or `timeout_s` expired — before clearance.  The token
        marks the thread's query class as background (queries the
        service runs classify accordingly) until release().

        Anti-starvation: a pause is bounded by OGT_BG_MAX_PAUSE_S
        (config bg_max_pause_s; 0 = unbounded) — after that the token is
        granted regardless, so sustained interactive saturation can only
        throttle maintenance to a trickle, never stall it outright."""
        if self._budget <= 0:
            return _NoopBgToken()
        now = time.monotonic()
        deadline = now + timeout_s if timeout_s is not None else None
        force_at = (now + self._bg_max_pause_s
                    if self._bg_max_pause_s > 0 else None)
        paused = False
        with self._cond:
            while not self._background_allowed_locked():
                if not paused:
                    paused = True
                    self._counters["bg_pauses"] += 1
                if stop is not None and stop.is_set():
                    return None
                if deadline is not None and time.monotonic() >= deadline:
                    return None
                if force_at is not None and time.monotonic() >= force_at:
                    self._counters["bg_forced"] += 1
                    break
                # bounded wait: io-alarm expiry is time-based, not
                # notified, so the gate re-polls
                self._cond.wait(0.05)
            self._bg_tokens += 1
        return _BgToken(self, name)

    def _background_allowed_locked(self) -> bool:
        if time.monotonic() < self._io_alarm_until:
            return False
        busy = self._active[_INTERACTIVE] + sum(
            1 for e in self._waiting if e[1] == _INTERACTIVE)
        pause_at = max(1, (self._max_concurrent * self._bg_pause_pct + 99) // 100)
        return busy < pause_at

    # -- per-tenant accounting -------------------------------------------------

    def charge_tenant(self, tenant: str, key: str, delta: int = 1) -> None:
        """Attribute background maintenance work (or a shed) to the
        owning tenant (database).  Always counted — cheap — but only
        SURFACED in gauges() while the governor is enabled, so the
        disabled governor keeps /debug/vars byte-identical."""
        if delta == 0:
            return
        with self._lock:
            acct = self._tenants.setdefault(tenant, {})
            acct[key] = acct.get(key, 0) + int(delta)

    def tenant_accounts(self) -> dict:
        with self._lock:
            return {t: dict(a) for t, a in self._tenants.items()}

    # -- shed/kill burst -> diagnostics ---------------------------------------

    def set_diagnostic_hook(self, fn) -> None:
        """fn(reason: str) — called (rate-limited, off-thread) when a
        shed/kill burst is detected.  services/sherlock.py registers its
        dump here; None detaches."""
        self._hook = fn

    def detach_diagnostic_hook(self, fn) -> None:
        if self._hook == fn:
            self._hook = None

    def trigger_diagnostic(self, reason: str) -> None:
        """Fire the diagnostic hook directly, off-thread (sherlock's own
        cooldown still rate-limits the dump).  Non-governor emergencies
        use this — the storage tier's first corruption/quarantine event
        wants thread stacks + the ledger on disk while the evidence is
        fresh."""
        hook = self._hook
        if hook is None:
            return

        def fire():
            try:
                hook(reason)
            except Exception:  # noqa: BLE001 — diagnostics never take
                pass           # down the detecting path
        threading.Thread(target=fire, daemon=True,
                         name="storage-diag").start()

    def _note_shed(self, reason: str) -> None:
        hook = None
        now = time.monotonic()
        with self._lock:
            self._shed_times.append(now)
            while self._shed_times and \
                    self._shed_times[0] < now - self._burst_window_s:
                self._shed_times.popleft()
            if (len(self._shed_times) >= self._burst_n
                    and now - self._last_hook >= self._hook_cooldown_s
                    and self._hook is not None):
                self._last_hook = now
                hook = self._hook
        if hook is not None:
            def fire():
                try:
                    hook(f"governor shed/kill burst ({reason})")
                except Exception:  # noqa: BLE001 — diagnostics never
                    pass           # take down the serving path
            threading.Thread(target=fire, daemon=True,
                             name="governor-diag").start()

    # -- observability --------------------------------------------------------

    def gauges(self) -> dict:
        """Stats-provider section for /debug/vars (ints only; empty when
        disabled so pass-through keeps /debug/vars byte-identical)."""
        if self._budget <= 0:
            return {}
        led = self.ledger()
        with self._lock:
            out = {
                "budget_bytes": self._budget,
                "active_interactive": self._active[_INTERACTIVE],
                "active_background": self._active[_BACKGROUND],
                "queue_depth": len(self._waiting),
                "bg_tokens": self._bg_tokens,
                "bp_active": int(self._bp_active),
                **self._counters,
            }
        for name, nb in led.items():
            out[f"ledger_{name}_bytes"] = nb
        out["ledger_total_bytes"] = sum(led.values())
        with self._lock:
            for tenant, acct in self._tenants.items():
                for key, v in acct.items():
                    out[f"tenant_{tenant}_{key}"] = v
        return out

    def admission_snapshot(self) -> dict:
        """Admission section of /debug/queries (querytracker provider)."""
        now = time.monotonic()
        with self._lock:
            return {
                "enabled": self._budget > 0,
                "max_concurrent": self._max_concurrent,
                "active": dict(self._active),
                "queue": [
                    {"kind": e[1], "waited_ms": int((now - e[2]) * 1000)}
                    for e in self._waiting
                ],
                "reservations": dict(self._res_by_qid),
                "counters": dict(self._counters),
            }

    def describe(self) -> dict:
        """Full status for /debug/ctrl?mod=governor."""
        return {
            "enabled": self.enabled(),
            "config": self.config(),
            "ledger": self.ledger(),
            "admission": self.admission_snapshot(),
            "tenants": self.tenant_accounts(),
        }


class _NoopBgToken:
    __slots__ = ()
    name = ""

    def release(self) -> None:
        pass


class InflightGauge:
    """Thread-safe in-flight byte gauge a worker-pool module registers
    with the ledger (scanpool/encodepool: one instance per module, so an
    accounting fix lands in both instead of drifting across copies)."""

    __slots__ = ("_lock", "_total")

    def __init__(self) -> None:
        self._lock = lockdep.Lock()
        self._total = 0

    def note(self, delta: int) -> None:
        with self._lock:
            self._total += delta

    def total(self) -> int:
        with self._lock:
            return max(0, self._total)


# process-wide governor (the reference's resource allocator singletons)
GOVERNOR = ResourceGovernor()


def _attach_admission_provider() -> None:
    # /debug/queries pairs in-flight queries with the admission state;
    # lazy so utils.governor has no import-time querytracker dependency
    from opengemini_tpu.utils.querytracker import GLOBAL as _TRACKER

    _TRACKER.set_admission_provider(GOVERNOR.admission_snapshot)


_attach_admission_provider()
