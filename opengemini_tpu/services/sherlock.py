"""Sherlock: watermark-triggered self-diagnostics.

Reference: lib/sherlock (sherlock.go:30, Start:109, startDumpLoop:125) —
a continuous CPU/memory/goroutine monitor that auto-dumps pprof profiles
when watermarks are crossed. Python equivalent: RSS and thread-count
watermarks; on crossing, dump every thread's stack plus a tracemalloc
top-allocations report into `<data>/sherlock/`, rate-limited with a
cooldown so a sustained spike produces one dump, not hundreds.
"""

from __future__ import annotations

import os
import sys
import time as _time
import traceback

from opengemini_tpu.services.base import Service, logger


def _rss_mb() -> float:
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / (1 << 20)
    except (OSError, ValueError, IndexError):  # pragma: no cover
        return 0.0


class SherlockService(Service):
    name = "sherlock"

    def __init__(self, engine, interval_s: float = 30.0,
                 mem_mb_watermark: float = 4096.0,
                 thread_watermark: int = 200,
                 cooldown_s: float = 600.0,
                 enable_tracemalloc: bool = False):
        super().__init__(interval_s)
        self.engine = engine
        self.mem_mb_watermark = mem_mb_watermark
        self.thread_watermark = thread_watermark
        self.cooldown_s = cooldown_s
        self._last_dump = float("-inf")  # monotonic() epoch is arbitrary
        self.dumps = 0
        # serializes the cooldown check+commit AND the dump itself: the
        # governor burst hook (diagnose, its own thread) races the
        # service tick (handle), and one window must yield ONE dump
        import threading
        from opengemini_tpu.utils import lockdep

        self._dump_lock = lockdep.Lock()
        if enable_tracemalloc:  # ~2x alloc overhead; opt-in like pprof heap
            import tracemalloc

            tracemalloc.start(10)
    def start(self) -> None:
        # a governor shed/kill burst triggers a dump (already rate-limited
        # on the governor side; our own cooldown still applies): the
        # moment load is being shed is exactly when the operator needs
        # thread stacks + the ledger on disk.  Registered here, not in
        # __init__: the process-global hook must not outlive (or pin) an
        # instance that was never run
        from opengemini_tpu.utils.governor import GOVERNOR

        GOVERNOR.set_diagnostic_hook(self.diagnose)
        super().start()

    def stop(self) -> None:
        from opengemini_tpu.utils.governor import GOVERNOR

        GOVERNOR.detach_diagnostic_hook(self.diagnose)
        super().stop()

    def diagnose(self, reason: str) -> str | None:
        """Force a diagnostic dump for an external trigger (the governor's
        shed/kill burst hook).  Honors the dump cooldown."""
        import threading

        return self._maybe_dump(reason, _rss_mb(), threading.active_count())

    def _maybe_dump(self, trigger: str, rss: float,
                    n_threads: int) -> str | None:
        """Cooldown-gated dump, safe against handle()/diagnose() racing
        from different threads.  The cooldown/counter commit only after
        the dump lands on disk: a failed dump (disk full) must not burn
        the window unretried."""
        with self._dump_lock:
            if _time.monotonic() - self._last_dump < self.cooldown_s:
                return None
            path = self._dump(trigger, rss, n_threads)
            self._last_dump = _time.monotonic()
            self.dumps += 1
            return path

    def handle(self) -> str | None:
        import threading

        rss = _rss_mb()
        n_threads = threading.active_count()
        trigger = None
        if rss > self.mem_mb_watermark:
            trigger = f"rss {rss:.0f}MB > {self.mem_mb_watermark:.0f}MB"
        elif n_threads > self.thread_watermark:
            trigger = f"threads {n_threads} > {self.thread_watermark}"
        if trigger is None:
            return None
        return self._maybe_dump(trigger, rss, n_threads)

    def _dump(self, trigger: str, rss: float, n_threads: int) -> str:
        out_dir = os.path.join(self.engine.root, "sherlock")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"dump-{_time.strftime('%Y%m%dT%H%M%S')}.txt")
        with open(path, "w", encoding="utf-8") as f:
            f.write(f"sherlock dump — trigger: {trigger}\n")
            f.write(f"rss_mb={rss:.1f} threads={n_threads}\n\n")
            try:
                # the governor ledger snapshot: which component holds the
                # memory / what the admission state was at dump time
                from opengemini_tpu.utils.governor import GOVERNOR

                f.write("== governor ==\n")
                import json as _json

                f.write(_json.dumps(GOVERNOR.describe(), indent=1))
                f.write("\n\n")
            except Exception:  # noqa: BLE001 — diagnostics best-effort
                pass
            try:
                # recent slow queries (utils/slowlog): the statements —
                # with stage/span attribution — that were dragging when
                # the watermark tripped
                from opengemini_tpu.utils.slowlog import GLOBAL as _SLOW

                slow = _SLOW.snapshot()
                if slow["records"]:
                    import json as _json

                    f.write("== slow queries ==\n")
                    f.write(_json.dumps(slow, indent=1, default=str))
                    f.write("\n\n")
            except Exception:  # noqa: BLE001 — diagnostics best-effort
                pass
            f.write("== thread stacks ==\n")
            for tid, frame in sys._current_frames().items():
                f.write(f"\n-- thread {tid} --\n")
                f.write("".join(traceback.format_stack(frame)))
            try:
                import tracemalloc

                if tracemalloc.is_tracing():
                    f.write("\n== top allocations ==\n")
                    snap = tracemalloc.take_snapshot()
                    for stat in snap.statistics("lineno")[:25]:
                        f.write(f"{stat}\n")
            except Exception:  # noqa: BLE001
                pass
        from opengemini_tpu.utils.stats import GLOBAL as _STATS

        _STATS.incr("sherlock", "sherlock_dumps")
        logger.warning("sherlock: dumped diagnostics to %s (%s)", path, trigger)
        return path
