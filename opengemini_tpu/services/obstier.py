"""Object-storage tiering service (reference: lib/fileops obs cold tier
behind the hierarchical mover): shard groups older than the threshold
are offloaded wholesale into the object store and hydrate back lazily
when a query touches their time range."""

from __future__ import annotations

import time as _time

from opengemini_tpu.services.base import Service, logger


class ObsTierService(Service):
    name = "obstier"

    def __init__(self, engine, age_ns: int, interval_s: float = 3600.0):
        super().__init__(interval_s)
        self.engine = engine
        self.age_ns = age_ns

    def handle(self, now_ns: int | None = None) -> int:
        if now_ns is None:
            now_ns = _time.time_ns()
        moved = 0
        with self.engine._lock:
            candidates = [
                key for key, sh in self.engine._shards.items()
                if sh.tmax <= now_ns - self.age_ns
            ]
        for db, rp, start in candidates:
            try:
                if self.engine.offload_shard(db, rp, start):
                    moved += 1
                    logger.info("offloaded %s/%s/%d to object store",
                                db, rp, start)
            except Exception:  # noqa: BLE001
                logger.exception("offload of %s/%s/%d failed", db, rp, start)
        return moved
