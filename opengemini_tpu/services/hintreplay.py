"""Hint replay service (reference: the HA writer's hinted-handoff
drainer): periodically delivers queued replica copies to recovered
nodes."""

from __future__ import annotations

from opengemini_tpu.services.base import Service, logger


class HintReplayService(Service):
    name = "hintreplay"

    def __init__(self, router, interval_s: float = 30.0):
        super().__init__(interval_s)
        self.router = router

    def handle(self) -> int:
        # member liveness: quorum-agreed failure view (SHOW CLUSTER status,
        # migration gates, read-primary demotion)
        self.router.exchange_health()
        n = self.router.replay_hints()
        if n:
            logger.info("hinted handoff: delivered %d points", n)
        return n
