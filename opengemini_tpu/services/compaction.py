"""Background compaction (reference: shard-level compact scheduling,
engine/compact.go + immutable LevelCompact compact.go:120): shards whose
immutable file count exceeds the threshold are merged. Compaction also
restores the pre-aggregation fast path: merged, non-overlapping chunks
qualify for block skipping where fragmented ones may not."""

from __future__ import annotations

from opengemini_tpu.services.base import Service, logger


class CompactionService(Service):
    name = "compaction"

    def __init__(self, engine, interval_s: float = 600.0, max_files: int = 4):
        super().__init__(interval_s)
        self.engine = engine
        self.max_files = max_files

    def handle(self) -> int:
        n = 0
        for shard in self.engine.all_shards():
            try:
                if shard.compact(max_files=self.max_files):
                    n += 1
            except Exception:  # noqa: BLE001
                logger.exception("compaction of %s failed", shard.path)
        return n
