"""Background compaction (reference: shard-level compact scheduling,
engine/compact.go + immutable LevelCompact compact.go:120): shards whose
immutable file count exceeds the threshold are merged. Compaction also
restores the pre-aggregation fast path: merged, non-overlapping chunks
qualify for block skipping where fragmented ones may not.

Every merge this service triggers swaps the shard's file set, which
invalidates the affected decoded-column cache generations
(storage/colcache.py — the invalidation lives at the swap sites in
storage/shard.py, so manual compact() calls are covered identically)."""

from __future__ import annotations

import time

from opengemini_tpu.services.base import Service, logger
from opengemini_tpu.utils.stats import GLOBAL as _STATS


class CompactionService(Service):
    name = "compaction"
    # low-priority: ticks acquire a governor background token and pause
    # under interactive load / IO alarms (utils/governor.py)
    governed = True

    def __init__(self, engine, interval_s: float = 600.0, max_files: int = 4):
        super().__init__(interval_s)
        self.engine = engine
        self.max_files = max_files

    def handle(self) -> int:
        n = 0
        fanout = max(2, self.max_files)
        t0 = time.perf_counter_ns()
        for shard in self.engine.all_shards():
            try:
                # leveled: drain every mergeable run this tick (sustained
                # ingest can flush faster than one merge per tick), each
                # merge O(run) not O(shard)
                while shard.compact_level(fanout=fanout):
                    n += 1
                    _STATS.incr("compaction", "leveled_merges")
                # out-of-order: late-arriving data leaves time-overlapping
                # files that leveled runs may never pick up; merge them
                # away so read-side merge amplification stays bounded
                # (reference: immutable/merge_out_of_order.go)
                while (shard.has_time_overlap()
                       and shard.compact_out_of_order(max_files=fanout)):
                    n += 1
                    _STATS.incr("compaction", "out_of_order_merges")
                # mixed levels can still let the count run away: full
                # merge as the independent backstop
                if shard.file_count() > 8 * fanout:
                    if shard.compact(max_files=fanout):
                        n += 1
                        _STATS.incr("compaction", "full_merges")
            except Exception:  # noqa: BLE001
                logger.exception("compaction of %s failed", shard.path)
        if n:
            # merge wall time per tick; together with the tsfwrite
            # compact_encode_ns / compact_write_ns split (/debug/vars)
            # this shows where compaction ticks actually spend their time
            _STATS.incr("compaction", "tick_ns", time.perf_counter_ns() - t0)
        return n
