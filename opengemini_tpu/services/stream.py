"""Stream engine: window aggregation AT INGEST.

Reference: services/stream + app/ts-store/stream (stream.go:45 Engine,
tag_task/time_task): registered stream tasks fold arriving points into
open time windows as they are written; windows flush to the target
measurement once closed (plus an allowed lateness DELAY). Unlike a
continuous query (which re-reads storage), a stream never re-scans —
state lives in memory keyed by (window, group tags).

Supported aggregates: accumulable ones — count/sum/min/max/mean.

Where this sits among the THREE continuous-computation tiers (see the
README "Rules & alerting" section for the full decision table):

  * StreamService (here) — ingest-time fold, zero re-read, accumulable
    InfluxQL aggregates only; in-memory window state, lost on restart
    (late data beyond DELAY is dropped, not re-folded).
  * ContinuousQueryService — scheduled SELECT ... INTO, re-reads
    storage for each closed window; arbitrary InfluxQL but O(window)
    per run and no late-data repair of already-written windows.
  * RuleManager (promql/rules.py) — continuous PromQL recording/alert
    rules over durably-watermarked incremental tile state: O(dirty
    tiles) per tick, late data re-dirties and is re-folded, results
    asserted bit-identical to a from-scratch evaluation.

Durations/deadlines here use time.perf_counter* (OGT040); time.time_ns
appears only for DATA timestamps (window assignment of arriving rows),
where wall-clock is the semantic.
"""

from __future__ import annotations

import threading
from opengemini_tpu.utils import lockdep
import time as _time

from opengemini_tpu.ops import window as winmod
from opengemini_tpu.record import FieldType
from opengemini_tpu.services.base import Service, logger
from opengemini_tpu.sql import ast
from opengemini_tpu.sql.parser import parse_one

ACCUMULABLE = {"count", "sum", "min", "max", "mean"}


class _TaskState:
    def __init__(self, db: str, task, stmt: ast.SelectStatement):
        self.db = db
        self.task = task
        self.stmt = stmt
        self.source = stmt.sources[0].name
        self.every = stmt.group_by_time.every_ns
        self.offset = stmt.group_by_time.offset_ns
        self.group_tags = list(stmt.group_by_tags)
        # (out_name, agg, field)
        self.aggs = []
        for f in stmt.fields:
            e = f.expr
            while isinstance(e, ast.ParenExpr):
                e = e.expr
            if not isinstance(e, ast.Call) or e.name not in ACCUMULABLE:
                raise ValueError(
                    f"stream supports only {sorted(ACCUMULABLE)} aggregates"
                )
            arg = e.args[0] if e.args else None
            if not isinstance(arg, ast.VarRef):
                raise ValueError("stream aggregate needs a field argument")
            self.aggs.append((f.alias or e.name, e.name, arg.name))
        # (window_start, tag tuple) -> {out_name: accum}
        self.windows: dict[tuple, dict] = {}
        # windows ending at/before this were already flushed; late points
        # beyond DELAY are dropped, never re-aggregated (a partial re-open
        # would overwrite the complete aggregate in the target)
        self.watermark_ns = -(2**62)


def validate_stream_select(stmt: ast.SelectStatement) -> None:
    """CREATE STREAM validation: accumulable aggs, single measurement
    source, target != source (a self-feeding stream would loop)."""
    if len(stmt.sources) != 1 or not isinstance(stmt.sources[0], ast.Measurement):
        raise ValueError("stream requires exactly one measurement source")
    src = stmt.sources[0]
    if not src.name:
        raise ValueError("stream source must be a named measurement")
    if src.database or src.rp:
        raise ValueError("stream source must be an unqualified measurement "
                         "in the stream's own database")
    if stmt.condition is not None:
        raise ValueError("stream WHERE conditions are not supported yet")
    if stmt.into.name == src.name:
        raise ValueError("stream target must differ from its source")
    # reuse the task-state constructor for aggregate validation
    _TaskState("", _ValidateTask(), stmt)


class _ValidateTask:
    name = "validate"
    delay_ns = 0
    select_text = ""


class StreamService(Service):
    name = "stream"
    # low-priority: window-flush ticks acquire a governor background
    # token and pause under interactive load / IO alarms
    # (utils/governor.py); ingest-side fold stays on the write path
    governed = True

    def __init__(self, engine, interval_s: float = 5.0):
        super().__init__(interval_s)
        self.engine = engine
        self._lock = lockdep.Lock()
        self._flushing = threading.local()
        self._states: dict[tuple[str, str], _TaskState] = {}
        engine.add_write_observer(self.on_write)

    # -- ingest hook -----------------------------------------------------

    def on_write(self, db: str, rp: str | None, points: list) -> None:
        d = self.engine.databases.get(db)
        if d is None or not d.streams:
            return
        with self.engine._lock:  # consistent snapshot vs CREATE/DROP STREAM
            tasks = list(d.streams.values())
        skip = getattr(self._flushing, "tasks", ())
        with self._lock:
            for task in tasks:
                if (db, task.name) in skip:
                    continue  # this stream's own flush output
                st = self._state(db, task)
                if st is None:
                    continue
                for mst, tags, t, fields in points:
                    if mst != st.source:
                        continue
                    wstart = int(winmod.window_start(t, st.every, st.offset))
                    if wstart + st.every <= st.watermark_ns:
                        continue  # late beyond DELAY: drop (reference behavior)
                    tagd = dict(tags)
                    key_tags = tuple(tagd.get(k, "") for k in st.group_tags)
                    acc = st.windows.setdefault((wstart, key_tags), {})
                    for out_name, agg, field in st.aggs:
                        entry = fields.get(field)
                        if entry is None:
                            continue
                        ftype, val = entry
                        if ftype == FieldType.STRING:
                            continue
                        _accumulate(acc, out_name, agg, float(val))

    def _state(self, db: str, task) -> _TaskState | None:
        key = (db, task.name)
        st = self._states.get(key)
        if st is None or st.task is not task:
            try:
                stmt = parse_one(task.select_text)
                st = _TaskState(db, task, stmt)
                self._states[key] = st
            except Exception:  # noqa: BLE001
                logger.exception("stream %s.%s has a bad select", db, task.name)
                return None
        return st

    # -- flush -----------------------------------------------------------

    def handle(self, now_ns: int | None = None) -> int:
        if now_ns is None:
            now_ns = _time.time_ns()
        flushed = 0
        with self._lock:
            states = list(self._states.values())
        for st in states:
            flushed += self._flush_state(st, now_ns)
        # drop states for dropped streams
        with self._lock:
            for key in list(self._states):
                db, name = key
                d = self.engine.databases.get(db)
                if d is None or name not in d.streams:
                    del self._states[key]
        return flushed

    def _flush_state(self, st: _TaskState, now_ns: int) -> int:
        cutoff = now_ns - st.task.delay_ns
        points = []
        with self._lock:
            st.watermark_ns = max(st.watermark_ns, cutoff)
            done = [
                k for k in st.windows if k[0] + st.every <= cutoff
            ]
            for k in done:
                wstart, key_tags = k
                acc = st.windows.pop(k)
                fields = {}
                for out_name, agg, _field in st.aggs:
                    v = _finalize(acc, out_name, agg)
                    if v is None:
                        continue
                    if agg == "count":
                        fields[out_name] = (FieldType.INT, int(v))
                    else:
                        fields[out_name] = (FieldType.FLOAT, float(v))
                if fields:
                    tags = tuple(
                        (tk, tv) for tk, tv in zip(st.group_tags, key_tags) if tv
                    )
                    points.append((st.stmt.into.name, tags, wstart, fields))
        if not points:
            return 0
        tgt_db = st.stmt.into.database or st.db
        # mark this task while writing so its own flush output can never
        # feed back into it (even via a db-qualified target)
        self._flushing.tasks = getattr(self._flushing, "tasks", set())
        self._flushing.tasks.add((st.db, st.task.name))
        try:
            self.engine.write_rows(tgt_db, points, rp=st.stmt.into.rp or None)
        finally:
            self._flushing.tasks.discard((st.db, st.task.name))
        return len(points)


def _accumulate(acc: dict, out_name: str, agg: str, val: float) -> None:
    cur = acc.get(out_name)
    if agg == "count":
        acc[out_name] = (cur or 0) + 1
    elif agg == "sum":
        acc[out_name] = (cur or 0.0) + val
    elif agg == "min":
        acc[out_name] = val if cur is None else min(cur, val)
    elif agg == "max":
        acc[out_name] = val if cur is None else max(cur, val)
    elif agg == "mean":
        s, c = cur or (0.0, 0)
        acc[out_name] = (s + val, c + 1)


def _finalize(acc: dict, out_name: str, agg: str):
    cur = acc.get(out_name)
    if cur is None:
        return None
    if agg == "mean":
        s, c = cur
        return s / c if c else None
    return cur
