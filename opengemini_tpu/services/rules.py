"""Continuous rule service: governed ticks for the PromQL rule engine.

Reference: the Prometheus rule manager's group scheduler
(rules/manager.go — each group evaluates on its interval), run here as a
governed background service like rollup/continuousquery: under
interactive saturation or an IO alarm the whole tick pauses, and inside
a tick each tenant (database) is CHARGED separately — tick time and
group counts land in the governor's per-tenant accounts, and a tenant
whose groups are skipped because the background gate closed mid-tick
gets a shed mark (the Taurus per-tenant governance argument,
arXiv:2506.20010).

Clustered, the raft META LEADER holds the lease (same gate as
services/continuous.py): with a data router every node's rule
evaluation reads the whole cluster, so N tickers would write N copies
of every recorded sample and fire N copies of every alert.  Without
data routing each node only sees its own writes and must keep ticking.

The actual evaluation — incremental tile maintenance, durable claim/
final-save ordering, the verify leg — lives in promql/rules.py
(RuleManager.tick_group); this module is only the scheduler skin.
"""

from __future__ import annotations

import time as _time

from opengemini_tpu.services.base import Service, logger
from opengemini_tpu.utils.stats import GLOBAL as STATS


class RulesService(Service):
    name = "rules"
    governed = True

    def __init__(self, engine, interval_s: float = 5.0, manager=None,
                 meta_store=None, router=None):
        super().__init__(interval_s)
        self.engine = engine
        # manager may be constructed lazily by the app (OGT_RULES gate);
        # falling back to engine.rules_hook keeps ctrl-declared groups
        # ticking even when the service was built first
        self._manager = manager
        self.meta_store = meta_store
        self.router = router

    @property
    def manager(self):
        return self._manager if self._manager is not None \
            else getattr(self.engine, "rules_hook", None)

    def handle(self, now_ns: int | None = None) -> int:
        mgr = self.manager
        if mgr is None:
            return 0
        if (self.meta_store is not None and self.router is not None
                and not self.meta_store.is_leader()):
            return 0
        if now_ns is None:
            now_ns = _time.time_ns()
        from opengemini_tpu.utils.governor import GOVERNOR

        ran = 0
        for db in mgr.dbs_with_groups():
            if self._stop.is_set():
                break
            if not GOVERNOR.background_allowed():
                # gate closed mid-tick: this tenant's groups are shed
                # this round (retried next tick) and the shed is charged
                # to THEM — rule lag is their signal
                GOVERNOR.charge_tenant(db, "rules_sheds", 1)
                STATS.incr("rules", "tick_sheds")
                continue
            t0 = _time.perf_counter_ns()
            try:
                n = mgr.tick(now_ns, db=db, stop=self._stop)
            except Exception:  # noqa: BLE001 — one tenant's bad group
                logger.exception("rule tick for %s failed", db)
                continue  # never starves the others
            ran += n
            GOVERNOR.charge_tenant(db, "rules_groups", n)
            GOVERNOR.charge_tenant(
                db, "rules_ms", (_time.perf_counter_ns() - t0) // 1_000_000)
        return ran
