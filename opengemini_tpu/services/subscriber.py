"""Subscriptions: forward written points to remote endpoints.

Reference: coordinator/subscriber.go SubscriberManager — written line
protocol is pushed to subscription destinations. Here a write observer
re-serializes points to line protocol and POSTs them to each
subscription's endpoints from a background queue (writes never block on
subscribers; a full queue drops batches like the reference's buffered
writer).

DDL: CREATE SUBSCRIPTION <name> ON <db> DESTINATIONS ALL|ANY '<url>', ...
     DROP SUBSCRIPTION <name> ON <db>; SHOW SUBSCRIPTIONS
ALL posts to every destination; ANY round-robins.
"""

from __future__ import annotations

import queue
import threading
import urllib.parse
import urllib.request

from opengemini_tpu.record import FieldType
from opengemini_tpu.services.base import logger


class Subscription:
    def __init__(self, name: str, mode: str, destinations: list[str]):
        self.name = name
        self.mode = mode  # ALL | ANY
        self.destinations = destinations
        self._rr = 0

    def to_json(self):
        return {"name": self.name, "mode": self.mode,
                "destinations": self.destinations}

    @classmethod
    def from_json(cls, j):
        return cls(j["name"], j["mode"], j["destinations"])


class SubscriberManager:
    def __init__(self, engine, max_queue: int = 1024, timeout_s: float = 2.0):
        self.engine = engine
        self.timeout_s = timeout_s
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="subscriber")
        engine.add_write_observer(self.on_write)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def on_write(self, db: str, rp: str | None, points: list) -> None:
        d = self.engine.databases.get(db)
        subs = getattr(d, "subscriptions", None) if d else None
        if not subs:
            return
        try:
            self._q.put_nowait((db, rp, points))
        except queue.Full:
            logger.warning("subscription queue full; dropping batch for %s", db)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                db, rp, points = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                d = self.engine.databases.get(db)
                subs = list(getattr(d, "subscriptions", {}).values()) if d else []
                if not subs:
                    continue
                body = points_to_lines(points).encode("utf-8")
                for sub in subs:
                    dests = (
                        sub.destinations
                        if sub.mode == "ALL"
                        else [sub.destinations[sub._rr % len(sub.destinations)]]
                    )
                    sub._rr += 1
                    for dest in dests:
                        self._post(dest, db, rp, body)
            except Exception:  # noqa: BLE001 — the worker must never die
                logger.exception("subscription forwarding failed")

    def _post(self, dest: str, db: str, rp: str | None, body: bytes) -> None:
        try:
            url = dest.rstrip("/") + "/write?db=" + urllib.parse.quote(db)
            if rp:
                url += "&rp=" + urllib.parse.quote(rp)
            req = urllib.request.Request(url, data=body, method="POST")
            urllib.request.urlopen(req, timeout=self.timeout_s).read()
        except (OSError, ValueError):
            logger.warning("subscription post to %s failed", dest)


def points_to_lines(points: list) -> str:
    """Structured points -> line protocol text (escaping-safe)."""
    from opengemini_tpu.ingest.line_protocol import _esc_key

    lines = []
    for mst, tags, t, fields in points:
        tag_str = "".join(
            f",{_esc_key(k)}={_esc_key(v)}" for k, v in tags
        )
        parts = []
        for name, (ftype, v) in fields.items():
            key = _esc_key(name)
            if ftype == FieldType.BOOL:
                parts.append(f"{key}={'true' if v else 'false'}")
            elif ftype == FieldType.INT:
                parts.append(f"{key}={int(v)}i")
            elif ftype == FieldType.FLOAT:
                parts.append(f"{key}={float(v)!r}")
            else:
                s = str(v).replace("\\", "\\\\").replace('"', '\\"')
                parts.append(f'{key}="{s}"')
        if parts:
            lines.append(f"{_esc_key(mst)}{tag_str} {','.join(parts)} {t}")
    return "\n".join(lines)
