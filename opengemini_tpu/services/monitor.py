"""Self-monitoring service: statistics pushed into the `_internal`
database (reference: lib/statisticsPusher pushing to file/http/_internal,
plus the ts-monitor agent)."""

from __future__ import annotations

import time as _time

from opengemini_tpu.record import FieldType
from opengemini_tpu.services.base import Service
from opengemini_tpu.utils.stats import GLOBAL as STATS

INTERNAL_DB = "_internal"


class MonitorService(Service):
    name = "monitor"

    def __init__(self, engine, interval_s: float = 10.0, hostname: str = "localhost"):
        super().__init__(interval_s)
        self.engine = engine
        self.hostname = hostname

    def handle(self) -> None:
        snap = STATS.snapshot()
        if not snap:
            return
        if INTERNAL_DB not in self.engine.databases:
            self.engine.create_database(INTERNAL_DB)
        now = _time.time_ns()
        points = []
        for module, vals in snap.items():
            fields = {k: (FieldType.INT, int(v)) for k, v in vals.items()}
            if fields:
                points.append(
                    (module, (("hostname", self.hostname),), now, fields)
                )
        if points:
            self.engine.write_rows(INTERNAL_DB, points)
