"""Self-monitoring service: statistics pushed into the `_internal`
database plus ogt_*-named self-writes into `_monitor` (reference:
lib/statisticsPusher pushing to file/http/_internal, and the ts-monitor
agent that makes the store queryable about itself).

Each tick:
  * `_internal`: one point per registry module with every counter as an
    INT field (the original expvar-shaped push).
  * `_monitor`: the /metrics view written back as line-protocol rows —
    measurement `ogt` carrying every scalar gauge under its exported
    `ogt_<module>_<key>` name, and one measurement per histogram family
    (`ogt_<name>`) with p50/p99/count/sum fields, labels as tags.
    Dashboards query the DB about itself with the same names a real
    Prometheus scrapes from GET /metrics.
"""

from __future__ import annotations

import time as _time

from opengemini_tpu.record import FieldType
from opengemini_tpu.services.base import Service
from opengemini_tpu.utils.stats import (GLOBAL as STATS, _RENAMES, _san,
                                        histograms_snapshot,
                                        snapshot_percentile)

INTERNAL_DB = "_internal"
MONITOR_DB = "_monitor"


class MonitorService(Service):
    name = "monitor"

    def __init__(self, engine, interval_s: float = 10.0, hostname: str = "localhost"):
        super().__init__(interval_s)
        self.engine = engine
        self.hostname = hostname

    def handle(self) -> None:
        snap = STATS.snapshot()
        if not snap:
            return
        now = _time.time_ns()
        self._push_internal(snap, now)
        self._push_monitor(snap, now)

    def _push_internal(self, snap: dict, now: int) -> None:
        if INTERNAL_DB not in self.engine.databases:
            self.engine.create_database(INTERNAL_DB)
        points = []
        for module, vals in snap.items():
            fields = {k: (FieldType.INT, int(v)) for k, v in vals.items()}
            if fields:
                points.append(
                    (module, (("hostname", self.hostname),), now, fields)
                )
        if points:
            self.engine.write_rows(INTERNAL_DB, points)

    def _push_monitor(self, snap: dict, now: int) -> None:
        if MONITOR_DB not in self.engine.databases:
            self.engine.create_database(MONITOR_DB)
        host_tag = (("hostname", self.hostname),)
        gauges = {}
        for module, vals in snap.items():
            for key, v in vals.items():
                if not isinstance(v, (int, float)):
                    continue
                renamed = _RENAMES.get((module, key))
                name = renamed[0] if renamed else _san(
                    f"ogt_{module}_{key}")
                gauges[name] = (FieldType.INT, int(v))
        points = []
        if gauges:
            points.append(("ogt", host_tag, now, gauges))
        for name, labels, hsnap in histograms_snapshot():
            if not hsnap["count"]:
                continue
            tags = host_tag + tuple(
                (str(k), str(v)) for k, v in labels)
            # p50/p99 in the family's own unit (seconds for latency
            # families, raw bytes for the devobs transfer sizes); the
            # sum field is named by unit so dashboards can't misread a
            # byte total as seconds
            seconds = hsnap.get("unit", "seconds") == "seconds"
            fields = {
                "p50": (FieldType.FLOAT, snapshot_percentile(hsnap, 50)),
                "p99": (FieldType.FLOAT, snapshot_percentile(hsnap, 99)),
                "count": (FieldType.INT, hsnap["count"]),
            }
            if seconds:
                fields["sum_seconds"] = (FieldType.FLOAT,
                                         hsnap["sum_ns"] / 1e9)
            else:
                fields["sum_bytes"] = (FieldType.INT, hsnap["sum_ns"])
            points.append((_san(f"ogt_{name}"), tags, now, fields))
        if points:
            self.engine.write_rows(MONITOR_DB, points)
