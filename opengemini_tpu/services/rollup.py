"""Rollup maintenance service: governed background folding.

Reference analogue: the continuous-query/downsample schedulers, but the
work unit is the incremental fold of dirty/new windows
(storage/rollup.py).  Ticks ride `Service._governed_tick` (PR 5): under
interactive saturation or an IO alarm the whole tick pauses like
compaction/downsample.  Inside a tick each tenant (database) folds
separately and is CHARGED separately — fold time and window counts land
in the governor's per-tenant accounts, and a tenant whose fold is
skipped because the background gate closed mid-tick gets a shed mark —
so one tenant's rollup churn is visible (and attributable) instead of
disappearing into a global counter (the Taurus per-tenant governance
argument, arXiv:2506.20010)."""

from __future__ import annotations

import time as _time

from opengemini_tpu.services.base import Service, logger
from opengemini_tpu.utils.stats import GLOBAL as STATS


class RollupService(Service):
    name = "rollup"
    governed = True

    def __init__(self, engine, interval_s: float = 5.0):
        super().__init__(interval_s)
        self.engine = engine

    def handle(self, now_ns: int | None = None) -> int:
        mgr = self.engine.rollup_mgr
        if mgr is None:
            return 0
        from opengemini_tpu.utils.governor import GOVERNOR

        folded = 0
        for db in mgr.dbs_with_specs():
            if self._stop.is_set():
                break
            if not GOVERNOR.background_allowed():
                # the gate closed mid-tick: remaining tenants are shed
                # this round (retried next tick) and the shed is charged
                # to THEM — their maintenance lag is their signal
                GOVERNOR.charge_tenant(db, "rollup_sheds", 1)
                STATS.incr("rollup", "tick_sheds")
                continue
            t0 = _time.perf_counter_ns()
            try:
                n = mgr.maintain_db(db, now_ns)
            except Exception:  # noqa: BLE001 — one tenant's bad fold
                logger.exception("rollup maintenance for %s failed", db)
                continue  # never starves the others
            folded += n
            GOVERNOR.charge_tenant(db, "rollup_windows", n)
            GOVERNOR.charge_tenant(
                db, "rollup_ms", (_time.perf_counter_ns() - t0) // 1_000_000)
        return folded
