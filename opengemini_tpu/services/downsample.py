"""Downsample service (reference: services/downsample/service.go:29-56):
periodically finds shards past their policy age and rewrites them at the
coarser resolution via the TPU batch path (storage/downsample.py)."""

from __future__ import annotations

from opengemini_tpu.services.base import Service


class DownsampleService(Service):
    name = "downsample"
    # low-priority: ticks acquire a governor background token and pause
    # under interactive load / IO alarms (utils/governor.py)
    governed = True

    def __init__(self, engine, interval_s: float = 3600.0):
        super().__init__(interval_s)
        self.engine = engine

    def handle(self) -> None:
        self.engine.run_downsample()
