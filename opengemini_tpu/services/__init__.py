"""Background services (reference: services/ — retention, downsample,
continuousquery, stream, ... driven per-node from services/base.go)."""

from opengemini_tpu.services.base import Service  # noqa: F401
