"""Continuous query scheduler.

Reference: services/continuousquery/service.go:53-130 — on each tick, run
every CQ whose next window has closed, executing its SELECT ... INTO over
the newly-closed GROUP BY time windows. The reference coordinates CQ
leases across sql nodes via meta; here the raft META LEADER is the lease
(handle() runs CQs only on the leader when clustered — see the
meta_store gate below; tested in
test_cluster_data.py::test_cq_runs_only_on_leader).

Where this sits among the THREE continuous-computation tiers (see the
README "Rules & alerting" section for the full decision table):

  * StreamService — ingest-time fold of accumulable InfluxQL aggregates
    into in-memory windows; never re-reads storage, can't repair late
    data past its DELAY.
  * ContinuousQueryService (here) — scheduled SELECT ... INTO that
    RE-READS storage for closed windows: arbitrary InfluxQL (joins,
    non-accumulable aggregates), at O(window) re-scan cost per run.
  * RuleManager (promql/rules.py) — continuous PromQL rules maintained
    incrementally over dirty-marked tile partials with a durable
    watermark: O(new tiles) per tick, late data re-dirties, answers
    asserted bit-identical to a from-scratch evaluation.

Durations/deadlines here use time.perf_counter* (OGT040); time.time_ns
appears only as the data-time `now` that window-close decisions are
made against, where wall-clock is the semantic.
"""

from __future__ import annotations

import logging
import time as _time

from opengemini_tpu.ops import window as winmod
from opengemini_tpu.services.base import Service
from opengemini_tpu.sql import ast
from opengemini_tpu.sql.parser import parse_one

logger = logging.getLogger("opengemini_tpu.services.cq")


class ContinuousQueryService(Service):
    name = "continuousquery"
    # a CQ is a real query (scan + aggregate + write-back), not a
    # watchdog: pause it while interactive occupancy is high, like
    # compaction/downsample
    governed = True

    def __init__(self, engine, executor, interval_s: float = 10.0,
                 meta_store=None):
        super().__init__(interval_s)
        self.engine = engine
        self.executor = executor
        # data-routed cluster: only the meta leader runs CQs — with a
        # router every node's CQ reads the WHOLE cluster, so N runners
        # would write N copies of every result row. Without data routing
        # each node aggregates only its own local writes, so every node
        # must keep running its CQs.
        self.meta_store = meta_store

    def handle(self, now_ns: int | None = None) -> int:
        if (self.meta_store is not None
                and getattr(self.executor, "router", None) is not None
                and not self.meta_store.is_leader()):
            return 0
        if now_ns is None:
            now_ns = _time.time_ns()
        ran = 0
        dirty = False
        for db_name, db in list(self.engine.databases.items()):
            for cq in list(db.continuous_queries.values()):
                try:
                    if self._run_cq(db_name, cq, now_ns):
                        ran += 1
                        dirty = True
                except Exception:  # noqa: BLE001 — one bad CQ never starves the rest
                    logger.exception("CQ %s.%s failed", db_name, cq.name)
        if dirty:
            self.engine.save_cq_state()
        return ran

    def _run_cq(self, db: str, cq, now_ns: int) -> bool:
        stmt = parse_one(cq.select_text)
        if not isinstance(stmt, ast.SelectStatement) or stmt.group_by_time is None:
            return False
        every = stmt.group_by_time.every_ns
        offset = stmt.group_by_time.offset_ns
        run_every = cq.resample_every_ns or every
        # windows that have fully closed since the last run; influx defaults
        # FOR to max(EVERY, interval) so EVERY > interval misses no windows
        end = int(winmod.window_start(now_ns, every, offset))
        lookback = cq.resample_for_ns or max(run_every, every)
        start = max(
            end - lookback,
            int(winmod.window_start(cq.last_run_ns, every, offset)) if cq.last_run_ns else end - lookback,
        )
        if end <= start or (cq.last_run_ns and now_ns - cq.last_run_ns < run_every):
            return False
        bounded = _with_time_bounds(stmt, start, end)
        # a CQ takes a (background-priority) admission slot and a
        # tracker qid like any client query: without these it would
        # bypass the governor's occupancy accounting AND the
        # reservation overdraft-kill (qid=None skips it), letting a
        # heavy CQ blow the memory ceiling while client traffic is
        # being shed.  AdmissionRejected skips the run; last_run_ns
        # stays put so the window is retried next tick.
        from opengemini_tpu.utils.governor import GOVERNOR, AdmissionRejected
        from opengemini_tpu.utils.querytracker import GLOBAL as TRACKER

        try:
            token = GOVERNOR.admit(kind="background")
        except AdmissionRejected:
            return False
        qid = None
        try:
            if GOVERNOR.enabled():
                # tracker registration only when governed: pass-through
                # must keep /debug/queries (and every other observable)
                # bit-identical to the pre-governor tree
                qid = TRACKER.register(cq.select_text, db)
            self.executor.execute_statement(bounded, db, now_ns)
        finally:
            if qid is not None:
                TRACKER.unregister(qid)
            token.release()
        cq.last_run_ns = now_ns
        return True


def _with_time_bounds(stmt: ast.SelectStatement, start_ns: int, end_ns: int):
    """AND the CQ's WHERE with [start, end) — the window injection the
    reference does when materializing CQ runs."""
    bound = ast.BinaryExpr(
        "AND",
        ast.BinaryExpr(">=", ast.VarRef("time"), ast.IntegerLiteral(start_ns)),
        ast.BinaryExpr("<", ast.VarRef("time"), ast.IntegerLiteral(end_ns)),
    )
    cond = bound if stmt.condition is None else ast.BinaryExpr("AND", stmt.condition, bound)
    import copy

    out = copy.copy(stmt)
    out.condition = cond
    return out
