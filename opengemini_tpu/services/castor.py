"""Anomaly detection (castor analogue).

Reference: services/castor + python/ts-udf — openGemini ships anomaly
detection as a Python sidecar driven through UDAF calls. This framework IS
Python on the query side, so the algorithms run in-process behind the
`detect(field, 'algorithm'[, threshold])` SQL function (host multi-row
path) — no sidecar protocol needed; heavier ML detectors can still hook
in here later.

Algorithms (the reference agent's classic detectors):
  mad    — robust z-score via median absolute deviation (default thr 3.0)
  sigma  — z-score against mean/stddev (default thr 3.0)
  iqr    — Tukey fences, thr x IQR beyond the quartiles (default thr 1.5)
"""

from __future__ import annotations

import numpy as np

ALGORITHMS = ("mad", "sigma", "iqr")

# user detectors loaded from [services] castor-udf-dir: name -> callable
# (reference: python/ts-udf pluggable algorithm scripts)
_UDFS: dict[str, object] = {}


def load_udfs(directory: str) -> list[str]:
    """Load every `<name>.py` in `directory` as a detector UDF. Each file
    must define `detect(values: np.ndarray, threshold: float|None)
    -> np.ndarray[bool]`. A broken file is skipped with a log line, never
    taking the server down. Returns the loaded names."""
    import logging
    import os

    log = logging.getLogger("opengemini_tpu.castor")
    loaded = []
    _UDFS.clear()  # idempotent reload: stale detectors must not linger
    if not os.path.isdir(directory):
        return loaded
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        name = fname[:-3].lower()
        if name in ALGORITHMS:
            log.warning("castor udf %r shadows a built-in; skipped", name)
            continue
        path = os.path.join(directory, fname)
        ns: dict = {"np": np, "numpy": np}
        try:
            with open(path, encoding="utf-8") as f:
                exec(compile(f.read(), path, "exec"), ns)  # noqa: S102
            fn = ns.get("detect")
            if not callable(fn):
                raise TypeError("no detect(values, threshold) function")
        except Exception:  # noqa: BLE001
            log.exception("castor udf %s failed to load", path)
            continue
        _UDFS[name] = fn
        loaded.append(name)
    return loaded


def detect(values: np.ndarray, algorithm: str, threshold: float | None = None) -> np.ndarray:
    """Boolean anomaly mask over a value series."""
    algorithm = algorithm.lower()
    n = len(values)
    if n == 0:
        return np.zeros(0, dtype=bool)
    v = values.astype(np.float64)
    if algorithm == "mad":
        thr = 3.0 if threshold is None else threshold
        med = np.median(v)
        mad = np.median(np.abs(v - med))
        if mad == 0:
            return v != med
        score = np.abs(v - med) / (1.4826 * mad)
        return score > thr
    if algorithm == "sigma":
        thr = 3.0 if threshold is None else threshold
        std = v.std()
        if std == 0:
            return np.zeros(n, dtype=bool)
        return np.abs(v - v.mean()) / std > thr
    if algorithm == "iqr":
        thr = 1.5 if threshold is None else threshold
        q1, q3 = np.percentile(v, [25, 75])
        iqr = q3 - q1
        return (v < q1 - thr * iqr) | (v > q3 + thr * iqr)
    udf = _UDFS.get(algorithm)
    if udf is not None:
        try:
            mask = np.asarray(udf(v, threshold))
        except ValueError:
            raise
        except Exception as e:  # noqa: BLE001 — udf bugs become clean errors
            raise ValueError(f"udf {algorithm!r} failed: {e}") from e
        if mask.shape != (n,):
            raise ValueError(
                f"udf {algorithm!r} returned shape {mask.shape}, "
                f"expected ({n},)"
            )
        return mask.astype(bool)
    names = list(ALGORITHMS) + sorted(_UDFS)
    raise ValueError(f"unknown detect algorithm {algorithm!r} "
                     f"(supported: {', '.join(names)})")
