"""Anomaly detection (castor analogue).

Reference: services/castor + python/ts-udf — openGemini ships anomaly
detection as a Python sidecar driven through UDAF calls. This framework IS
Python on the query side, so the algorithms run in-process behind the
`detect(field, 'algorithm'[, threshold])` SQL function (host multi-row
path) — no sidecar protocol needed; heavier ML detectors can still hook
in here later.

Algorithms (the reference agent's classic detectors):
  mad    — robust z-score via median absolute deviation (default thr 3.0)
  sigma  — z-score against mean/stddev (default thr 3.0)
  iqr    — Tukey fences, thr x IQR beyond the quartiles (default thr 1.5)
"""

from __future__ import annotations

import numpy as np

ALGORITHMS = ("mad", "sigma", "iqr")

# user detectors loaded from [services] castor-udf-dir: name -> callable
# (reference: python/ts-udf pluggable algorithm scripts)
_UDFS: dict[str, object] = {}


def load_udfs(directory: str) -> list[str]:
    """Load every `<name>.py` in `directory` as a detector UDF. Each file
    must define `detect(values: np.ndarray, threshold: float|None)
    -> np.ndarray[bool]`. A broken file is skipped with a log line, never
    taking the server down. Returns the loaded names."""
    import logging
    import os

    log = logging.getLogger("opengemini_tpu.castor")
    loaded = []
    _UDFS.clear()  # idempotent reload: stale detectors must not linger
    if not os.path.isdir(directory):
        return loaded
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        name = fname[:-3].lower()
        if name in ALGORITHMS:
            log.warning("castor udf %r shadows a built-in; skipped", name)
            continue
        path = os.path.join(directory, fname)
        ns: dict = {"np": np, "numpy": np}
        try:
            with open(path, encoding="utf-8") as f:
                exec(compile(f.read(), path, "exec"), ns)  # noqa: S102
            fn = ns.get("detect")
            if not callable(fn):
                raise TypeError("no detect(values, threshold) function")
        except Exception:  # noqa: BLE001
            log.exception("castor udf %s failed to load", path)
            continue
        _UDFS[name] = fn
        loaded.append(name)
    return loaded


def _baseline(algorithm: str, v: np.ndarray,
              threshold: float | None) -> tuple[float, dict]:
    """(threshold, fitted params) for a builtin algorithm — the ONE place
    the formulas and default thresholds live (stateless detect, fit, and
    fitted detect all share it)."""
    if algorithm == "mad":
        thr = 3.0 if threshold is None else float(threshold)
        med = float(np.median(v))
        return thr, {"median": med,
                     "mad": float(np.median(np.abs(v - med)))}
    if algorithm == "sigma":
        thr = 3.0 if threshold is None else float(threshold)
        return thr, {"mean": float(v.mean()), "std": float(v.std())}
    if algorithm == "iqr":
        thr = 1.5 if threshold is None else float(threshold)
        q1, q3 = np.percentile(v, [25, 75])
        return thr, {"q1": float(q1), "q3": float(q3)}
    raise ValueError(f"unknown algorithm {algorithm!r}")


def _score(algorithm: str, params: dict, thr: float,
           v: np.ndarray) -> np.ndarray:
    if algorithm == "mad":
        med, mad = params["median"], params["mad"]
        if mad == 0:
            return v != med
        return np.abs(v - med) / (1.4826 * mad) > thr
    if algorithm == "sigma":
        if params["std"] == 0:
            return np.zeros(len(v), dtype=bool)
        return np.abs(v - params["mean"]) / params["std"] > thr
    if algorithm == "iqr":
        iqr = params["q3"] - params["q1"]
        return (v < params["q1"] - thr * iqr) | (v > params["q3"] + thr * iqr)
    raise ValueError(f"unknown algorithm {algorithm!r}")


def detect(values: np.ndarray, algorithm: str, threshold: float | None = None) -> np.ndarray:
    """Boolean anomaly mask over a value series (stateless: the baseline
    is fitted on the same window it scores)."""
    algorithm = algorithm.lower()
    n = len(values)
    if n == 0:
        return np.zeros(0, dtype=bool)
    v = values.astype(np.float64)
    if algorithm in ALGORITHMS:
        thr, params = _baseline(algorithm, v, threshold)
        return _score(algorithm, params, thr, v)
    udf = _UDFS.get(algorithm)
    if udf is not None:
        try:
            mask = np.asarray(udf(v, threshold))
        except ValueError:
            raise
        except Exception as e:  # noqa: BLE001 — udf bugs become clean errors
            raise ValueError(f"udf {algorithm!r} failed: {e}") from e
        if mask.shape != (n,):
            raise ValueError(
                f"udf {algorithm!r} returned shape {mask.shape}, "
                f"expected ({n},)"
            )
        return mask.astype(bool)
    names = list(ALGORITHMS) + sorted(_UDFS)
    raise ValueError(f"unknown detect algorithm {algorithm!r} "
                     f"(supported: {', '.join(names)})")


# -- fitted models (reference: the castor fit pipeline + model lifecycle,
# services/castor/service.go:32-143, python/ts-udf/server) ------------------

import json as _json
import os as _os
import threading as _threading
import time as _time


def fit(algorithm: str, values: np.ndarray, threshold: float | None = None) -> dict:
    """Train a detector on a value series: learn the baseline statistics
    the algorithm needs so later detect() calls score NEW data against
    the TRAINING window (the point of fit vs stateless detection)."""
    algorithm = algorithm.lower()
    v = np.asarray(values, dtype=np.float64)
    v = v[np.isfinite(v)]
    if len(v) < 8:
        raise ValueError(f"model fit needs >= 8 finite points, got {len(v)}")
    if algorithm not in ALGORITHMS:
        raise ValueError(
            f"unknown fit algorithm {algorithm!r} "
            f"(supported: {', '.join(ALGORITHMS)})")
    thr, params = _baseline(algorithm, v, threshold)
    return {
        "algorithm": algorithm,
        "threshold": thr,
        "params": params,
        "trained_rows": int(len(v)),
        "fitted_at": int(_time.time()),
    }


def detect_fitted(model: dict, values: np.ndarray,
                  threshold: float | None = None) -> np.ndarray:
    """Score values against a fitted model's training baseline. An
    explicit query-time threshold overrides the persisted one."""
    v = np.asarray(values, dtype=np.float64)
    thr = float(model["threshold"]) if threshold is None else float(threshold)
    return _score(model["algorithm"], model["params"], thr, v)


class ModelStore:
    """Persisted fitted models: one JSON artifact per model under
    <engine-root>/models/ (atomic replace on save, reloaded on open —
    the reference keeps model files under the castor sidecar's model
    dirs with version counters)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = _threading.Lock()
        _os.makedirs(path, exist_ok=True)

    def _file(self, name: str) -> str:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"bad model name {name!r}")
        return _os.path.join(self.path, name + ".json")

    def save(self, name: str, doc: dict) -> None:
        with self._lock:
            tmp = self._file(name) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                _json.dump(doc, f)
                f.flush()
                _os.fsync(f.fileno())
            _os.replace(tmp, self._file(name))

    def get(self, name: str) -> dict | None:
        try:
            with open(self._file(name), encoding="utf-8") as f:
                return _json.load(f)
        except (OSError, ValueError):
            return None

    def names(self) -> list[str]:
        return sorted(
            f[:-5] for f in _os.listdir(self.path) if f.endswith(".json"))

    def drop(self, name: str) -> bool:
        with self._lock:
            try:
                _os.remove(self._file(name))
                return True
            except OSError:
                return False
