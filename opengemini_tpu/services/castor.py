"""Anomaly detection (castor analogue).

Reference: services/castor + python/ts-udf — openGemini ships anomaly
detection as a Python sidecar driven through UDAF calls. This framework IS
Python on the query side, so the algorithms run in-process behind the
`detect(field, 'algorithm'[, threshold])` SQL function (host multi-row
path) — no sidecar protocol needed; heavier ML detectors can still hook
in here later.

Algorithms (the reference agent's classic detectors):
  mad    — robust z-score via median absolute deviation (default thr 3.0)
  sigma  — z-score against mean/stddev (default thr 3.0)
  iqr    — Tukey fences, thr x IQR beyond the quartiles (default thr 1.5)
"""

from __future__ import annotations

import numpy as np

ALGORITHMS = ("mad", "sigma", "iqr")


def detect(values: np.ndarray, algorithm: str, threshold: float | None = None) -> np.ndarray:
    """Boolean anomaly mask over a value series."""
    algorithm = algorithm.lower()
    n = len(values)
    if n == 0:
        return np.zeros(0, dtype=bool)
    v = values.astype(np.float64)
    if algorithm == "mad":
        thr = 3.0 if threshold is None else threshold
        med = np.median(v)
        mad = np.median(np.abs(v - med))
        if mad == 0:
            return v != med
        score = np.abs(v - med) / (1.4826 * mad)
        return score > thr
    if algorithm == "sigma":
        thr = 3.0 if threshold is None else threshold
        std = v.std()
        if std == 0:
            return np.zeros(n, dtype=bool)
        return np.abs(v - v.mean()) / std > thr
    if algorithm == "iqr":
        thr = 1.5 if threshold is None else threshold
        q1, q3 = np.percentile(v, [25, 75])
        iqr = q3 - q1
        return (v < q1 - thr * iqr) | (v > q3 + thr * iqr)
    raise ValueError(f"unknown detect algorithm {algorithm!r} "
                     f"(supported: {', '.join(ALGORITHMS)})")
