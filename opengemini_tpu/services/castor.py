"""Anomaly detection (castor analogue).

Reference: services/castor + python/ts-udf — openGemini ships anomaly
detection as a Python sidecar driven through UDAF calls. This framework IS
Python on the query side, so the algorithms run in-process behind the
`detect(field, 'algorithm'[, threshold])` SQL function (host multi-row
path) — no sidecar protocol needed; heavier ML detectors can still hook
in here later.

Algorithms (the reference agent's classic detectors):
  mad    — robust z-score via median absolute deviation (default thr 3.0)
  sigma  — z-score against mean/stddev (default thr 3.0)
  iqr    — Tukey fences, thr x IQR beyond the quartiles (default thr 1.5)
"""

from __future__ import annotations

import numpy as np

ALGORITHMS = ("mad", "sigma", "iqr", "stl")

# user detectors loaded from [services] castor-udf-dir: name -> callable
# (reference: python/ts-udf pluggable algorithm scripts)
_UDFS: dict[str, object] = {}


def load_udfs(directory: str) -> list[str]:
    """Load every `<name>.py` in `directory` as a detector UDF. Each file
    must define `detect(values: np.ndarray, threshold: float|None)
    -> np.ndarray[bool]`. A broken file is skipped with a log line, never
    taking the server down. Returns the loaded names."""
    import logging
    import os

    log = logging.getLogger("opengemini_tpu.castor")
    loaded = []
    _UDFS.clear()  # idempotent reload: stale detectors must not linger
    if not os.path.isdir(directory):
        return loaded
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        name = fname[:-3].lower()
        if name in ALGORITHMS:
            log.warning("castor udf %r shadows a built-in; skipped", name)
            continue
        path = os.path.join(directory, fname)
        ns: dict = {"np": np, "numpy": np}
        try:
            with open(path, encoding="utf-8") as f:
                exec(compile(f.read(), path, "exec"), ns)  # noqa: S102
            fn = ns.get("detect")
            if not callable(fn):
                raise TypeError("no detect(values, threshold) function")
        except Exception:  # noqa: BLE001
            log.exception("castor udf %s failed to load", path)
            continue
        _UDFS[name] = fn
        loaded.append(name)
    return loaded


# -- robust seasonal decomposition (original; fills the role of the
# reference's STL-based sudden-increase pipeline,
# python/ts-udf/server/udf/sudden_increase_STL3.py, without statsmodels) --


def _running_median(v: np.ndarray, window: int) -> np.ndarray:
    """Odd-window running median with edge replication — the robust
    trend extractor (outliers cannot drag a median trend)."""
    half = window // 2
    padded = np.concatenate([np.full(half, v[0]), v, np.full(half, v[-1])])
    shape = (len(v), window)
    strides = (padded.strides[0], padded.strides[0])
    mat = np.lib.stride_tricks.as_strided(padded, shape, strides)
    return np.median(mat, axis=1)


def robust_decompose(v: np.ndarray, period: int = 3):
    """(trend, seasonal, resid): running-median trend over ~2 periods,
    per-phase median seasonal profile (centered), remainder residual."""
    n = len(v)
    period = max(int(period), 2)
    win = min(2 * period + 1, n if n % 2 else n - 1)
    win = max(win, 3)
    trend = _running_median(v, win)
    detr = v - trend
    phases = np.arange(n) % period
    seasonal_prof = np.zeros(period)
    for p in range(period):
        sel = detr[phases == p]
        if len(sel):
            seasonal_prof[p] = np.median(sel)
    seasonal_prof -= seasonal_prof.mean()  # centered, like STL
    seasonal = seasonal_prof[phases]
    resid = v - trend - seasonal
    return trend, seasonal, resid, seasonal_prof


# sudden-increase defaults (reference hyper_params,
# sudden_increase_STL3.py:30-37)
_STL_DEFAULTS = {
    "period": 3,
    "std_window": 20,
    "sensitivity": 3.0,
    "resid_weight": 2.0,
    "trend_weight": 3.0,
    "all_weight": 3.0,
    "top_percent": 0.5,
}


def _mean_std_indices(seq: np.ndarray, weight: float) -> np.ndarray:
    """Indices beyond mean ± weight*std, both directions."""
    m, s = float(seq.mean()), float(seq.std())
    return np.flatnonzero(np.abs(seq - m) > weight * s)


def stl_sudden_change(v: np.ndarray, params: dict | None = None
                      ) -> np.ndarray:
    """Sudden increase/decrease detection via robust decomposition:
    candidates = outliers of the residual, the trend, and the raw values
    of the scored half against the reference half; each candidate then
    scores against a local sliding window (flagged points excluded, std
    floored at 5% of the local mean) and only the top-scoring fraction
    survives. Same pipeline shape as the reference's STL3 detector;
    the decomposition is the original numpy one above."""
    p = dict(_STL_DEFAULTS)
    if params:
        p.update(params)
    n = len(v)
    if n < 8:
        return np.zeros(n, dtype=bool)
    start = n // 2 if n > 60 else max(n - 30, 0)
    trend, _seasonal, resid, _prof = robust_decompose(v, int(p["period"]))
    cand = set(_mean_std_indices(resid, p["resid_weight"]).tolist())
    cand |= set(_mean_std_indices(trend, p["trend_weight"]).tolist())
    ref = v[:start] if start else v
    m, s = float(ref.mean()), float(ref.std())
    tail = np.flatnonzero(np.abs(v[start:] - m) > p["all_weight"] * s)
    cand |= set((tail + start).tolist())
    if not cand:
        return np.zeros(n, dtype=bool)
    cand_arr = np.array(sorted(cand))
    scored_idx, scores = [], []
    w = int(p["std_window"])
    for i in cand_arr[cand_arr >= start]:
        lo = max(int(i) - w, 0)
        window = v[lo:int(i)]
        keep = np.setdiff1d(np.arange(lo, int(i)), cand_arr,
                            assume_unique=False) - lo
        clean = window[keep] if len(keep) else window
        if len(clean) == 0:
            clean = ref
        wm, ws = float(clean.mean()), float(clean.std())
        floor = abs(wm) * 0.05
        ws = max(ws, floor, 1e-12)
        dev = abs(float(v[int(i)]) - wm)
        if dev > p["sensitivity"] * ws:
            scored_idx.append(int(i))
            scores.append(dev / ws)
    mask = np.zeros(n, dtype=bool)
    if not scores:
        return mask
    cutoff = max(scores) * float(p["top_percent"])
    for i, sc in zip(scored_idx, scores):
        if sc >= cutoff:
            mask[i] = True
    return mask


def _baseline(algorithm: str, v: np.ndarray,
              threshold: float | None) -> tuple[float, dict]:
    """(threshold, fitted params) for a builtin algorithm — the ONE place
    the formulas and default thresholds live (stateless detect, fit, and
    fitted detect all share it)."""
    if algorithm == "stl":
        # fit = learn the seasonal profile + residual spread of the
        # TRAINING window (reference PipelineDetector.fit_run persists
        # the pipeline state; fit_detect.py:32)
        thr = (_STL_DEFAULTS["sensitivity"] if threshold is None
               else float(threshold))
        period = _STL_DEFAULTS["period"]
        trend, _seas, resid, prof = robust_decompose(v, period)
        return thr, {
            "period": period,
            "seasonal": [float(x) for x in prof],
            "level": float(np.median(trend[-2 * period:])),
            "resid_std": float(max(resid.std(), 1e-12)),
        }
    if algorithm == "mad":
        thr = 3.0 if threshold is None else float(threshold)
        med = float(np.median(v))
        return thr, {"median": med,
                     "mad": float(np.median(np.abs(v - med)))}
    if algorithm == "sigma":
        thr = 3.0 if threshold is None else float(threshold)
        return thr, {"mean": float(v.mean()), "std": float(v.std())}
    if algorithm == "iqr":
        thr = 1.5 if threshold is None else float(threshold)
        q1, q3 = np.percentile(v, [25, 75])
        return thr, {"q1": float(q1), "q3": float(q3)}
    raise ValueError(f"unknown algorithm {algorithm!r}")


def _score(algorithm: str, params: dict, thr: float,
           v: np.ndarray) -> np.ndarray:
    if algorithm == "stl":
        if "seasonal" not in params:
            # stateless detect(): run the full sudden-change pipeline on
            # the scored window itself
            return stl_sudden_change(v, {"sensitivity": thr})
        # fitted: score against the TRAINED seasonal profile + level.
        # The scored window carries no timestamps, so its phase origin
        # is unknown — align by best fit: try every cyclic offset of the
        # profile and keep the one minimizing total absolute deviation
        # (a mis-anchored phase would turn the seasonal amplitude itself
        # into systematic false anomalies)
        prof = np.asarray(params["seasonal"], dtype=np.float64)
        period = int(params["period"])
        idx = np.arange(len(v))
        best_dev = None
        for off in range(period):
            expected = params["level"] + prof[(idx + off) % period]
            dev = np.abs(v - expected)
            if best_dev is None or dev.sum() < best_dev.sum():
                best_dev = dev
        return best_dev / params["resid_std"] > thr
    if algorithm == "mad":
        med, mad = params["median"], params["mad"]
        if mad == 0:
            return v != med
        return np.abs(v - med) / (1.4826 * mad) > thr
    if algorithm == "sigma":
        if params["std"] == 0:
            return np.zeros(len(v), dtype=bool)
        return np.abs(v - params["mean"]) / params["std"] > thr
    if algorithm == "iqr":
        iqr = params["q3"] - params["q1"]
        return (v < params["q1"] - thr * iqr) | (v > params["q3"] + thr * iqr)
    raise ValueError(f"unknown algorithm {algorithm!r}")


def detect(values: np.ndarray, algorithm: str, threshold: float | None = None) -> np.ndarray:
    """Boolean anomaly mask over a value series (stateless: the baseline
    is fitted on the same window it scores)."""
    algorithm = algorithm.lower()
    n = len(values)
    if n == 0:
        return np.zeros(0, dtype=bool)
    v = values.astype(np.float64)
    if algorithm == "stl":
        # stateless: the sudden-change pipeline fits and scores the same
        # window (threshold overrides the sensitivity)
        params = {} if threshold is None else {"sensitivity": float(threshold)}
        return stl_sudden_change(v, params)
    if algorithm in ALGORITHMS:
        thr, params = _baseline(algorithm, v, threshold)
        return _score(algorithm, params, thr, v)
    udf = _UDFS.get(algorithm)
    if udf is not None:
        try:
            mask = np.asarray(udf(v, threshold))
        except ValueError:
            raise
        except Exception as e:  # noqa: BLE001 — udf bugs become clean errors
            raise ValueError(f"udf {algorithm!r} failed: {e}") from e
        if mask.shape != (n,):
            raise ValueError(
                f"udf {algorithm!r} returned shape {mask.shape}, "
                f"expected ({n},)"
            )
        return mask.astype(bool)
    names = list(ALGORITHMS) + sorted(_UDFS)
    raise ValueError(f"unknown detect algorithm {algorithm!r} "
                     f"(supported: {', '.join(names)})")


# -- fitted models (reference: the castor fit pipeline + model lifecycle,
# services/castor/service.go:32-143, python/ts-udf/server) ------------------

import json as _json
import os as _os
import threading as _threading
from opengemini_tpu.utils import lockdep
import time as _time


def fit(algorithm: str, values: np.ndarray, threshold: float | None = None) -> dict:
    """Train a detector on a value series: learn the baseline statistics
    the algorithm needs so later detect() calls score NEW data against
    the TRAINING window (the point of fit vs stateless detection)."""
    algorithm = algorithm.lower()
    v = np.asarray(values, dtype=np.float64)
    v = v[np.isfinite(v)]
    if len(v) < 8:
        raise ValueError(f"model fit needs >= 8 finite points, got {len(v)}")
    if algorithm not in ALGORITHMS:
        raise ValueError(
            f"unknown fit algorithm {algorithm!r} "
            f"(supported: {', '.join(ALGORITHMS)})")
    thr, params = _baseline(algorithm, v, threshold)
    return {
        "algorithm": algorithm,
        "threshold": thr,
        "params": params,
        "trained_rows": int(len(v)),
        # wall-clock record: model provenance shown to operators
        "fitted_at": int(_time.time()),  # ogtlint: disable=OGT040
    }


def detect_fitted(model: dict, values: np.ndarray,
                  threshold: float | None = None) -> np.ndarray:
    """Score values against a fitted model's training baseline. An
    explicit query-time threshold overrides the persisted one."""
    v = np.asarray(values, dtype=np.float64)
    thr = float(model["threshold"]) if threshold is None else float(threshold)
    return _score(model["algorithm"], model["params"], thr, v)


class StreamDetector:
    """Incremental (at-ingest) scoring — the stream entry point next to
    the batch detect() SQL surface (reference: castor's batch vs stream
    handlers, python/ts-udf/server/handler.py). Keeps a bounded history
    ring; each push() scores ONLY the new points, against the fitted
    model when one is attached, else against the stateless algorithm
    over history + new points."""

    def __init__(self, algorithm: str, threshold: float | None = None,
                 model: dict | None = None, history: int = 512):
        self.algorithm = algorithm.lower()
        self.threshold = threshold
        self.model = model
        self.history = int(history)
        self._ring = np.empty(0, dtype=np.float64)
        if self.algorithm not in ALGORITHMS and self.algorithm not in _UDFS:
            raise ValueError(f"unknown detect algorithm {algorithm!r}")

    def push(self, values: np.ndarray) -> np.ndarray:
        v = np.asarray(values, dtype=np.float64)
        if len(v) == 0:
            return np.zeros(0, dtype=bool)
        if self.model is not None:
            mask = detect_fitted(self.model, v, self.threshold)
        else:
            window = np.concatenate([self._ring, v])
            mask = detect(window, self.algorithm, self.threshold)[
                len(self._ring):]
        self._ring = np.concatenate([self._ring, v])[-self.history:]
        return mask


class ModelStore:
    """Persisted fitted models: one JSON artifact per model under
    <engine-root>/models/ (atomic replace on save, reloaded on open —
    the reference keeps model files under the castor sidecar's model
    dirs with version counters)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = lockdep.Lock()
        _os.makedirs(path, exist_ok=True)

    def _file(self, name: str) -> str:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"bad model name {name!r}")
        return _os.path.join(self.path, name + ".json")

    def save(self, name: str, doc: dict) -> None:
        with self._lock:
            tmp = self._file(name) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                _json.dump(doc, f)
                f.flush()
                _os.fsync(f.fileno())
            _os.replace(tmp, self._file(name))

    def get(self, name: str) -> dict | None:
        try:
            with open(self._file(name), encoding="utf-8") as f:
                return _json.load(f)
        except (OSError, ValueError):
            return None

    def names(self) -> list[str]:
        return sorted(
            f[:-5] for f in _os.listdir(self.path) if f.endswith(".json"))

    def drop(self, name: str) -> bool:
        with self._lock:
            try:
                _os.remove(self._file(name))
                return True
            except OSError:
                return False
