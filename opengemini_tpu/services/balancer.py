"""Load-aware balance service: periodic skew check on the meta leader.

Reference: app/ts-meta/meta/balance_manager.go +
master_pt_balance_manager.go — the reference's balance managers react to
load reports and move PT ownership; rendezvous placement here already
self-balances on membership change, so this service covers the OTHER
case: byte-size skew between nodes with stable membership. Decisions are
raft-replicated placement overrides; the data moves when the shedding
node's own MigrationService observes it no longer owns the group.
"""

from __future__ import annotations

from opengemini_tpu.services.base import Service, logger


class BalanceService(Service):
    name = "balancer"

    def __init__(self, router, meta_store, interval_s: float = 3600.0,
                 min_skew_mb: int = 64, skew_ratio: float = 1.3):
        super().__init__(interval_s)
        self.router = router
        self.meta_store = meta_store
        self.min_skew_bytes = int(min_skew_mb) << 20
        self.skew_ratio = float(skew_ratio)

    def handle(self) -> int:
        if not getattr(self.meta_store, "is_leader", lambda: True)():
            return 0  # one decision-maker per cluster
        move = self.router.balance_round(
            min_skew_bytes=self.min_skew_bytes,
            skew_ratio=self.skew_ratio,
        )
        if move:
            logger.info(
                "balance: group %s (%d bytes) %s -> %s (owners %s)",
                move["group"], move["bytes"], move["from"], move["to"],
                move["owners"])
            return 1
        return 0
