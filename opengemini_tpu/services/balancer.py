"""Load-aware balance service: periodic skew check on the meta leader.

Reference: app/ts-meta/meta/balance_manager.go +
master_pt_balance_manager.go — the reference's balance managers react to
load reports and move PT ownership; rendezvous placement here already
self-balances on membership change, so this service covers the OTHER
case: byte-size skew between nodes with stable membership. Decisions are
raft-replicated placement overrides; the data moves when the shedding
node's own MigrationService observes it no longer owns the group.

Each pass runs under a perf_counter deadline (half the service interval,
capped at 30s): collect_loads stops polling peers once the budget is
spent, and breaker-open peers fail fast via CircuitOpen instead of
eating a full RPC timeout each — so a dead node can never stretch a
balance pass across the next scheduled one.
"""

from __future__ import annotations

from time import perf_counter

from opengemini_tpu.services.base import Service, logger


class BalanceService(Service):
    name = "balancer"

    def __init__(self, router, meta_store, interval_s: float = 3600.0,
                 min_skew_mb: int = 64, skew_ratio: float = 1.3):
        super().__init__(interval_s)
        self.router = router
        self.meta_store = meta_store
        self.min_skew_bytes = int(min_skew_mb) << 20
        self.skew_ratio = float(skew_ratio)
        self.budget_s = min(30.0, max(1.0, interval_s / 2.0))

    def handle(self) -> int:
        if not getattr(self.meta_store, "is_leader", lambda: True)():
            return 0  # one decision-maker per cluster
        t0 = perf_counter()
        move = self.router.balance_round(
            min_skew_bytes=self.min_skew_bytes,
            skew_ratio=self.skew_ratio,
            budget_s=self.budget_s,
        )
        elapsed = perf_counter() - t0
        if elapsed > self.budget_s:
            logger.warning("balance: pass took %.1fs (budget %.1fs) — "
                           "slow peers truncated the load poll",
                           elapsed, self.budget_s)
        if move:
            logger.info(
                "balance: group %s (%d bytes) %s -> %s (owners %s) in %.2fs",
                move["group"], move["bytes"], move["from"], move["to"],
                move["owners"], elapsed)
            return 1
        return 0
