"""Retention enforcement service (reference: services/retention/service.go:81).

Expired-shard drops close each shard, which releases its decoded-column
cache entries (storage/colcache.py via Shard.close); a recreated shard
at the same path can never alias them (generation-keyed entries)."""

from __future__ import annotations

from opengemini_tpu.services.base import Service


class RetentionService(Service):
    name = "retention"

    def __init__(self, engine, interval_s: float = 1800.0):
        super().__init__(interval_s)
        self.engine = engine

    def handle(self) -> None:
        self.engine.drop_expired_shards()
        # the deferred half of DROP MEASUREMENT (mark-delete semantics)
        self.engine.purge_dropped_measurements()
