"""Anti-entropy service: rf>1 replica digest exchange + read repair.

Reference: the raft-replicated data path keeps replicas consistent by
construction (engine/engine_replication.go, lib/raftconn); the
rendezvous+LWW data plane heals known-down nodes with hinted handoff but
a SILENTLY diverged replica (partial disk loss, dropped hint file) would
otherwise never reconverge. This service compares per-(shard-group,
measurement) content digests between owners and pulls diverged
measurements back for last-write-wins merge."""

from __future__ import annotations

from opengemini_tpu.services.base import Service, logger


class AntiEntropyService(Service):
    name = "antientropy"

    def __init__(self, router, interval_s: float = 300.0):
        super().__init__(interval_s)
        self.router = router

    def handle(self) -> int:
        n = self.router.anti_entropy_round()
        if n:
            logger.info("anti-entropy: repaired %d (group, measurement) "
                        "divergences", n)
        return n
