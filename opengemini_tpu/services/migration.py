"""Shard migration service: pushes shard groups whose rendezvous
ownership moved away (membership change) to their new owners and drops
the local copies (reference: app/ts-meta/meta/migrate_state_machine.go,
the balancer + engine_ha.go segment moves)."""

from __future__ import annotations

from opengemini_tpu.services.base import Service, logger


class MigrationService(Service):
    name = "migration"

    def __init__(self, router, interval_s: float = 60.0,
                 staging_ttl_s: float = 900.0):
        super().__init__(interval_s)
        self.router = router
        self.staging_ttl_s = staging_ttl_s

    def handle(self) -> int:
        # janitor first: expire staging left by pushers that died
        # mid-stream (the Rollback that survives coordinator death)
        expired = self.router.engine.expire_staging(self.staging_ttl_s)
        if expired:
            logger.info("migration: expired %d stale staging areas", expired)
        n = self.router.migrate_round()
        if n:
            logger.info("migration: moved %d shard groups to new owners", n)
        return n
