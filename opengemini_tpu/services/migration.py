"""Shard migration service: pushes shard groups whose rendezvous
ownership moved away (membership change) to their new owners and drops
the local copies (reference: app/ts-meta/meta/migrate_state_machine.go,
the balancer + engine_ha.go segment moves)."""

from __future__ import annotations

from opengemini_tpu.services.base import Service, logger


class MigrationService(Service):
    name = "migration"

    def __init__(self, router, interval_s: float = 60.0):
        super().__init__(interval_s)
        self.router = router

    def handle(self) -> int:
        n = self.router.migrate_round()
        if n:
            logger.info("migration: moved %d shard groups to new owners", n)
        return n
