"""Background integrity scrub: verify block CRCs, feed quarantine,
trigger replica repair.

The third leg of the media-fault tier (storage/diskfault.py injects,
TSF block CRCs detect, shard quarantine contains): latent corruption in
a cold file would otherwise sit undetected until a query happens to
decode the damaged block — possibly months later, after the last good
replica rotated away.  The scrub walks every shard's immutable files at
a byte-budgeted pace (Taurus, arXiv:2506.20010, treats storage-media
failure as a first-class repair-from-replica event; the reference's
analogue is the HA store's background verification), verifying each
block's CRC WITHOUT decoding or polluting caches.

On damage: the file is quarantined through the owning shard (durable
marker, out of the read set, counters + sherlock dump), and — when a
DataRouter with rf>1 is attached — an anti-entropy round is triggered
so the lost rows re-replicate from a healthy owner without operator
action: detect → quarantine → digest divergence → pull → LWW merge.

Governance: ticks ride ``Service._governed_tick`` like compaction, and
each tenant's scrubbed bytes are charged to its governor account the
way rollup folds are (`GOVERNOR.charge_tenant`), so scrub IO is
attributable per database and pauses under interactive saturation.

Knobs (env, config, /debug/ctrl?mod=scrub):
  OGT_SCRUB=0              disable entirely (service ticks are inert)
  OGT_SCRUB_INTERVAL_S     tick interval (default 30; config
                           scrub-interval-s)
  OGT_SCRUB_MB             per-tick byte budget (default 4; config
                           scrub-mb; ctrl mb=)
"""

from __future__ import annotations

import os
import time as _time

from opengemini_tpu.services.base import Service, logger
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.utils.stats import histogram as _histogram

# per-file verify latency (ogt_scrub_seconds at /metrics): how long one
# file's CRC sweep holds the background token
_H_SCRUB = _histogram("scrub_seconds")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def enabled_by_env() -> bool:
    return os.environ.get("OGT_SCRUB", "") != "0"


class ScrubService(Service):
    name = "scrub"
    governed = True

    def __init__(self, engine, interval_s: float | None = None,
                 router=None, mb_per_tick: int | None = None):
        if interval_s is None:
            interval_s = float(os.environ.get("OGT_SCRUB_INTERVAL_S",
                                              "") or 30.0)
        super().__init__(interval_s)
        self.engine = engine
        self.router = router  # rf>1: repair trigger (may be set later)
        self.mb_per_tick = (mb_per_tick if mb_per_tick is not None
                            else _env_int("OGT_SCRUB_MB", 4))
        self.enabled = enabled_by_env()
        # resume cursor: (file path, reader gen) -> next block index.
        # In-memory only — a restart re-scrubs from the front, which is
        # the safe direction for an integrity sweep.
        self._cursor: dict[tuple[str, int], int] = {}
        self._done: set[tuple[str, int]] = set()
        self.passes = 0
        # a ctrl op=tick racing the background service tick must not
        # interleave cursor/done mutations (regressed cursors, double
        # verification charged twice, double pass counts)
        import threading
        from opengemini_tpu.utils import lockdep

        self._tick_lock = lockdep.Lock()

    # -- one tick ----------------------------------------------------------

    def handle(self) -> int:
        """Verify up to the byte budget; returns bytes verified this
        tick.  Damage quarantines the file and (rf>1) triggers an
        anti-entropy repair round after the sweep.  Serialized: a ctrl
        op=tick and the background ticker share the cursor state."""
        if not self.enabled:
            return 0
        with self._tick_lock:
            return self._sweep()

    def _sweep(self) -> int:
        from opengemini_tpu.storage.tsf import CorruptFile
        from opengemini_tpu.utils import tracing
        from opengemini_tpu.utils.governor import GOVERNOR

        t_tick = _time.perf_counter_ns()
        # float-tolerant (tests pace at sub-MB budgets)
        budget = int(self.mb_per_tick * (1 << 20))
        verified = 0
        quarantined = 0
        with self.engine._lock:
            shards = list(self.engine._shards.items())
        # enumerate the COMPLETE live set before verifying anything:
        # pass completion compares _done against every live file, so a
        # budget that runs dry mid-iteration cannot mistake a partial
        # sweep for a full pass (which would reset _done and starve the
        # shards later in the order forever)
        work: list = []
        live_keys: set[tuple[str, int]] = set()
        for (db, _rp, _start), sh in shards:
            with sh._lock:
                files = list(sh._files)
            for reader in files:
                live_keys.add((reader.path, reader.gen))
                work.append((db, sh, reader))
        for db, sh, reader in work:
            if budget <= 0 or self._stop.is_set():
                break
            key = (reader.path, reader.gen)
            if key in self._done:
                continue
            if not getattr(reader, "block_crc", False):
                # legacy revision-1 file: no seals to verify (its
                # meta CRC was checked at open) — count it done
                self._done.add(key)
                STATS.incr("scrub", "legacy_skipped_total")
                continue
            t0 = _time.perf_counter_ns()
            locs = reader.data_locs()
            idx = self._cursor.get(key, 0)
            n = 0
            try:
                while idx < len(locs) and budget > 0:
                    n += reader.verify_block(locs[idx])
                    budget -= locs[idx][1]
                    idx += 1
            except CorruptFile as e:
                quarantined += 1
                STATS.incr("scrub", "corruptions_found_total")
                logger.error("scrub: %s", e)
                sh.quarantine_file(e.path, e.why)
                self._cursor.pop(key, None)
                self._done.add(key)  # out of the read set now
            except OSError:
                # file retired under us mid-sweep: not damage
                self._cursor.pop(key, None)
                self._done.add(key)
            else:
                if idx >= len(locs):
                    self._cursor.pop(key, None)
                    self._done.add(key)
                    STATS.incr("scrub", "files_verified_total")
                else:
                    self._cursor[key] = idx
            verified += n
            if n:
                GOVERNOR.charge_tenant(db, "scrub_bytes", n)
            _H_SCRUB.observe_ns(_time.perf_counter_ns() - t0)
            if budget <= 0 or self._stop.is_set():
                break
        # forget retired files; a full pass over everything live resets
        # the done-set so the sweep is continuous
        self._done &= live_keys
        self._cursor = {k: v for k, v in self._cursor.items()
                        if k in live_keys}
        if live_keys and self._done >= live_keys and not self._cursor:
            self._done.clear()
            self.passes += 1
            STATS.incr("scrub", "passes_total")
        STATS.incr("scrub", "bytes_total", verified)
        tracing.record_stage("scrub", _time.perf_counter_ns() - t_tick)
        if quarantined:
            self._repair()
        return verified

    def _repair(self) -> None:
        """rf>1 self-heal: pull the quarantined data back from a healthy
        replica through the anti-entropy digest/pull path."""
        router = self.router
        if router is None or getattr(router, "rf", 1) <= 1:
            return
        try:
            n = router.anti_entropy_round()
        except Exception:  # noqa: BLE001 — repair is retried next round
            logger.exception("scrub: repair round failed")
            return
        STATS.incr("scrub", "repairs_triggered_total")
        if n:
            STATS.incr("scrub", "repaired_divergences_total", n)
            logger.warning(
                "scrub: repaired %d diverged (group, measurement) pairs "
                "after quarantine", n)

    # -- introspection / ctrl ----------------------------------------------

    def tick_now(self) -> int:
        """One synchronous sweep (ctrl op=tick, tests, torture verify);
        ungated like Service.tick — manual triggers express intent."""
        return self.handle()

    def status(self) -> dict:
        return {
            "enabled": self.enabled,
            "interval_s": self.interval_s,
            "mb_per_tick": self.mb_per_tick,
            "passes": self.passes,
            "in_progress_files": len(self._cursor),
            "done_files": len(self._done),
            "counters": STATS.counters("scrub"),
        }
