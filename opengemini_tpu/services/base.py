"""Service base: an interval-ticked background worker.

Reference: services/base.go — every service is a ticker loop with
open/close lifecycle; errors are logged, never fatal to the process.
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger("opengemini_tpu.services")


class Service:
    name = "service"

    def __init__(self, interval_s: float):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def handle(self) -> None:  # override
        raise NotImplementedError

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"svc-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def tick(self) -> None:
        """Run one iteration synchronously (tests and manual triggers)."""
        self.handle()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.handle()
            except Exception as e:  # noqa: BLE001 — service loops never die
                try:
                    from opengemini_tpu.utils import errno as _errno

                    note = _errno.tag(e)
                except Exception:  # noqa: BLE001 — classify() must never
                    note = "errno=?"  # kill the loop it annotates
                logger.exception(
                    "service %s tick failed [%s]", self.name, note)
