"""Service base: an interval-ticked background worker.

Reference: services/base.go — every service is a ticker loop with
open/close lifecycle; errors are logged, never fatal to the process.
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger("opengemini_tpu.services")


class Service:
    name = "service"
    # governed services (compaction/downsample/stream/CQ) acquire a
    # low-priority token from the resource governor per tick and pause
    # while interactive query occupancy is high or an IO alarm is recent
    # (utils/governor.py background throttling; pass-through when the
    # governor is disabled).  Watchdog-style services (iodetector,
    # sherlock, monitor) stay ungoverned — pausing them under load would
    # blind the diagnostics exactly when they matter.
    governed = False

    def __init__(self, interval_s: float):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def handle(self) -> None:  # override
        raise NotImplementedError

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"svc-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def tick(self) -> None:
        """Run one iteration synchronously (tests and manual triggers).
        Deliberately ungated: a manual trigger expresses operator intent,
        and tests need deterministic ticks."""
        self.handle()

    def _governed_tick(self) -> None:
        if not self.governed:
            self.handle()
            return
        from opengemini_tpu.utils.governor import GOVERNOR

        token = GOVERNOR.acquire_background(self.name, stop=self._stop)
        if token is None:
            return  # stopping while paused: skip the tick entirely
        try:
            self.handle()
        finally:
            token.release()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._governed_tick()
            except Exception as e:  # noqa: BLE001 — service loops never die
                try:
                    from opengemini_tpu.utils import errno as _errno

                    note = _errno.tag(e)
                except Exception:  # noqa: BLE001 — classify() must never
                    note = "errno=?"  # kill the loop it annotates
                logger.exception(
                    "service %s tick failed [%s]", self.name, note)
