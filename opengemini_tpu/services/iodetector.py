"""IO-hang watchdog (reference: lib/iodetector — a stuck disk triggers an
alarm, optionally suicide so the cluster fails over instead of limping).

Each tick performs a small write+fsync probe in the data directory FROM A
SEPARATE THREAD with a deadline; a probe that misses the deadline means
the volume is hanging and the configured action fires (log alarm, or
`fatal=True` process exit so orchestration restarts/fails over the node).
"""

from __future__ import annotations

import os
import threading
import time as _time

from opengemini_tpu.services.base import Service, logger


class IoDetectorService(Service):
    name = "iodetector"

    def __init__(self, engine, interval_s: float = 30.0,
                 probe_timeout_s: float = 10.0, fatal: bool = False):
        super().__init__(interval_s)
        self.engine = engine
        self.probe_timeout_s = probe_timeout_s
        self.fatal = fatal
        self.alarms = 0
        self._probe_thread: threading.Thread | None = None

    def handle(self) -> bool:
        """Returns True when the probe completed in time."""
        if self._probe_thread is not None and self._probe_thread.is_alive():
            # previous probe still stuck in fsync: the disk is still hung;
            # count the repeat alarm but don't stack another blocked thread
            self.alarms += 1
            self._note_alarm()
            logger.error("iodetector: previous probe still hung (alarm #%d)",
                         self.alarms)
            if self.fatal:
                logger.critical("iodetector: fatal — exiting for failover")
                os._exit(3)
            return False
        done = threading.Event()
        err: list = []

        def probe():
            try:
                path = os.path.join(self.engine.root, ".iodetector")
                with open(path, "w", encoding="utf-8") as f:
                    f.write(str(_time.time_ns()))
                    f.flush()
                    os.fsync(f.fileno())
                done.set()
            except OSError as e:  # pragma: no cover - disk failure
                err.append(e)
                done.set()

        t = threading.Thread(target=probe, daemon=True, name="io-probe")
        self._probe_thread = t
        t.start()
        ok = done.wait(self.probe_timeout_s) and not err
        if not ok:
            self.alarms += 1
            self._note_alarm()
            logger.error(
                "iodetector: disk probe %s after %.1fs (alarm #%d)",
                "failed" if err else "hung", self.probe_timeout_s, self.alarms,
            )
            if self.fatal:
                logger.critical("iodetector: fatal — exiting for failover")
                os._exit(3)
        return ok

    @staticmethod
    def _note_alarm() -> None:
        """Feed the resource governor: a hung disk pauses background
        compaction/downsample/stream work so the recovering volume serves
        interactive traffic and flushes first (utils/governor.py)."""
        from opengemini_tpu.utils.governor import GOVERNOR

        GOVERNOR.note_io_alarm()
