"""Hierarchical (tiered) storage service.

Reference: services/hierarchical/service.go:32-76 — warm shards move to
cold storage after an age threshold. Here: the shard directory moves to
the cold tier and a symlink keeps the hot path valid, so every code path
(readers, WAL, backup) continues to work unchanged. Object-store (OBS)
tiers plug in behind the same move operation in a later round.

Concurrency/failure contract:
  - writers take shard._lock, so WAL/index handles close safely under it;
  - READERS are lockless: old TSFReader objects are NOT closed — their
    fds stay valid across the rename (POSIX), and close on GC, matching
    storage/shard._retire_files;
  - any failure rolls the move back so the shard keeps serving.
"""

from __future__ import annotations

import os
import shutil
import time as _time

from opengemini_tpu.services.base import Service, logger


class HierarchicalService(Service):
    name = "hierarchical"

    def __init__(self, engine, cold_dir: str, age_ns: int,
                 interval_s: float = 3600.0):
        super().__init__(interval_s)
        self.engine = engine
        self.cold_dir = os.path.abspath(cold_dir)
        self.age_ns = age_ns

    def handle(self, now_ns: int | None = None) -> int:
        if now_ns is None:
            now_ns = _time.time_ns()
        moved = 0
        for shard in self.engine.all_shards():
            try:
                if shard.tmax > now_ns - self.age_ns:
                    continue
                if os.path.islink(shard.path):
                    continue  # already cold
                moved += self._move(shard)
            except Exception:  # noqa: BLE001
                logger.exception("tiering of %s failed", shard.path)
        return moved

    def _move(self, shard) -> int:
        rel = os.path.relpath(shard.path, self.engine.root)
        cold_path = os.path.abspath(os.path.join(self.cold_dir, rel))
        os.makedirs(os.path.dirname(cold_path), exist_ok=True)
        # _flush_lock before _lock (shard lock-order rule; the flush
        # below re-enters the flush lock)
        with shard._flush_lock, shard._lock:
            shard.flush()
            # close WRITE handles only (writers are locked out by _lock);
            # reader objects stay open for lockless in-flight queries
            shard.wal.close()
            shard.index.close()
            moved = False
            try:
                shutil.move(shard.path, cold_path)
                moved = True
                os.symlink(cold_path, shard.path)
                self._reopen(shard)
            except BaseException:
                # roll back so the shard keeps serving from the hot tier
                try:
                    if moved and not os.path.exists(shard.path):
                        shutil.move(cold_path, shard.path)
                    elif moved:  # symlink created but reopen failed
                        os.unlink(shard.path)
                        shutil.move(cold_path, shard.path)
                finally:
                    self._reopen(shard)
                raise
        logger.info("moved shard %s to cold tier %s", rel, cold_path)
        return 1

    def _reopen(self, shard) -> None:
        from opengemini_tpu.index.mergeset import open_series_index
        from opengemini_tpu.storage.tsf import TSFReader
        from opengemini_tpu.storage.wal import WAL

        shard.index = open_series_index(shard.path)
        shard.wal = WAL(os.path.join(shard.path, "wal.log"), sync=shard.wal.sync)
        # file-set swap: release the old readers' decoded-column cache
        # entries (their generations can never be hit again) and stamp
        # the fresh readers with the shard's cache namespace
        shard.drop_cached_columns()
        shard._files = [
            shard._adopt(TSFReader(os.path.join(shard.path, f)))
            for f in sorted(os.listdir(shard.path))
            if f.endswith(".tsf")
        ]
