"""ts-server: the single-process all-in-one server binary.

Reference: app/ts-server (run/run.go:38) + the app.Command lifecycle
(app/command.go:39-58). `python -m opengemini_tpu.server.app -config x.toml`
or `opengemini_tpu.server.app.main([...])`.

Config (TOML, reference lib/config style):
    [data]
    dir = "/var/lib/opengemini-tpu"
    wal-fsync = false
    flush-threshold-mb = 64
    [http]
    bind-address = "127.0.0.1:8086"
    tls-cert = "/etc/ogt/node.crt"   # serve https (client + peer traffic)
    tls-key = "/etc/ogt/node.key"
    tls-ca = "/etc/ogt/ca.crt"       # peer-client trust (else system CAs)
    tls-insecure-skip-verify = false # self-signed lab clusters
    [device]
    mesh-axes = ["shard", "time"]   # enables the multi-chip aggregate path
    mesh-devices = 0                # 0/absent = every local device
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

try:
    import tomllib  # py311+
except ModuleNotFoundError:  # pragma: no cover — exercised on py<3.11
    try:
        import tomli as tomllib  # the pre-3.11 backport, same API
    except ModuleNotFoundError:
        tomllib = None  # config loading degrades to defaults-only

from opengemini_tpu.server.http import HttpService
from opengemini_tpu.utils import peers as peernet
from opengemini_tpu.storage.engine import Engine

DEFAULTS = {
    "data": {"dir": "./ogtpu-data", "wal-fsync": False, "flush-threshold-mb": 64},
    "http": {"bind-address": "127.0.0.1:8086"},
}


def load_config(path: str | None) -> dict:
    cfg = {k: dict(v) for k, v in DEFAULTS.items()}
    if path:
        if tomllib is None:
            raise SystemExit(
                "-config requires a TOML parser: Python >= 3.11 "
                "(tomllib) or the tomli package"
            )
        with open(path, "rb") as f:
            user = tomllib.load(f)
        for section, vals in user.items():
            cfg.setdefault(section, {}).update(vals)
    return cfg


_JAX_DISTRIBUTED_UP = False


def _init_jax_distributed(dev_cfg: dict) -> None:
    """[device] coordinator-address + num-processes + process-id ->
    jax.distributed.initialize BEFORE backend init, so jax.devices()
    spans every host of the slice and make_mesh builds a global mesh
    (DCN between hosts, ICI within — SURVEY §7 step 4; the reference's
    analogue is its spdy node mesh). Must run before any jax use;
    idempotent per process."""
    global _JAX_DISTRIBUTED_UP
    coord = dev_cfg.get("coordinator-address")
    if not coord or _JAX_DISTRIBUTED_UP:
        return
    missing = [k for k in ("num-processes", "process-id")
               if dev_cfg.get(k) is None]
    if missing:
        raise SystemExit(
            "[device] coordinator-address requires "
            + " and ".join(missing))
    import jax

    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(dev_cfg["num-processes"]),
        process_id=int(dev_cfg["process-id"]),
    )
    _JAX_DISTRIBUTED_UP = True
    print(
        f"jax.distributed up: process {dev_cfg['process-id']}/"
        f"{dev_cfg['num-processes']} via {coord}", flush=True)


def _configure_device_mesh(dev_cfg: dict) -> None:
    """[device] mesh-axes -> a process-wide jax mesh: every dense batch
    (grid / bucketed) and the AggBatch shard_map path then run multi-chip
    (parallel/runtime.set_mesh; VERDICT r3 #3 — previously no production
    code path ever built a mesh). The reference's always-on shard fan-out
    analogue is coordinator/shard_mapper.go:61."""
    from opengemini_tpu.parallel import runtime as prt

    # multi-host init is independent of the mesh config: a coordinator
    # address alone must still join the slice (jax.devices() then spans
    # every host even if this node runs without a mesh)
    _init_jax_distributed(dev_cfg)
    axes = dev_cfg.get("mesh-axes")
    if not axes:
        # the mesh is process-global: a config without [device] must not
        # inherit one from an earlier build() in the same process
        prt.set_mesh(None)
        return
    mesh = _build_mesh(dev_cfg)
    prt.set_mesh(mesh)
    print(
        "device mesh: "
        f"{dict(zip(mesh.axis_names, mesh.devices.shape))}", flush=True)


def _build_mesh(dev_cfg: dict):
    """mesh-axes/mesh-devices -> a Mesh (the one [device] parsing shared
    by boot and SIGHUP reload, so both always build the same geometry
    for the same file)."""
    from opengemini_tpu.parallel import distributed as dist

    n = int(dev_cfg.get("mesh-devices", 0)) or None
    return dist.make_mesh(n, tuple(dev_cfg.get("mesh-axes")))


def build(cfg: dict) -> HttpService:
    hint_service = None
    _configure_device_mesh(cfg.get("device", {}))
    data = cfg["data"]
    engine = Engine(
        data["dir"],
        sync_wal=bool(data.get("wal-fsync", False)),
        flush_threshold_bytes=int(data.get("flush-threshold-mb", 64)) << 20,
        tag_arrays=bool(data.get("enable-tag-array", False)),
    )
    host, _, port = cfg["http"]["bind-address"].partition(":")
    http_cfg = cfg["http"]
    tls = None
    if http_cfg.get("tls-cert") and http_cfg.get("tls-key"):
        # [http] tls-cert/tls-key serve the listener over https
        tls = {"certfile": http_cfg["tls-cert"],
               "keyfile": http_cfg["tls-key"]}
    if tls or http_cfg.get("tls-ca") or http_cfg.get(
            "tls-insecure-skip-verify"):
        # peer clients (raft, /internal/*, registrar) speak https whenever
        # ANY tls-* key is set: a node behind a TLS-terminating proxy (no
        # serving cert of its own) still needs https to its peers
        peernet.configure_tls(
            ca_file=http_cfg.get("tls-ca") or None,
            skip_verify=bool(http_cfg.get("tls-insecure-skip-verify",
                                          False)),
        )
    else:
        # process-global, like the device mesh: a config without TLS must
        # not inherit https peer mode from an earlier build()
        peernet.reset()
    svc = HttpService(
        engine, host or "127.0.0.1", int(port or 8086),
        auth_enabled=bool(http_cfg.get("auth-enabled", False)),
        tls=tls,
    )
    meta_cfg = cfg.get("meta")
    if meta_cfg and meta_cfg.get("node-id"):
        # clustered meta plane (reference ts-meta): peers are "id@host:port"
        from opengemini_tpu.meta.service import HttpTransport, MetaStore

        peers = {}
        for p in meta_cfg.get("peers", []):
            pid, sep, addr = p.partition("@")
            if not sep or not pid or ":" not in addr:
                raise ValueError(
                    f"meta.peers entries must be 'id@host:port', got {p!r}"
                )
            peers[pid] = addr
        node_id = meta_cfg["node-id"]
        token = meta_cfg.get("token", "")
        transport = HttpTransport(
            peers, token=token,
            self_addr=meta_cfg.get("advertise", cfg["http"]["bind-address"]),
        )
        svc.meta_store = MetaStore(
            node_id, sorted(set(peers) | {node_id}), transport,
            storage_path=os.path.join(engine.root, "meta.raftlog"),
            compact_threshold=int(meta_cfg.get("compact-threshold", 512)),
        )
        svc.meta_store.token = token
        svc.meta_store.attach_engine(engine)  # replicated DDL -> local engine
        svc.meta_store.attach_users(svc.users)  # replicated user commands
        svc.executor.meta_store = svc.meta_store
        if meta_cfg.get("join"):
            # passive until our conf-add commits: a joiner must never
            # self-elect off its partial seed view
            svc.meta_store.node.learner = True
        svc.meta_store.start()
        if meta_cfg.get("join"):
            # new node: ask the existing cluster's leader to add us, then
            # raft catches us up (snapshot or log) automatically
            _spawn_joiner(
                meta_cfg["join"], node_id,
                meta_cfg.get("advertise", cfg["http"]["bind-address"]), token,
            )
    flight_cfg = cfg.get("flight", {})
    if flight_cfg.get("bind-address"):
        from opengemini_tpu.server.flight import FlightService

        fhost, _, fport = flight_cfg["bind-address"].partition(":")
        svc.flight = FlightService(
            engine, svc.executor, fhost or "127.0.0.1", int(fport or 8087),
            users=svc.users, auth_enabled=bool(cfg["http"].get("auth-enabled", False)),
        )
    cluster_cfg = cfg.get("cluster", {})
    if cluster_cfg.get("data-routing") and svc.meta_store is not None:
        from opengemini_tpu.parallel.cluster import DataRouter

        meta_cfg = cfg.get("meta", {})
        advertise = meta_cfg.get("advertise", cfg["http"]["bind-address"])
        svc.router = DataRouter(
            engine, svc.meta_store, meta_cfg["node-id"], advertise,
            token=meta_cfg.get("token", ""),
            rf=int(cluster_cfg.get("replication-factor", 1)),
            write_consistency=str(
                cluster_cfg.get("write-consistency", "one")),
        )
        svc.executor.router = svc.router
        if str(cluster_cfg.get("ha-policy", "write-available")) == \
                "replication":
            # strict mode: raft-committed writes per replica group
            from opengemini_tpu.parallel.datarep import DataReplication

            svc.router.datarep = DataReplication(
                svc.router, token=meta_cfg.get("token", ""))
        if svc.flight is not None:
            svc.flight.router = svc.router
        _spawn_registrar(svc.meta_store, meta_cfg["node-id"], advertise,
                         meta_cfg.get("token", ""))
        from opengemini_tpu.services.hintreplay import HintReplayService

        # at rf=1 there are never hints to replay, but the same ticker
        # drives member health probes for SHOW CLUSTER
        hint_service = HintReplayService(
            svc.router, float(cluster_cfg.get("hint-interval-s", 30)))
    svc.services = _build_services(cfg, svc)
    if hint_service is not None:
        svc.services.append(hint_service)
    if svc.router is not None and svc.router.rf > 1:
        from opengemini_tpu.services.antientropy import AntiEntropyService

        svc.services.append(AntiEntropyService(
            svc.router,
            float(cluster_cfg.get("anti-entropy-interval-s", 300))))
    if svc.router is not None:
        from opengemini_tpu.services.migration import MigrationService

        svc.services.append(MigrationService(
            svc.router,
            float(cluster_cfg.get("migration-interval-s", 60)),
            staging_ttl_s=float(
                cluster_cfg.get("migration-staging-ttl-s", 900)),
        ))
    if svc.router is not None and svc.meta_store is not None and \
            float(cluster_cfg.get("balance-interval-s", 3600)) > 0:
        from opengemini_tpu.services.balancer import BalanceService

        svc.services.append(BalanceService(
            svc.router, svc.meta_store,
            float(cluster_cfg.get("balance-interval-s", 3600)),
            min_skew_mb=int(cluster_cfg.get("balance-min-skew-mb", 64)),
            skew_ratio=float(cluster_cfg.get("balance-skew-ratio", 1.3)),
        ))
    return svc


def _spawn_registrar(meta_store, node_id: str, addr: str, token: str) -> None:
    """Register this node in the FSM data-node roster (leader-routed,
    retried until the cluster has a leader)."""
    import json as _json
    import urllib.request as _rq

    def run():
        import time as _time

        cmd = {"op": "register_node", "id": node_id, "addr": addr,
               "role": "data"}
        for _ in range(300):
            if meta_store.fsm.nodes.get(node_id, {}).get("addr") == addr:
                return  # already registered (replayed log or prior run)
            if meta_store.is_leader():
                if meta_store.propose_and_wait(cmd):
                    return
            else:
                hint = meta_store.leader_hint()
                laddr = meta_store.meta_members().get(hint or "", "")
                if laddr:
                    try:
                        req = _rq.Request(
                            peernet.url(laddr, "/cluster/register"),
                            data=_json.dumps({
                                "id": node_id, "addr": addr,
                                "role": "data", "token": token,
                            }).encode(),
                            headers={"Content-Type": "application/json"},
                            method="POST",
                        )
                        with peernet.urlopen(req, timeout=3) as r:
                            if r.status == 200:
                                return
                    except OSError:
                        pass
            _time.sleep(1)

    threading.Thread(target=run, daemon=True, name="data-register").start()


def _spawn_joiner(seed: str, node_id: str, addr: str, token: str) -> None:
    import json as _json
    import urllib.request as _rq

    def run():
        import time as _time

        target = seed
        body = {"id": node_id, "addr": addr, "token": token}
        for _ in range(120):
            try:
                req = _rq.Request(
                    peernet.url(target, "/raft/join"),
                    data=_json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"}, method="POST",
                )
                with peernet.urlopen(req, timeout=3) as r:
                    if r.status == 200:
                        print(f"joined meta cluster via {target}", flush=True)
                        return
            except OSError as e:
                # a 409 from a follower carries the leader's address
                if hasattr(e, "read"):
                    try:
                        hint = _json.loads(e.read()).get("leader_addr")
                        if hint:
                            target = hint
                    except Exception:  # noqa: BLE001
                        target = seed
            _time.sleep(1)
        print("meta join failed after retries", flush=True)

    threading.Thread(target=run, daemon=True, name="meta-join").start()


def _build_services(cfg: dict, svc: HttpService) -> list:
    from opengemini_tpu.services.continuous import ContinuousQueryService
    from opengemini_tpu.services.downsample import DownsampleService
    from opengemini_tpu.services.monitor import MonitorService
    from opengemini_tpu.services.retention import RetentionService

    sc = cfg.get("services", {})
    out = [
        RetentionService(svc.engine, float(sc.get("retention-interval-s", 1800))),
        DownsampleService(svc.engine, float(sc.get("downsample-interval-s", 3600))),
        ContinuousQueryService(
            svc.engine, svc.executor, float(sc.get("cq-interval-s", 10)),
            meta_store=svc.meta_store,
        ),
    ]
    if sc.get("store-monitor", True):
        out.append(MonitorService(svc.engine, float(sc.get("monitor-interval-s", 10))))
    from opengemini_tpu.services.compaction import CompactionService
    from opengemini_tpu.services.stream import StreamService

    out.append(StreamService(svc.engine, float(sc.get("stream-interval-s", 5))))
    from opengemini_tpu.services.rollup import RollupService

    # inert (one None check per tick) until a rollup spec is declared
    out.append(RollupService(
        svc.engine, float(sc.get("rollup-interval-s", 5))))
    from opengemini_tpu.promql.rules import enabled_by_env as _rules_on
    from opengemini_tpu.services.rules import RulesService

    if _rules_on():
        from opengemini_tpu.promql.rules import RuleManager

        # constructed eagerly so persisted groups resume ticking after a
        # restart (the durable claim/watermark contract needs the
        # manager live before traffic); OGT_RULES=0 keeps rules_hook
        # None and every write path bit-identical
        svc.rules_manager = RuleManager(svc.engine, prom=svc.prom)
        out.append(RulesService(
            svc.engine, float(sc.get("rules-interval-s", 5)),
            manager=svc.rules_manager, meta_store=svc.meta_store,
            router=svc.router))
    out.append(CompactionService(
        svc.engine, float(sc.get("compact-interval-s", 600)),
        int(sc.get("compact-max-files", 4)),
    ))
    from opengemini_tpu.services.scrub import ScrubService

    # background integrity scrub (block CRC verification feeding
    # quarantine + rf>1 anti-entropy repair); OGT_SCRUB=0 disables.
    # Registered on svc so /debug/ctrl?mod=scrub controls THIS instance.
    svc.scrub_service = ScrubService(
        svc.engine,
        float(sc.get("scrub-interval-s", 0) or 0) or None,
        router=svc.router,
        mb_per_tick=(int(sc["scrub-mb"]) if "scrub-mb" in sc else None),
    )
    out.append(svc.scrub_service)
    from opengemini_tpu.services.subscriber import SubscriberManager

    svc.subscriber = SubscriberManager(svc.engine)
    from opengemini_tpu.services.iodetector import IoDetectorService
    from opengemini_tpu.services.sherlock import SherlockService

    out.append(IoDetectorService(
        svc.engine, float(sc.get("iodetector-interval-s", 30)),
        float(sc.get("iodetector-timeout-s", 10)),
        bool(sc.get("iodetector-fatal", False)),
    ))
    out.append(SherlockService(
        svc.engine, float(sc.get("sherlock-interval-s", 30)),
        float(sc.get("sherlock-mem-mb", 4096)),
        int(sc.get("sherlock-threads", 200)),
        float(sc.get("sherlock-cooldown-s", 600)),
        bool(sc.get("sherlock-tracemalloc", False)),
    ))
    if sc.get("castor-udf-dir"):
        from opengemini_tpu.services.castor import load_udfs

        names = load_udfs(sc["castor-udf-dir"])
        if names:
            print(f"castor udfs loaded: {', '.join(names)}", flush=True)
    if sc.get("obs-dir") or sc.get("obs-url"):
        from opengemini_tpu.services.obstier import ObsTierService

        if sc.get("obs-url"):
            # remote S3-compatible bucket endpoint (reference: lib/obs)
            from opengemini_tpu.storage.objstore import HTTPObjectStore

            store = HTTPObjectStore(
                sc["obs-url"], token=sc.get("obs-token") or None)
        else:
            from opengemini_tpu.storage.objstore import FSObjectStore

            store = FSObjectStore(sc["obs-dir"])
        svc.engine.attach_object_store(store)
        out.append(ObsTierService(
            svc.engine,
            int(float(sc.get("obs-age-days", 90)) * 86400e9),
            float(sc.get("obs-interval-s", 3600)),
        ))
    if sc.get("cold-dir"):
        from opengemini_tpu.services.hierarchical import HierarchicalService

        out.append(HierarchicalService(
            svc.engine, sc["cold-dir"],
            int(float(sc.get("cold-age-days", 30)) * 86400e9),
            float(sc.get("hierarchical-interval-s", 3600)),
        ))
    return out


def _apply_runtime_config(svc: HttpService, cfg: dict) -> list[str]:
    """Hot-apply the reloadable subset of [services] to running services
    (reference: lib/config runtimecfg — SIGHUP re-reads the file; only
    tick intervals and watermark-style knobs change live, topology
    doesn't). Returns a list of 'service.field=value' changes."""
    sc = cfg.get("services", {})
    plans = {
        "retention": {"interval_s": ("retention-interval-s", float)},
        "downsample": {"interval_s": ("downsample-interval-s", float)},
        "continuousquery": {"interval_s": ("cq-interval-s", float)},
        "monitor": {"interval_s": ("monitor-interval-s", float)},
        "stream": {"interval_s": ("stream-interval-s", float)},
        "compaction": {"interval_s": ("compact-interval-s", float),
                       "max_files": ("compact-max-files", int)},
        "hierarchical": {"interval_s": ("hierarchical-interval-s", float)},
        "obstier": {"interval_s": ("obs-interval-s", float)},
        "iodetector": {"interval_s": ("iodetector-interval-s", float),
                       "probe_timeout_s": ("iodetector-timeout-s", float),
                       "fatal": ("iodetector-fatal", bool)},
        "sherlock": {"interval_s": ("sherlock-interval-s", float),
                     "mem_mb_watermark": ("sherlock-mem-mb", float),
                     "thread_watermark": ("sherlock-threads", int),
                     "cooldown_s": ("sherlock-cooldown-s", float)},
        "scrub": {"interval_s": ("scrub-interval-s", float),
                  "mb_per_tick": ("scrub-mb", int)},
    }
    # two-phase: convert EVERYTHING first so a bad value rejects the whole
    # reload instead of leaving a half-applied config behind an error
    staged = []
    for s in svc.services:
        plan = plans.get(s.name)
        if not plan:
            continue
        for attr, (key, conv) in plan.items():
            if key in sc:
                staged.append((s, attr, conv(sc[key])))
    changed = []
    for s, attr, new in staged:
        if getattr(s, attr, None) != new:
            setattr(s, attr, new)
            changed.append(f"{s.name}.{attr}={new}")
    # NOTE: a shortened interval takes effect after the service's current
    # wait expires (the ticker re-reads interval_s each iteration)
    changed.extend(_apply_mesh_config(cfg.get("device", {})))
    return changed


def _apply_mesh_config(dev_cfg: dict) -> list[str]:
    """Hot-apply a changed [device] mesh on SIGHUP. Safe now that every
    sharded-buffer cache rekeys on runtime.mesh_epoch() (models/grid.py,
    models/ragged.py) and the colcache device tier reshards retained
    entries with the stale buffers donated — a live swap reshards, it
    never serves a dead mesh. No-op when the effective mesh geometry is
    unchanged (rebuilding an identical mesh would bump the epoch and
    force every cache to reshard for nothing). Multi-host topology
    (coordinator-address et al.) stays boot-only, like the reference's
    runtimecfg."""
    from opengemini_tpu.parallel import runtime as prt

    axes = tuple(dev_cfg.get("mesh-axes") or ())
    cur = prt.get_mesh()
    if not axes:
        if cur is None:
            return []
        prt.set_mesh(None)
        return ["device.mesh=off"]
    import jax

    n = int(dev_cfg.get("mesh-devices", 0)) or len(jax.devices())
    if cur is not None and tuple(cur.axis_names) == axes and cur.size == n:
        return []
    mesh = _build_mesh(dev_cfg)
    prt.set_mesh(mesh)
    return ["device.mesh="
            + str(dict(zip(mesh.axis_names, mesh.devices.shape)))]


def _ensure_device_backend(timeout_s: float = 20.0) -> None:
    """Degrade to CPU when the configured accelerator backend is broken.

    Some environments pin a device platform (e.g. via sitecustomize)
    whose plugin fails to load or hangs at init in a server process; the
    first query would then crash or block forever. Probe the default
    backend in a SUBPROCESS under a timeout (an in-process jax.devices()
    on a hung tunnel is not interruptible) and force the CPU platform
    before any in-process jax use when the probe fails. Production hosts
    with working devices are unaffected. Only the CLI entrypoint probes:
    embedders calling build() pick their own platform, and tests pin CPU
    in conftest. OGTPU_SKIP_BACKEND_PROBE=1 skips the probe (known-good
    device; also avoids serial probe cost when spawning many servers);
    OGTPU_BACKEND_PROBE_TIMEOUT raises the budget on slow hosts where a
    healthy device could miss the default window."""
    if os.environ.get("OGTPU_SKIP_BACKEND_PROBE"):
        return
    import subprocess

    try:
        timeout_s = float(os.environ.get("OGTPU_BACKEND_PROBE_TIMEOUT",
                                         timeout_s))
    except ValueError:
        print("ignoring non-numeric OGTPU_BACKEND_PROBE_TIMEOUT", flush=True)
    code = ("import jax, jax.numpy as jnp;"
            "jnp.ones((2,), jnp.float32).sum().block_until_ready();"
            "print('OK', jax.default_backend())")
    why = None
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
        if r.returncode != 0 or "OK" not in r.stdout:
            lines = (r.stderr or r.stdout).strip().splitlines()
            errs = [ln for ln in lines if "Error" in ln] or lines[-1:]
            detail = errs[-1].strip() if errs else "no output"
            why = f"probe exited {r.returncode}: {detail}"
    except subprocess.TimeoutExpired:
        why = f"probe timed out after {timeout_s:g}s (device init hung)"
    except OSError as exc:
        why = f"probe failed to spawn: {exc}"
    if why is not None:
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(f"device backend unavailable ({why}); serving on CPU "
              "[set OGTPU_SKIP_BACKEND_PROBE=1 or "
              "OGTPU_BACKEND_PROBE_TIMEOUT to override]", flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ts-server", description="opengemini-tpu all-in-one server")
    ap.add_argument("-config", default=None, help="TOML config path")
    ap.add_argument("-pidfile", default=None, help="write process id to this file")
    args = ap.parse_args(argv)
    _ensure_device_backend()
    svc = build(load_config(args.config))
    svc.start()
    if svc.flight is not None:
        svc.flight.start()
    for s in svc.services:
        s.start()
    stop_event = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop_event.set())

    # installed BEFORE the pidfile exists: a supervisor that reads the
    # pidfile and fires an immediate reload must not hit the default
    # SIGHUP disposition (terminate)
    def on_hup(*_):
        try:
            changed = _apply_runtime_config(svc, load_config(args.config))
            print("config reloaded: " + (", ".join(changed) or "no changes"),
                  flush=True)
        except Exception as e:  # noqa: BLE001 — a bad file must not kill us
            print(f"config reload failed: {e}", flush=True)

    signal.signal(signal.SIGHUP, on_hup)
    if args.pidfile:
        with open(args.pidfile, "w", encoding="utf-8") as f:
            f.write(str(os.getpid()))
    scheme = "https" if svc.tls_enabled else "http"
    print(f"opengemini-tpu ts-server listening on {scheme}://:{svc.port}",
          flush=True)
    stop_event.wait()
    print("shutting down", flush=True)
    for s in svc.services:
        s.stop()
    if getattr(svc, "subscriber", None) is not None:
        svc.subscriber.stop()
    if svc.flight is not None:
        svc.flight.stop()
    if svc.meta_store is not None:
        svc.meta_store.stop()
    if getattr(svc.router, "datarep", None) is not None:
        svc.router.datarep.stop()
    if getattr(svc, "rules_manager", None) is not None:
        svc.rules_manager.close()  # final state fsync + hook detach
    svc.stop()
    svc.engine.close()
    if args.pidfile:
        try:
            os.remove(args.pidfile)
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
