"""InfluxDB 1.x-compatible HTTP API.

Reference routes (lib/util/lifted/influx/httpd/handler.go:257-280 and
handler_prom.go:86-312):
  GET/POST /query      InfluxQL, params q/db/epoch/pretty/chunked(ignored)
  POST     /write      line protocol, params db/rp/precision
  POST     /api/v2/write  bucket=db[/rp], precision
  GET/POST /api/v1/query, /api/v1/query_range   PromQL (params db opt.)
  GET      /api/v1/labels, /api/v1/label/<name>/values
  GET      /ping, /health
Auth and TLS are deferred to the cluster round; this is the ts-server
single-node surface.
"""

from __future__ import annotations

import gzip
import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from opengemini_tpu import __version__
from opengemini_tpu.ingest.line_protocol import ParseError
from opengemini_tpu.promql.engine import PromEngine, PromError
from opengemini_tpu.promql.parser import PromParseError, parse_duration_s
from opengemini_tpu.utils.querytracker import QueryKilled
from opengemini_tpu.query import condition as cond
from opengemini_tpu.query.executor import Executor
from opengemini_tpu.record import FieldTypeConflict
from opengemini_tpu.storage.shard import FileQuarantined
from opengemini_tpu.storage.engine import (NS, DatabaseNotFound, Engine,
                                           WriteError)
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.failpoint import inject as _fp
from opengemini_tpu.utils.governor import GOVERNOR, AdmissionRejected
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.utils.stats import observe_ns as _observe_ns

_EPOCH_DIV = {"ns": 1, "u": 1_000, "µ": 1_000, "ms": 1_000_000, "s": 1_000_000_000,
              "m": 60_000_000_000, "h": 3_600_000_000_000}

# early-reply keep-alive drain bounds (_send): a rejected request body
# larger than the cap — or one that stalls longer than the timeout —
# closes the connection instead of being read out
_DRAIN_CAP_BYTES = 8 << 20
_DRAIN_TIMEOUT_S = 10.0


def _route_of(path: str) -> str:
    """Coarse route class for the HTTP latency histograms: a FIXED
    vocabulary so /metrics label cardinality stays bounded no matter
    what paths clients probe."""
    if path in ("/query",):
        return "query"
    if path in ("/write", "/api/v2/write"):
        return "write"
    if path in ("/api/v1/prom/write", "/api/v1/otlp/metrics"):
        return "write"
    if path.startswith("/api/v1/"):
        return "prom"
    if path.startswith("/internal/"):
        return "internal"
    if path.startswith("/debug/") or path == "/metrics":
        return "debug"
    if path.startswith("/raft/") or path.startswith("/cluster/"):
        return "cluster"
    if path == "/repo" or path.startswith("/repo/"):
        return "logstore"
    if path in ("/ping", "/health"):
        return "health"
    return "other"


def time_now_s() -> float:
    import time as _t

    # wall clock: PromQL evaluation timestamp, not a duration
    return _t.time()  # ogtlint: disable=OGT040


def _prom_time(s: str | None) -> float:
    """Prom API time param: unix seconds (float) or RFC3339."""
    if s is None:
        raise ValueError("missing time parameter")
    try:
        return float(s)
    except ValueError:
        pass
    return cond.parse_rfc3339(s) / 1e9


def _prom_step(s: str | None) -> float:
    if s is None:
        raise ValueError("missing step parameter")
    try:
        return float(s)
    except ValueError:
        return parse_duration_s(s)


class _TLSThreadingServer(ThreadingHTTPServer):
    """TLS handshake in the worker thread: accept() returns the raw
    connection immediately (do_handshake_on_connect=False on the wrapped
    listener); finish_request — which ThreadingMixIn already runs in the
    per-connection thread — performs the bounded handshake."""

    def finish_request(self, request, client_address):
        import socket
        import ssl

        try:
            request.settimeout(30)
            request.do_handshake()
            request.settimeout(None)
        except (ssl.SSLError, OSError, socket.timeout):
            try:
                request.close()
            except OSError:
                pass
            return
        super().finish_request(request, client_address)


class HttpService:
    """Owns the HTTP listener; one Engine + Executor behind it."""

    def __init__(self, engine: Engine, host: str = "127.0.0.1", port: int = 8086,
                 prom_db: str = "prom", auth_enabled: bool = False,
                 tls: dict | None = None):
        self.engine = engine
        self.auth_enabled = auth_enabled
        self.executor = Executor(engine, auth_enabled=auth_enabled)
        self.users = self.executor.users
        self.prom = PromEngine(engine)
        self.prom_db = prom_db
        self.services: list = []  # populated by server.app.build
        self.meta_store = None  # MetaStore when clustered (server.app.build)
        self.router = None  # DataRouter when [cluster] data-routing is on
        self.flight = None  # FlightService when [flight] is configured
        self.scrub_service = None  # ScrubService (app build or lazy ctrl)
        from opengemini_tpu.server.logstore import LogStoreAPI

        self.logstore = LogStoreAPI(self)  # /repo log-mode surface
        # monitoring: SHOW QUERIES / /debug/queries pair in-flight
        # queries with the live acked-vs-durable ledger (PR 4)
        from opengemini_tpu.utils.querytracker import GLOBAL as _TRACKER

        _TRACKER.set_durability_provider(engine.durability_snapshot)
        handler = _make_handler(self)
        if tls:
            # serve every surface — client API, /internal/* data plane,
            # /raft/* — over TLS (reference: the https options of
            # lib/config sql.go applied to the httpd listener). The
            # handshake runs in the per-connection WORKER thread
            # (_TLSThreadingServer), never in the accept loop — one
            # stalled client must not block all new connections.
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls["certfile"], tls["keyfile"])
            self.httpd = _TLSThreadingServer((host, port), handler)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        else:
            self.httpd = ThreadingHTTPServer((host, port), handler)
        self.tls_enabled = bool(tls)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def format_result(result: dict, epoch: str | None) -> dict:
    """Convert internal ns times to the requested epoch, or RFC3339."""
    for res in result.get("results", []):
        for series in res.get("series", []):
            cols = series.get("columns", [])
            if not cols or cols[0] != "time":
                continue
            for row in series.get("values", []):
                t = row[0]
                if not isinstance(t, int):
                    continue
                if epoch:
                    row[0] = t // _EPOCH_DIV.get(epoch, 1)
                else:
                    row[0] = cond.format_rfc3339(t)
    return result


def _null_nonfinite(obj):
    """Deep-copy with non-finite floats replaced by None (influx marshals
    null). Only runs when a payload actually contains one."""
    import math

    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _null_nonfinite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_null_nonfinite(v) for v in obj]
    return obj


def _make_handler(svc: HttpService):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "opengemini-tpu/" + __version__
        # headers and payload flush as separate send()s; with Nagle on,
        # the payload send stalls ~40ms waiting for the client's delayed
        # ACK of the header packet — every keep-alive response paid it
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # quiet; logging layer comes later
            pass

        # -- plumbing -------------------------------------------------------

        def _params(self) -> dict:
            parsed = urllib.parse.urlparse(self.path)
            qs = urllib.parse.parse_qs(parsed.query)
            return {k: v[-1] for k, v in qs.items()}

        def _body(self) -> bytes:
            """Read (and cache) the request body. Caching makes _body()
            idempotent so handlers can drain the socket for keep-alive
            correctness even when they ignore the payload."""
            cached = getattr(self, "_body_cache", None)
            if cached is not None:
                return cached
            length = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(length) if length else b""
            if self.headers.get("Content-Encoding") == "gzip":
                data = gzip.decompress(data)
            self._body_cache = data
            return data

        def _internal_request(self, svc) -> dict | None:
            """Parse + authorize a peer-to-peer /internal/* request: one
            shared implementation of the cluster-token policy (the data
            plane must not bypass auth without the shared secret vouching
            for the caller). Sends the error response and returns None on
            rejection."""
            try:
                req = json.loads(self._body())
            except ValueError:
                req = None
            if not isinstance(req, dict) or not req.get("db"):
                self._send_json(400, {"error": "db required"})
                return None
            token = getattr(svc.meta_store, "token", "") if svc.meta_store else ""
            if token and req.get("token") != token:
                self._send_json(403, {"error": "bad cluster token"})
                return None
            if not token and svc.auth_enabled:
                self._send_json(403, {"error": "cluster token required"})
                return None
            return req

        @staticmethod
        def _primary_filter(svc, req):
            """rf>1 shard filter: serve only groups this node is PRIMARY
            for among the caller's live set, so each group is counted
            exactly once cluster-wide."""
            live = req.get("live")
            if (int(req.get("rf", 1)) > 1 and live
                    and svc.router is not None):
                return lambda sh: svc.router.is_primary(
                    req["db"], req.get("rp"), sh.tmin, live)
            return None

        def _send(self, code: int, payload: bytes = b"", ctype: str = "application/json"):
            # keep-alive correctness for EVERY early reply (auth failure,
            # bad request, shed) on a request whose body was never read:
            # unread payload left in the socket desyncs the next
            # pipelined request into BrokenPipe/BadStatusLine storms
            # under torture load.  _body() caches, so handlers that
            # already read it pay nothing; draining before the status
            # line keeps the HTTP exchange well-ordered.
            if getattr(self, "_body_cache", None) is None and \
                    self.headers.get("Content-Length"):
                try:
                    # raw socket consumption only: a shed/reject reply
                    # must not pay gzip decompression for a payload it
                    # is refusing to process.  Draining is bounded — an
                    # oversized rejected body costs a connection close,
                    # not reading it all just to preserve keep-alive
                    n = int(self.headers["Content-Length"])
                    if n > _DRAIN_CAP_BYTES:
                        self.close_connection = True
                    else:
                        # bounded wait: a client that declared a length
                        # and stalls must cost a closed connection, not
                        # a pinned handler thread (pre-auth DoS)
                        prev = self.connection.gettimeout()
                        self.connection.settimeout(_DRAIN_TIMEOUT_S)
                        try:
                            while n > 0:
                                got = self.rfile.read(min(n, 1 << 20))
                                if not got:
                                    break
                                n -= len(got)
                        finally:
                            self.connection.settimeout(prev)
                        if n > 0:  # short body: socket is desynced
                            self.close_connection = True
                except (OSError, ValueError):
                    # torn/stalled socket: reply anyway, then close (the
                    # unread remainder makes keep-alive unusable)
                    self.close_connection = True
                self._body_cache = b""
            self.send_response(code)
            if self.close_connection:
                self.send_header("Connection", "close")
            if payload:
                self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("X-Influxdb-Version", "1.8.0-" + __version__)
            extra = getattr(self, "_extra_headers", None)
            if extra:
                for k, v in extra.items():
                    self.send_header(k, v)
                self._extra_headers = None
            self.end_headers()
            if payload:
                self.wfile.write(payload)

        def _send_err(self, status: int, exc: BaseException,
                      extra: dict | None = None):
            """Error response with the stable errno taxonomy attached:
            X-Ogt-Errno header + errno field (reference lib/errno — the
            code is what fleet log triage greps)."""
            from opengemini_tpu.utils import errno as _errno

            code, mod = _errno.classify(exc)
            body = {"error": str(exc), "errno": code,
                    "module": mod.name.lower()}
            if extra:
                body.update(extra)
            self._send_json(status, body,
                            headers={"X-Ogt-Errno": str(code)})

        def _send_json(self, code: int, obj: dict, pretty: bool = False,
                       headers: dict | None = None):
            self._extra_headers = headers
            indent = 4 if pretty else None
            try:
                # strict JSON: a stray non-finite float anywhere in a
                # result must not serialize as a bare NaN/Infinity literal
                # (unparseable by standard clients). allow_nan=False makes
                # the common all-finite case zero-cost; only offending
                # payloads pay for the sanitize walk.
                data = json.dumps(obj, indent=indent, allow_nan=False) + "\n"
            except ValueError:
                data = json.dumps(_null_nonfinite(obj), indent=indent) + "\n"
            self._send(code, data.encode("utf-8"))

        def _authenticate(self, params: dict):
            """Basic auth header or u/p params (influx 1.x). Returns the
            user, or None when auth is disabled; sends 401 and returns
            False on failure."""
            if not svc.auth_enabled:
                return None
            if len(svc.users) == 0:
                # bootstrap: with no users yet, requests pass so the first
                # admin can be created (influx 1.x behavior)
                return None
            from opengemini_tpu.meta.users import AuthError
            import base64

            name = params.get("u")
            pw = params.get("p")
            header = self.headers.get("Authorization", "")
            if name is None and header.startswith("Basic "):
                try:
                    raw = base64.b64decode(header[6:]).decode("utf-8")
                    name, _, pw = raw.partition(":")
                except Exception:  # noqa: BLE001
                    name = None
            if name is None:
                self._send_json(401, {"error": "unable to parse authentication credentials"})
                return False
            try:
                return svc.users.authenticate(name, pw or "")
            except AuthError as e:
                self._send_err(401, e)
                return False

        # -- routes ---------------------------------------------------------

        def do_GET(self):
            self._observed("GET", self._do_get)

        def do_POST(self):
            self._observed("POST", self._do_post)

        def do_DELETE(self):
            self._observed("DELETE", self._do_delete)

        def _observed(self, method: str, dispatch) -> None:
            """Endpoint latency histograms (ogt_http_request_seconds,
            labeled by coarse route class + method).  One enabled-flag
            read when histograms are off (OGT_TRACE=0)."""
            from opengemini_tpu.utils.stats import obs_enabled

            if not obs_enabled():
                dispatch()
                return
            import time as _t

            t0 = _t.perf_counter_ns()
            try:
                dispatch()
            finally:
                _observe_ns(
                    "http_request_seconds", _t.perf_counter_ns() - t0,
                    route=_route_of(urllib.parse.urlparse(self.path).path),
                    method=method)

        def _do_get(self):
            self._form_pairs = ()  # reset per request (keep-alive reuse)
            self._body_cache = None
            path = urllib.parse.urlparse(self.path).path
            if path == "/ping":
                self._send(204)
            elif path == "/health":
                self._send_json(200, {"name": "opengemini-tpu", "status": "pass",
                                      "version": __version__})
            elif path == "/query":
                self._handle_query(self._params(), read_only=True)
            elif path == "/api/v1/consume":
                self._handle_consume(self._params())
            elif path == "/repo" or path.startswith("/repo/"):
                self._logstore("GET", path, self._params())
            elif path.startswith("/api/v1/"):
                self._handle_prom(path, self._params())
            elif path == "/raft/status" and svc.meta_store is not None:
                user = self._authenticate(self._params())
                if user is False:
                    return
                self._send_json(200, svc.meta_store.status())
            elif path == "/cluster/health" and svc.router is not None:
                # peer view exchange for the quorum failure view
                # (DataRouter.exchange_health); token-gated like the
                # /internal data plane
                token = getattr(svc.router, "token", "")
                sent = self.headers.get("X-Ogt-Token", "")
                if token and sent != token:
                    self._send_json(403, {"error": "bad cluster token"})
                    return
                if not token and svc.auth_enabled:
                    self._send_json(403, {"error": "cluster token required"})
                    return
                import time as _t

                ts = svc.router.health_ts
                self._send_json(200, {
                    "id": svc.router.self_id,
                    "health": svc.router.health,
                    # RELATIVE age of the probe, not a wall-clock stamp:
                    # the voter's staleness cut must not depend on clocks
                    # agreeing across nodes (NTP skew > the threshold
                    # would silently disqualify a healthy peer's votes)
                    "age_s": (_t.time() - ts) if ts else None,  # ogtlint: disable=OGT040 (health_ts wall pair)
                })
            elif path == "/metrics":
                # Prometheus text-format export (the statisticsPusher
                # analogue): every registry counter/gauge + histogram
                # under ogt_* names, scrapeable by a real Prometheus
                from opengemini_tpu.utils.stats import render_prometheus

                self._send(
                    200, render_prometheus(__version__).encode("utf-8"),
                    ctype="text/plain; version=0.0.4; charset=utf-8")
            elif path == "/debug/vars":
                import time as _t

                snap = {"system": {"uptime_s": round(
                    _t.perf_counter() - STATS.started_pc, 1),
                                   "version": __version__}}
                snap.update(STATS.snapshot())
                self._send_json(200, snap)
            elif path == "/debug/queries":
                from opengemini_tpu.utils.querytracker import (
                    GLOBAL as _TRACKER,
                )

                self._send_json(200, _TRACKER.full_snapshot())
            elif path == "/debug/device":
                # device-runtime telemetry (utils/devobs.py): device
                # table, jit-cache inventory, retained-buffer ledger by
                # owner, bounded recent-compile ring, capability probes —
                # plus the offload planner's model/decision state
                # (query/offload.py; devobs itself stays decoupled)
                from opengemini_tpu.query import offload as _offload
                from opengemini_tpu.utils import devobs as _devobs

                doc = _devobs.debug_doc()
                doc["planner"] = _offload.GLOBAL.debug_doc()
                self._send_json(200, doc)
            elif path == "/debug/trace":
                self._handle_debug_trace(self._params())
            elif path == "/debug/slow":
                from opengemini_tpu.utils.slowlog import GLOBAL as _SLOW

                self._send_json(200, _SLOW.snapshot())
            else:
                self._send_json(404, {"error": "not found"})

        def _handle_debug_trace(self, params: dict) -> None:
            """?qid= serves one stitched span tree (a RUNNING query's
            live tree, else the finished-trace ring); ?trace_id= looks
            up by trace id; bare = newest-first summaries."""
            from opengemini_tpu.utils.querytracker import GLOBAL as _TRACKER

            qid_s = params.get("qid", "")
            if qid_s:
                try:
                    qid = int(qid_s)
                except ValueError:
                    self._send_json(400, {"error": f"bad qid {qid_s!r}"})
                    return
                live = _TRACKER.trace_of(qid)
                if live is not None:
                    self._send_json(200, {
                        "qid": qid, "status": "running",
                        "trace_id": live.trace_id,
                        "trace": live.to_dict()})
                    return
                doc = tracing.get_trace(qid=qid)
                if doc is None:
                    self._send_json(
                        404, {"error": f"no trace for qid {qid} "
                              "(finished long ago, or OGT_TRACE off)"})
                    return
                self._send_json(200, dict(doc, status="finished"))
                return
            tid = params.get("trace_id", "")
            if tid:
                doc = tracing.get_trace(trace_id=tid)
                if doc is None:
                    self._send_json(
                        404, {"error": f"no trace {tid!r}"})
                    return
                self._send_json(200, dict(doc, status="finished"))
                return
            self._send_json(200, {
                "enabled": tracing.trace_enabled(),
                "recent": tracing.recent_traces()})

        def _merge_form_body(self, params: dict) -> None:
            body = self._body().decode("utf-8", errors="replace")
            if body and self.headers.get("Content-Type", "").startswith(
                "application/x-www-form-urlencoded"
            ):
                self._form_pairs = urllib.parse.parse_qsl(body)
                for k, v in urllib.parse.parse_qs(body).items():
                    params.setdefault(k, v[-1])

        def _do_post(self):
            self._form_pairs = ()  # reset per request (keep-alive reuse)
            self._body_cache = None
            path = urllib.parse.urlparse(self.path).path
            params = self._params()
            if path == "/query":
                self._merge_form_body(params)
                self._handle_query(params)
            elif path == "/write":
                self._handle_write(params, db=params.get("db", ""),
                                   rp=params.get("rp") or None)
            elif path == "/api/v2/write":
                bucket = params.get("bucket", "")
                db, _, rp = bucket.partition("/")
                self._handle_write(params, db=db, rp=rp or None)
            elif path == "/api/v1/prom/write":
                self._handle_prom_remote_write(params)
            elif path == "/api/v1/prom/read":
                self._handle_prom_remote_read(params)
            elif path == "/api/v1/otlp/metrics":
                self._handle_otlp_metrics(params)
            elif path == "/repo" or path.startswith("/repo/"):
                self._logstore("POST", path, params)
            elif path.startswith("/api/v1/"):
                self._merge_form_body(params)
                self._handle_prom(path, params)
            elif path == "/raft/msg" and svc.meta_store is not None:
                from opengemini_tpu.meta.raft import RaftNode as _RN

                try:
                    msg = json.loads(self._body())
                except ValueError:
                    msg = None
                if not _RN.valid_message(msg):
                    self._send_json(400, {"error": "bad raft message"})
                    return
                token = getattr(svc.meta_store, "token", "")
                if token and msg.pop("token", None) != token:
                    self._send_json(403, {"error": "bad cluster token"})
                    return
                msg.pop("token", None)
                sender_addr = msg.pop("addr", None)
                if sender_addr:
                    # learn the sender's reachable address (token already
                    # verified): lets a joiner answer a leader it has
                    # never seen in config
                    transport = svc.meta_store.node.transport
                    addr_of = getattr(transport, "addr_of", None)
                    if addr_of is not None:
                        addr_of[msg["from"]] = sender_addr
                svc.meta_store.node.deliver(msg)
                self._send(204)
            elif path == "/internal/write":
                req = self._internal_request(svc)
                if req is None:
                    return
                # replica-side backpressure: the coordinator classifies
                # this 429 as transient and queues the copy as a hint,
                # so shedding here never costs acked durability
                if self._shed_write_if_backpressured():
                    return
                from opengemini_tpu.parallel.cluster import decode_points

                # replica-side child span: a routed write from a traced
                # coordinator executes under it and ships it back in the
                # ack, so the coordinator's tree shows which replica
                # (and which phase) ate the time
                _rtrace = tracing.start_remote(
                    "internal_write", req.get("trace"),
                    node=getattr(svc.router, "self_id", "") or "")
                _fp("internal-write-before-apply")  # replica copy pending
                try:
                    points = decode_points(req.get("points", []))
                    if _rtrace is not None:
                        with tracing.activate(_rtrace), \
                                _rtrace.span("apply") as _sp:
                            n_rows = svc.engine.write_rows(
                                req["db"], points,
                                rp=req.get("rp") or None)
                            _sp.add_field("rows", n_rows)
                    else:
                        svc.engine.write_rows(req["db"], points,
                                              rp=req.get("rp") or None)
                except DatabaseNotFound as e:
                    # a replica lagging meta propagation transiently
                    # lacks the db: 404 keeps the copy hinted until it
                    # appears (the coordinator poisons only on 400)
                    self._send_err(404, e)
                    return
                except (FieldTypeConflict, KeyError, TypeError,
                        ValueError) as e:
                    self._send_json(400, {"error": f"bad points: {e}"})
                    return
                except WriteError as e:
                    # deterministic rejection of THIS payload (unknown
                    # rp, invalid measurement): 400 so the coordinator
                    # classifies it poison instead of hinting a copy
                    # that can never be delivered — 403 stays reserved
                    # for the cluster-token check, whose rotation
                    # window is transient and must not destroy hints
                    self._send_err(400, e)
                    return
                # the hairiest replica edge: the write IS durable but the
                # ack dies here — the coordinator must classify it
                # unreachable and hint a (LWW-idempotent) duplicate copy
                _fp("internal-write-before-reply")
                out = {"ok": True}
                sub = tracing.ship_subtree(_rtrace)
                if sub is not None:
                    out["trace"] = sub
                self._send_json(200, out)
            elif path == "/internal/raftdata":
                # per-replica-group raft traffic (strict replication mode)
                dr = getattr(getattr(svc, "router", None), "datarep", None)
                if dr is None:
                    self._send_json(404, {"error": "replication mode off"})
                    return
                from opengemini_tpu.meta.raft import RaftNode as _RN

                try:
                    msg = json.loads(self._body())
                except ValueError:
                    msg = None
                if not isinstance(msg, dict):
                    self._send_json(400, {"error": "bad raft message"})
                    return
                if dr.token and msg.pop("token", None) != dr.token:
                    self._send_json(403, {"error": "bad cluster token"})
                    return
                if not dr.token and svc.auth_enabled:
                    self._send_json(403, {"error": "cluster token required"})
                    return
                msg.pop("token", None)
                msg.pop("addr", None)
                core = {k: v for k, v in msg.items()
                        if k not in ("group", "owners")}
                if not _RN.valid_message(core):
                    self._send_json(400, {"error": "bad raft message"})
                    return
                dr.deliver(msg)
                self._send(204)
            elif path == "/internal/raftdata_propose":
                dr = getattr(getattr(svc, "router", None), "datarep", None)
                if dr is None:
                    self._send_json(404, {"error": "replication mode off"})
                    return
                try:
                    req = json.loads(self._body())
                except ValueError:
                    req = None
                if not isinstance(req, dict) or not req.get("db"):
                    self._send_json(400, {"error": "db required"})
                    return
                if dr.token and req.pop("token", None) != dr.token:
                    self._send_json(403, {"error": "bad cluster token"})
                    return
                if not dr.token and svc.auth_enabled:
                    self._send_json(403, {"error": "cluster token required"})
                    return
                self._send_json(200, dr.handle_propose(req))
            elif path == "/internal/migrate":
                # two-phase shard-group migration (reference engine_ha.go
                # PreAssign/Assign/Rollback): begin -> staged writes ->
                # commit | abort; staging is invisible to queries and
                # TTL-expired if the pusher dies (MigrationService)
                req = self._internal_request(svc)
                if req is None:
                    return
                from opengemini_tpu.parallel.cluster import decode_points

                op = req.get("phase")
                mig = str(req.get("mig_id", ""))
                try:
                    if op == "begin":
                        _fp("internal-migrate-begin")
                        svc.engine.begin_staging(
                            req["db"], req.get("rp") or None,
                            int(req["group_start"]), mig)
                        out = {"ok": True}
                    elif op == "write":
                        _fp("internal-migrate-write")
                        n = svc.engine.write_staging(
                            mig, decode_points(req.get("points", [])))
                        out = {"ok": True, "rows": n}
                    elif op == "commit":
                        _fp("internal-migrate-commit")  # staged, not live
                        out = {"ok": True,
                               "rows": svc.engine.commit_staging(mig)}
                        # committed (marker durable) but the ack can still
                        # die here — the pusher's retried commit must get
                        # ok from the marker, not a restream
                        _fp("internal-migrate-commit-before-reply")
                    elif op == "abort":
                        _fp("internal-migrate-abort")
                        # always ok: an unknown mig means nothing is
                        # staged (never begun, TTL-expired, or already
                        # committed — where abort must NOT undo the
                        # fold), so the rollback is trivially complete
                        out = {"ok": True,
                               "aborted": svc.engine.abort_staging(mig)}
                    else:
                        self._send_json(400, {"error": f"bad phase {op!r}"})
                        return
                except (KeyError, TypeError, ValueError) as e:
                    self._send_json(400, {"error": f"bad migrate request: {e}"})
                    return
                except WriteError as e:
                    self._send_err(403, e)
                    return
                self._send_json(200, out)
            elif path in ("/internal/select_meta", "/internal/select_partials"):
                req = self._internal_request(svc)
                if req is None:
                    return
                # remote-initiated scans compete for the same memory as
                # local queries: admit them so peer fan-out cannot drive
                # a node past its budget while it sheds its own clients.
                # A 503 here surfaces on the coordinator as a clean
                # query error (PartialsUnavailable), not a node-down.
                try:
                    with GOVERNOR.admitted():
                        if path == "/internal/select_meta":
                            from opengemini_tpu.parallel.cluster import (
                                serialize_select_meta,
                            )

                            self._send_json(200, serialize_select_meta(
                                svc.engine, req["db"], req.get("rp"),
                                req.get("mst", ""),
                                int(req.get("tmin", -(2**62))),
                                int(req.get("tmax", 2**62)),
                                shard_filter=self._primary_filter(svc, req),
                            ))
                            return
                        from opengemini_tpu.query.partials import (
                            compute_partials,
                        )

                        try:
                            body = compute_partials(
                                svc.engine, svc.router, req)
                        except (KeyError, TypeError, ValueError) as e:
                            self._send_json(
                                400,
                                {"error": f"bad partials request: {e}"})
                            return
                except AdmissionRejected as e:
                    self._send_json(
                        503, {"error": str(e)},
                        headers={"Retry-After": str(e.retry_after_s)})
                    return
                self._send(200, body, ctype="application/octet-stream")
            elif path == "/internal/groups":
                # anti-entropy: which shard groups does this node hold?
                req = self._internal_request(svc)
                if req is None:
                    return
                groups = [[db, rp, start]
                          for (db, rp, start) in sorted(svc.engine._shards)]
                self._send_json(200, {"groups": groups})
            elif path == "/internal/load":
                # balancer: this node's shard-group byte footprint
                req = self._internal_request(svc)
                if req is None:
                    return
                self._send_json(200, svc.engine.disk_usage())
            elif path == "/internal/digest":
                # anti-entropy: this node's logical content digest of one
                # shard group (rf>1 replica divergence detection)
                req = self._internal_request(svc)
                if req is None:
                    return
                group = int(req.get("group_start", 0))
                digest: dict = {}
                for sh in svc.engine.shards_for_range(
                        req["db"], req.get("rp"), group, group + 1):
                    if sh.tmin == group:
                        digest = sh.content_digest()
                self._send_json(200, {"digest": digest})
            elif path in ("/internal/scan", "/internal/measurements"):
                from opengemini_tpu.parallel.cluster import serialize_series

                req = self._internal_request(svc)
                if req is None:
                    return
                if path == "/internal/scan":
                    # raw-series exchange materializes full decoded
                    # columns for a peer — the memory-heaviest remote
                    # read, so it takes an admission slot like
                    # select_partials.  The coordinator maps a 503 to
                    # a clean RemoteScanError, not a node-down.
                    try:
                        with GOVERNOR.admitted():
                            shard_filter = self._primary_filter(svc, req)
                            args = (svc.engine, req["db"], req.get("rp"),
                                    req.get("mst", ""),
                                    int(req.get("tmin", -(2**62))),
                                    int(req.get("tmax", 2**62)))
                            tkw = {
                                "trace_ctx": req.get("trace"),
                                "node": getattr(svc.router, "self_id", "")
                                or "",
                            }
                            if req.get("fmt") == "bin":
                                from opengemini_tpu.parallel.cluster import (
                                    serialize_series_binary,
                                )

                                self._send(200, serialize_series_binary(
                                    *args, shard_filter=shard_filter,
                                    **tkw),
                                    ctype="application/octet-stream")
                                return
                            payload = serialize_series(
                                *args, shard_filter=shard_filter, **tkw,
                            )
                    except AdmissionRejected as e:
                        self._send_json(
                            503, {"error": str(e)},
                            headers={"Retry-After": str(e.retry_after_s)})
                        return
                    except FileQuarantined as e:
                        # media damage detected mid-scan: the file is
                        # quarantined; answer a clean 500 so the
                        # coordinator's failover serves these ranges
                        # from a replica this round (a retry here
                        # succeeds without the file)
                        self._send_err(500, e)
                        return
                else:
                    names = set()
                    for sh in svc.engine.shards_for_range(
                            req["db"], req.get("rp"), -(2**62), 2**62):
                        names.update(sh.measurements())
                    payload = {"measurements": sorted(names)}
                self._send_json(200, payload)
            elif path in ("/cluster/register", "/cluster/deregister",
                          "/cluster/placement") and svc.meta_store is not None:
                try:
                    req = json.loads(self._body())
                except ValueError:
                    req = None
                if not isinstance(req, dict):
                    self._send_json(400, {"error": "json body required"})
                    return
                token = getattr(svc.meta_store, "token", "")
                if token and req.get("token") != token:
                    self._send_json(403, {"error": "bad cluster token"})
                    return
                if not token and svc.auth_enabled:
                    # roster/placement writes must not bypass auth without
                    # a shared secret (an attacker-registered node — or an
                    # attacker-placed group — would receive a share of all
                    # writes and feed every query)
                    self._send_json(403, {"error": "cluster token required"})
                    return
                if not svc.meta_store.is_leader():
                    hint = svc.meta_store.leader_hint()
                    self._send_json(
                        409, {"error": "not the meta leader", "leader": hint,
                              "leader_addr": svc.meta_store.meta_members().get(
                                  hint, "")})
                    return
                if path == "/cluster/register":
                    if not req.get("id") or not req.get("addr"):
                        self._send_json(400, {"error": "id and addr required"})
                        return
                    cmd = {"op": "register_node", "id": req["id"],
                           "addr": req["addr"],
                           "role": req.get("role", "data")}
                elif path == "/cluster/deregister":
                    # decommission roster drop, forwarded from the leaving
                    # node (or a survivor forcing out a dead peer)
                    if not req.get("id"):
                        self._send_json(400, {"error": "id required"})
                        return
                    cmd = {"op": "remove_node", "id": req["id"]}
                else:  # /cluster/placement — drain/balance owner override
                    owners_l = req.get("owners")
                    if (not req.get("key") or not isinstance(owners_l, list)
                            or not owners_l
                            or not all(isinstance(o, str) for o in owners_l)):
                        self._send_json(
                            400, {"error": "key and owners[] required"})
                        return
                    cmd = {"op": "set_placement", "key": req["key"],
                           "owners": owners_l}
                ok = svc.meta_store.propose_and_wait(cmd)
                self._send_json(200 if ok else 503,
                                {"ok": True} if ok else {"error": "no quorum"})
            elif path in ("/raft/join", "/raft/remove") and svc.meta_store is not None:
                try:
                    req = json.loads(self._body())
                except ValueError:
                    req = None
                if not isinstance(req, dict) or not req.get("id"):
                    self._send_json(400, {"error": "id required"})
                    return
                token = getattr(svc.meta_store, "token", "")
                if token and req.get("token") != token:
                    self._send_json(403, {"error": "bad cluster token"})
                    return
                if not svc.meta_store.is_leader():
                    hint = svc.meta_store.leader_hint()
                    self._send_json(
                        409, {"error": "not the meta leader", "leader": hint,
                              "leader_addr": svc.meta_store.meta_members().get(
                                  hint, "")})
                    return
                if path == "/raft/join":
                    if not req.get("addr"):
                        self._send_json(400, {"error": "addr required"})
                        return
                    ok = svc.meta_store.propose_conf_change(
                        "add", req["id"], req["addr"])
                else:
                    ok = svc.meta_store.propose_conf_change("remove", req["id"])
                if ok:
                    self._send_json(200, {"ok": True})
                else:
                    self._send_json(503, {"error": "conf change failed"})
            elif path == "/debug/ctrl":
                self._handle_syscontrol(params)
            else:
                self._send_json(404, {"error": "not found"})

        def _do_delete(self):
            self._form_pairs = ()  # reset per request (keep-alive reuse)
            self._body_cache = None
            path = urllib.parse.urlparse(self.path).path
            if path.startswith("/repo/"):
                self._logstore("DELETE", path, self._params())
            else:
                self._send_json(404, {"error": "not found"})

        def _handle_syscontrol(self, params: dict):
            """Runtime admin toggles (reference: lib/syscontrol
            syscontrol.go:42-300, /debug/ctrl?mod=...&switchon=...)."""
            user = self._authenticate(params)
            if user is False:
                return
            if svc.auth_enabled and not (user and user.admin):
                code = 401 if user is None else 403
                self._send_json(code, {"error": "admin required"})
                return
            mod = params.get("mod", "")
            on = params.get("switchon", "").lower() in ("true", "1")
            if mod == "disablewrite":
                svc.engine.write_disabled = on
            elif mod == "disableread":
                svc.engine.read_disabled = on
            elif mod == "readonly":
                svc.engine.write_disabled = on
            elif mod == "flush":
                svc.engine.flush_all()
            elif mod == "durability":
                # online acked-vs-durable invariant check (PR 4): cross-
                # checks every clean shard's ledger live and reports
                # loss/duplication without stopping the engine.  ONE
                # snapshot drives both fields, so the violations always
                # match the ledger state reported next to them.
                snap = svc.engine.durability_snapshot()
                violations = svc.engine.durability_check(snap)
                self._send_json(200, {
                    "status": "ok" if not violations else "violated",
                    "violations": violations,
                    "durability": snap,
                })
                return
            elif mod == "governor":
                # runtime tuning of the resource governor: each knob
                # changes only when passed; no knobs = status query.
                # budget_mb=0 disables (pass-through).
                knobs = {}
                for key in ("budget_mb", "max_concurrent", "queue",
                            "timeout_ms", "hiwat_pct", "lowat_pct",
                            "overdraft_pct", "bg_pause_pct",
                            "bg_max_pause_s", "bp_cache_ms"):
                    if key in params:
                        try:
                            # the anti-starvation bound is a duration —
                            # fractional seconds are meaningful
                            knobs[key] = (float(params[key])
                                          if key == "bg_max_pause_s"
                                          else int(params[key]))
                        except ValueError:
                            self._send_json(
                                400, {"error": f"bad {key}={params[key]!r}"})
                            return
                if knobs:
                    GOVERNOR.configure(**knobs)
                self._send_json(200, {"status": "ok",
                                      "governor": GOVERNOR.describe()})
                return
            elif mod == "netfault":
                # deterministic network-fault rules for THIS node's
                # OUTBOUND peer traffic (parallel/netfault.py): the
                # torture harness's partition lever.  No action =
                # status; action=off clears one rule; clear=1 heals all.
                from opengemini_tpu.parallel import netfault as _nf

                if params.get("clear", "").lower() in ("1", "true", "all"):
                    _nf.clear_all()
                    self._send_json(200, {"status": "ok", "rules": []})
                    return
                action = params.get("action", "")
                if not action:
                    self._send_json(200, {"rules": _nf.rules(),
                                          "hits": _nf.hits()})
                    return
                src = params.get("src", "*")
                dst = params.get("dst", "*")
                pat = params.get("path", "*")
                if action == "off":
                    _nf.clear_rule(src, dst, pat)
                else:
                    try:
                        _nf.set_rule(src, dst, pat, action)
                    except ValueError as e:
                        self._send_json(400, {"error": str(e)})
                        return
                self._send_json(200, {"status": "ok",
                                      "rules": _nf.rules()})
                return
            elif mod == "diskfault":
                # deterministic MEDIA-fault rules for this node's
                # storage IO (storage/diskfault.py): the scribble
                # torture's bit-flip/torn-write/EIO lever.  No action =
                # status; action=off clears one rule; clear=1 heals all.
                from opengemini_tpu.storage import diskfault as _df

                if params.get("clear", "").lower() in ("1", "true", "all"):
                    _df.clear_all()
                    self._send_json(200, {"status": "ok", "rules": []})
                    return
                action = params.get("action", "")
                if not action:
                    self._send_json(200, {"rules": _df.rules(),
                                          "hits": _df.hits()})
                    return
                pat = params.get("path", "*")
                if action == "off":
                    _df.clear_rule(pat)
                else:
                    try:
                        _df.set_rule(pat, action)
                    except ValueError as e:
                        self._send_json(400, {"error": str(e)})
                        return
                self._send_json(200, {"status": "ok",
                                      "rules": _df.rules()})
                return
            elif mod == "scrub":
                # integrity-scrub control (services/scrub.py): status +
                # quarantine inventory, op=tick forces one governed
                # sweep now, op=purge deletes quarantined files from
                # disk, mb=/interval_s= tune the pace live.
                from opengemini_tpu.services.scrub import ScrubService

                scrub = getattr(svc, "scrub_service", None)
                if scrub is None:
                    # no background service wired (embedded/test server):
                    # a ctrl-owned instance still serves manual ticks
                    scrub = svc.scrub_service = ScrubService(
                        svc.engine, 3600.0, router=svc.router)
                if scrub.router is None and svc.router is not None:
                    scrub.router = svc.router
                # two-phase knob apply (like app._apply_runtime_config):
                # a bad second param must reject the WHOLE request, not
                # leave the first knob silently half-applied
                staged = []
                for key, conv, attr in (("mb", int, "mb_per_tick"),
                                        ("interval_s", float,
                                         "interval_s")):
                    if key in params:
                        try:
                            val = conv(params[key])
                            if val <= 0:
                                raise ValueError(f"{key} must be > 0")
                        except ValueError as e:
                            self._send_json(400, {"error": str(e)})
                            return
                        staged.append((attr, val))
                for attr, val in staged:
                    setattr(scrub, attr, val)
                out = {"status": "ok"}
                op = params.get("op", "")
                if op == "tick":
                    out["verified_bytes"] = scrub.tick_now()
                elif op == "purge":
                    out["purged_files"] = svc.engine.purge_quarantined()
                elif op:
                    self._send_json(400, {"error": f"unknown op {op!r}"})
                    return
                out["scrub"] = scrub.status()
                out["quarantine"] = svc.engine.quarantine_snapshot()
                self._send_json(200, out)
                return
            elif mod == "cluster":
                # synchronous cluster-service rounds + RPC-hardening
                # knobs: lets the torture harness (and operators) force
                # a migrate/balance/hint-replay/anti-entropy round NOW
                # instead of waiting out a service interval, and inspect
                # breaker/staging/hint state between faults.
                router = svc.router
                if router is None:
                    self._send_json(400, {"error": "no data router"})
                    return
                for key, conv in (("cb_threshold", int),
                                  ("cb_cooldown_s", float),
                                  ("probe_timeout_s", float),
                                  ("rpc_retries", int)):
                    if key in params:
                        try:
                            val = conv(params[key])
                        except ValueError:
                            self._send_json(
                                400, {"error": f"bad {key}={params[key]!r}"})
                            return
                        # same clamps as the constructor: a negative
                        # retry count would make _post_raw's attempt
                        # loop run zero times and return None
                        if key == "cb_threshold":
                            router.breaker.threshold = val
                        elif key == "cb_cooldown_s":
                            router.breaker.cooldown_s = max(0.0, val)
                        elif key == "rpc_retries":
                            router.rpc_retries = max(0, val)
                        else:  # probe_timeout_s
                            router.probe_timeout_s = max(0.05, val)
                op = params.get("op", "")
                out: dict = {"status": "ok"}
                try:
                    if op == "migrate":
                        out["expired"] = svc.engine.expire_staging(
                            float(params.get("staging_ttl_s", 900)))
                        out["moved"] = router.migrate_round()
                    elif op == "balance":
                        out["move"] = router.balance_round()
                    elif op == "move":
                        out["move"] = router.force_move(
                            params.get("db") or None,
                            dest=params.get("dest") or None)
                    elif op == "hints":
                        out["delivered"] = router.replay_hints()
                    elif op == "antientropy":
                        out["repaired"] = router.anti_entropy_round()
                    elif op == "health":
                        out["health"] = router.exchange_health()
                    elif op == "add":
                        # elastic membership: register a data node in the
                        # roster (a [meta] join node self-registers; this
                        # covers pre-registration + repair)
                        out["add"] = router.add_node(
                            params.get("id", ""), params.get("addr", ""),
                            params.get("role", "data"))
                    elif op == "drain":
                        # one drain pass: disown + migrate + hint replay
                        out["drain"] = router.drain_round()
                    elif op == "decommission":
                        # drain-then-remove this node, or forced removal
                        # of a dead peer via node=<id>
                        out["decommission"] = router.decommission(
                            node=params.get("node") or None,
                            deadline_s=float(
                                params.get("deadline_s", 60.0)))
                    elif op:
                        self._send_json(
                            400, {"error": f"unknown cluster op {op!r}"})
                        return
                except Exception as e:  # noqa: BLE001 — a faulted round
                    # must report, not drop the ctrl connection
                    self._send_json(500, {"error": f"{op} failed: {e}"})
                    return
                out["breaker"] = router.breaker.snapshot()
                out["staging"] = svc.engine.staging_ids()
                out["pending_hints"] = sorted(router.pending_hint_nodes())
                out["nodes"] = sorted(router.data_nodes())
                out["decommission_state"] = router.decommission_state
                self._send_json(200, out)
                return
            elif mod == "rollup":
                # materialized-rollup ops (storage/rollup.py):
                #   (none)/status      per-spec watermark/dirty/backlog
                #   op=flush           run maintenance synchronously NOW
                #   op=invalidate      re-dirty [from,to) (all when unset)
                #   op=declare         declare a spec (db, name,
                #                      measurement, every_s | every_ns,
                #                      [fields, sketch, delay_s, rp])
                #   op=drop            drop a spec (db, name)
                from opengemini_tpu.storage.rollup import (
                    RollupSpec, enabled_by_env)

                op = params.get("op", "")
                mgr = svc.engine.rollup_mgr
                out = {"status": "ok", "enabled": enabled_by_env()}
                try:
                    if op == "declare":
                        every_ns = (
                            int(params["every_ns"]) if "every_ns" in params
                            else int(float(params["every_s"]) * NS))
                        fields = (params["fields"].split(",")
                                  if params.get("fields") else None)
                        delay_ns = (int(float(params["delay_s"]) * NS)
                                    if "delay_s" in params else None)
                        spec = RollupSpec(
                            params["name"], params["measurement"], every_ns,
                            rp=params.get("rp") or None, fields=fields,
                            sketch=params.get("sketch", "1") not in
                            ("0", "false"),
                            delay_ns=delay_ns)
                        svc.engine.create_rollup(params["db"], spec)
                        mgr = svc.engine.rollup_mgr
                    elif op == "drop":
                        svc.engine.drop_rollup(params["db"], params["name"])
                    elif op == "flush":
                        if mgr is not None:
                            out["folded"] = mgr.maintain()
                    elif op == "invalidate":
                        if mgr is not None:
                            out["invalidated"] = mgr.invalidate(
                                params["db"], params.get("name") or None,
                                int(params["from"]) if "from" in params
                                else None,
                                int(params["to"]) if "to" in params
                                else None)
                    elif op and op != "status":
                        self._send_json(
                            400, {"error": f"unknown rollup op {op!r}"})
                        return
                except KeyError as e:
                    self._send_json(
                        400, {"error": f"missing parameter {e.args[0]!r}"})
                    return
                except (ValueError, WriteError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                out["specs"] = mgr.status() if mgr is not None else {}
                self._send_json(200, out)
                return
            elif mod == "rules":
                # continuous rule engine ops (promql/rules.py):
                #   (none)/status      per-group watermark/alerts/tiles
                #   op=declare         declare a group (db, group,
                #                      [interval_s, lateness_s]) and/or
                #                      one rule (record=<name> |
                #                      alert=<name>, expr, [for_s,
                #                      labels, annotations] — JSON)
                #   op=drop            drop a rule (db, group, name) or
                #                      a whole group (db, group)
                #   op=tick            evaluate due groups NOW
                from opengemini_tpu.promql.rules import (
                    Rule, RuleError, RuleManager, enabled_by_env)

                op = params.get("op", "")
                mgr = svc.engine.rules_hook
                out = {"status": "ok", "enabled": enabled_by_env()}
                try:
                    if op == "declare":
                        if mgr is None and enabled_by_env():
                            # same lazy-construction idiom as rollups:
                            # the manager exists once config does
                            mgr = RuleManager(svc.engine)
                            svc.rules_manager = mgr
                        if mgr is None:
                            self._send_json(
                                400, {"error": "rules disabled (OGT_RULES=0)"})
                            return
                        interval_s = (float(params["interval_s"])
                                      if "interval_s" in params else None)
                        lateness_s = (float(params["lateness_s"])
                                      if "lateness_s" in params else None)
                        if "record" in params or "alert" in params:
                            kind = ("recording" if "record" in params
                                    else "alerting")
                            name = params.get("record") or params["alert"]
                            rule = Rule(
                                name, params["expr"], kind,
                                labels=json.loads(params["labels"])
                                if params.get("labels") else None,
                                for_s=float(params.get("for_s", 0.0)),
                                annotations=json.loads(params["annotations"])
                                if params.get("annotations") else None)
                            mgr.add_rule(params["db"], params["group"],
                                         rule, interval_s, lateness_s)
                        else:
                            mgr.declare_group(params["db"], params["group"],
                                              interval_s, lateness_s)
                    elif op == "drop":
                        if mgr is None:
                            self._send_json(
                                400, {"error": "no rule manager"})
                            return
                        if params.get("name"):
                            mgr.drop_rule(params["db"], params["group"],
                                          params["name"])
                        else:
                            mgr.drop_group(params["db"], params["group"])
                    elif op == "tick":
                        if mgr is not None:
                            out["ticked"] = mgr.tick(
                                int(params["now_ns"]) if "now_ns" in params
                                else None,
                                db=params.get("db") or None)
                    elif op and op != "status":
                        self._send_json(
                            400, {"error": f"unknown rules op {op!r}"})
                        return
                except KeyError as e:
                    self._send_json(
                        400, {"error": f"missing parameter {e.args[0]!r}"})
                    return
                except (RuleError, ValueError, WriteError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                out["groups"] = mgr.status() if mgr is not None else {}
                self._send_json(200, out)
                return
            elif mod == "obs":
                # observability runtime tuning: trace capture on/off,
                # histogram arming, slow-query threshold + ring bound.
                # No knobs = status query.
                from opengemini_tpu.utils.slowlog import GLOBAL as _SLOW
                from opengemini_tpu.utils.stats import (obs_enabled,
                                                        set_obs_enabled)

                try:
                    if "trace" in params:
                        tracing.set_trace_enabled(
                            params["trace"] in ("1", "true"))
                    if "hist" in params:
                        set_obs_enabled(params["hist"] in ("1", "true"))
                    if "slow_ms" in params:
                        v = params["slow_ms"]
                        # slow_ms= (empty) or slow_ms=off disables
                        _SLOW.configure(
                            slow_ms=None if v in ("", "off", "none")
                            else float(v))
                    if "slow_max" in params:
                        _SLOW.configure(slow_max=max(1, int(params["slow_max"])))
                except ValueError as e:
                    self._send_json(400, {"error": str(e)})
                    return
                if params.get("clear", "") in ("1", "true"):
                    _SLOW.clear()
                    tracing.clear_recent()
                slow = _SLOW.snapshot()
                self._send_json(200, {
                    "status": "ok",
                    "trace": tracing.trace_enabled(),
                    "hist": obs_enabled(),
                    "slow_ms": slow["threshold_ms"],
                    "slow_max": slow["max_records"],
                    "slow_captured": slow["captured"],
                })
                return
            elif mod == "devobs":
                # device-runtime telemetry tuning: arm/disarm, warm-mark
                # the recompile tripwire, clear the compile ring, and
                # on-demand jax.profiler capture (single-capture guard).
                # No knobs = status query.
                from opengemini_tpu.utils import devobs as _devobs

                if "arm" in params:
                    _devobs.set_enabled(params["arm"] in ("1", "true"))
                if params.get("clear", "") in ("1", "true"):
                    _devobs.reset()
                op = params.get("op", "")
                if op == "mark_warm":
                    _devobs.mark_warm()
                elif op == "clear_warm":
                    _devobs.clear_warm()
                elif op == "profile":
                    try:
                        seconds = float(params.get("seconds", "2"))
                    except ValueError:
                        self._send_json(400, {
                            "error": f"bad seconds "
                                     f"{params.get('seconds')!r}"})
                        return
                    try:
                        started = _devobs.start_profile(
                            seconds, logdir=params.get("dir") or None)
                    except RuntimeError as e:
                        # capture already active (or backend refused):
                        # 409 so retry loops back off instead of
                        # stacking captures
                        self._send_json(409, {"error": str(e)})
                        return
                    self._send_json(200, {"status": "ok",
                                          "profile": started})
                    return
                elif op:
                    self._send_json(400, {
                        "error": f"unknown devobs op {op!r}"})
                    return
                self._send_json(200, {
                    "status": "ok",
                    "armed": _devobs.enabled(),
                    "compiles_since_warm": _devobs.compiles_since_warm(),
                    "ledger_bytes": _devobs.LEDGER.total_bytes(),
                    "profile": _devobs.profile_status(),
                })
                return
            elif mod == "offload":
                # adaptive offload planner (query/offload.py): arm/clear/
                # freeze the cost model, tune the decision knobs, pin the
                # prom host-kernels override, run a pre-warm sweep.
                # No knobs = status query (the planner debug doc).
                from opengemini_tpu.query import offload as _offload

                if "arm" in params:
                    _offload.set_enabled(params["arm"] in ("1", "true"))
                if "freeze" in params:
                    _offload.GLOBAL.set_frozen(
                        params["freeze"] in ("1", "true"))
                if params.get("clear", "") in ("1", "true"):
                    _offload.GLOBAL.clear()
                if "host_kernels" in params:
                    try:
                        _offload.set_prom_host_kernels_mode(
                            params["host_kernels"])
                    except ValueError as e:
                        self._send_json(400, {"error": str(e)})
                        return
                if "force" in params:
                    v = params["force"]
                    try:
                        _offload.set_force(
                            None if v in ("", "none") else v)
                    except ValueError as e:
                        self._send_json(400, {"error": str(e)})
                        return
                knobs = {}
                for k in ("min_samples", "explore_after"):
                    if k in params:
                        try:
                            knobs[k] = int(params[k])
                        except ValueError:
                            self._send_json(400, {
                                "error": f"bad {k} {params[k]!r}"})
                            return
                for k in ("amortize", "ewma"):
                    if k in params:
                        try:
                            knobs[k] = float(params[k])
                        except ValueError:
                            self._send_json(400, {
                                "error": f"bad {k} {params[k]!r}"})
                            return
                if knobs:
                    _offload.GLOBAL.configure(**knobs)
                op = params.get("op", "")
                if op == "prewarm":
                    ran = _offload.prewarm_once()
                    self._send_json(200, {"status": "ok",
                                          "prewarmed": ran})
                    return
                elif op:
                    self._send_json(400, {
                        "error": f"unknown offload op {op!r}"})
                    return
                doc = _offload.GLOBAL.debug_doc()
                doc["status"] = "ok"
                self._send_json(200, doc)
                return
            elif mod == "failpoint":
                from opengemini_tpu.utils import failpoint as _fpmod

                name = params.get("name", "")
                action = params.get("action", "")
                if not name:
                    self._send_json(200, {"active": _fpmod.active()})
                    return
                if action in ("", "off"):
                    _fpmod.disable(name)
                else:
                    _fpmod.enable(name, action)
                self._send_json(200, {"status": "ok", "failpoint": name,
                                      "action": action or "off"})
                return
            else:
                self._send_json(400, {"error": f"unknown syscontrol mod {mod!r}"})
                return
            self._send_json(200, {"status": "ok", "mod": mod, "switchon": on})

        def _handle_query(self, params: dict, read_only: bool = False):
            user = self._authenticate(params)
            if user is False:
                return
            q = params.get("q", "")
            if not q:
                self._send_json(400, {"error": "missing required parameter \"q\""})
                return
            from opengemini_tpu.meta.users import AuthError

            try:
                result = svc.executor.execute(
                    q, db=params.get("db", ""), read_only=read_only, user=user
                )
            except AuthError as e:
                self._send_err(403, e)
                return
            except AdmissionRejected as e:
                # admission control shed (resource governor): 503 +
                # Retry-After so well-behaved clients back off instead
                # of retrying into the same overload
                self._send_json(
                    503, {"error": str(e)},
                    headers={"Retry-After": str(e.retry_after_s)})
                return
            epoch = params.get("epoch")
            pretty = params.get("pretty") in ("true", "1")
            result = format_result(result, epoch)
            if params.get("chunked") in ("true", "1"):
                try:
                    chunk_size = max(1, int(params.get("chunk_size", 10_000)))
                except ValueError:
                    self._send_json(400, {"error": "bad chunk_size"})
                    return
                self._send_chunked(result, chunk_size)
                return
            self._send_json(200, result, pretty)

        def _send_chunked(self, result: dict, chunk_size: int):
            """Influx chunked responses: newline-delimited JSON documents
            STREAMED via HTTP chunked transfer encoding — each document is
            serialized and written independently, never the whole response
            (handler.go chunked write path)."""
            # drained: /query reads params via _merge_form_body/_body()
            # before execution ever reaches here
            self.send_response(200)  # ogtlint: disable=OGT020
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("X-Influxdb-Version", "1.8.0-" + __version__)
            self.end_headers()

            def emit(doc: dict) -> None:
                data = (json.dumps(doc) + "\n").encode("utf-8")
                self.wfile.write(f"{len(data):X}\r\n".encode("ascii"))
                self.wfile.write(data)
                self.wfile.write(b"\r\n")

            for res in result.get("results", []):
                base = {k: v for k, v in res.items() if k != "series"}
                series_list = res.get("series", [])
                if not series_list:
                    emit({"results": [base]})
                    continue
                for series in series_list:
                    values = series.get("values", [])
                    for off in range(0, max(len(values), 1), chunk_size):
                        part = dict(series)
                        part["values"] = values[off : off + chunk_size]
                        if off + chunk_size < len(values):
                            part["partial"] = True
                        emit({"results": [dict(base, series=[part])]})
            self.wfile.write(b"0\r\n\r\n")

        def _handle_prom(self, path: str, params: dict):
            """Prometheus HTTP API v1 (reference: handler_prom.go)."""
            user = self._authenticate(params)
            if user is False:
                return
            db = params.get("db", svc.prom_db)
            if svc.auth_enabled and not (user and user.can("READ", db)):
                code = 401 if user is None else 403
                self._send_json(code, {"status": "error", "error": "read not authorized"})
                return
            try:
                if path == "/api/v1/query_range":
                    # PromQL reads scan like any interactive query and must
                    # take an admission slot — otherwise this surface is an
                    # ungoverned side door around the /query sheds
                    with GOVERNOR.admitted():
                        data = svc.prom.query_range(
                            params.get("query", ""),
                            _prom_time(params.get("start")),
                            _prom_time(params.get("end")),
                            _prom_step(params.get("step")),
                            db,
                        )
                elif path == "/api/v1/query":
                    t = params.get("time")
                    with GOVERNOR.admitted():
                        data = svc.prom.query_instant(
                            params.get("query", ""),
                            _prom_time(t) if t else time_now_s(),
                            db,
                        )
                elif path == "/api/v1/labels":
                    data = self._prom_labels(db)
                elif path == "/api/v1/series":
                    data = self._prom_series(db, params)
                elif path.startswith("/api/v1/label/") and path.endswith("/values"):
                    name = path[len("/api/v1/label/") : -len("/values")]
                    data = self._prom_label_values(db, name)
                elif path == "/api/v1/rules":
                    # prometheus rules endpoint (promql/rules.py) —
                    # empty groups, not 404, when no manager is live
                    mgr = svc.engine.rules_hook
                    data = mgr.rules_api() if mgr is not None \
                        else {"groups": []}
                elif path == "/api/v1/alerts":
                    mgr = svc.engine.rules_hook
                    data = mgr.alerts_api() if mgr is not None \
                        else {"alerts": []}
                else:
                    self._send_json(404, {"status": "error", "error": "not found"})
                    return
            except AdmissionRejected as e:
                self._send_json(
                    503,
                    {"status": "error", "errorType": "unavailable",
                     "error": str(e)},
                    headers={"Retry-After": str(e.retry_after_s)})
                return
            except QueryKilled as e:
                # prom queries register with the query tracker now, so
                # KILL QUERY cancels them like any /query statement
                self._send_json(
                    422, {"status": "error", "errorType": "canceled",
                          "error": str(e)})
                return
            except (PromError, PromParseError, ValueError, OverflowError, re.error) as e:
                self._send_json(
                    400, {"status": "error", "errorType": "bad_data", "error": str(e)}
                )
                return
            self._send_json(200, {"status": "success", "data": data})

        def _prom_labels(self, db):
            names = {"__name__"}
            for sh in svc.engine.shards_for_range(db, None, -(2**62), 2**62):
                for mst in sh.measurements():
                    names.update(sh.index.tag_keys(mst))
            return sorted(names)

        def _prom_series(self, db, params):
            """/api/v1/series?match[]=selector — label sets of matching
            series, index-only (reference: prom compat, handler_prom.go).
            match[] may repeat; GET query string and POST form bodies both
            count (promtool/Grafana POST urlencoded bodies)."""
            from opengemini_tpu.promql import parser as prom_parser

            matches = [v for k, v in self._raw_params() if k == "match[]"]
            matches += [v for k, v in getattr(self, "_form_pairs", ())
                        if k == "match[]"]
            if not matches:
                raise ValueError("missing match[] parameter")
            out = []
            seen = set()
            for expr_text in matches:
                expr = prom_parser.parse(expr_text)
                if not isinstance(expr, prom_parser.VectorSelector):
                    raise ValueError("match[] must be a vector selector")
                for labels in svc.prom.series_labels(expr, db):
                    key = tuple(sorted(labels.items()))
                    if key not in seen:
                        seen.add(key)
                        out.append(labels)
            return out

        def _raw_params(self) -> list[tuple[str, str]]:
            parsed = urllib.parse.urlparse(self.path)
            return urllib.parse.parse_qsl(parsed.query)

        def _prom_label_values(self, db, name):
            vals = set()
            for sh in svc.engine.shards_for_range(db, None, -(2**62), 2**62):
                for mst in sh.measurements():
                    if name == "__name__":
                        vals.add(mst)
                    else:
                        vals.update(sh.index.tag_values(mst, name))
            return sorted(vals)

        def _handle_consume(self, params: dict):
            """Kafka-like cursor reads over a measurement (reference:
            services/consume — log-stream consumption with cursors).
            GET /api/v1/consume?db=&measurement=&cursor=&limit=
            cursor is opaque: "t:k" = rows consumed up to time t, k rows
            already taken AT exactly t (exact resume across ns ties)."""
            user = self._authenticate(params)
            if user is False:
                return
            db = params.get("db", "")
            mst = params.get("measurement", "")
            if svc.auth_enabled and not (user and user.can("READ", db)):
                # no bootstrap exemption: with auth on and zero users the
                # only open operation is creating the first admin
                self._send_json(403, {"error": "read not authorized"})
                return
            if getattr(svc.engine, "read_disabled", False):
                self._send_json(403, {"error": "reads are disabled (syscontrol)"})
                return
            if not db or not mst:
                self._send_json(400, {"error": "db and measurement are required"})
                return
            try:
                limit = int(params.get("limit", 1000))
            except ValueError:
                self._send_json(400, {"error": "bad limit"})
                return
            limit = max(1, min(limit, 10_000))
            cursor = params.get("cursor", "")
            from_t, skip_at_t = 0, 0
            if cursor:
                try:
                    a, _, b = cursor.partition(":")
                    from_t, skip_at_t = int(a), int(b)
                except ValueError:
                    self._send_json(400, {"error": "bad cursor"})
                    return
            try:
                with GOVERNOR.admitted():
                    rows, total = self._consume_gather(
                        db, mst, from_t, skip_at_t + limit)
            except AdmissionRejected as e:
                # consume decodes every matched series row >= from_t —
                # an interactive read surface like any other, so it must
                # take an admission slot rather than bypass the governor
                self._send_json(
                    503, {"error": str(e)},
                    headers={"Retry-After": str(e.retry_after_s)})
                return
            pos = 0
            remaining_skip = skip_at_t
            while pos < len(rows) and rows[pos][0] == from_t and remaining_skip > 0:
                pos += 1
                remaining_skip -= 1
            out = rows[pos : pos + limit]
            if out:
                last_t = out[-1][0]
                taken_at_last = sum(1 for r in out if r[0] == last_t)
                if last_t == from_t:
                    taken_at_last += skip_at_t - remaining_skip
                next_cursor = f"{last_t}:{taken_at_last}"
            else:
                next_cursor = cursor or "0:0"
            self._send_json(200, {
                "rows": [
                    {"time": t, "tags": tags, "fields": fields}
                    for t, tags, fields in out
                ],
                "cursor": next_cursor,
                "exhausted": total - (skip_at_t - remaining_skip) - len(out) <= 0,
            })

        def _consume_gather(self, db: str, mst: str, from_t: int,
                            need: int) -> tuple[list, int]:
            """Materialize one consume page: gather per-series arrays and
            bound python-row materialization to the page via the
            `need`-th (= skip + limit, ties included) smallest timestamp.
            Returns (sorted rows, total matched row count)."""
            import numpy as _np

            from opengemini_tpu.query.functions import py_value

            series_recs = []
            all_times = []
            for sh in svc.engine.shards_of_db(db):
                for sid in sorted(sh.index.series_ids(mst)):
                    rec = sh.read_series(mst, sid, from_t, 2**62)
                    if not len(rec):
                        continue
                    series_recs.append((sh.index.tags_of(sid), rec))
                    all_times.append(rec.times)
            total = sum(len(t) for t in all_times)
            if total and need < total:
                merged = _np.concatenate(all_times)
                kth = _np.partition(merged, need - 1)[need - 1]
                page_tmax = int(kth)  # inclusive; ties included below
            else:
                page_tmax = None
            rows = []
            for tags, rec in series_recs:
                sel = (
                    _np.nonzero(rec.times <= page_tmax)[0]
                    if page_tmax is not None
                    else range(len(rec))
                )
                for i in sel:
                    fields = {
                        name: py_value(col.values[i])
                        for name, col in rec.columns.items()
                        if col.valid[i]
                    }
                    rows.append((int(rec.times[i]), tags, fields))
            rows.sort(key=lambda r: r[0])
            return rows, total

        def _logstore(self, method: str, path: str, params: dict) -> None:
            """Dispatch to the /repo log-mode surface with governor shed
            mapping: logstore endpoints execute queries through the same
            admitted executor, so AdmissionRejected must answer 503 +
            Retry-After here too (not a dropped connection)."""
            try:
                handled = svc.logstore.handle(self, method, path, params)
            except AdmissionRejected as e:
                self._body()  # drain any unread body: keep-alive correctness
                self._send_json(
                    503, {"error": str(e)},
                    headers={"Retry-After": str(e.retry_after_s)})
                return
            if not handled:
                self._send_json(404, {"error": "not found"})

        def _shed_write_if_backpressured(self) -> bool:
            """Write-path backpressure (resource governor): when the
            memtable+WAL backlog is over the high watermark, answer 429 +
            Retry-After instead of growing RSS unboundedly.  Returns True
            when the write was shed (response already sent)."""
            retry_after = GOVERNOR.write_backpressure()
            if retry_after is None:
                return False
            self._body()  # drain the unread body: keep-alive correctness
            self._send_json(
                429,
                {"error": "write backpressure: memtable+WAL backlog over "
                          "the high watermark; retry later"},
                headers={"Retry-After": str(retry_after)})
            return True

        def _check_write_auth(self, params: dict, db: str) -> bool:
            user = self._authenticate(params)
            if user is False:
                return False
            if svc.auth_enabled and not (user and user.can("WRITE", db)):
                code = 401 if user is None else 403
                self._send_json(
                    code, {"error": f"write not authorized on {db!r}"})
                return False
            if not db:
                self._send_json(400, {"error": "database is required"})
                return False
            return True

        def _maybe_snappy(self, data: bytes) -> bytes:
            """Remote write/read bodies are snappy block compressed
            (Content-Encoding: snappy); tolerate raw protobuf too."""
            from opengemini_tpu.ingest import protowire as pw

            if self.headers.get("Content-Encoding") == "snappy":
                return pw.snappy_uncompress(data)
            try:
                return pw.snappy_uncompress(data)
            except pw.WireError:
                return data

        def _write_decoded_points(self, db: str, rp, points,
                                  consistency=None) -> bool:
            try:
                router = getattr(svc, "router", None)
                if router is not None:
                    router.routed_write(db, rp, points,
                                        consistency=consistency)
                else:
                    svc.engine.write_rows(db, points, rp=rp)
            except DatabaseNotFound as e:
                self._send_err(404, e)
                return False
            except (FieldTypeConflict, ValueError) as e:
                self._send_err(400, e, extra={"error": f"partial write: {e}"})
                return False
            except WriteError as e:
                self._send_err(403, e)
                return False
            return True

        def _handle_prom_remote_write(self, params: dict) -> None:
            """Prometheus remote write: snappy(protobuf WriteRequest)
            (reference: handler_prom.go:86 servePromWrite)."""
            from opengemini_tpu.ingest import prom_remote
            from opengemini_tpu.ingest.protowire import WireError

            db = params.get("db", "")
            if not self._check_write_auth(params, db):
                return
            if self._shed_write_if_backpressured():
                return
            try:
                body = self._maybe_snappy(self._body())
                points = prom_remote.decode_write_request(body)
            except (WireError, UnicodeDecodeError) as e:
                self._send_json(400, {"error": f"bad remote write body: {e}"})
                return
            if self._write_decoded_points(db, params.get("rp") or None, points):
                self._send(204)

        def _handle_prom_remote_read(self, params: dict) -> None:
            """Prometheus remote read: snappy(ReadRequest) ->
            snappy(ReadResponse) raw samples (reference:
            handler_prom.go servePromRead)."""
            from opengemini_tpu.ingest import prom_remote
            from opengemini_tpu.ingest import protowire as pw

            db = params.get("db", "")
            user = self._authenticate(params)
            if user is False:
                return
            if svc.auth_enabled and not (user and user.can("READ", db)):
                code = 401 if user is None else 403
                self._send_json(code, {"error": f"read not authorized on {db!r}"})
                return
            if not db:
                self._send_json(400, {"error": "database is required"})
                return
            try:
                body = self._maybe_snappy(self._body())
                queries = prom_remote.decode_read_request(body)
            except pw.WireError as e:
                self._send_json(400, {"error": f"bad remote read body: {e}"})
                return
            try:
                with GOVERNOR.admitted():
                    results = self._prom_remote_read_results(db, queries)
            except AdmissionRejected as e:
                # remote read materializes full matched series — it must
                # take an admission slot like every interactive read, not
                # bypass the governor (body already drained above)
                self._send_json(
                    503, {"error": str(e)},
                    headers={"Retry-After": str(e.retry_after_s)})
                return
            payload = prom_remote.encode_read_response(results)
            from opengemini_tpu.ingest.protowire import snappy_compress_literal
            out = snappy_compress_literal(payload)
            # drained: the read request was decoded from _body() above
            self.send_response(200)  # ogtlint: disable=OGT020
            self.send_header("Content-Type", "application/x-protobuf")
            self.send_header("Content-Encoding", "snappy")
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        def _prom_remote_read_results(self, db, queries) -> list:
            from opengemini_tpu.ingest import prom_remote
            from opengemini_tpu.promql.engine import _match_sids
            from opengemini_tpu.promql.parser import LabelMatcher

            MS = 1_000_000
            results = []
            for q in queries:
                metric = ""
                matchers = []
                for op, name, value in q["matchers"]:
                    if name == "__name__" and op == "=":
                        metric = value
                    else:
                        matchers.append(LabelMatcher(name, op, value))
                series_out = []
                if metric:
                    tmin = q["start_ms"] * MS
                    tmax = q["end_ms"] * MS + 1
                    per_key: dict = {}
                    for sh in svc.engine.shards_for_range(db, None, tmin, tmax):
                        for sid in sorted(_match_sids(sh, metric, matchers)):
                            rec = sh.read_series(
                                metric, sid, tmin, tmax,
                                fields=[prom_remote.VALUE_FIELD])
                            col = rec.columns.get(prom_remote.VALUE_FIELD)
                            if col is None or not len(rec):
                                continue
                            tags = sh.index.tags_of(sid)
                            key = tuple(sorted(tags.items()))
                            bucket = per_key.setdefault(key, (dict(tags), []))
                            v = col.valid
                            bucket[1].extend(
                                zip((rec.times[v] // MS).tolist(),
                                    col.values[v].tolist()))
                    for key in sorted(per_key):
                        labels, samples = per_key[key]
                        labels["__name__"] = metric
                        series_out.append((labels, sorted(samples)))
                results.append(series_out)
            return results

        def _handle_otlp_metrics(self, params: dict) -> None:
            """OTLP/HTTP metrics export (protobuf body, optional gzip)
            (reference: handler_otlp.go serveOtlpMetricsWrite)."""
            from opengemini_tpu.ingest import otlp
            from opengemini_tpu.ingest.protowire import WireError

            db = params.get("db", "")
            if not self._check_write_auth(params, db):
                return
            if self._shed_write_if_backpressured():
                return
            try:
                points = otlp.decode_metrics_request(self._body())
            except (WireError, UnicodeDecodeError) as e:
                self._send_json(400, {"error": f"bad OTLP body: {e}"})
                return
            if self._write_decoded_points(db, params.get("rp") or None, points):
                # empty ExportMetricsServiceResponse
                # drained: the OTLP payload was decoded from _body() above
                self.send_response(200)  # ogtlint: disable=OGT020
                self.send_header("Content-Type", "application/x-protobuf")
                self.send_header("Content-Length", "0")
                self.end_headers()

        def _handle_write(self, params: dict, db: str, rp):
            internal = bool(self.headers.get("X-Ogt-Internal"))
            if internal:
                # peer-forwarded write: the shared cluster token vouches
                # for it (the coordinator already authenticated the client)
                token = getattr(svc.meta_store, "token", "") if svc.meta_store else ""
                if (token and self.headers.get("X-Ogt-Token") != token) or (
                        not token and svc.auth_enabled):
                    self._send_json(403, {"error": "bad cluster token"})
                    return
            else:
                user = self._authenticate(params)
                if user is False:
                    return
                if svc.auth_enabled and not (user and user.can("WRITE", db)):
                    code = 401 if user is None else 403
                    self._send_json(
                        code, {"error": f"write not authorized on {db!r}"})
                    return
            if not db:
                self._send_json(400, {"error": "database is required"})
                return
            if self._shed_write_if_backpressured():
                return
            precision = params.get("precision", "ns")
            if precision == "n":
                precision = "ns"
            # coordinator-side write trace (OGT_TRACE=1): routed-write
            # RPC fan-out under it carries wire ctx, replica ack spans
            # graft back, and the stitched tree lands in the
            # /debug/trace ring (no qid — writes are not tracked
            # queries; addressable by trace_id)
            wtrace = None
            if tracing.trace_enabled() and not internal:
                wtrace = tracing.Trace("write")
                wtrace.root.add_field("database", db)
            try:
                if wtrace is not None:
                    with tracing.activate(wtrace):
                        self._write_dispatch(params, db, rp, precision,
                                             internal)
                else:
                    self._write_dispatch(params, db, rp, precision,
                                         internal)
            finally:
                if wtrace is not None:
                    wtrace.finish()
                    tracing.note_finished(None, wtrace, {"database": db})

        def _write_dispatch(self, params: dict, db: str, rp,
                            precision: str, internal: bool) -> None:
            try:
                router = getattr(svc, "router", None)
                if router is not None and not internal:
                    self._routed_write(router, db, rp, precision,
                                       consistency=params.get("consistency"))
                    return
                svc.engine.write_lines(db, self._body(), precision=precision, rp=rp)
            except DatabaseNotFound as e:
                self._send_err(404, e)
                return
            except (ParseError, FieldTypeConflict, ValueError) as e:
                self._send_err(400, e, extra={"error": f"partial write: {e}"})
                return
            except WriteError as e:
                self._send_err(403, e)
                return
            self._send(204)

        def _routed_write(self, router, db: str, rp, precision: str,
                          consistency=None):
            """Coordinator write: parse, then the shared routed_write
            sequence (split by owner, local structural write, structured
            JSON forwards)."""
            import time as _time

            if consistency is not None and consistency not in (
                    "any", "one", "quorum", "all"):
                # client typo = 400, never a retriable 503
                self._send_json(400, {
                    "error": f"invalid consistency {consistency!r} "
                             "(any, one, quorum, all)"})
                return

            from opengemini_tpu.ingest.line_protocol import parse_lines
            from opengemini_tpu.parallel.cluster import RemoteScanError

            try:
                points = parse_lines(self._body(), precision, _time.time_ns())
                router.routed_write(db, rp, points,
                                    consistency=consistency)
            except RemoteScanError as e:
                self._send_json(503, {"error": f"forward failed: {e}"})
                return
            except DatabaseNotFound as e:
                self._send_err(404, e)
                return
            except (ParseError, FieldTypeConflict, ValueError) as e:
                self._send_err(400, e, extra={"error": f"partial write: {e}"})
                return
            except WriteError as e:
                self._send_err(403, e)
                return
            except OSError as e:
                self._send_json(503, {"error": f"forward failed: {e}"})
                return
            self._send(204)

    return Handler
