"""Log-storage mode: repositories, logstreams, JSON log ingest, and
pipe-syntax log search over HTTP.

Reference surface: lib/util/lifted/influx/httpd/handler_logstore*.go —
repository/logstream CRUD (a repository is a database, a logstream is a
retention policy + measurement, handler_logstore.go:199-495), ndjson
upload with precision/mapping/log-tags (:1052 getLogWriteRequest, :1125
parseJson), PPL log query + histogram + context endpoints
(handler_logstore_query.go:277 serveQueryLog, :120 QueryParam), and
cursor-based consumption (handler_logstore_consume.go).

TPU-native mapping: logs are ordinary engine rows (tags + a ``content``
string field plus any structured fields), so the whole existing path
serves them — text-index-pruned scans for full-text terms (match() →
native/textindex.cpp sidecars), device-side window counts for
histograms, device aggregation for analytics. The PPL grammar
(sql/logparser.py) compiles onto InfluxQL and runs through the standard
executor; EXTRACT patterns and alias predicates run host-side over the
result page only.

Routes (all under ``/repo``)::

    POST   /repo/{repo}                          create repository
    GET    /repo                                 list repositories
    GET    /repo/{repo}                          show (logstreams)
    DELETE /repo/{repo}                          drop repository
    POST   /repo/{repo}/logstreams/{ls}          create logstream {"ttl": days}
    DELETE /repo/{repo}/logstreams/{ls}          drop logstream
    GET    /repo/{repo}/logstreams               list logstreams
    POST   .../logstreams/{ls}/upload            ndjson ingest
    GET    .../logstreams/{ls}/logs              PPL search (scroll cursor)
    GET    .../logstreams/{ls}/histogram         time-bucketed counts
    GET    .../logstreams/{ls}/context           rows around a timestamp
    GET    .../logstreams/{ls}/analytics         agg GROUP BY over logs
    GET    .../logstreams/{ls}/consume/logs      cursor consumption
    GET    .../logstreams/{ls}/consume/cursor-time
"""

from __future__ import annotations

import json
import re
import time as _time
import urllib.parse

from opengemini_tpu.ingest.line_protocol import FieldType
from opengemini_tpu.sql import logparser
from opengemini_tpu.storage.engine import DatabaseNotFound

from opengemini_tpu.sql.lexer import parse_duration_ns as _parse_interval_ns

NS_PER_MS = 1_000_000
NS_PER_DAY = 86_400 * 10**9
_NAME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_-]{0,127}$")
_PRECISION = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000,
              "": 1_000_000}
_MAX_LIMIT = 1000


class LogStoreAPI:
    """Stateless handler collection; one instance per HttpService."""

    def __init__(self, svc):
        self.svc = svc

    # -- dispatch ------------------------------------------------------------

    def handle(self, h, method: str, path: str, params: dict) -> bool:
        """Route a /repo request. Returns False when the path is not ours
        (caller falls through to its 404)."""
        if path != "/repo" and not path.startswith("/repo/"):
            return False
        if method in ("POST", "DELETE"):
            # drain the request body up front (cached; see Handler._body):
            # several routes ignore their payload, and unread bytes would
            # desync the next request on a keep-alive connection
            h._body()
        parts = [urllib.parse.unquote(p) for p in path.split("/") if p][1:]
        # validate name segments up front: repo/logstream names are
        # interpolated into InfluxQL identifiers downstream, so anything
        # outside the create-time charset is rejected before it can reach
        # the executor (identifier injection)
        for seg in (parts[0:1] + parts[2:3]):
            if seg and not _NAME_RE.match(seg):
                h._send_json(400, {"error": "invalid repository/logstream name"})
                return True
        try:
            if not parts:
                if method == "GET":
                    self._list_repos(h, params)
                    return True
                return False
            repo = parts[0]
            if len(parts) == 1:
                self._repo_crud(h, method, repo, params)
                return True
            if parts[1] != "logstreams":
                h._send_json(404, {"error": "not found"})
                return True
            if len(parts) == 2:
                if method == "GET":
                    self._list_streams(h, repo, params)
                    return True
                return False
            ls = parts[2]
            if len(parts) == 3:
                self._stream_crud(h, method, repo, ls, params)
                return True
            action = parts[3]
            if len(parts) == 5 and action == "consume":
                action = "consume/" + parts[4]
            elif len(parts) != 4:
                h._send_json(404, {"error": "not found"})
                return True
            fn = {
                "upload": self._upload,
                "logs": self._query_logs,
                "histogram": self._histogram,
                "context": self._context,
                "analytics": self._analytics,
                "consume/logs": self._consume_logs,
                "consume/cursor-time": self._consume_cursor_time,
            }.get(action)
            if fn is None:
                h._send_json(404, {"error": "not found"})
                return True
            fn(h, method, repo, ls, params)
            return True
        except DatabaseNotFound as e:
            h._send_json(404, {"error": f"repository not found: {e}"})
            return True
        except logparser.LogParseError as e:
            h._send_json(400, {"error": f"bad log query: {e}"})
            return True
        except (ValueError, TypeError) as e:
            # bad numeric query params (from/to/limit/...) and kin
            h._send_json(400, {"error": f"bad request: {e}"})
            return True

    # -- auth helpers --------------------------------------------------------

    def _auth(self, h, params, need: str, db: str):
        """Returns the user, or None after sending an error response."""
        user = h._authenticate(params)
        if user is False:
            return None
        if self.svc.auth_enabled:
            if need == "ADMIN":
                if not (user and getattr(user, "admin", False)):
                    h._send_json(403, {"error": "admin required"})
                    return None
            elif not (user and user.can(need, db)):
                h._send_json(403, {"error": f"{need.lower()} not authorized"})
                return None
        return user or True

    # -- repository / logstream CRUD ----------------------------------------

    def _list_repos(self, h, params):
        if self._auth(h, params, "ADMIN", "") is None:
            return
        h._send_json(200, {"repositories": sorted(self.svc.engine.database_names())})

    def _repo_crud(self, h, method, repo, params):
        eng = self.svc.engine
        if method == "POST":
            if self._auth(h, params, "ADMIN", repo) is None:
                return
            if not _NAME_RE.match(repo):
                h._send_json(400, {"error": "invalid repository name"})
                return
            if repo in eng.database_names():
                h._send_json(400, {"error": "repository already exists"})
                return
            eng.create_database(repo)
            h._send_json(200, {"success": True})
        elif method == "DELETE":
            if self._auth(h, params, "ADMIN", repo) is None:
                return
            if repo not in eng.database_names():
                h._send_json(404, {"error": "repository not found"})
                return
            eng.drop_database(repo)
            h._send_json(200, {"success": True})
        elif method == "GET":
            if self._auth(h, params, "READ", repo) is None:
                return
            if repo not in eng.database_names():
                h._send_json(404, {"error": "repository not found"})
                return
            h._send_json(200, {"repository": repo,
                               "logstreams": self._streams_of(repo)})
        else:
            h._send_json(405, {"error": "method not allowed"})

    def _streams_of(self, repo) -> list[dict]:
        d = self.svc.engine.databases[repo]
        out = []
        for name, rp in sorted(d.rps.items()):
            if name == d.default_rp and name == "autogen":
                continue  # the implicit default RP is not a logstream
            out.append({
                "name": name,
                "ttl_days": rp.duration_ns // NS_PER_DAY if rp.duration_ns else 0,
            })
        return out

    def _list_streams(self, h, repo, params):
        if self._auth(h, params, "READ", repo) is None:
            return
        if repo not in self.svc.engine.database_names():
            h._send_json(404, {"error": "repository not found"})
            return
        h._send_json(200, {"logstreams": self._streams_of(repo)})

    def _stream_crud(self, h, method, repo, ls, params):
        eng = self.svc.engine
        if method == "POST":
            if self._auth(h, params, "ADMIN", repo) is None:
                return
            if not _NAME_RE.match(ls):
                h._send_json(400, {"error": "invalid logstream name"})
                return
            if repo not in eng.database_names():
                h._send_json(404, {"error": "repository not found"})
                return
            if ls in eng.databases[repo].rps:
                h._send_json(400, {"error": "logstream already exists"})
                return
            opts = {}
            body = h._body()
            if body:
                try:
                    opts = json.loads(body)
                except ValueError:
                    h._send_json(400, {"error": "bad options body"})
                    return
            ttl_days = int(opts.get("ttl", 0) or 0)
            eng.create_retention_policy(
                repo, ls, duration_ns=ttl_days * NS_PER_DAY
            )
            h._send_json(200, {"success": True})
        elif method == "DELETE":
            if self._auth(h, params, "ADMIN", repo) is None:
                return
            if (repo not in eng.database_names()
                    or ls not in eng.databases[repo].rps):
                h._send_json(404, {"error": "logstream not found"})
                return
            eng.drop_retention_policy(repo, ls)
            h._send_json(200, {"success": True})
        elif method == "GET":
            if self._auth(h, params, "READ", repo) is None:
                return
            if repo not in eng.database_names():
                h._send_json(404, {"error": "repository not found"})
                return
            for s in self._streams_of(repo):
                if s["name"] == ls:
                    h._send_json(200, s)
                    return
            h._send_json(404, {"error": "logstream not found"})
        else:
            h._send_json(405, {"error": "method not allowed"})

    # -- upload --------------------------------------------------------------

    def _upload(self, h, method, repo, ls, params):
        if method != "POST":
            h._send_json(405, {"error": "method not allowed"})
            return
        if self._auth(h, params, "WRITE", repo) is None:
            return
        eng = self.svc.engine
        if repo not in eng.database_names() or ls not in eng.databases[repo].rps:
            h._send_json(404, {"error": "logstream not found"})
            return
        precision = params.get("precision", "")
        mult = _PRECISION.get(precision)
        if mult is None:
            h._send_json(400, {"error": f"invalid precision {precision!r}"})
            return
        mapping = {"timestamp": "time", "discard": [], "tags": []}
        if params.get("mapping"):
            try:
                user_map = json.loads(params["mapping"])
                if not isinstance(user_map, dict):
                    raise ValueError("mapping must be an object")
                mapping.update(user_map)
            except ValueError as e:
                h._send_json(400, {"error": f"bad mapping: {e}"})
                return
        log_tags = {}
        hdr = h.headers.get("log-tags", "")
        if hdr:
            try:
                log_tags = json.loads(hdr)
                if not isinstance(log_tags, dict):
                    raise ValueError("log-tags must be a JSON object")
            except ValueError as e:
                h._send_json(400, {"error": f"bad log-tags header: {e}"})
                return
        body = h._body()
        if params.get("type", "") == "json_array":
            try:
                objs = json.loads(body)
                if not isinstance(objs, list):
                    raise ValueError("expected a JSON array")
            except ValueError as e:
                h._send_json(400, {"error": f"bad body: {e}"})
                return
        else:
            objs = []
            for line in body.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    objs.append(json.loads(line))
                except ValueError:
                    objs.append(line.decode("utf-8", "replace")
                                if isinstance(line, bytes) else line)
        # non-object entries (bare scalars, plain-text lines) become
        # content-only rows — a log file of bare lines must ingest the
        # same way whether or not the lines happen to parse as JSON
        objs = [
            o if isinstance(o, dict)
            else {"content": o if isinstance(o, str) else json.dumps(o)}
            for o in objs
        ]
        now_ns = _time.time_ns()
        ts_field = mapping["timestamp"]
        discard = set(mapping.get("discard") or [])
        tag_fields = set(mapping.get("tags") or [])
        points, failed = [], 0
        for obj in objs:
            if not isinstance(obj, dict):
                failed += 1
                continue
            t_ns = now_ns
            raw_t = obj.get(ts_field)
            if raw_t is not None:
                try:
                    # ints stay exact: routing through float would corrupt
                    # ns-precision epochs above 2^53
                    t_int = raw_t if isinstance(raw_t, int) else int(float(raw_t))
                    t_ns = t_int * mult
                except (TypeError, ValueError):
                    failed += 1
                    continue
            tags = dict(log_tags)
            fields = {}
            content_parts = []
            for k, v in obj.items():
                if k == ts_field or k in discard:
                    continue
                if k in tag_fields:
                    tags[k] = str(v)
                elif isinstance(v, bool):
                    fields[k] = (FieldType.BOOL, v)
                elif isinstance(v, (int, float)):
                    fields[k] = (FieldType.FLOAT, float(v))
                elif isinstance(v, str):
                    fields[k] = (FieldType.STRING, v)
                else:  # nested objects/arrays: flatten into content
                    content_parts.append(f"{k}={json.dumps(v, sort_keys=True)}")
            if "content" not in fields:
                # every log row carries content: full-text terms and
                # histogram counts key off it (reference default log schema,
                # handler_logstore.go getDefaultSchemaForLog)
                base = " ".join(content_parts)
                if not base:
                    base = json.dumps(
                        {k: v for k, v in obj.items() if k != ts_field},
                        sort_keys=True,
                    )
                fields["content"] = (FieldType.STRING, base)
            points.append((ls, tuple(sorted(tags.items())), t_ns, fields))
        if not points:
            h._send_json(400, {"error": "no valid log lines", "failed": failed})
            return
        if self.svc.router is not None:
            n = self.svc.router.routed_write(repo, ls, points)
        else:
            n = self.svc.engine.write_rows(repo, points, rp=ls)
        h._send_json(200, {"success": True, "written": n, "failed": failed})

    # -- query ---------------------------------------------------------------

    def _time_range(self, params) -> tuple[int, int]:
        """from/to in ms (reference QueryLogRequest), defaults last hour."""
        now_ms = _time.time_ns() // NS_PER_MS
        frm = int(params.get("from", now_ms - 3_600_000))
        to = int(params.get("to", now_ms))
        return frm * NS_PER_MS, to * NS_PER_MS

    def _run_select(self, h, repo, ls, where: str | None, tmin: int, tmax: int,
                    order_desc: bool, limit: int, user) -> list[dict] | None:
        """SELECT * over the logstream through the standard executor;
        returns row dicts (timestamp ns + tags + fields) or None after an
        error response."""
        conds = [f"time >= {tmin}", f"time < {tmax}"]
        if where:
            conds.append(where)
        q = (
            f'SELECT * FROM "{repo}"."{ls}"."{ls}" WHERE '
            + " AND ".join(conds)
            + " GROUP BY * ORDER BY time "
            + ("DESC" if order_desc else "ASC")
            + f" LIMIT {limit}"
        )
        res = self.svc.executor.execute(
            q, db=repo, user=None if user is True else user
        )
        stmt = res["results"][0]
        if "error" in stmt:
            h._send_json(400, {"error": stmt["error"]})
            return None
        rows = []
        for series in stmt.get("series", []):
            cols = series["columns"]
            tags = series.get("tags") or {}
            for vals in series["values"]:
                row = dict(tags)
                for c, v in zip(cols, vals):
                    if v is None:
                        continue
                    row["timestamp" if c == "time" else c] = v
                rows.append(row)
        rows.sort(key=lambda r: r.get("timestamp", 0), reverse=order_desc)
        return rows[:limit]

    def _query_logs(self, h, method, repo, ls, params):
        user = self._auth(h, params, "READ", repo)
        if user is None:
            return
        t0 = _time.perf_counter()
        lq = logparser.parse_log_query(params.get("q", ""))
        aliases = set(lq.aliases)
        where = logparser.to_influxql_where(lq.cond, aliases)
        tmin, tmax = self._time_range(params)
        limit = max(1, min(int(params.get("limit", 10)), _MAX_LIMIT))
        reverse = params.get("reverse", "true").lower() != "false"
        # scroll cursor: "<ns>:<k>" = k rows already served AT exactly <ns>
        skip_at, cur_t = 0, None
        scroll_id = params.get("scroll_id", "")
        if scroll_id:
            try:
                a, _, b = scroll_id.partition(":")
                cur_t, skip_at = int(a), int(b)
            except ValueError:
                h._send_json(400, {"error": "bad scroll_id"})
                return
            # a crafted skip component must not defeat the page cap (fetch
            # = limit + skip_at becomes the engine LIMIT below); ties at
            # one timestamp beyond 10x the max page size are not a thing
            # a legitimate cursor can produce
            if not (0 <= cur_t and 0 <= skip_at <= 10 * _MAX_LIMIT):
                h._send_json(400, {"error": "bad scroll_id"})
                return
            if reverse:
                tmax = min(tmax, cur_t + 1)  # inclusive of ties at cur_t
            else:
                tmin = max(tmin, cur_t)
        fetch = limit + skip_at
        fetched = self._run_select(h, repo, ls, where, tmin, tmax, reverse,
                                   fetch, user)
        if fetched is None:
            return
        page_full = len(fetched) >= fetch
        # drop already-served ties at the cursor time
        raw = fetched
        if cur_t is not None and skip_at:
            kept, dropped = [], 0
            for r in raw:
                if dropped < skip_at and r.get("timestamp") == cur_t:
                    dropped += 1
                    continue
                kept.append(r)
            raw = kept
        # EXTRACT + alias predicates run downstream of the engine page; the
        # scroll cursor tracks progress through the RAW stream so a page
        # whose rows are mostly filtered out still advances and never
        # terminates early (complete only when the engine page ran dry)
        logparser.apply_extract(lq.extract, raw)
        if aliases:
            pred = logparser.alias_row_filter(lq.cond, aliases)
            flt = [(i, r) for i, r in enumerate(raw) if pred(r)]
        else:
            flt = list(enumerate(raw))
        if len(flt) > limit:
            flt = flt[:limit]
            consumed = raw[: flt[-1][0] + 1]
            more = True
        else:
            consumed = raw
            more = page_full and bool(raw)
        rows = [r for _i, r in flt]
        if params.get("highlight", "").lower() == "true":
            terms = _fulltext_terms(lq.cond)
            for r in rows:
                r["highlight"] = [
                    t for t in terms
                    if t.lower() in str(r.get("content", "")).lower()
                ]
        next_scroll = ""
        if more and consumed:
            last_t = consumed[-1]["timestamp"]
            ties = sum(1 for r in consumed if r["timestamp"] == last_t)
            if cur_t == last_t:
                ties += skip_at
            next_scroll = f"{last_t}:{ties}"
        for r in rows:
            r["timestamp"] = r["timestamp"] // NS_PER_MS  # ms out, like from/to
        h._send_json(200, {
            "success": True,
            "logs": rows,
            "count": len(rows),
            "scroll_id": next_scroll,
            "complete_progress": 100 if not next_scroll else 0,
            "took_ms": round((_time.perf_counter() - t0) * 1000, 2),
        })

    def _histogram(self, h, method, repo, ls, params):
        user = self._auth(h, params, "READ", repo)
        if user is None:
            return
        lq = logparser.parse_log_query(params.get("q", ""))
        if lq.extract is not None:
            h._send_json(400, {"error": "EXTRACT is not supported in histograms"})
            return
        where = logparser.to_influxql_where(lq.cond)
        tmin, tmax = self._time_range(params)
        interval_ns = _parse_interval_ns(params.get("interval", "")) or max(
            (tmax - tmin) // 60, NS_PER_MS
        )
        # whole-ms interval: GROUP BY time() below is expressed in ms, so a
        # sub-ms remainder would make reported bucket bounds drift off the
        # engine's actual buckets
        interval_ns = max(interval_ns // NS_PER_MS, 1) * NS_PER_MS
        conds = [f"time >= {tmin}", f"time < {tmax}"]
        if where:
            conds.append(where)
        q = (
            f'SELECT count(content) FROM "{repo}"."{ls}"."{ls}" WHERE '
            + " AND ".join(conds)
            + f" GROUP BY time({interval_ns // NS_PER_MS}ms) fill(0)"
        )
        res = self.svc.executor.execute(
            q, db=repo, user=None if user is True else user
        )
        stmt = res["results"][0]
        if "error" in stmt:
            h._send_json(400, {"error": stmt["error"]})
            return
        buckets, total = [], 0
        for series in stmt.get("series", []):
            for t_ns, cnt in series["values"]:
                cnt = int(cnt or 0)
                total += cnt
                buckets.append({
                    "from": t_ns // NS_PER_MS,
                    "to": (t_ns + interval_ns) // NS_PER_MS,
                    "count": cnt,
                })
        h._send_json(200, {"success": True, "histograms": buckets,
                           "count": total})

    def _context(self, h, method, repo, ls, params):
        """Rows surrounding a timestamp (reference serveContextQueryLog)."""
        user = self._auth(h, params, "READ", repo)
        if user is None:
            return
        lq = logparser.parse_log_query(params.get("q", ""))
        aliases = set(lq.aliases)
        where = logparser.to_influxql_where(lq.cond, aliases)
        try:
            ts_ms = int(params["timestamp"])
        except (KeyError, ValueError):
            h._send_json(400, {"error": "timestamp (ms) is required"})
            return
        back = max(0, min(int(params.get("backward", 10)), _MAX_LIMIT))
        fwd = max(0, min(int(params.get("forward", 10)), _MAX_LIMIT))
        ts_ns = ts_ms * NS_PER_MS
        tmin, tmax = self._time_range(params)
        before = self._run_select(h, repo, ls, where, tmin, ts_ns, True,
                                  back, user) if back else []
        if before is None:
            return
        after = self._run_select(h, repo, ls, where, ts_ns, tmax, False,
                                 fwd, user) if fwd else []
        if after is None:
            return
        rows = list(reversed(before)) + after
        logparser.apply_extract(lq.extract, rows)
        if aliases:
            pred = logparser.alias_row_filter(lq.cond, aliases)
            rows = [r for r in rows if pred(r)]
        for r in rows:
            r["timestamp"] = r["timestamp"] // NS_PER_MS
        h._send_json(200, {"success": True, "logs": rows, "count": len(rows)})

    def _analytics(self, h, method, repo, ls, params):
        """Aggregated view over logs: count/sum/mean/min/max of a field,
        grouped by a tag and/or time buckets — the device aggregation path
        (reference serveAnalytics / serveAggLogQuery)."""
        user = self._auth(h, params, "READ", repo)
        if user is None:
            return
        lq = logparser.parse_log_query(params.get("q", ""))
        if lq.extract is not None:
            h._send_json(400, {"error": "EXTRACT is not supported in analytics"})
            return
        where = logparser.to_influxql_where(lq.cond)
        tmin, tmax = self._time_range(params)
        agg = params.get("agg", "count").lower()
        if agg not in ("count", "sum", "mean", "min", "max"):
            h._send_json(400, {"error": f"unsupported agg {agg!r}"})
            return
        field = params.get("field", "content" if agg == "count" else "")
        if not re.match(r"^[A-Za-z_][A-Za-z0-9_]*$", field or ""):
            h._send_json(400, {"error": "field is required"})
            return
        groups = []
        group_by = params.get("group_by", "")
        if group_by:
            if not re.match(r"^[A-Za-z_][A-Za-z0-9_]*$", group_by):
                h._send_json(400, {"error": "bad group_by"})
                return
            groups.append(f'"{group_by}"')
        interval_ns = _parse_interval_ns(params.get("interval", ""))
        if interval_ns:
            groups.append(f"time({interval_ns // NS_PER_MS}ms)")
        conds = [f"time >= {tmin}", f"time < {tmax}"]
        if where:
            conds.append(where)
        q = (
            f'SELECT {agg}("{field}") FROM "{repo}"."{ls}"."{ls}" WHERE '
            + " AND ".join(conds)
            + (" GROUP BY " + ", ".join(groups) if groups else "")
        )
        res = self.svc.executor.execute(
            q, db=repo, user=None if user is True else user
        )
        stmt = res["results"][0]
        if "error" in stmt:
            h._send_json(400, {"error": stmt["error"]})
            return
        out = []
        for series in stmt.get("series", []):
            tags = series.get("tags") or {}
            for vals in series["values"]:
                t_ns, v = vals[0], vals[1]
                row = dict(tags)
                if interval_ns:
                    row["from"] = t_ns // NS_PER_MS
                    row["to"] = (t_ns + interval_ns) // NS_PER_MS
                row[agg] = v
                out.append(row)
        h._send_json(200, {"success": True, "analytics": out})

    # -- consumption ---------------------------------------------------------

    def _consume_logs(self, h, method, repo, ls, params):
        """Kafka-like consumption, delegated to the shared consume
        implementation (services/consume parity; same opaque cursor)."""
        p = dict(params)
        p["db"] = repo
        p["measurement"] = ls
        h._handle_consume(p)

    def _consume_cursor_time(self, h, method, repo, ls, params):
        """Map a wall-clock time (ms) to a consume cursor."""
        if self._auth(h, params, "READ", repo) is None:
            return
        try:
            frm = int(params["from"])
        except (KeyError, ValueError):
            h._send_json(400, {"error": "from (ms) is required"})
            return
        h._send_json(200, {"cursor": f"{frm * NS_PER_MS}:0"})


def _fulltext_terms(node) -> list[str]:
    out: list[str] = []

    def walk(n):
        if isinstance(n, logparser.Term) and n.op == "match" and isinstance(
            n.value, str
        ):
            out.append(n.value)
        elif isinstance(n, (logparser.And, logparser.Or)):
            for c in n.children:
                walk(c)

    if node is not None:
        walk(node)
    return out
