"""Arrow Flight surface: columnar writes (DoPut) and query results
(DoGet) as Arrow record batches.

Reference: openGemini's arrow flight service (app/ts-server arrow flight
listener + coordinator RecordWriter path, services/arrowflight) — the
high-throughput columnar ingest alternative to line protocol. Here the
batch decodes straight into the structured write path (never through
line-protocol text), and DoGet streams a statement's result series as
one Arrow table.

DoPut descriptor (JSON): {"db": ..., "rp": ..., "measurement": ...,
"tag_columns": [...]} — remaining non-time columns are fields. A
column named "time" (int64, ns) is required.
DoGet ticket (JSON): {"db": ..., "q": "SELECT ..."}.
"""

from __future__ import annotations

import json

import numpy as np

from opengemini_tpu.record import FieldType


def _require_flight():
    import pyarrow.flight as fl  # noqa: F401

    return fl


class FlightService:
    """pyarrow.flight server wrapper; start()/stop() like HttpService."""

    def __init__(self, engine, executor, host: str = "127.0.0.1",
                 port: int = 8087, users=None, auth_enabled: bool = False,
                 router=None):
        fl = _require_flight()
        self.engine = engine
        self.executor = executor
        self.users = users
        self.auth_enabled = auth_enabled
        self.router = router
        outer = self

        class _Server(fl.FlightServerBase):
            def __init__(self):
                super().__init__(f"grpc://{host}:{port}")

            def do_put(self, context, descriptor, reader, writer):
                meta = json.loads(descriptor.command or b"{}")
                user = outer._check_auth(meta)
                if user is not None and not user.can("WRITE", meta.get("db", "")):
                    raise fl.FlightUnauthorizedError("write not authorized")
                # write-path backpressure (resource governor): shed as
                # UNAVAILABLE — the flight analogue of HTTP 429 +
                # Retry-After (the window rides the message text)
                from opengemini_tpu.utils.governor import GOVERNOR

                retry_after = GOVERNOR.write_backpressure()
                if retry_after is not None:
                    raise fl.FlightUnavailableError(
                        "write backpressure: memtable+WAL backlog over the "
                        f"high watermark; retry after {retry_after}s")
                table = reader.read_all()
                outer.write_table(
                    meta.get("db", ""), meta.get("rp"),
                    meta.get("measurement", ""),
                    list(meta.get("tag_columns", [])), table,
                )

            def do_get(self, context, ticket):
                req = json.loads(ticket.ticket or b"{}")
                user = outer._check_auth(req)
                from opengemini_tpu.utils.governor import AdmissionRejected

                try:
                    table = outer.query_table(req.get("db", ""),
                                              req.get("q", ""), user=user)
                except AdmissionRejected as e:
                    # admission shed: UNAVAILABLE (flight analogue of the
                    # HTTP 503 + Retry-After)
                    raise fl.FlightUnavailableError(
                        f"{e}; retry after {e.retry_after_s}s") from None
                return fl.RecordBatchStream(table)

            def do_action(self, context, action):
                if action.type == "ping":
                    return iter([fl.Result(b"ok")])
                raise KeyError(f"unknown action {action.type!r}")

        self._server_cls = _Server
        self._server = None
        self._thread = None
        self.port = port

    def _check_auth(self, req: dict):
        """Credentials ride in the request JSON ({"u": ..., "p": ...}) —
        flight's gRPC handshake plumbing varies by pyarrow version, so the
        token travels in-band like the HTTP surface's u/p params. Returns
        the authenticated user (None when auth is off)."""
        if not self.auth_enabled:
            return None
        fl = _require_flight()
        from opengemini_tpu.meta.users import AuthError

        try:
            return self.users.authenticate(req.get("u", ""), req.get("p", ""))
        except AuthError as e:
            raise fl.FlightUnauthenticatedError(str(e)) from None

    # -- conversion --------------------------------------------------------

    def write_table(self, db: str, rp, measurement: str,
                    tag_columns: list[str], table) -> int:
        if not db or not measurement:
            raise ValueError("db and measurement are required")
        import pyarrow as pa

        names = table.column_names
        if "time" not in names:
            raise ValueError("a 'time' column (int64 ns) is required")
        tcol = table.column("time")
        if tcol.null_count:
            # a null here would cast through NaN to -2^63 and be stored as
            # a "valid" garbage timestamp
            raise ValueError("'time' column must not contain nulls")
        if not pa.types.is_integer(tcol.type):
            raise ValueError("'time' column must be integer nanoseconds")
        times = np.asarray(tcol.to_numpy(zero_copy_only=False), dtype=np.int64)
        tag_cols = {
            n: table.column(n).to_pylist() for n in tag_columns if n in names
        }
        field_names = [n for n in names
                       if n != "time" and n not in tag_columns]
        field_data = []
        for n in field_names:
            col = table.column(n)
            t = col.type
            if pa.types.is_integer(t):
                ftype = FieldType.INT
            elif pa.types.is_floating(t):
                ftype = FieldType.FLOAT
            elif pa.types.is_boolean(t):
                ftype = FieldType.BOOL
            else:
                ftype = FieldType.STRING
            field_data.append((n, ftype, col.to_pylist()))
        points = []
        for i in range(len(table)):
            tags = tuple(sorted(
                (k, str(v[i])) for k, v in tag_cols.items()
                if v[i] is not None
            ))
            fields = {}
            for n, ftype, vals in field_data:
                v = vals[i]
                if v is None:
                    continue
                if ftype == FieldType.STRING:
                    v = str(v)
                fields[n] = (ftype, v)
            if fields:
                points.append((measurement, tags, int(times[i]), fields))
        if not points:
            return 0
        if self.router is not None:
            return self.router.routed_write(db, rp, points)
        return self.engine.write_rows(db, points, rp=rp)

    def query_table(self, db: str, q: str, user=None):
        import pyarrow as pa

        # read_only like HTTP GET: the result-streaming endpoint must not
        # execute mutating statements
        res = self.executor.execute(q, db=db, user=user,
                                    read_only=True)["results"][0]
        if "error" in res:
            fl = _require_flight()
            raise fl.FlightServerError(res["error"])
        series = res.get("series", [])
        if not series:
            return pa.table({})
        # one table over the UNION of all series' columns (multi-source
        # selects differ per series) plus tag columns; a tag key that is
        # also a result column keeps the column value
        tag_keys = sorted({k for s in series for k in (s.get("tags") or {})})
        all_cols: list[str] = []
        for s in series:
            for c in s["columns"]:
                if c not in all_cols:
                    all_cols.append(c)
        out_cols = all_cols + [k for k in tag_keys if k not in all_cols]
        data: dict[str, list] = {c: [] for c in out_cols}
        for s in series:
            tags = s.get("tags") or {}
            cols = s["columns"]
            for row in s["values"]:
                rowmap = dict(zip(cols, row))
                for c in out_cols:
                    if c in rowmap:
                        data[c].append(rowmap[c])
                    else:
                        data[c].append(tags.get(c))
        return pa.table(data)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        import threading

        self._server = self._server_cls()
        self.port = self._server.port  # real bound port (supports port=0)
        self._thread = threading.Thread(
            target=self._server.serve, daemon=True, name="flight"
        )
        self._thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
