"""HTTP protocol front-end (reference: lib/util/lifted/influx/httpd)."""
