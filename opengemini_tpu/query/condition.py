"""WHERE-clause decomposition: time range, tag filter, field filter.

Reference: the reference splits conditions during plan building
(influxql.ConditionExpr / getTimeRange in lifted influx/query); here the
split is explicit: the AND-tree is walked once, each leaf classified as a
time bound (-> scan range), a tag comparison (-> inverted-index sid set),
or a field comparison (-> vectorized numpy row mask applied before device
transfer).
"""

from __future__ import annotations

import datetime as _dt
import re

import numpy as np

from opengemini_tpu.sql import ast

MIN_TIME = -(2**63) + 1
MAX_TIME = 2**63 - 1


class ConditionError(ValueError):
    pass


class SplitCondition:
    """tmin inclusive, tmax exclusive (ns); tag_expr / field_expr /
    mixed_expr are AST subtrees or None. mixed_expr holds conjuncts whose
    subtree references BOTH tags and fields (e.g. `tag = 'x' OR field > 1`
    or `tag != field`): tags can only prune a sid SUPERSET for it
    (tag_superset_sids); the exact answer needs per-row evaluation with
    the series' tag values injected as columns (eval_row_filter)."""

    def __init__(self, tmin, tmax, tag_expr, field_expr, mixed_expr=None,
                 tag_keys=frozenset()):
        self.tmin = tmin
        self.tmax = tmax
        self.tag_expr = tag_expr
        self.field_expr = field_expr
        self.mixed_expr = mixed_expr
        self.tag_keys = tag_keys
        # /*+ full_series|specific_series */: mixed_expr was consumed as a
        # series-level filter (series_only_sids) — no per-row evaluation.
        # A flag rather than nulling mixed_expr: remote peers still need
        # the expression to apply the same series-level filter.
        self.mixed_series_level = False

    @property
    def has_row_filter(self) -> bool:
        return self.field_expr is not None or (
            self.mixed_expr is not None and not self.mixed_series_level)


def split(cond, tag_keys: set[str], now_ns: int) -> SplitCondition:
    tmin, tmax = MIN_TIME, MAX_TIME
    tag_parts: list = []
    field_parts: list = []
    mixed_parts: list = []

    def walk(e):
        nonlocal tmin, tmax
        e = _strip(e)
        if e is None:
            return
        if isinstance(e, ast.BinaryExpr) and e.op == "AND":
            walk(e.lhs)
            walk(e.rhs)
            return
        if _is_time_cond(e):
            lo, hi = _time_bounds(e, now_ns)
            tmin = max(tmin, lo)
            tmax = min(tmax, hi)
            return
        refs = _collect_refs(e)
        if "time" in refs or "Time" in refs:
            # influx rejects OR'd time conditions; silently dropping them
            # would return wrong rows
            raise ConditionError(
                "time conditions must be AND-ed at the top level of WHERE"
            )
        if refs and refs <= tag_keys:
            tag_parts.append(e)
        elif refs and not (refs & tag_keys):
            field_parts.append(e)
        elif not refs:
            field_parts.append(e)  # constant condition
        else:
            # subtree mixing tags and fields (reference evaluates arbitrary
            # condition trees, lib/binaryfilterfunc functions.go:143)
            mixed_parts.append(e)

    walk(cond)
    return SplitCondition(
        tmin, tmax, _and_join(tag_parts), _and_join(field_parts),
        _and_join(mixed_parts), frozenset(tag_keys),
    )


def _and_join(parts: list):
    if not parts:
        return None
    e = parts[0]
    for p in parts[1:]:
        e = ast.BinaryExpr("AND", e, p)
    return e


def _strip(e):
    while isinstance(e, ast.ParenExpr):
        e = e.expr
    return e


def _is_time_cond(e) -> bool:
    if not isinstance(e, ast.BinaryExpr):
        return False
    lhs, rhs = _strip(e.lhs), _strip(e.rhs)
    return (isinstance(lhs, ast.VarRef) and lhs.name.lower() == "time") or (
        isinstance(rhs, ast.VarRef) and rhs.name.lower() == "time"
    )


def _collect_refs(e) -> set[str]:
    out: set[str] = set()

    def walk(x):
        x = _strip(x)
        if isinstance(x, ast.VarRef):
            out.add(x.name)
        elif isinstance(x, ast.BinaryExpr):
            walk(x.lhs)
            walk(x.rhs)
        elif isinstance(x, ast.UnaryExpr):
            walk(x.expr)
        elif isinstance(x, ast.Call):
            for a in x.args:
                walk(a)

    walk(e)
    return out


def _time_bounds(e: ast.BinaryExpr, now_ns: int) -> tuple[int, int]:
    lhs, rhs = _strip(e.lhs), _strip(e.rhs)
    op = e.op
    if isinstance(rhs, ast.VarRef) and rhs.name.lower() == "time":
        # flip: lit OP time  ->  time OP' lit
        lhs, rhs = rhs, lhs
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
    t = eval_time_expr(rhs, now_ns)
    if op == ">":
        return (t + 1, MAX_TIME)
    if op == ">=":
        return (t, MAX_TIME)
    if op == "<":
        return (MIN_TIME, t)
    if op == "<=":
        return (MIN_TIME, t + 1)
    if op == "=":
        return (t, t + 1)
    raise ConditionError(f"unsupported time operator {op!r}")


def eval_time_expr(e, now_ns: int) -> int:
    """Evaluate a time-valued expression: now(), literals, +/- arithmetic."""
    e = _strip(e)
    if isinstance(e, ast.Call) and e.name == "now":
        return now_ns
    if isinstance(e, ast.IntegerLiteral):
        return e.val  # bare integers in time context are ns
    if isinstance(e, ast.NumberLiteral):
        return int(e.val)
    if isinstance(e, ast.DurationLiteral):
        return e.val_ns
    if isinstance(e, ast.StringLiteral):
        return parse_rfc3339(e.val)
    if isinstance(e, ast.UnaryExpr) and e.op == "-":
        return -eval_time_expr(e.expr, now_ns)
    if isinstance(e, ast.BinaryExpr) and e.op in ("+", "-"):
        a = eval_time_expr(e.lhs, now_ns)
        b = eval_time_expr(e.rhs, now_ns)
        return a + b if e.op == "+" else a - b
    raise ConditionError(f"cannot evaluate time expression: {e}")


_TIME_FORMATS = [
    "%Y-%m-%dT%H:%M:%S.%fZ",
    "%Y-%m-%dT%H:%M:%SZ",
    "%Y-%m-%d %H:%M:%S.%f",
    "%Y-%m-%d %H:%M:%S",
    "%Y-%m-%d",
]


def parse_rfc3339(s: str) -> int:
    # strptime %f caps at microseconds; peel off a 7-9 digit fraction so
    # ns-precision literals ('...T00:00:00.000000001Z') parse exactly
    frac_ns = 0
    m = re.match(r"^(.*T\d\d:\d\d:\d\d)\.(\d{7,9})(Z|[+-].*)$", s)
    if m:
        digits = m.group(2)
        frac_ns = int(digits.ljust(9, "0"))
        s = m.group(1) + m.group(3)
    for fmt in _TIME_FORMATS:
        try:
            dt = _dt.datetime.strptime(s, fmt).replace(tzinfo=_dt.timezone.utc)
            return (int(dt.timestamp()) * 1_000_000_000 + dt.microsecond * 1000
                    + frac_ns)
        except ValueError:
            continue
    raise ConditionError(f"bad time string {s!r}")


def format_rfc3339(t_ns: int) -> str:
    dt = _dt.datetime.fromtimestamp(t_ns // 1_000_000_000, tz=_dt.timezone.utc)
    frac = t_ns % 1_000_000_000
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    if frac == 0:
        return base + "Z"
    s = f"{frac:09d}".rstrip("0")
    return f"{base}.{s}Z"


# -- tag filter -> sid sets --------------------------------------------------


def eval_tag_expr(expr, index, measurement: str) -> set[int]:
    """Evaluate a tags-only filter to a set of series ids via the inverted
    index (reference: engine/index/tsi/search.go tag filter search)."""
    expr = _strip(expr)
    if expr is None:
        return index.series_ids(measurement)
    if isinstance(expr, ast.BinaryExpr):
        if expr.op == "AND":
            return eval_tag_expr(expr.lhs, index, measurement) & eval_tag_expr(
                expr.rhs, index, measurement
            )
        if expr.op == "OR":
            return eval_tag_expr(expr.lhs, index, measurement) | eval_tag_expr(
                expr.rhs, index, measurement
            )
        lhs, rhs = _strip(expr.lhs), _strip(expr.rhs)
        if isinstance(rhs, ast.VarRef) and not isinstance(lhs, ast.VarRef):
            lhs, rhs = rhs, lhs
        if not isinstance(lhs, ast.VarRef):
            raise ConditionError(f"bad tag condition: {expr}")
        key = lhs.name
        if expr.op in ("=", "!=", "<>"):
            if isinstance(rhs, ast.VarRef):
                # tag-to-tag comparison (reference: `tennant = tennant`
                # matches everything, Where_With_Tags#17); distinct tags
                # compare per series
                all_sids = index.series_ids(measurement)
                if key == rhs.name:
                    return set(all_sids) if expr.op == "=" else set()
                out = set()
                for sid in all_sids:
                    tags = index.tags_of(sid)
                    same = tags.get(key) == tags.get(rhs.name)
                    if same == (expr.op == "="):
                        out.add(sid)
                return out
            if not isinstance(rhs, ast.StringLiteral):
                # tag vs non-string literal matches nothing — a typed
                # mismatch, not a statement error (reference
                # TagFilter#0: `where tag1=1` returns empty)
                return (
                    set() if expr.op == "="
                    else set(index.series_ids(measurement))
                )
            if expr.op == "=":
                return index.match_eq(measurement, key, rhs.val)
            return index.match_neq(measurement, key, rhs.val)
        if expr.op in ("=~", "!~"):
            if not isinstance(rhs, ast.RegexLiteral):
                raise ConditionError("regex comparison requires a regex")
            return index.match_regex(measurement, key, rhs.pattern, negate=expr.op == "!~")
    raise ConditionError(f"unsupported tag filter: {expr}")


def _as_sid_arr(sids) -> np.ndarray:
    """A set-returning walk result as the sorted int64 array the
    columnar composition path works in."""
    if isinstance(sids, np.ndarray):
        return sids
    if not sids:
        return np.empty(0, np.int64)
    return np.fromiter(sorted(sids), np.int64, len(sids))


def eval_tag_sids(expr, index, measurement: str) -> np.ndarray:
    """eval_tag_expr over sorted int64 sid arrays: the columnar label
    tier (index.labels) answers leaves with posting arrays and AND/OR
    compose with np.intersect1d/union1d — no per-leaf Python set
    materialization. With the tier knob-disabled the set walk runs and
    the result converts; same sids either way."""
    from opengemini_tpu.index import labels as _labels

    tier = _labels.tier_for(index)
    if tier is None:
        return _as_sid_arr(eval_tag_expr(expr, index, measurement))
    return _eval_tag_arr(expr, tier.snapshot(measurement))


def _eval_tag_arr(expr, snap) -> np.ndarray:
    expr = _strip(expr)
    if expr is None:
        return snap.sids
    if isinstance(expr, ast.BinaryExpr):
        if expr.op == "AND":
            lhs = _eval_tag_arr(expr.lhs, snap)
            if lhs.size == 0:
                return lhs
            return np.intersect1d(lhs, _eval_tag_arr(expr.rhs, snap),
                                  assume_unique=True)
        if expr.op == "OR":
            return np.union1d(_eval_tag_arr(expr.lhs, snap),
                              _eval_tag_arr(expr.rhs, snap))
        lhs, rhs = _strip(expr.lhs), _strip(expr.rhs)
        if isinstance(rhs, ast.VarRef) and not isinstance(lhs, ast.VarRef):
            lhs, rhs = rhs, lhs
        if not isinstance(lhs, ast.VarRef):
            raise ConditionError(f"bad tag condition: {expr}")
        key = lhs.name
        if expr.op in ("=", "!=", "<>"):
            if isinstance(rhs, ast.VarRef):
                return snap.match_tag_compare(key, rhs.name,
                                              expr.op == "=")
            if not isinstance(rhs, ast.StringLiteral):
                # typed mismatch matches nothing (see eval_tag_expr)
                return (np.empty(0, np.int64) if expr.op == "="
                        else snap.sids)
            if expr.op == "=":
                return snap.match_eq(key, rhs.val)
            return snap.match_neq(key, rhs.val)
        if expr.op in ("=~", "!~"):
            if not isinstance(rhs, ast.RegexLiteral):
                raise ConditionError("regex comparison requires a regex")
            return snap.match_regex(key, rhs.pattern,
                                    negate=expr.op == "!~")
    raise ConditionError(f"unsupported tag filter: {expr}")


def tag_superset_arr(expr, index, measurement: str,
                     tag_keys: set[str]) -> np.ndarray:
    """tag_superset_sids over sorted sid arrays (same widening rules)."""
    from opengemini_tpu.index import labels as _labels

    tier = _labels.tier_for(index)
    if tier is None:
        return _as_sid_arr(
            tag_superset_sids(expr, index, measurement, tag_keys))
    return _superset_arr(expr, tier.snapshot(measurement), tag_keys)


def _superset_arr(expr, snap, tag_keys: set[str]) -> np.ndarray:
    expr = _strip(expr)
    if expr is None:
        return snap.sids
    if isinstance(expr, ast.BinaryExpr):
        if expr.op == "AND":
            return np.intersect1d(_superset_arr(expr.lhs, snap, tag_keys),
                                  _superset_arr(expr.rhs, snap, tag_keys),
                                  assume_unique=True)
        if expr.op == "OR":
            return np.union1d(_superset_arr(expr.lhs, snap, tag_keys),
                              _superset_arr(expr.rhs, snap, tag_keys))
    refs = _collect_refs(expr)
    if refs and refs <= tag_keys and isinstance(expr, ast.BinaryExpr):
        lhs, rhs = _strip(expr.lhs), _strip(expr.rhs)
        for side in (lhs, rhs):
            if isinstance(side, ast.StringLiteral) and side.val == "" \
                    and expr.op == "=":
                return snap.sids
            if isinstance(side, ast.RegexLiteral) and expr.op == "=~" \
                    and re.search(side.pattern, ""):
                return snap.sids
        try:
            return _eval_tag_arr(expr, snap)
        except ConditionError:
            return snap.sids
    return snap.sids


def series_only_arr(expr, index, measurement: str,
                    tag_keys: set[str]) -> np.ndarray:
    """series_only_sids over sorted sid arrays (field leaves are empty)."""
    from opengemini_tpu.index import labels as _labels

    tier = _labels.tier_for(index)
    if tier is None:
        return _as_sid_arr(
            series_only_sids(expr, index, measurement, tag_keys))
    return _series_only_arr(expr, tier.snapshot(measurement), tag_keys)


def _series_only_arr(expr, snap, tag_keys: set[str]) -> np.ndarray:
    expr = _strip(expr)
    if expr is None:
        return snap.sids
    if isinstance(expr, ast.BinaryExpr):
        if expr.op == "AND":
            return np.intersect1d(
                _series_only_arr(expr.lhs, snap, tag_keys),
                _series_only_arr(expr.rhs, snap, tag_keys),
                assume_unique=True)
        if expr.op == "OR":
            return np.union1d(_series_only_arr(expr.lhs, snap, tag_keys),
                              _series_only_arr(expr.rhs, snap, tag_keys))
    refs = _collect_refs(expr)
    if refs and refs <= tag_keys:
        try:
            return _eval_tag_arr(expr, snap)
        except ConditionError:
            return np.empty(0, np.int64)
    return np.empty(0, np.int64)  # field leaves identify no series


def tag_superset_sids(expr, index, measurement: str, tag_keys: set[str]) -> set[int]:
    """SOUND sid superset for a mixed tag/field tree: every sid that could
    possibly satisfy the condition on some row. Field leaves (and any leaf
    the index cannot answer conservatively) widen to all sids; tag leaves
    use the inverted index. Used to prune the scan before the exact
    per-row evaluation (eval_row_filter)."""
    expr = _strip(expr)
    all_sids = index.series_ids(measurement)
    if expr is None:
        return set(all_sids)
    if isinstance(expr, ast.BinaryExpr):
        if expr.op == "AND":
            return tag_superset_sids(expr.lhs, index, measurement, tag_keys) & \
                tag_superset_sids(expr.rhs, index, measurement, tag_keys)
        if expr.op == "OR":
            return tag_superset_sids(expr.lhs, index, measurement, tag_keys) | \
                tag_superset_sids(expr.rhs, index, measurement, tag_keys)
    refs = _collect_refs(expr)
    if refs and refs <= tag_keys and isinstance(expr, ast.BinaryExpr):
        # widen when the leaf can match series MISSING the tag (which the
        # index has no posting for): `tag = ''` and regexes matching ''
        lhs, rhs = _strip(expr.lhs), _strip(expr.rhs)
        for side in (lhs, rhs):
            if isinstance(side, ast.StringLiteral) and side.val == "" \
                    and expr.op == "=":
                return set(all_sids)
            if isinstance(side, ast.RegexLiteral) and expr.op == "=~" \
                    and re.search(side.pattern, ""):
                return set(all_sids)
        try:
            return eval_tag_expr(expr, index, measurement)
        except ConditionError:
            return set(all_sids)
    return set(all_sids)


def series_only_sids(expr, index, measurement: str, tag_keys: set[str]) -> set[int]:
    """Series-level evaluation for /*+ full_series */ and
    /*+ specific_series */ hints (reference: hybrid store reader's
    series-keyed scan): the condition identifies whole series, so field
    leaves evaluate FALSE and the tag tree selects sids directly."""
    expr = _strip(expr)
    if expr is None:
        return set(index.series_ids(measurement))
    if isinstance(expr, ast.BinaryExpr):
        if expr.op == "AND":
            return series_only_sids(expr.lhs, index, measurement, tag_keys) & \
                series_only_sids(expr.rhs, index, measurement, tag_keys)
        if expr.op == "OR":
            return series_only_sids(expr.lhs, index, measurement, tag_keys) | \
                series_only_sids(expr.rhs, index, measurement, tag_keys)
    refs = _collect_refs(expr)
    if refs and refs <= tag_keys:
        try:
            return eval_tag_expr(expr, index, measurement)
        except ConditionError:
            return set()
    return set()  # field leaves identify no series


# -- field filter -> numpy mask ----------------------------------------------


def field_filter_refs(expr) -> set[str]:
    return _collect_refs(expr)


def row_filter_refs(sc: "SplitCondition") -> set[str]:
    """Storage FIELD names the row filters read: field_expr refs plus the
    non-tag refs of mixed_expr (tag refs come from the index, not chunks)."""
    refs = set()
    if sc.field_expr is not None:
        refs |= _collect_refs(sc.field_expr)
    if sc.mixed_expr is not None and not sc.mixed_series_level:
        refs |= _collect_refs(sc.mixed_expr) - set(sc.tag_keys)
    return refs


def _with_tag_columns(rec, tag_refs, tags=None, sid_arr=None, index=None):
    """Record plus the series' tag values as broadcast string columns.
    Missing tags inject as '' (influx: an absent tag compares as the
    empty string at row level). `tags` serves the per-series case;
    (sid_arr, index) the bulk case (per-row lookup via the sid column)."""
    from opengemini_tpu.record import Column, FieldType, Record

    n = len(rec)
    cols = dict(rec.columns)
    for key in tag_refs:
        if tags is not None:
            vals = np.full(n, tags.get(key, ""), dtype=object)
        else:
            uniq = np.unique(sid_arr)
            lut = {int(s): index.tags_of(int(s)).get(key, "") for s in uniq}
            vals = np.array([lut[int(s)] for s in sid_arr], dtype=object)
        cols[key] = Column(FieldType.STRING, vals, np.ones(n, dtype=np.bool_))
    return Record(rec.times, cols)


def eval_row_filter(sc: "SplitCondition", rec, tags=None, sid_arr=None,
                    index=None) -> np.ndarray:
    """Combined per-row mask: field_expr AND mixed_expr (the latter with
    the series' tags injected as columns). Callers pass `tags` (per-series
    scans) or `sid_arr` + `index` (bulk scans)."""
    if sc.field_expr is not None:
        m = eval_field_expr(sc.field_expr, rec)
    else:
        m = np.ones(len(rec), dtype=np.bool_)
    if sc.mixed_expr is not None and not sc.mixed_series_level:
        tag_refs = _collect_refs(sc.mixed_expr) & set(sc.tag_keys)
        rec2 = _with_tag_columns(rec, tag_refs, tags, sid_arr, index)
        m = m & eval_field_expr(sc.mixed_expr, rec2)
    return m


def eval_field_expr(expr, record) -> np.ndarray:
    """Vectorized row mask for a fields-only filter over a Record. Null
    (invalid) values compare false, like the reference's cond functions
    (lib/binaryfilterfunc functions.go:143)."""
    n = len(record)
    expr = _strip(expr)
    if expr is None:
        return np.ones(n, dtype=np.bool_)
    if isinstance(expr, ast.BinaryExpr):
        if expr.op == "AND":
            return eval_field_expr(expr.lhs, record) & eval_field_expr(expr.rhs, record)
        if expr.op == "OR":
            return eval_field_expr(expr.lhs, record) | eval_field_expr(expr.rhs, record)
        lhs, rhs = _strip(expr.lhs), _strip(expr.rhs)
        op = expr.op
        if isinstance(rhs, ast.VarRef) and not isinstance(lhs, ast.VarRef):
            lhs, rhs = rhs, lhs
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if isinstance(lhs, ast.VarRef) and isinstance(rhs, ast.VarRef):
            # column vs column (tag-vs-field compares arrive here with the
            # tag injected as a string column — eval_row_filter)
            a = record.columns.get(lhs.name)
            b = record.columns.get(rhs.name)
            if a is None or b is None:
                return np.zeros(n, dtype=np.bool_)
            if (a.values.dtype == object) != (b.values.dtype == object):
                return np.zeros(n, dtype=np.bool_)  # typed mismatch
            av, bv = a.values, b.values
            if av.dtype == object:
                # ordered compares on object arrays choke on None at
                # invalid rows; the mask below discards them anyway
                av = np.where(a.valid, av, "")
                bv = np.where(b.valid, bv, "")
            with np.errstate(invalid="ignore"):
                if op == "=":
                    m = av == bv
                elif op in ("!=", "<>"):
                    m = av != bv
                elif op == "<":
                    m = av < bv
                elif op == "<=":
                    m = av <= bv
                elif op == ">":
                    m = av > bv
                elif op == ">=":
                    m = av >= bv
                else:
                    raise ConditionError(f"unsupported field operator {op!r}")
            return np.asarray(m, dtype=np.bool_) & a.valid & b.valid
        if isinstance(lhs, ast.VarRef):
            col = record.columns.get(lhs.name)
            if col is None:
                return np.zeros(n, dtype=np.bool_)
            if isinstance(rhs, ast.RegexLiteral):
                rx = re.compile(rhs.pattern)
                vals = np.array(
                    [bool(rx.search(v)) if isinstance(v, str) else False for v in col.values]
                )
                m = vals if op == "=~" else ~vals
                return m & col.valid
            lit = _literal_value(rhs)
            vals = col.values
            if isinstance(lit, str) != (col.values.dtype == object):
                return np.zeros(n, dtype=np.bool_)
            with np.errstate(invalid="ignore"):
                if op == "=":
                    m = vals == lit
                elif op in ("!=", "<>"):
                    m = vals != lit
                elif op == "<":
                    m = vals < lit
                elif op == "<=":
                    m = vals <= lit
                elif op == ">":
                    m = vals > lit
                elif op == ">=":
                    m = vals >= lit
                else:
                    raise ConditionError(f"unsupported field operator {op!r}")
            return np.asarray(m, dtype=np.bool_) & col.valid
    if isinstance(expr, ast.BooleanLiteral):
        return np.full(n, expr.val, dtype=np.bool_)
    if isinstance(expr, ast.Call) and expr.name == "match":
        # full-text token match over a string field (reference: logstore
        # MATCH operator backed by the C++ text index)
        from opengemini_tpu.native.textindex import match_token

        if len(expr.args) != 2:
            raise ConditionError("match() takes (field, 'token')")
        fld = _strip(expr.args[0])
        tok = _strip(expr.args[1])
        if not isinstance(fld, ast.VarRef) or not isinstance(tok, ast.StringLiteral):
            raise ConditionError("match() takes (field, 'token')")
        col = record.columns.get(fld.name)
        if col is None:
            return np.zeros(n, dtype=np.bool_)
        return match_token(col.values, col.valid, tok.val)
    raise ConditionError(f"unsupported field filter: {expr}")


def conjunctive_match_terms(expr) -> list[tuple[str, str]]:
    """(field, token) pairs for match() calls that are top-level CONJUNCTS
    of the field filter — only those may prune series (a match under an
    OR constrains nothing on its own)."""
    expr = _strip(expr)
    if expr is None:
        return []
    if isinstance(expr, ast.BinaryExpr) and expr.op == "AND":
        return conjunctive_match_terms(expr.lhs) + conjunctive_match_terms(expr.rhs)
    if isinstance(expr, ast.Call) and expr.name == "match" and len(expr.args) == 2:
        fld, tok = _strip(expr.args[0]), _strip(expr.args[1])
        if isinstance(fld, ast.VarRef) and isinstance(tok, ast.StringLiteral):
            return [(fld.name, tok.val)]
    return []


def _literal_value(e):
    e = _strip(e)
    if isinstance(e, ast.NumberLiteral):
        return e.val
    if isinstance(e, ast.IntegerLiteral):
        return e.val
    if isinstance(e, ast.StringLiteral):
        return e.val
    if isinstance(e, ast.BooleanLiteral):
        return e.val
    if isinstance(e, ast.UnaryExpr) and e.op == "-":
        return -_literal_value(e.expr)
    raise ConditionError(f"expected literal, got {e}")


def exact_series_tags(expr, tag_keys) -> dict:
    """All tag-equality pairs appearing anywhere in a condition tree.

    The /*+ full_series */ contract (reference influxql FullSeriesQuery,
    parser.go:37): the collected pairs form the EXACT series key — a
    series carrying additional tags does not match even where the
    predicate itself holds (TestServer_Query_FullSeries: host=server01
    selects cpu,host=server01 but not cpu,host=server01,region=uswest).
    Non-tag terms (field predicates, OR branches) contribute pairs but
    never widen the match.
    """
    pairs: dict[str, str] = {}

    def walk(e):
        e = _strip(e)
        if isinstance(e, ast.BinaryExpr):
            if e.op in ("AND", "OR"):
                walk(e.lhs)
                walk(e.rhs)
                return
            lhs, rhs = _strip(e.lhs), _strip(e.rhs)
            if (
                e.op == "="
                and isinstance(lhs, ast.VarRef)
                and lhs.name in tag_keys
                and isinstance(rhs, ast.StringLiteral)
            ):
                pairs[lhs.name] = rhs.val

    if expr is not None:
        walk(expr)
    return pairs
