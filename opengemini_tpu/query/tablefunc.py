"""Table functions (reference: engine/executor/table_function_factory.go
RegistryTableFunctionOp — the registry ships one production operator,
``rca``, engine/executor/rca.go FaultDemarcation).

``rca`` is root-cause fault demarcation: given anomaly/alarm/event rows
(fields ``id``/``name``/``entity_id``/``type``/``annotations``) and an
entity topology graph, BFS outward from a core entity, expanding only
through entities whose events are time-correlated with the core
entity's anomaly timestamps, and return the implicated subgraph.

Exposed through InfluxQL as ``SELECT rca('<params json>') FROM events
WHERE time >= ... AND time < ...`` — the statement-level equivalent of
the reference's table-function plan node (logic_plan.go:3863
LogicalTableFunction). The params JSON carries what the reference
splits between AlgoParam and the graph input::

    {
      "hop_count": 2,            # BFS radius per anomalous entity
      "bfs_narrow": false,       # shrink radius to 1 after first hit
      "task": {"metadata": {"core_entity_id": "...",
                             "anomaly_entity_id": [...optional...]}},
      "topology": {"nodes": [{"uid": ..., ...}],
                    "edges": [{"source": ..., "target": ..., ...}]}
    }
"""

from __future__ import annotations

import bisect
import json

HALF_HOUR_MS = 30 * 60 * 1000
TWO_HOUR_MS = 120 * 60 * 1000


class TableFunctionError(ValueError):
    pass


def _within(target_ts: int, sorted_ts: list[int], close_ms: int) -> bool:
    """Nearest-timestamp proximity check (reference rca.go:66
    isWithinTSRange)."""
    pos = bisect.bisect_left(sorted_ts, target_ts)
    for i in (pos, pos - 1):
        if 0 <= i < len(sorted_ts) and abs(target_ts - sorted_ts[i]) <= close_ms:
            return True
    return False


def _annotations(row: dict) -> dict:
    raw = row.get("annotations", "")
    if isinstance(raw, dict):
        return raw
    try:
        got = json.loads(raw or "{}")
    except ValueError as e:
        raise TableFunctionError(f"rca: bad annotations JSON: {e}") from None
    if not isinstance(got, dict):
        raise TableFunctionError("rca: annotations must be a JSON object")
    return got


def _index_rows(rows: list[dict]) -> dict[str, list[tuple[str, dict]]]:
    """entity_id -> [(type, parsed annotations)] — one pass so the BFS's
    per-entity correlation checks are O(rows of that entity) instead of
    rescanning (and re-parsing JSON for) the whole event set."""
    idx: dict[str, list[tuple[str, dict]]] = {}
    for row in rows:
        ent = row.get("entity_id")
        if ent is None:
            continue
        idx.setdefault(str(ent), []).append((row.get("type"), _annotations(row)))
    return idx


def _is_anomaly(anomaly_ts: list[int], entity_id: str,
                row_idx: dict[str, list[tuple[str, dict]]]) -> bool:
    """Event-type-specific time correlation (reference rca.go:83
    isAnomaly): anomalies match any of their timestamps within 30min;
    alarms use start_time (30min with an end_time, 2h open-ended);
    events use end_time/start_time/create_time at 30min/2h/2h."""
    for etype, ann in row_idx.get(entity_id, []):
        if etype == "anomaly":
            ts_list = ann.get("timestamps")
            if ts_list is None:
                raise TableFunctionError("rca: timestamps not found in annotations")
            for ts in ts_list:
                if _within(int(ts), anomaly_ts, HALF_HOUR_MS):
                    return True
        elif etype == "alarm":
            start = ann.get("start_time")
            if start is None:
                raise TableFunctionError("rca: fired timestamp not found in annotations")
            close = HALF_HOUR_MS if "end_time" in ann else TWO_HOUR_MS
            if _within(int(start), anomaly_ts, close):
                return True
        elif etype == "event":
            if "end_time" in ann:
                if _within(int(ann["end_time"]), anomaly_ts, HALF_HOUR_MS):
                    return True
            elif "start_time" in ann:
                if _within(int(ann["start_time"]), anomaly_ts, TWO_HOUR_MS):
                    return True
            else:
                created = ann.get("create_time")
                if created is None:
                    raise TableFunctionError(
                        "rca: created timestamp not found in annotations"
                    )
                if _within(int(created), anomaly_ts, TWO_HOUR_MS):
                    return True
    return False


def _core_anomaly_ts(row_idx: dict[str, list[tuple[str, dict]]],
                     core_id: str, meta: dict) -> list[int]:
    """Anomaly timestamps of the core entity (reference rca.go:302
    extractCoreAnomalyTimestamps): every 'anomaly' row of the core
    entity — or of the task's anomaly_entity_id list when present.
    STRICT like _is_anomaly: an anomaly row without timestamps is an
    error here too, not silently skipped (the same row would abort the
    BFS later anyway)."""
    ids = {core_id}
    extra = meta.get("anomaly_entity_id")
    if isinstance(extra, list):
        ids.update(str(x) for x in extra)
    out: set[int] = set()
    for ent in ids:
        for etype, ann in row_idx.get(ent, []):
            if etype != "anomaly":
                continue
            ts_list = ann.get("timestamps")
            if ts_list is None:
                raise TableFunctionError("rca: timestamps not found in annotations")
            for ts in ts_list:
                out.add(int(ts))
    if not out:
        raise TableFunctionError(
            f"rca: no anomaly timestamps found for core entity {core_id!r}"
        )
    return sorted(out)


def _edge_uid(edge: dict) -> str:
    return (f"{edge.get('source')}_{edge.get('source_topo', '')}"
            f"::::{edge.get('target')}_{edge.get('target_topo', '')}")


def fault_demarcation(rows: list[dict], params: dict) -> dict:
    """The BFS core (reference rca.go:160 FaultDemarcation): walk the
    topology outward from the core entity; every time-correlated entity
    spawns a bounded sub-BFS (hop_count, default 2) whose frontier joins
    the main queue; edges into the visited set are collected once;
    bfs_narrow shrinks the radius to 1 after the first expansion."""
    task = params.get("task") or {}
    meta = task.get("metadata")
    if not isinstance(meta, dict):
        raise TableFunctionError("rca: meta not found in algoParams")
    core_id = meta.get("core_entity_id")
    if not isinstance(core_id, str):
        raise TableFunctionError("rca: core entity not found in task meta")
    topo = params.get("topology") or {}
    nodes = topo.get("nodes") or []
    edges = topo.get("edges") or []
    # hop_count 0 means "use the default radius of 2" — the reference's
    # exact rule (rca.go: `if BFSHopCount == 0 { BFSHopCount = 2 }`)
    hop_count = int(params.get("hop_count") or 0) or 2
    narrow = bool(params.get("bfs_narrow"))

    row_idx = _index_rows(rows)
    anomaly_ts = _core_anomaly_ts(row_idx, core_id, meta)
    node_idx: dict[str, list[dict]] = {}
    for n in nodes:
        node_idx.setdefault(str(n.get("uid")), []).append(n)
    by_source: dict[str, list[dict]] = {}
    by_target: dict[str, list[dict]] = {}
    for e in edges:
        by_source.setdefault(str(e.get("source")), []).append(e)
        by_target.setdefault(str(e.get("target")), []).append(e)

    edge_list: list[dict] = []
    seen_edges: set[str] = set()
    visited = {core_id}
    queue = [core_id]
    node_list = list(node_idx.get(core_id, []))
    idx = 0
    while idx < len(queue):
        cur = queue[idx]
        if not _is_anomaly(anomaly_ts, cur, row_idx):
            idx += 1
            continue
        tmp_visited = {cur}
        tmp_nodes = [cur]
        tmp_hops = [0]
        t = 0
        while t < len(tmp_nodes):
            ent = tmp_nodes[t]
            for e in by_source.get(ent, []):
                other = str(e.get("target"))
                uid = _edge_uid(e)
                if uid not in seen_edges and (other in visited or other in tmp_visited):
                    seen_edges.add(uid)
                    edge_list.append(e)
                if tmp_hops[t] < hop_count and other not in tmp_visited:
                    tmp_visited.add(other)
                    tmp_nodes.append(other)
                    tmp_hops.append(tmp_hops[t] + 1)
            for e in by_target.get(ent, []):
                other = str(e.get("source"))
                uid = _edge_uid(e)
                if uid not in seen_edges and (other in visited or other in tmp_visited):
                    seen_edges.add(uid)
                    edge_list.append(e)
                if tmp_hops[t] < hop_count and other not in tmp_visited:
                    tmp_visited.add(other)
                    tmp_nodes.append(other)
                    tmp_hops.append(tmp_hops[t] + 1)
            t += 1
        for ent in sorted(tmp_visited):
            if ent not in visited:
                node_list.extend(node_idx.get(ent, []))
                visited.add(ent)
                queue.append(ent)
        if narrow:
            hop_count = 1
        idx += 1
    return {"nodes": node_list, "edges": edge_list}


def run_rca(rows: list[dict], params_json: str) -> dict:
    try:
        params = json.loads(params_json)
    except ValueError as e:
        raise TableFunctionError(f"rca: bad params JSON: {e}") from None
    if not isinstance(params, dict):
        raise TableFunctionError("rca: params must be a JSON object")
    return fault_demarcation(rows, params)


TABLE_FUNCTIONS = {"rca": run_rca}
