"""Adaptive host/device offload planner: devobs telemetry as a
per-stage cost model.

Every host-vs-device choice in the query path used to be a hand-tuned
static gate: the device-decode transfer gates (ops/device_decode.py),
the `OGT_PROM_HOST_KERNELS` env read, the CPU host-numpy shortcut, the
mesh-overrides.  The GPU-augmented OLAP literature (arXiv:2601.19911)
makes offload a PLANNER decision fed by measured kernel and transfer
costs; TiLT (arXiv:2301.12030) amortizes compile cost over observed
query-shape recurrence.  PR 13's devobs tier already measures
everything the model needs — compile wall per (kernel, geometry),
per-site transfer throughput histograms, warm exec walls, recurrence
hit counts — so this module closes the loop:

  cost model   per (kernel, geometry) the planner keeps one record per
      candidate route (host / device / mesh): sample count, the cold
      first-run wall (carries the compile), and a warm EWMA.  Routes
      without measurements estimate from priors where the call site can
      supply them — byte volumes at the measured `device-decode` H2D
      throughput (falling back to a fixed default, which reduces the
      comparison to the exact pre-planner byte inequality) — and stay
      un-estimable otherwise.

  decision     decide() picks the route per stage:
      prior   the static gate's choice, verbatim — always while the
              incumbent route has fewer than `min_samples` samples, and
              always when the planner is off (`OGT_OFFLOAD=0`) or the
              model is cold.  A cold model makes EXACTLY the choices
              the static gates make today — bit-identically, since
              every route computes the same result (x64 parity).
      amortize a geometry that has NEVER compiled on the static
              device/mesh route stays on the host until its observed
              recurrence covers the kernel family's measured compile
              wall: compile_s <= amortize * host_cost * uses.  This is
              the production story: a million tiny dashboard queries
              never justify a ~1 s fused compile and stay on the host
              path; a recurring heavy scan covers it within a few uses,
              pays it once, and moves to the device, automatically.
              (Inert while the model is cold — no compile data, no
              override — so a cold planner still mirrors the gates.)
      explore once a geometry has recurred more than `explore_after`
              times, ONE trial of an unmeasured candidate route — gated
              by the same amortization contract against the incumbent's
              per-use cost.
      model   all candidates measured (or byte-estimable): argmin of
              estimated cost, ties to the static choice.

  observation  call sites wrap the routed stage in perf_counter and
      feed observe() — frozen planners (ctrl freeze=1) drop new samples
      and stop exploring, pinning the current model for A/B work.

  pre-warm     compile sites register zero-arg program builders per
      (kernel, geometry); prewarm_once() replays the top-K hottest
      (by inventory hits) so queries never pay first-compile inline,
      then arms the recompile tripwire via devobs.mark_warm().
      `OGT_OFFLOAD_PREWARM=1` runs sweeps on a background thread.

Decision records land in the per-query tracker (routes per stage in
/debug/queries), the bounded decision ring + model state in
/debug/device's `planner` section, and `ogt_offload_*` counters in
/metrics.  `POST /debug/ctrl?mod=offload` arms/clears/freezes and tunes
the knobs live.

Knobs (README "Adaptive offload"): OGT_OFFLOAD (0 = static gates,
bit-identical pre-planner behavior), OGT_OFFLOAD_MIN_SAMPLES,
OGT_OFFLOAD_EXPLORE_AFTER, OGT_OFFLOAD_AMORTIZE, OGT_OFFLOAD_EWMA,
OGT_OFFLOAD_RING, OGT_OFFLOAD_PREWARM, OGT_OFFLOAD_PREWARM_TOPK,
OGT_OFFLOAD_PREWARM_S.  OGT_PROM_HOST_KERNELS resolves here too (once,
ctrl-reloadable) instead of per-query in promql/engine.py.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque

from opengemini_tpu.utils import lockdep
from opengemini_tpu.utils.stats import GLOBAL as _STATS

ROUTES = ("host", "device", "mesh")

_ON = os.environ.get("OGT_OFFLOAD", "1") not in ("", "0")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# resolved ONCE at import (the satellite fix for the per-query
# os.environ read at promql/engine.py): "" = auto (CPU backend answers
# host), "1"/"0" force.  Hot-reloadable via /debug/ctrl?mod=offload.
_PROM_HOST_KERNELS = os.environ.get("OGT_PROM_HOST_KERNELS", "")

# forced route for A/B work (bench legs, forced-all-host vs
# forced-all-device): decide() answers this route whenever it is a
# candidate, and gate_prior() stands aside for it
_FORCE = os.environ.get("OGT_OFFLOAD_FORCE", "") or None

# model-state bound: past this many live (kernel, geometry) records the
# oldest is evicted (a fleet churning thousands of distinct geometries
# is exactly the workload the static priors serve fine)
_GEO_MAX = 512

# unmeasured-transfer prior: one fixed throughput for EVERY route, so a
# byte-hinted comparison with zero measurements reduces to the exact
# byte inequality the static gates used
_DEFAULT_BYTES_PER_S = 1 << 30


def enabled() -> bool:
    return _ON


def set_enabled(on: bool) -> None:
    global _ON
    _ON = bool(on)


def force_route() -> str | None:
    return _FORCE


def set_force(route: str | None) -> None:
    global _FORCE
    if route is not None and route not in ROUTES:
        raise ValueError(f"bad forced route {route!r} (want one of "
                         f"{'/'.join(ROUTES)} or none)")
    _FORCE = route


def prom_host_kernels_mode() -> str:
    """The resolved OGT_PROM_HOST_KERNELS override: "1" pins the tiled
    kernels to host numpy, "0" pins them off-host, "" auto (backend
    decides).  One mechanism: the engine's _host_kernels() static
    default AND the planner's candidate pruning both read this."""
    return _PROM_HOST_KERNELS


def set_prom_host_kernels_mode(mode: str) -> None:
    global _PROM_HOST_KERNELS
    if mode in ("auto", "none"):
        mode = ""
    if mode not in ("", "0", "1"):
        raise ValueError(f"bad host_kernels mode {mode!r} "
                         "(want 0, 1, or auto)")
    _PROM_HOST_KERNELS = mode


def geo_key(geometry) -> str:
    """Stable string key for a geometry — matches str(geometry) so the
    planner's keys line up with the devobs inventory's."""
    return str(geometry)


def _geo_cells(geometry) -> int:
    """Product of the numeric extents in a geometry (nested tuples
    flattened, non-numeric entries like dtype strings ignored) — the
    size proxy that lets one kernel-wide PER-CELL cost aggregate prior
    geometries of very different scales: a heavy scan's samples must
    not make every tiny dashboard shape look expensive."""
    n = 1
    stack = [geometry]
    while stack:
        x = stack.pop()
        if isinstance(x, (tuple, list)):
            stack.extend(x)
        elif not isinstance(x, bool) and hasattr(x, "__index__"):
            v = int(x)
            if v > 0:
                n *= v
    return n


class _Route:
    """Per-route sample record: cold first run (carries compile +
    first-touch transfer), warm EWMA of the rest."""

    __slots__ = ("count", "cold_s", "ewma_s", "last_s")

    def __init__(self) -> None:
        self.count = 0
        self.cold_s = None
        self.ewma_s = None
        self.last_s = None

    def add(self, seconds: float, alpha: float) -> None:
        seconds = max(0.0, float(seconds))
        self.count += 1
        self.last_s = seconds
        if self.count == 1:
            self.cold_s = seconds
            self.ewma_s = seconds
        elif self.count == 2:
            # the cold sample carries the compile + first-touch
            # transfers: the first WARM sample replaces it outright so
            # the warm estimate is not compile-poisoned for the next
            # hundred decisions (cold cost is amortization's job)
            self.ewma_s = seconds
        else:
            self.ewma_s = self.ewma_s * (1.0 - alpha) + seconds * alpha

    def doc(self) -> dict:
        return {
            "count": self.count,
            "cold_ms": None if self.cold_s is None
            else round(self.cold_s * 1e3, 3),
            "ewma_ms": None if self.ewma_s is None
            else round(self.ewma_s * 1e3, 3),
            "last_ms": None if self.last_s is None
            else round(self.last_s * 1e3, 3),
        }


class Planner:
    """The process-wide offload planner (GLOBAL below)."""

    def __init__(self) -> None:
        self._lock = lockdep.Lock()
        self._geo: OrderedDict[tuple, dict] = OrderedDict()
        self._kernel_routes: dict[str, dict[str, _Route]] = {}
        self._ring: deque = deque(
            maxlen=max(16, _env_int("OGT_OFFLOAD_RING", 128)))
        self._frozen = False
        self.min_samples = max(1, _env_int("OGT_OFFLOAD_MIN_SAMPLES", 2))
        self.explore_after = max(
            0, _env_int("OGT_OFFLOAD_EXPLORE_AFTER", 3))
        self.amortize = max(0.0, _env_float("OGT_OFFLOAD_AMORTIZE", 4.0))
        self.ewma = min(1.0, max(
            0.01, _env_float("OGT_OFFLOAD_EWMA", 0.3)))

    # -- knobs ----------------------------------------------------------

    def configure(self, min_samples: int | None = None,
                  explore_after: int | None = None,
                  amortize: float | None = None,
                  ewma: float | None = None) -> None:
        with self._lock:
            if min_samples is not None:
                self.min_samples = max(1, int(min_samples))
            if explore_after is not None:
                self.explore_after = max(0, int(explore_after))
            if amortize is not None:
                self.amortize = max(0.0, float(amortize))
            if ewma is not None:
                self.ewma = min(1.0, max(0.01, float(ewma)))

    def frozen(self) -> bool:
        return self._frozen

    def set_frozen(self, on: bool) -> None:
        with self._lock:
            self._frozen = bool(on)

    def clear(self) -> None:
        """Drop the model and the decision ring (ctrl clear=1, tests)."""
        with self._lock:
            self._geo.clear()
            self._kernel_routes.clear()
            self._ring.clear()

    # -- model ----------------------------------------------------------

    def _state_locked(self, kernel: str, geo: str) -> dict:
        key = (kernel, geo)
        g = self._geo.get(key)
        if g is None:
            if len(self._geo) >= _GEO_MAX:
                self._geo.popitem(last=False)
                _STATS.incr("offload", "state_evictions_total")
            g = self._geo[key] = {"uses": 0, "routes": {}}
        return g

    def _estimate_locked(self, g: dict, kernel: str, route: str,
                         bytes_hint: dict | None,
                         cells: int) -> float | None:
        """Warm per-use cost estimate for one route, best data first:
        this geometry's measurements, then a byte hint at measured
        throughput, then the kernel-wide PER-CELL aggregate scaled to
        this geometry's cell count (a new geometry of a known kernel
        inherits the family's typical per-cell cost, not the absolute
        wall of whatever scale happened to be measured first)."""
        r = g["routes"].get(route)
        if r is not None and r.count >= 1:
            return r.ewma_s
        if bytes_hint is not None and route in bytes_hint:
            return bytes_hint[route] / _measured_throughput()
        kr = self._kernel_routes.get(kernel, {}).get(route)
        if kr is not None and kr.count >= 1:
            return kr.ewma_s * cells
        return None

    def observe(self, kernel: str, geometry, route: str,
                seconds: float) -> None:
        """One measured wall sample for the routed stage.  Dropped when
        the planner is off (zero-overhead pass-through) or frozen (the
        pinned model must not drift during an A/B).  Feeds both the
        per-geometry record and the kernel-wide PER-CELL aggregate (the
        prior for geometries not yet seen)."""
        if not _ON or self._frozen:
            return
        with self._lock:
            g = self._state_locked(kernel, geo_key(geometry))
            r = g["routes"].get(route)
            if r is None:
                r = g["routes"][route] = _Route()
            r.add(seconds, self.ewma)
            kr = self._kernel_routes.setdefault(kernel, {}).get(route)
            if kr is None:
                kr = self._kernel_routes[kernel][route] = _Route()
            kr.add(seconds / _geo_cells(geometry), self.ewma)
        _STATS.incr("offload", "observations_total")

    def decide(self, kernel: str, geometry, candidates, static: str,
               stage: str | None = None,
               bytes_hint: dict | None = None) -> str:
        """Pick the route for one stage.  `static` is the pre-planner
        gate's choice and is returned verbatim whenever the planner is
        off, the model is cold, or the estimates tie — the bit-identity
        contract.  `bytes_hint` maps routes to their transfer byte
        volume when the call site knows it (the decode gates), giving
        unmeasured routes a throughput-based prior estimate."""
        if _FORCE is not None and _FORCE in candidates:
            _STATS.incr("offload", "forced_total")
            self._note_tracker(stage or kernel, _FORCE)
            return _FORCE
        if not _ON or len(candidates) <= 1:
            return static
        geo = geo_key(geometry)
        cells = _geo_cells(geometry)
        with self._lock:
            g = self._state_locked(kernel, geo)
            if not self._frozen:
                g["uses"] += 1
            uses = g["uses"]
            est = {c: self._estimate_locked(g, kernel, c, bytes_hint,
                                            cells)
                   for c in candidates}
            inc = g["routes"].get(static)
            inc_n = inc.count if inc is not None else 0
            route, reason = static, "prior"
            amort = self._amortize_locked(
                kernel, geo, g, candidates, static, est, uses)
            if amort is not None:
                route, reason = amort
            elif inc_n >= self.min_samples:
                if not self._frozen:
                    route, reason = self._explore_locked(
                        kernel, g, candidates, static, est, uses)
                if reason == "prior":
                    route, reason = self._model_locked(
                        candidates, static, est)
                if (route != "host" and route != static
                        and not self._frozen):
                    rr = g["routes"].get(route)
                    if ((rr is None or rr.count == 0)
                            and (kernel, geo) not in _pw_warm
                            and _compile_estimate_s(kernel) > 0.0):
                        # the flip away from the static host route is
                        # justified, but this geometry's device program
                        # never compiled: no query pays that first
                        # compile inline — stay on the host and hand
                        # the compile to the background pre-warmer
                        route, reason = "host", "prewarm"
            rec = {
                "kernel": kernel, "geometry": geo,
                "route": route, "reason": reason, "uses": uses,
                "est_ms": {c: None if e is None else round(e * 1e3, 3)
                           for c, e in est.items()},
            }
            if stage:
                rec["stage"] = stage
            self._ring.append(rec)
        if reason == "prewarm" and not self._frozen:
            _request_prewarm(kernel, geo)
        _STATS.incr("offload", "decisions_total")
        _STATS.incr("offload", reason + "_total")
        if route in ROUTES:
            _STATS.incr("offload", "route_" + route + "_total")
        self._note_tracker(stage or kernel, route)
        return route

    def _amortize_locked(self, kernel, geo, g, candidates, static, est,
                         uses):
        """Up-front amortization for a geometry that has NEVER run on
        the static device/mesh route: its first run pays the kernel
        family's measured compile wall, so stay on the host until the
        observed recurrence covers it (C <= amortize x per-use x uses)
        — and even then, stay on the host until the BACKGROUND
        pre-warmer has compiled the program ("prewarm"): no query ever
        pays a first compile inline.  Returns None to let the normal
        prior/explore/model flow decide: when the static route is the
        host, when the geometry already compiled (its first sample
        exists, or the pre-warmer marked it warm), or when the model is
        truly cold (no compile data anywhere — the bit-identity
        contract says a cold planner must mirror the static gates
        exactly)."""
        if static == "host" or "host" not in candidates:
            return None
        r = g["routes"].get(static)
        if r is not None and r.count >= 1:
            return None
        comp = _compile_estimate_s(kernel)
        if comp <= 0.0:
            return None
        if (kernel, geo) in _pw_warm:
            return None
        per_use = est.get("host")
        if per_use is None:
            # No host data yet for this kernel: assume a 1ms host run.
            # The very first amortize->host decision produces a real
            # host sample, so this default decides one routing at most.
            per_use = 1e-3
        if comp > self.amortize * max(per_use, 1e-9) * uses:
            return "host", "amortize"
        return "host", "prewarm"

    def _explore_locked(self, kernel, g, candidates, static, est, uses):
        """ONE trial of the least-sampled unmeasured candidate — gated
        on recurrence (uses > explore_after) and on the amortization
        contract: the candidate's predicted first-run overhead (the
        kernel-family compile wall measured by devobs) spread over the
        observed recurrence must stay within `amortize` x the
        incumbent's per-use cost.  No compile data -> no predicted
        overhead -> recurrence alone gates the trial."""
        if uses <= self.explore_after:
            return static, "prior"
        under = [c for c in candidates
                 if c != static
                 and (g["routes"].get(c) is None
                      or g["routes"][c].count < self.min_samples)]
        if not under:
            return static, "prior"
        inc_est = est.get(static)
        if inc_est is None:
            return static, "prior"
        under.sort(key=lambda c: (g["routes"][c].count
                                  if c in g["routes"] else 0))
        cand = under[0]
        first_cost = (0.0 if cand == "host"
                      else _compile_estimate_s(kernel))
        if first_cost > self.amortize * max(inc_est, 1e-9) * uses:
            _STATS.incr("offload", "explore_deferred_total")
            return static, "prior"
        return cand, "explore"

    def _model_locked(self, candidates, static, est):
        """Argmin of estimated cost over the estimable candidates; ties
        (and an un-estimable field) resolve to the static choice."""
        best, best_e = static, est.get(static)
        if best_e is None:
            return static, "prior"
        for c in candidates:
            e = est.get(c)
            if e is not None and e < best_e:
                best, best_e = c, e
        return best, "model"

    @staticmethod
    def _note_tracker(stage: str, route: str) -> None:
        from opengemini_tpu.utils.querytracker import GLOBAL as _TRACKER

        _TRACKER.note_route(_TRACKER.current_qid(), stage, route)

    # -- the static decode gates, as zero-sample priors ------------------

    def gate_prior(self, kernel: str, geometry, device_bytes: int,
                   host_bytes: int, route: str = "device") -> bool:
        """The device-decode cost gates, subsumed: with NO measured
        samples for `route` on this (kernel, geometry) this is EXACTLY
        the pre-planner byte inequality (ship encoded iff the encoded
        transfer undercuts the decoded buffer it replaces).  Once the
        route has real wall samples, decide() owns the choice and the
        byte rule stops second-guessing it — one mechanism, not two."""
        if _FORCE == route:
            return True
        if _ON:
            with self._lock:
                g = self._geo.get((kernel, geo_key(geometry)))
                r = g["routes"].get(route) if g is not None else None
                if r is not None and r.count >= 1:
                    return True
        ok = int(device_bytes) < int(host_bytes)
        if not ok:
            _STATS.incr("offload", "gate_vetoes_total")
        return ok

    # -- introspection ---------------------------------------------------

    def decisions(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in reversed(self._ring)]

    def model_snapshot(self, limit: int = 64) -> list[dict]:
        with self._lock:
            rows = sorted(self._geo.items(),
                          key=lambda kv: -kv[1]["uses"])[:limit]
            return [
                {"kernel": k, "geometry": geo, "uses": g["uses"],
                 "routes": {r: st.doc() for r, st in g["routes"].items()}}
                for (k, geo), g in rows
            ]

    def debug_doc(self) -> dict:
        """The `planner` section of GET /debug/device."""
        return {
            "enabled": _ON,
            "frozen": self._frozen,
            "knobs": {
                "min_samples": self.min_samples,
                "explore_after": self.explore_after,
                "amortize": self.amortize,
                "ewma": self.ewma,
                "prom_host_kernels": _PROM_HOST_KERNELS or "auto",
                "force": _FORCE or "none",
            },
            "counters": _STATS.counters("offload"),
            "model": self.model_snapshot(),
            "decisions": self.decisions(),
            "prewarm": prewarm_status(),
        }


def _measured_throughput() -> float:
    """Measured device H2D throughput (bytes/s) across the armed
    per-site histograms, defaulting so unmeasured comparisons reduce to
    the plain byte inequality."""
    try:
        from opengemini_tpu.utils.stats import histograms_snapshot

        by_site: dict[tuple, list] = {}
        for name, labels, snap in histograms_snapshot():
            if name in ("device_h2d_bytes", "device_h2d_seconds"):
                by_site.setdefault(labels, [0.0, 0.0])
                if name == "device_h2d_bytes":
                    by_site[labels][0] += snap["sum_ns"]
                else:
                    by_site[labels][1] += snap["sum_ns"] / 1e9
        nbytes = sum(v[0] for v in by_site.values())
        secs = sum(v[1] for v in by_site.values())
        if nbytes > 0 and secs > 1e-6:
            return nbytes / secs
    except Exception:  # noqa: BLE001 — a broken estimate is no estimate
        pass
    return float(_DEFAULT_BYTES_PER_S)


def _compile_estimate_s(kernel: str) -> float:
    """Predicted first-compile wall for a kernel family, from the devobs
    inventory's measured walls (prefix match: the planner's
    `grid_decode` label covers the `grid_decode_fused` /
    `grid_decode_imat` compile sites).  0.0 with no data — recurrence
    alone gates exploration then."""
    if not kernel:
        return 0.0
    from opengemini_tpu.utils import devobs

    walls = []
    for k, doc in devobs.inventory().items():
        if not k.startswith(kernel):
            continue
        walls.extend(g["wall_ms"] for g in doc["geometries"]
                     if g["wall_ms"] > 0)
    if not walls:
        return 0.0
    return (sum(walls) / len(walls)) / 1e3


GLOBAL = Planner()


# -- pre-warmer ---------------------------------------------------------------

_pw_lock = lockdep.Lock()
_builders: OrderedDict[tuple, object] = OrderedDict()
_BUILDERS_MAX = 256
_pw_thread: threading.Thread | None = None
_pw_stop = threading.Event()
_pw_last: dict = {}
# flip-justified geometries move host -> device through these three
# states: the planner WANTS the compile (decide() said the recurrence
# covers it), a kick is INFLIGHT on a background thread, the key is
# WARM (program compiled; decide() may now route to the device without
# an inline first-compile).  Reads are GIL-atomic set membership; all
# transitions happen under _pw_lock.
_pw_want: set = set()
_pw_inflight: set = set()
_pw_warm: set = set()


def geometry_warm(kernel: str, geometry) -> bool:
    """Whether the pre-warmer has compiled this (kernel, geometry) —
    the planner only flips a never-run geometry onto the device once
    this is true, so no query ever pays the first compile inline."""
    return (kernel, geo_key(geometry)) in _pw_warm


def wants_prewarm(kernel: str, geometry) -> bool:
    """Whether decide() flagged this (kernel, geometry) as
    flip-justified but has no builder yet.  Call sites that can build
    the device program cheaply (the plan is already in hand) check this
    after a "host" decision and register_builder() — which kicks the
    background compile immediately."""
    key = (kernel, geo_key(geometry))
    with _pw_lock:
        return (key in _pw_want and key not in _pw_inflight
                and key not in _pw_warm)


def _request_prewarm(kernel: str, geo: str) -> None:
    """decide() said the recurrence covers the compile: kick the
    background compile if a builder is registered, else leave the want
    flag for the call site (wants_prewarm -> register_builder)."""
    key = (kernel, geo)
    with _pw_lock:
        if key in _pw_warm or key in _pw_inflight:
            return
        builder = _builders.get(key)
        if builder is None:
            _pw_want.add(key)
            return
        _pw_want.discard(key)
        _pw_inflight.add(key)
    _spawn_kick(key, builder)


def _spawn_kick(key: tuple, builder) -> None:
    def run():
        try:
            builder()
        except Exception:  # noqa: BLE001 — an advisory compile; the
            pass           # geometry just stays on the host route
        else:
            _pw_warm.add(key)
            _STATS.incr("offload", "prewarm_compiles_total")
        finally:
            with _pw_lock:
                _pw_inflight.discard(key)

    threading.Thread(target=run, name="offload-prewarm-kick",
                     daemon=True).start()


def register_builder(kernel: str, geometry, builder) -> None:
    """Register the zero-arg program builder for one (kernel, geometry)
    so the pre-warmer can compile it off the query path.  Builders are
    idempotent (the compile sites' lru_caches make re-invocation a hit);
    the registry is bounded and keeps the most recent geometries.  A
    builder arriving for a key decide() already flagged flip-justified
    (wants_prewarm) kicks its background compile right away."""
    key = (kernel, geo_key(geometry))
    kick = False
    with _pw_lock:
        _builders.pop(key, None)
        _builders[key] = builder
        while len(_builders) > _BUILDERS_MAX:
            _builders.popitem(last=False)
        if (key in _pw_want and key not in _pw_inflight
                and key not in _pw_warm):
            _pw_want.discard(key)
            _pw_inflight.add(key)
            kick = True
    if kick:
        _spawn_kick(key, builder)
    if os.environ.get("OGT_OFFLOAD_PREWARM", "") in ("1", "true"):
        start_prewarmer()


def prewarm_once(topk: int | None = None) -> list[dict]:
    """One sweep: rank the registered builders by devobs inventory hit
    counts, compile the top-K, then mark the tripwire warm — queries
    arriving after the sweep must not compile these geometries inline.
    Returns the (kernel, geometry, ok) records of what ran."""
    from opengemini_tpu.utils import devobs

    if topk is None:
        topk = max(1, _env_int("OGT_OFFLOAD_PREWARM_TOPK", 4))
    hits: dict[tuple, int] = {}
    for k, doc in devobs.inventory().items():
        for g in doc["geometries"]:
            hits[(k, g["geometry"])] = hits.get(
                (k, g["geometry"]), 0) + g["hits"]
    with _pw_lock:
        ranked = sorted(_builders.items(),
                        key=lambda kv: -hits.get(kv[0], 0))[:topk]
    ran = []
    for (kernel, geo), builder in ranked:
        rec = {"kernel": kernel, "geometry": geo,
               "hits": hits.get((kernel, geo), 0), "ok": True}
        try:
            builder()
            _STATS.incr("offload", "prewarm_compiles_total")
            _pw_warm.add((kernel, geo))
        except Exception as e:  # noqa: BLE001 — one bad builder must
            rec["ok"] = False    # not starve the rest of the sweep
            rec["error"] = f"{type(e).__name__}: {e}"
        ran.append(rec)
    devobs.mark_warm()
    with _pw_lock:
        _pw_last.clear()
        _pw_last.update(ran=len(ran),
                        ok=sum(1 for r in ran if r["ok"]))
    return ran


def start_prewarmer(interval_s: float | None = None) -> bool:
    """Start the background sweep thread (idempotent).  Returns whether
    a new thread started."""
    global _pw_thread
    if interval_s is None:
        interval_s = max(0.2, _env_float("OGT_OFFLOAD_PREWARM_S", 5.0))
    with _pw_lock:
        if _pw_thread is not None and _pw_thread.is_alive():
            return False
        _pw_stop.clear()

        def run():
            while not _pw_stop.wait(interval_s):
                try:
                    prewarm_once()
                except Exception:  # noqa: BLE001 — the warmer is advisory
                    pass

        _pw_thread = threading.Thread(
            target=run, name="offload-prewarm", daemon=True)
        _pw_thread.start()
    return True


def stop_prewarmer() -> None:
    global _pw_thread
    _pw_stop.set()
    t = _pw_thread
    if t is not None:
        t.join(timeout=2)
    _pw_thread = None


def prewarm_status() -> dict:
    with _pw_lock:
        return {
            "registered": len(_builders),
            "warm": len(_pw_warm),
            "wanted": len(_pw_want),
            "inflight": len(_pw_inflight),
            "thread_alive": (_pw_thread is not None
                             and _pw_thread.is_alive()),
            "last": dict(_pw_last),
        }


def reset() -> None:
    """Test hygiene: model, ring, builders, frozen flag, and the resolved
    host-kernels override back to the environment's answer."""
    global _PROM_HOST_KERNELS, _FORCE
    GLOBAL.clear()
    GLOBAL.set_frozen(False)
    stop_prewarmer()
    with _pw_lock:
        _builders.clear()
        _pw_last.clear()
        _pw_want.clear()
        _pw_inflight.clear()
        _pw_warm.clear()
    _PROM_HOST_KERNELS = os.environ.get("OGT_PROM_HOST_KERNELS", "")
    _FORCE = os.environ.get("OGT_OFFLOAD_FORCE", "") or None
