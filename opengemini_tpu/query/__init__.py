"""Query planning + execution (reference: engine/executor, 64k LoC Go).

The reference executes a DAG of goroutine transforms streaming chunks; the
TPU-native design instead compiles each query shape into a jitted segmented
-reduction graph (the plan-template idea, engine/executor/select.go:121
SqlPlanTemplate) and runs the scan->group->reduce stage as one device
program per (aggregate, shape) template.
"""
