"""Statement executor: AST -> scan -> device reduce -> InfluxDB JSON rows.

The single-node equivalent of the reference's StatementExecutor
(lifted/influx/coordinator/statement_executor.go:206) + executor.Select
(engine/executor/select.go:52) + the store-side cursor/agg stack
(engine/iterators.go, aggregate_cursor.go): shard mapping, index search,
chunk scan with pre-agg skipping, then one jitted segmented-reduction
program per aggregate (models/templates.py), then fill/limit/format.

Results use influx wire shape:
    {"results": [{"statement_id": 0, "series": [
        {"name": ..., "tags": {...}, "columns": [...], "values": [[...]]}]}]}
Times in values are int ns; the HTTP layer formats RFC3339/epoch.
"""

from __future__ import annotations

import contextlib
import math
import os
import re
import threading as _threading
from opengemini_tpu.utils import lockdep
import time as _time
from dataclasses import dataclass

import numpy as np

from opengemini_tpu.models import ragged, templates
from opengemini_tpu.ops import aggregates as aggmod
from opengemini_tpu.parallel import cluster as pcluster
from opengemini_tpu.ops import window as winmod
from opengemini_tpu.query import condition as cond
from opengemini_tpu.query import functions as fnmod
from opengemini_tpu.record import (EncodedColumn, FieldType,
                                   FieldTypeConflict)
from opengemini_tpu.sql import ast
from opengemini_tpu.storage import colcache as colcache_mod
from opengemini_tpu.storage import scanpool
from opengemini_tpu.storage.shard import FileQuarantined
from opengemini_tpu.storage.tsf import CorruptFile
from opengemini_tpu.meta.users import AuthError as _AuthError
from opengemini_tpu.storage.engine import WriteError
from opengemini_tpu.utils import devobs
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.governor import GOVERNOR
from opengemini_tpu.utils.querytracker import (GLOBAL as TRACKER,
                                               QueryKilled, redact as _redact)
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.sql.parser import parse

from opengemini_tpu.query.qhelpers import *  # noqa: F401,F403 — split helpers (VERDICT r3 #7)
from opengemini_tpu.query.qhelpers import (  # noqa: F401
    NS, MAX_SELECT_BUCKETS, QueryError,
)
from opengemini_tpu.query.hostpath import HostPathMixin
from opengemini_tpu.query.showddl import ShowDdlMixin
from opengemini_tpu.query.subquery import SubqueryMixin


@dataclass
class ScanContext:
    """Output of the shared select prologue (_scan_context)."""

    sc: object
    shards: list
    tmin: int
    tmax: int
    schema: dict
    tag_keys: set
    group_time: object
    aligned: int
    W: int
    group_tags: list
    group_keys: list
    scan_plan: list
    live: list | None = None  # cluster live set pinned by the remote round





def pick_batch(schema, agg_names, field: str, dtype, grid_ctx=None):
    """Batch implementation for one field given the aggregate names that
    will run on it. With a GROUP BY time() context (`grid_ctx` =
    (W, every_ns)), dense-capable aggregates try the regular-grid
    windows-on-lanes batch first (models/grid.py — the fastest layout,
    with built-in fallback when the scanned data is not constant-stride);
    otherwise they use the ragged->dense bucketed batch (~100x over
    scatter on TPU, models/ragged.py); rank-based ones
    (percentile/median/count_distinct) keep the lexsort AggBatch. Shared
    by the local aggregate path and the data-node partial computation
    (query/partials.py) so both sides pick identical numerics."""
    from opengemini_tpu.models import grid as _grid
    from opengemini_tpu.models import ragged as _ragged
    from opengemini_tpu.models import templates as _templates

    if (
        schema.get(field) == FieldType.INT
        and all(n in _ragged.INT_EXACT_AGGS for n in agg_names)
        and any(n in ("sum", "mean") for n in agg_names)
    ):
        # int64-exact host path: float compute would corrupt ints beyond
        # the mantissa (2^24 on-TPU f32). count alone is value-independent
        # and stays on the fast device path.
        return _ragged.IntExactBatch()
    # NOTE: a configured device mesh no longer reroutes dense-capable
    # aggregates to AggBatch — the grid and bucketed layouts themselves go
    # multi-chip by sharding their independent row axes (zero-collective
    # GSPMD partitioning, distributed.shard_leading_axis), so multi-chip
    # keeps the 62-160+ G rows/s dense kernels instead of the scatter
    # family. AggBatch's shard_map path still serves its own cases.
    if (
        grid_ctx is not None
        and not os.environ.get("OGTPU_DISABLE_GRID")  # A/B knob (bench.py)
        and schema.get(field) in (FieldType.FLOAT, FieldType.INT)
        and all(n in _grid.GRID_AGGS for n in agg_names)
    ):
        return _grid.GridBatch(dtype, grid_ctx[0], grid_ctx[1])
    if all(n in _ragged.DENSE_AGGS for n in agg_names):
        return _ragged.BucketedBatch(dtype)
    return _templates.AggBatch(dtype)




# sliced-scan tuning: slice when the estimated scan exceeds this many
# rows; each slice targets this many rows (bounds the dense grid well
# under models/grid._MAX_GRID_CELLS and overlaps decode with compute)
SLICE_THRESHOLD_ROWS = int(os.environ.get("OGTPU_SLICE_THRESHOLD", "0")) \
    or 24_000_000
SLICE_TARGET_ROWS = int(os.environ.get("OGTPU_SLICE_TARGET", "0")) \
    or 2_000_000


def _plan_scan_slices(shards, mst, scan_plan, aligned, every_ns, W,
                      tmin, tmax):
    """Window-aligned slice plan [(w0, W_s, lo, hi)] covering
    [tmin, tmax), or None when the scan is small enough to run in one
    pass. Row counts come from chunk metadata (no decode)."""
    total_rows = 0
    total_chunks = 0
    for sh in shards:
        approx = getattr(sh, "approx_rows", None)
        if approx is None:
            return None  # remote/duck-typed shard: no cheap estimate
        r, c = approx(mst, tmin, tmax)
        total_rows += r
        total_chunks += c
    if total_rows < SLICE_THRESHOLD_ROWS:
        return None
    rows_per_window = max(total_rows // W, 1)
    # plain target-based width. Chunk-span-aligned slices were tried and
    # measured SLOWER at 1B (512s vs 373s warm): the decoded-column LRU
    # already amortizes adjacent-slice re-decodes of a straddling chunk,
    # while wider slices pay real grid-assembly and merge costs.
    W_s = max(int(SLICE_TARGET_ROWS // rows_per_window), 1)
    if W_s >= W:
        return None
    n_slices = -(-W // W_s)
    if total_chunks * n_slices > max(total_rows // 64, 65536):
        # every slice re-sweeps the chunk metadata: with many tiny
        # chunks that sweep would dominate the decode it saves (the
        # budget still admits billion-row scans over ~64k-row chunks:
        # 15k chunks x 500 slices = 7.6M sweeps vs 15.6M allowed)
        return None
    plan = []
    w0 = 0
    while w0 < W:
        ws = min(W_s, W - w0)
        lo = aligned + w0 * every_ns
        hi = aligned + (w0 + ws) * every_ns
        plan.append((w0, ws, max(lo, tmin), min(hi, tmax)))
        w0 += ws
    return plan


def _device_scan_token(db, rp, mst, sc, group_time, group_tags, all_tags,
                       tmin, tmax, aligned, W, dtype, scan_ranges, shards):
    """Scan signature for the decoded-column cache's device tier
    (storage/colcache.py): everything that determines a GridBatch's
    assembled (values, mask) grids — the statement's non-time shape (like
    resultcache.fingerprint), the resolved time geometry, the actually
    scanned ranges (the incremental cache may shrink them per execution),
    and every shard's (path, data_version).  data_version bumps on any
    logical-content change (writes, deletes, rewrites) but not on
    flush/compact, whose merged reads are bit-identical by construction —
    the same trust the incremental result cache is built on.  Returns
    None when any shard lacks the versioning contract (remote proxies)."""
    import json as _json

    from opengemini_tpu.sql import astjson

    sigs = []
    for sh in shards:
        ver = getattr(sh, "data_version", None)
        path = getattr(sh, "path", None)
        if ver is None or path is None:
            return None
        sigs.append((path, ver))
    return _json.dumps(
        [
            db, rp or "", mst,
            astjson.to_json(sc.tag_expr),
            astjson.to_json(sc.field_expr),
            astjson.to_json(sc.mixed_expr),
            bool(sc.mixed_series_level),
            group_time.every_ns, group_time.offset_ns,
            list(group_tags), bool(all_tags),
            tmin, tmax, aligned, W, str(dtype),
            [list(r) for r in scan_ranges], sorted(sigs),
        ],
        separators=(",", ":"),
    )


class _ScanStager:
    """Batched column materialization for the per-series scan tail: the
    serial loop fed each tiny per-series record into the device batches
    one add() at a time — at high cardinality that is hundreds of
    thousands of numpy slivers the batch freeze must re-concatenate.
    The stager accumulates the per-record column views and flushes ONE
    contiguous array set per field (values cast once on the big array),
    preserving the exact row order of the serial path so results are
    bit-identical.  Record boundaries are forwarded to batches that want
    them (GridBatch run detection) — per-shard sid numbering is
    independent, so equal sid values from different shards must not fuse
    into one stride run."""

    def __init__(self, needed_fields, dtype, batches, time_aggs,
                 time_segs, time_vals, aligned):
        self.needed_fields = needed_fields
        self.dtype = dtype
        self.batches = batches
        self.time_aggs = time_aggs
        self.time_segs = time_segs
        self.time_vals = time_vals
        self.aligned = aligned
        # shared per-record arrays: [(times, seg, sid)]
        self._recs: list[tuple] = []
        # field -> [(record index, values|None, mask)]
        self._per_field: dict[str, list] = {f: [] for f in needed_fields}

    def add(self, rec, seg, fmask, sid):
        if self.time_aggs:
            m = fmask if fmask is not None else slice(None)
            self.time_segs.append(seg[m])
            self.time_vals.append(rec.times[m])
        ri = len(self._recs)
        self._recs.append((rec.times, seg, sid))
        for fname in self.needed_fields:
            col = rec.columns.get(fname)
            if col is None:
                continue
            m = col.valid if fmask is None else (col.valid & fmask)
            batch = self.batches[fname]
            if isinstance(batch, ragged.IntExactBatch):
                vals = col.values  # int64 end-to-end, no float cast
            elif col.ftype == FieldType.STRING:
                vals = None  # count-only payload: zeros at flush
            elif (isinstance(col, EncodedColumn)
                    and hasattr(batch, "add_encoded")):
                # still-attached raw blocks (record.EncodedColumn, decoded
                # or not): keep the view — flush composes one encoded
                # column per field so the grid freeze's offload planner
                # (query/offload.py) decides host-vs-device per query
                vals = col
            else:
                vals = col.values  # cast once per flush, not per record
            self._per_field[fname].append((ri, vals, m))

    def _gather(self, rec_idx):
        """(times, seg, sids, rel, boundaries) over the given records —
        concatenated ONCE and shared by every field present in all
        records (the common schema-complete case)."""
        times = np.concatenate([self._recs[i][0] for i in rec_idx])
        seg = np.concatenate([self._recs[i][1] for i in rec_idx])
        sids = np.concatenate([
            np.full(len(self._recs[i][0]), self._recs[i][2], np.int64)
            for i in rec_idx])
        lens = np.asarray(
            [len(self._recs[i][0]) for i in rec_idx], np.int64)
        return times, seg, sids, times - self.aligned, np.cumsum(lens)[:-1]

    def flush(self):
        shared = None  # lazy: only fields present in EVERY record share
        all_idx = list(range(len(self._recs)))
        for fname, entries in self._per_field.items():
            if not entries:
                continue
            batch = self.batches[fname]
            rec_idx = [e[0] for e in entries]
            if rec_idx == all_idx:
                if shared is None:
                    shared = self._gather(all_idx)
                times, seg, sids, rel, bounds = shared
            else:
                times, seg, sids, rel, bounds = self._gather(rec_idx)
            mask = np.concatenate([e[2] for e in entries])
            if all(isinstance(v, EncodedColumn) for _ri, v, _m in entries):
                # every record kept its raw blocks: compose ONE encoded
                # row-run view for the whole flush and hand it to the
                # batch's encoded path — the freeze's offload planner
                # routes it, and any host fallback decodes through the
                # shared roots (bit-identical).  A composition overflow
                # (run cap) drops to the copying path below.
                merged = entries[0][1]
                for _ri, v, _m in entries[1:]:
                    merged = merged.concat(v)
                    if not isinstance(merged, EncodedColumn):
                        break
                if isinstance(merged, EncodedColumn):
                    batch.add_encoded(merged, rel, seg, mask, times,
                                      sids=sids, boundaries=bounds)
                    self._per_field[fname] = []
                    continue
            # value payloads dispatch PER RECORD, exactly like the serial
            # _add_record_to_batches: a field may be numeric in one shard
            # and string (None marker -> zero payload) in another
            parts = [
                np.zeros(len(self._recs[ri][0]), dtype=self.dtype)
                if v is None
                else (v.values if isinstance(v, EncodedColumn) else v)
                for ri, v, _m in entries
            ]
            vals = parts[0] if len(parts) == 1 else np.concatenate(parts)
            if not isinstance(batch, ragged.IntExactBatch):
                vals = vals.astype(self.dtype)
            if getattr(batch, "accepts_boundaries", False):
                batch.add(vals, rel, seg, mask, times, sids=sids,
                          boundaries=bounds)
            else:
                batch.add(vals, rel, seg, mask, times, sids=sids)
            self._per_field[fname] = []
        self._recs = []


def _stitch_sliced(sliced_out, spec, params, field_name, num_groups, W,
                   num_segments):
    """Combine per-slice run() outputs into the global segment arrays.
    Window-aligned slices make every (group, window) segment live in
    exactly one slice, so stitching is pure placement — no cross-slice
    combine for ANY per-window aggregate. sel is not stitched: selector
    timestamps are only consulted without GROUP BY time(), and slicing
    requires GROUP BY time()."""
    out = counts = None
    for w0, W_s, sbatches in sliced_out:
        b = sbatches[field_name]
        if b.n == 0:
            continue
        if getattr(b, "supports_want_sel", False):
            o, _sel, c = b.run(spec, num_groups * W_s, params,
                               want_sel=False)
        else:
            o, _sel, c = b.run(spec, num_groups * W_s, params)
        if out is None:
            out = np.zeros(num_segments, dtype=o.dtype)
            counts = np.zeros(num_segments, dtype=c.dtype)
        out.reshape(num_groups, W)[:, w0:w0 + W_s] = \
            o.reshape(num_groups, W_s)
        counts.reshape(num_groups, W)[:, w0:w0 + W_s] = \
            c.reshape(num_groups, W_s)
    if out is None:
        out = np.zeros(num_segments, dtype=np.float64)
        counts = np.zeros(num_segments, dtype=np.int64)
    return out, None, counts


_READONLY_STMTS = (
    ast.SelectStatement,
    ast.UnionStatement,
    ast.ShowDatabases,
    ast.ShowMeasurements,
    ast.ShowTagKeys,
    ast.ShowTagValues,
    ast.ShowFieldKeys,
    ast.ShowSeries,
    ast.ShowRetentionPolicies,
    ast.ShowContinuousQueries,
    ast.ShowUsers,
    ast.ShowGrants,
    ast.ShowMeasurementCardinality,
    ast.ShowSeriesCardinality,
    ast.ShowSeriesExactCardinality,
    ast.ShowShards,
    ast.ShowStats,
    ast.ShowDiagnostics,
    ast.ShowStreams,
    ast.ShowSubscriptions,
    ast.ShowQueries,
    ast.ShowModels,
)



def _is_readonly(stmt) -> bool:
    if isinstance(stmt, ast.ExplainStatement):
        # EXPLAIN ANALYZE executes the inner select — INTO would mutate
        return stmt.select is None or stmt.select.into is None
    if not isinstance(stmt, _READONLY_STMTS):
        return False
    # SELECT ... INTO mutates
    return not (isinstance(stmt, ast.SelectStatement) and stmt.into is not None)




class Executor(ShowDdlMixin, SubqueryMixin, HostPathMixin):
    def __init__(self, engine, users=None, auth_enabled: bool = False,
                 meta_store=None):
        from opengemini_tpu.meta.users import UserStore

        self.engine = engine
        self.users = users if users is not None else UserStore(
            os.path.join(engine.root, "users.json")
        )
        self.auth_enabled = auth_enabled
        # when clustered, database/RP/user DDL replicates through raft
        self.meta_store = meta_store
        # multi-node data plane (parallel/cluster.DataRouter): peers serve
        # raw columns, aggregation stays on this node's device
        self.router = None
        # serializes leader-side user DDL: check-then-propose must not race
        # across HTTP threads (duplicate CREATE USER would silently replace
        # the first user's credentials)
        self._user_ddl_lock = lockdep.Lock()
        # incremental GROUP BY time() result cache (query/resultcache.py)
        from opengemini_tpu.query.resultcache import IncrementalCache

        self._inc_cache = IncrementalCache()
        # per-thread stack of CTE names being expanded (cycle detection)
        self._cte_state = _threading.local()


    def execute(
        self, text: str, db: str = "", now_ns: int | None = None,
        read_only: bool = False, user=None,
    ) -> dict:
        """read_only=True (HTTP GET) rejects mutating statements — influx
        1.x requires POST for anything but SELECT/SHOW. `user` is the
        authenticated user when auth is enabled (privilege checks)."""
        if now_ns is None:
            now_ns = _time.time_ns()
        try:
            stmts = parse(text)
        except ValueError as e:
            return {"results": [{"statement_id": 0, "error": f"error parsing query: {e}"}]}
        STATS.incr("executor", "queries")
        # admission control (utils/governor.py): may raise
        # AdmissionRejected, which the HTTP layer maps to 503 +
        # Retry-After and flight to UNAVAILABLE — deliberately NOT a
        # statement error in a 200.  Pass-through (no lock, no wait)
        # when the governor is disabled.
        # t0 BEFORE admit(): a query that spent 5s in the admission
        # queue and 10ms executing is slow BY 5s — the slow log must see
        # client-perceived duration or overload (its prime use case)
        # escapes capture, and admission_wait could exceed duration_ms
        t0 = _time.perf_counter_ns()
        token = GOVERNOR.admit()
        qid = None
        trace = None
        try:
            qid = TRACKER.register(text, db)
            if token.waited_ns:
                # attribute the admission wait like any other query stage
                # (shows in /debug/queries stages and /debug/vars
                # query_stages — the trace-span channel)
                TRACKER.add_stage_ns(qid, "admission_wait", token.waited_ns)
                tracing.record_stage("admission_wait", token.waited_ns)
            if tracing.trace_enabled():
                # per-query span tree (OGT_TRACE=1): activated thread-
                # locally so deep callees — cluster RPC fan-out, the
                # partials path — attach spans and wire ctx without a
                # parameter threaded through every signature
                trace = tracing.Trace("query")
                trace.root.add_field("statement", _redact(text))
                trace.root.add_field("database", db)
                TRACKER.set_trace(qid, trace)
                with tracing.activate(trace):
                    return self._execute_statements(
                        stmts, db, now_ns, read_only, user)
            return self._execute_statements(stmts, db, now_ns, read_only, user)
        finally:
            dur_ns = _time.perf_counter_ns() - t0
            if trace is not None:
                trace.finish()
                tracing.note_finished(qid, trace, {"database": db})
            from opengemini_tpu.utils.slowlog import GLOBAL as SLOWLOG

            if SLOWLOG.enabled():
                # capture BEFORE unregister: the stage attribution map
                # lives on the running-query entry
                SLOWLOG.note(qid, text, db, dur_ns / 1e6, trace=trace,
                             stages=TRACKER.stages_of(qid))
            if qid is not None:
                TRACKER.unregister(qid)
            token.release()


    def _execute_statements(self, stmts, db, now_ns, read_only, user) -> dict:
        results = []
        for i, stmt in enumerate(stmts):
            try:
                # a killed query must not run its REMAINING statements
                # either (the next one might be destructive DDL)
                TRACKER.check()
                if read_only and not _is_readonly(stmt):
                    raise QueryError(
                        f"{type(stmt).__name__} queries must be sent via POST"
                    )
                if self.auth_enabled:
                    if len(self.users) == 0:
                        # bootstrap: ONLY creating the first admin is open
                        if not (isinstance(stmt, ast.CreateUser) and stmt.admin):
                            raise _AuthError(
                                "create an admin user first: CREATE USER <name> "
                                "WITH PASSWORD '<pw>' WITH ALL PRIVILEGES"
                            )
                    else:
                        self._authorize(stmt, user, db)
                if self.engine.read_disabled and isinstance(
                    stmt, (ast.SelectStatement, ast.ExplainStatement)
                ):
                    raise QueryError("reads are disabled (syscontrol)")
                res = self.execute_statement(stmt, db, now_ns, user=user)
            except (
                QueryError, cond.ConditionError, KeyError, ValueError,
                re.error, FieldTypeConflict, WriteError, QueryKilled,
                FileQuarantined,
            ) as e:
                # _AuthError deliberately NOT caught: authorization failures
                # must surface as HTTP 401/403, not statement errors in a 200.
                # FileQuarantined IS caught: the detecting query fails as a
                # clean per-statement error (the file is already out of the
                # read set; a retry succeeds) instead of a dropped connection
                res = {"error": str(e)}
            res["statement_id"] = i
            results.append(res)
        return {"results": results}


    def _authorize(self, stmt, user, db: str) -> None:
        """Privilege checks (reference: httpd auth + meta user privileges).
        READ for selects/shows, WRITE for SELECT INTO, admin for DDL and
        user management; SET PASSWORD allowed for self."""
        from opengemini_tpu.meta.users import AuthError

        if user is None:
            raise AuthError("authorization required")
        if user.admin:
            return
        if isinstance(stmt, ast.SetPassword) and stmt.name == user.name:
            return
        if isinstance(stmt, ast.ShowDatabases):
            return  # any authenticated user; rows are filtered to
            # authorized dbs in execute_statement (influx semantics)
        select = None
        if isinstance(stmt, ast.ExplainStatement):
            select = stmt.select
        elif isinstance(stmt, ast.SelectStatement):
            select = stmt
        elif isinstance(stmt, ast.UnionStatement):
            for sel in stmt.selects:
                self._authorize(sel, user, db)
            return
        if select is not None:
            # READ must hold on EVERY source database — including
            # per-source overrides (FROM "otherdb"..m) and subquery inner
            # sources — not just the request's db param; WRITE likewise on
            # the INTO target's own database.
            for sdb in sorted(self._select_source_dbs(select, db)):
                if not user.can("READ", sdb):
                    raise AuthError(f"user {user.name!r} lacks READ on {sdb!r}")
            # checked on the SELECT itself whether it arrived bare or
            # wrapped in EXPLAIN [ANALYZE] — analyze executes the write
            if select.into is not None:
                tdb = select.into.database or db
                if not user.can("WRITE", tdb):
                    raise AuthError(f"user {user.name!r} lacks WRITE on {tdb!r}")
            return
        if isinstance(
            stmt,
            (ast.ShowMeasurements, ast.ShowTagKeys, ast.ShowTagValues,
             ast.ShowFieldKeys, ast.ShowSeries, ast.ShowRetentionPolicies,
             ast.ShowContinuousQueries, ast.ShowMeasurementCardinality,
             ast.ShowSeriesCardinality, ast.ShowSeriesExactCardinality),
        ):
            if user.can("READ", getattr(stmt, "database", "") or db):
                return
            raise AuthError(f"user {user.name!r} lacks READ on {db!r}")
        raise AuthError(f"user {user.name!r} is not authorized (admin required)")


    @staticmethod
    def _select_source_dbs(select, default_db: str) -> set:
        """Every database a SELECT reads from, recursing into subqueries."""
        dbs = set()

        seen: set[int] = set()

        def walk(s):
            if s is None or id(s) in seen:
                return
            seen.add(id(s))
            if isinstance(s, ast.UnionStatement):
                for sel in s.selects:
                    walk(sel)
                return
            if not s.sources:
                dbs.add(default_db)
            for src in s.sources:
                walk_src(src, s)
            walk_cond(s.condition)

        def walk_src(src, owner):
            if isinstance(src, ast.SubQuery):
                walk(src.stmt)
            elif isinstance(src, ast.JoinSource):
                walk_src(src.left, owner)
                walk_src(src.right, owner)
            elif owner.ctes and src.name in owner.ctes:
                walk(owner.ctes[src.name])
            else:
                dbs.add(src.database or default_db)

        def walk_cond(e):
            if e is None:
                return
            if isinstance(e, ast.InSubquery):
                walk(e.stmt)
            elif isinstance(e, ast.BinaryExpr):
                walk_cond(e.lhs)
                walk_cond(e.rhs)
            elif isinstance(e, (ast.ParenExpr, ast.UnaryExpr)):
                walk_cond(e.expr)

        walk(select)
        return dbs


    def _explain(self, stmt: ast.ExplainStatement, db: str, now_ns: int) -> dict:
        """EXPLAIN [ANALYZE] SELECT (reference:
        executeExplainAnalyzeStatement, statement_executor.go:943)."""
        sel = stmt.select
        if stmt.analyze:
            trace = tracing.Trace("EXPLAIN ANALYZE")
            # activated so cluster RPCs under the analyze run carry wire
            # ctx and replica subtrees stitch into THIS tree
            with tracing.activate(trace):
                self._select(sel, db, now_ns, trace=trace)
            trace.finish()
            lines = trace.render()
            return _series_result(
                "", None, ["EXPLAIN ANALYZE"], [[line] for line in lines]
            )
        # EXPLAIN: describe the plan without executing (same validation
        # as _select so the output never lies about a missing database)
        lines = []
        path = {
            "raw": "RAW SCAN (host merge)",
            "device": "DEVICE SEGMENTED REDUCTION (jit plan template)",
            "host": "HOST FUNCTION PIPELINE",
        }[_classify_select(sel)]
        for src in sel.sources:
            if isinstance(src, ast.SubQuery):
                raise QueryError("subqueries are not supported yet")
            src_db = src.database or db
            if not src_db:
                raise QueryError("database name required")
            if src_db not in self.engine.databases:
                raise QueryError(f"database not found: {src_db}")
            names = self._resolve_measurements(src, src_db)
            for mst in names:
                ctx = self._scan_context(sel, src_db, src.rp or None, mst, now_ns)
                lines.append(f"QUERY PLAN for {mst}: {path}")
                if ctx is None:
                    lines.append("    no matching shards/series")
                    continue
                lines.append(f"    shards: {len(ctx.shards)}")
                lines.append(f"    series: {len(ctx.scan_plan)}")
                lines.append(f"    groups: {len(ctx.group_keys)}  windows: {ctx.W}")
                lines.append(
                    f"    time range: [{ctx.tmin}, {ctx.tmax})  "
                    f"segments: {len(ctx.group_keys) * ctx.W}"
                )
        return _series_result("", None, ["QUERY PLAN"], [[line] for line in lines])


    def _select(self, stmt: ast.SelectStatement, db: str, now_ns: int,
                trace=tracing.NOOP) -> dict:
        if trace is tracing.NOOP:
            # adopt the per-query tree the executor activated (OGT_TRACE);
            # EXPLAIN ANALYZE passes its own trace explicitly
            trace = tracing.current()
        stmt = self._rewrite_in_subqueries(stmt, db, now_ns)
        if stmt is None:
            return {}  # IN (empty subquery result): no rows can match
        if len(stmt.fields) == 1:
            only = _strip_expr(stmt.fields[0].expr)
            if isinstance(only, ast.Call) and only.name == "compare":
                return self._select_compare(stmt, only, db, now_ns)
            from opengemini_tpu.query import tablefunc as tfmod

            if isinstance(only, ast.Call) and only.name in tfmod.TABLE_FUNCTIONS:
                return self._select_table_function(stmt, only, db, now_ns)
        # constant (string-literal) columns: allowed only WITH an alias
        # and only alongside at least one variable field (reference
        # TestServer_Query_Constant_Column; error text matches)
        n_const = 0
        for f in stmt.fields:
            if isinstance(_strip_expr(f.expr), ast.StringLiteral):
                if not f.alias:
                    raise QueryError("field must contain at least one variable")
                n_const += 1
        if n_const == len(stmt.fields):
            return {}  # only constants: empty result, no error
        multi = self._multi_source_plan(stmt, db)
        if multi == "rewrite":
            # aggregates over multiple sources run on the UNION of rows
            # (reference: count(age) FROM mst,mst1 = one combined count,
            # TestServer_Query_MultiMeasurements) — rewrite as the same
            # select over a raw SELECT * subquery spanning every source
            import copy as _copy

            inner = ast.SelectStatement(
                fields=[ast.Field(expr=ast.Wildcard())],
                sources=list(stmt.sources),
                ctes=stmt.ctes,
            )
            outer = _copy.copy(stmt)
            outer.sources = [ast.SubQuery(inner)]
            return self._select(outer, db, now_ns, trace)
        all_series = []
        for src in stmt.sources:
            if isinstance(src, ast.JoinSource):
                from opengemini_tpu.query import join as joinmod

                all_series.extend(
                    joinmod.select_join(self, stmt, src, db, now_ns)
                )
                continue
            if (isinstance(src, ast.Measurement) and stmt.ctes
                    and src.name in stmt.ctes):
                all_series.extend(
                    self._select_cte(stmt, src, db, now_ns, trace)
                )
                continue
            if isinstance(src, ast.SubQuery):
                all_series.extend(
                    self._select_from_subquery(stmt, src, db, now_ns, trace)
                )
                continue
            src_db = src.database or db
            if not src_db:
                raise QueryError("database name required")
            if src_db not in self.engine.databases:
                raise QueryError(f"database not found: {src_db}")
            names = self._resolve_measurements(src, src_db)
            for mst in names:
                with trace.span(f"select: {mst}"):
                    all_series.extend(
                        self._select_measurement(
                            stmt, src_db, src.rp or None, mst, now_ns, trace
                        )
                    )
        if multi == "merge":
            all_series = _merge_multi_source(all_series, stmt)
        # SLIMIT/SOFFSET over series
        if stmt.soffset:
            all_series = all_series[stmt.soffset :]
        if stmt.slimit:
            all_series = all_series[: stmt.slimit]
        if stmt.into is not None:
            written = self._write_into(stmt.into, db, all_series)
            return _series_result("result", None, ["time", "written"], [[0, written]])
        if not all_series:
            return {}
        return {"series": all_series}


    def _multi_source_plan(self, stmt, db: str) -> str | None:
        """How a multi-source FROM combines (reference
        TestServer_Query_MultiMeasurements: sources UNION into one series
        named 'mst,mst1'):
          - None: single effective source (or joins/CTEs — their own
            machinery), no combining
          - 'merge': raw projection — evaluate per source, merge output
            series by tagset (name-joined, column-unioned, rows coalesced)
          - 'rewrite': aggregates — re-run as agg over a raw SELECT *
            subquery so the aggregation sees the UNION of rows
        """
        srcs = stmt.sources
        if any(isinstance(s, ast.JoinSource) for s in srcs):
            return None
        if any(isinstance(s, ast.Measurement) and stmt.ctes
               and s.name in stmt.ctes for s in srcs):
            return None
        n_effective = 0
        for s in srcs:
            if isinstance(s, ast.SubQuery):
                n_effective += 1
            elif isinstance(s, ast.Measurement):
                if s.regex:
                    try:
                        n_effective += len(
                            self._resolve_measurements(s, s.database or db)
                        )
                    except Exception:  # noqa: BLE001 — resolution errors surface later
                        n_effective += 1
                else:
                    n_effective += 1
        if n_effective <= 1:
            return None
        if _classify_select(stmt) == "raw":
            return "merge"
        if len(srcs) <= 1:
            # a single regex source with aggregates keeps per-measurement
            # series (influx semantics); only EXPLICIT multi-source
            # aggregates union their rows
            return None
        # already inside the rewrite's own inner (SELECT * is raw) can't
        # reach here; anything aggregating combines via the union rewrite
        return "rewrite"


    def _select_cte(self, stmt, src: ast.Measurement, db: str, now_ns: int,
                    trace=tracing.NOOP) -> list[dict]:
        """FROM <cte-name>: execute the WITH binding as a subquery, with
        cycle detection (reference error text: CTE_Query expectations)."""
        name = src.name
        active = getattr(self._cte_state, "active", None)
        if active is None:
            active = self._cte_state.active = set()
        if name in active:
            raise QueryError(
                f"Unsupported feature: recursive call to itself {name}")
        active.add(name)
        try:
            sub = ast.SubQuery(stmt.ctes[name], alias=src.alias or name)
            return self._select_from_subquery(stmt, sub, db, now_ns, trace)
        finally:
            active.discard(name)


    def _rewrite_in_subqueries(self, stmt, db: str, now_ns: int):
        """Replace `<ref> IN (SELECT ...)` predicates with OR-chains of
        equalities against the subquery's first output column.  Returns
        None when an IN set is empty (the predicate can never match)."""
        if stmt.condition is None or not _has_in_subquery(stmt.condition):
            return stmt
        import copy

        empty = []

        def resolve(e, under_or=False):
            if isinstance(e, ast.InSubquery):
                # CTE refs inside the IN-subquery resolve with cycle checks
                res = self._select(e.stmt, db, now_ns)
                values = []
                seen = set()
                for s in res.get("series", []):
                    for row in s.get("values", []):
                        if len(row) < 2 or row[1] is None:
                            continue
                        if row[1] not in seen:
                            seen.add(row[1])
                            values.append(row[1])
                if not values:
                    if under_or:
                        # an always-false leaf under OR must not erase the
                        # other branch; no representable false leaf exists
                        # in the condition machinery yet
                        raise QueryError(
                            "IN (empty subquery result) under OR is not supported")
                    empty.append(True)
                    return e
                out = None
                for v in values:
                    if isinstance(v, bool):
                        lit = ast.BooleanLiteral(v)
                    elif isinstance(v, (int,)):
                        lit = ast.IntegerLiteral(v)
                    elif isinstance(v, float):
                        lit = ast.NumberLiteral(v)
                    else:
                        lit = ast.StringLiteral(str(v))
                    eq = ast.BinaryExpr("=", e.ref, lit)
                    out = eq if out is None else ast.BinaryExpr("OR", out, eq)
                return out
            if isinstance(e, ast.BinaryExpr):
                sub_or = under_or or e.op.upper() == "OR"
                return ast.BinaryExpr(
                    e.op, resolve(e.lhs, sub_or), resolve(e.rhs, sub_or))
            if isinstance(e, ast.ParenExpr):
                return ast.ParenExpr(resolve(e.expr, under_or))
            if isinstance(e, ast.UnaryExpr):
                return ast.UnaryExpr(e.op, resolve(e.expr, True))
            return e

        new_cond = resolve(stmt.condition)
        if empty:
            return None
        stmt = copy.copy(stmt)
        stmt.condition = new_cond
        return stmt


    def _select_compare(self, stmt, call, db: str, now_ns: int) -> dict:
        """compare(ref, off...): evaluate the source over the WHERE range
        and over each range shifted back by `off` seconds (or a duration),
        align rows by (tags, time+off), and emit ref1..refN plus
        ref1/refK ratio columns (reference: openGemini compare UDF,
        TestServer_Query_Compare_Functions)."""
        import copy as _copy
        from dataclasses import replace as _dc_replace

        if len(call.args) < 2:
            raise QueryError(
                "invalid number of arguments for compare, expected more "
                f"than one arguments, got {len(call.args)}")
        ref_e = _strip_expr(call.args[0])
        if not isinstance(ref_e, ast.VarRef):
            raise QueryError("compare() first argument must be a column")
        ref = ref_e.name
        offsets = []
        for a in call.args[1:]:
            v = _call_param_value(a)
            # bare integers are seconds; durations come in as ns
            offsets.append(int(v) * NS if isinstance(v, int) and
                           not isinstance(_strip_expr(a), ast.DurationLiteral)
                           else int(v))
        if not stmt.sources:
            raise QueryError("compare() requires a FROM source")
        src = stmt.sources[0]
        if isinstance(src, ast.SubQuery):
            inner = src.stmt
        elif isinstance(src, ast.Measurement):
            # raw field compare: first(field) over the range
            inner = ast.SelectStatement(
                fields=[ast.Field(ast.Call("first", (ast.VarRef(ref),)),
                                  alias=ref)],
                sources=[src],
            )
            inner.ctes = stmt.ctes
        else:
            raise QueryError("compare() source must be a measurement or subquery")

        sc = cond.split(stmt.condition, set(), now_ns)
        if sc.tmin == cond.MIN_TIME or sc.tmax == cond.MAX_TIME:
            raise QueryError("compare() requires an explicit time range")

        runs = []
        for off in [0] + offsets:
            bound = ast.BinaryExpr(
                "AND",
                ast.BinaryExpr(">=", ast.VarRef("time"),
                               ast.IntegerLiteral(sc.tmin - off)),
                ast.BinaryExpr("<", ast.VarRef("time"),
                               ast.IntegerLiteral(sc.tmax - off)),
            )
            run_inner = _copy.copy(inner)
            gt = getattr(run_inner, "group_by_time", None)
            if gt is not None and not gt.offset_ns:
                # openGemini anchors compare() windows at the (shifted)
                # RANGE START, not the epoch grid: the reference output
                # rows carry tmin-aligned times
                # (TestServer_Query_Compare_Functions#10). A NON-ZERO
                # user GROUP BY time offset is respected; an explicit 0s
                # offset is indistinguishable from the default in the AST
                # and re-anchors too (InfluxQL treats the forms
                # identically).
                run_inner.group_by_time = _dc_replace(
                    gt, offset_ns=(sc.tmin - off) % gt.every_ns)
            run_stmt = ast.SelectStatement(
                fields=[ast.Field(ast.VarRef(ref))],
                sources=[ast.SubQuery(run_inner)],
                condition=bound,
                group_by_all_tags=True,
            )
            run_stmt.ctes = stmt.ctes
            res = self._select(run_stmt, db, now_ns)
            data: dict[tuple, dict[int, object]] = {}
            name = "compare"
            for ser in res.get("series", []):
                name = ser.get("name", name)
                key = tuple(sorted((ser.get("tags") or {}).items()))
                bucket = data.setdefault(key, {})
                ci = ser["columns"].index(ref) if ref in ser["columns"] else 1
                for row in ser["values"]:
                    if row[ci] is not None:
                        bucket[row[0] + off] = row[ci]
            runs.append((name, data))

        src_name = runs[0][0] if runs else "compare"
        all_keys = sorted({k for _n, d in runs for k in d})
        k_runs = len(runs)
        columns = (["time"] + [f"{ref}{i+1}" for i in range(k_runs)]
                   + [f"{ref}1/{ref}{i+1}" for i in range(1, k_runs)])
        out_series = []
        for key in all_keys:
            times = sorted({t for _n, d in runs for t in d.get(key, {})})
            rows = []
            for t in times:
                vals = [d.get(key, {}).get(t) for _n, d in runs]
                ratios = []
                for i in range(1, k_runs):
                    a, b = vals[0], vals[i]
                    ratios.append(
                        a / b if a is not None and b not in (None, 0) else None)
                rows.append([t] + vals + ratios)
            if not rows:
                continue
            series = {"name": src_name, "columns": columns, "values": rows}
            if key:
                series["tags"] = dict(key)
            out_series.append(series)
        return {"series": out_series} if out_series else {}


    def _resolve_measurements(self, src: ast.Measurement, db: str) -> list[str]:
        if src.name:
            return [src.name]
        rx = re.compile(src.regex)
        shards = self.engine.shards_for_range(db, src.rp or None, cond.MIN_TIME, cond.MAX_TIME)
        names = set()
        for sh in shards:
            for m in sh.measurements():
                if rx.search(m):
                    names.add(m)
        if self.router is not None:
            try:
                remote = self.router.remote_measurements(db, src.rp or None)
            except Exception as e:  # noqa: BLE001
                raise QueryError(str(e)) from e
            names.update(m for m in remote if rx.search(m))
        return sorted(names)


    def _measurement_schema(self, db, rp, mst) -> dict:
        schema: dict = {}
        for sh in self.engine.shards_for_range(db, rp, cond.MIN_TIME, cond.MAX_TIME):
            schema.update(sh.schema(mst))
        return schema


    def _select_measurement(self, stmt, db, rp, mst, now_ns, trace=tracing.NOOP) -> list[dict]:
        if _has_call_wildcard(stmt):
            stmt = _expand_call_wildcards(
                stmt, self._measurement_schema(db, rp, mst)
            )
        # percentile_approx: answered from chunk histogram sketches
        if len(stmt.fields) == 1:
            only = _strip_expr(stmt.fields[0].expr)
            if isinstance(only, ast.Call) and only.name == "percentile_approx":
                return self._select_percentile_approx(
                    stmt, db, rp, mst, now_ns, only
                )
        aux_plan = _selector_aux_plan(stmt)
        if aux_plan is not None:
            return self._select_selector_aux(stmt, db, rp, mst, now_ns, aux_plan)
        kind = _classify_select(stmt)
        if kind == "device" and _needs_string_host_path(
            stmt, lambda: self._measurement_schema(db, rp, mst)
        ):
            # first/last/etc on STRING fields: the device batch layout is
            # numeric; the host path computes them exactly
            kind = "host"
        if kind == "raw":
            return self._select_raw(stmt, db, rp, mst, now_ns)
        if kind == "device":
            return self._select_agg(
                stmt, db, rp, mst, now_ns, _collect_calls(stmt.fields), trace
            )
        return self._select_host(stmt, db, rp, mst, now_ns)

    # -- shared scan planning ----------------------------------------------


    def _all_shards_with_remote(self, db, rp, mst, condition, now_ns,
                                remote_mode="raw"):
        """Local shards + remote representation from peer data nodes
        (when clustered routing is on). remote_mode:
          "raw"  — RemoteShard row proxies (full column exchange);
          "meta" — one MetaShard carrying remote tag keys / schema /
                   extent only; the rows stay put and arrive later as
                   per-(group, window) partials (aggregate pushdown).
        Returns (shards, live_node_list | None)."""
        shards = self.engine.shards_for_range(db, rp, cond.MIN_TIME, cond.MAX_TIME)
        live = None
        if self.router is not None:
            from opengemini_tpu.parallel.cluster import MetaShard

            pre = cond.split(condition, set(), now_ns)
            try:
                if remote_mode == "meta":
                    meta, live = self.router.select_meta(
                        db, rp, mst, pre.tmin, pre.tmax
                    )
                    remote = []
                    if meta is not None and meta["dmin"] is not None:
                        remote = [MetaShard(
                            mst, meta["tag_keys"], meta["schema"],
                            meta["dmin"], meta["dmax"],
                        )]
                else:
                    remote, live = self.router.scan_shards(
                        db, rp, mst, pre.tmin, pre.tmax
                    )
            except pcluster.PartialsUnavailable:
                # a live peer rejected the metadata round (governor
                # shed / rolling upgrade): propagate so the pushdown
                # driver falls back to the raw column exchange instead
                # of flattening this into a hard QueryError
                raise
            except Exception as e:  # noqa: BLE001 — partial data = wrong data
                raise QueryError(str(e)) from e
            if self.router.rf > 1:
                # replicated groups: keep only those WE are primary for
                # among the live set; replicas held here would double-count
                shards = [
                    sh for sh in shards
                    if self.router.is_primary(db, rp, sh.tmin, live)
                ]
            shards = shards + remote
        return shards, live


    def _scan_context(self, stmt, db, rp, mst, now_ns, remote_mode="raw"):
        """Shared prologue of every select path: schema/tag keys, WHERE
        split, shard mapping, data-driven range clamp, window grid, group
        construction (reference: the Prepare + MapShards steps,
        SURVEY.md §3.2). Returns None when nothing matches."""
        if self.engine.is_measurement_dropped(db, mst):
            return None  # mark-deleted: hidden from SELECT pre-purge
        shards_all, live = self._all_shards_with_remote(
            db, rp, mst, stmt.condition, now_ns, remote_mode
        )
        tag_keys: set[str] = set()
        schema: dict[str, FieldType] = {}
        for sh in shards_all:
            tag_keys.update(sh.index.tag_keys(mst))
            schema.update(sh.schema(mst))
        if not schema and stmt.group_by_all_tags:
            raise QueryError("measurement not found")  # see _select_raw
        sc = cond.split(stmt.condition, tag_keys, now_ns)
        tmin, tmax = sc.tmin, sc.tmax
        explicit_tmin = tmin != cond.MIN_TIME
        explicit_tmax = tmax != cond.MAX_TIME
        shards = [sh for sh in shards_all if sh.tmax > tmin and sh.tmin < tmax]
        if not shards:
            return None
        # data-driven clamp of an unbounded range (influx uses epoch 0/now)
        if not explicit_tmin or not explicit_tmax:
            dmin, dmax = _data_time_range(shards, mst)
            if dmin is None:
                return None
            if not explicit_tmin:
                tmin = dmin
            if not explicit_tmax:
                tmax = dmax + 1
        if tmax <= tmin:
            return None
        group_time = stmt.group_by_time
        if group_time:
            aligned = int(winmod.window_start(tmin, group_time.every_ns, group_time.offset_ns))
            every = group_time.every_ns
            if not explicit_tmax and stmt.limit and stmt.ascending:
                # unbounded upper + LIMIT: the reference iterates windows
                # to now(); emitting exactly offset+limit windows from the
                # data start is equivalent and bounded
                want = stmt.offset + stmt.limit
                tmax = max(tmax, min(now_ns, aligned + want * every))
            W = winmod.num_windows(tmin, tmax, every, group_time.offset_ns)
            if W > MAX_SELECT_BUCKETS:
                raise QueryError(
                    f"GROUP BY time({every}ns) would create {W} buckets "
                    f"(max {MAX_SELECT_BUCKETS})"
                )
        else:
            # output timestamp of whole-range aggregates: the explicit WHERE
            # lower bound, else epoch 0 (influx semantics; the data-driven
            # clamp above must not leak into result rows)
            aligned = tmin if explicit_tmin else 0
            W = 1
        group_tags = self._group_tags(stmt, shards, mst)
        # ordered group keys + per-(shard, sid) membership
        gid_of: dict[tuple, int] = {}
        group_keys: list[tuple] = []
        scan_plan = []  # (shard, sid, gid)
        # GROUP BY time emits fill rows even for series with zero matching
        # rows — pruning those series would change the emitted series set,
        # so the index only prunes un-windowed scans
        match_terms = (
            [] if group_time else cond.conjunctive_match_terms(sc.field_expr)
        )
        # /*+ full_series|specific_series */: the WHERE identifies whole
        # series — evaluate mixed tag/field trees at the series level and
        # skip their per-row filter (reference: hybrid store reader hints)
        hinted = bool({"full_series", "specific_series"}
                      & set(getattr(stmt, "hints", ())))
        exact_tags = (
            cond.exact_series_tags(stmt.condition, tag_keys)
            if "full_series" in getattr(stmt, "hints", ()) else None
        ) or None  # no tag equalities -> the hint pins nothing
        for sh in shards:
            # sorted int64 arrays end-to-end: the columnar label tier
            # answers the tag tree and the mixed-tree prunes intersect
            # without per-shard Python set materialization
            sids = cond.eval_tag_sids(sc.tag_expr, sh.index, mst)
            if sc.mixed_expr is not None and sids.size:
                if hinted:
                    sids = np.intersect1d(
                        sids, cond.series_only_arr(
                            sc.mixed_expr, sh.index, mst, sc.tag_keys),
                        assume_unique=True)
                else:
                    sids = np.intersect1d(
                        sids, cond.tag_superset_arr(
                            sc.mixed_expr, sh.index, mst, sc.tag_keys),
                        assume_unique=True)
            if exact_tags is not None and sids.size:
                keep = [s for s in sids.tolist()
                        if sh.index.tags_of(s) == exact_tags]
                sids = np.asarray(keep, np.int64)
            sids = _prune_text_sids(sh, mst, sids, match_terms)
            for sid in sids.tolist():
                tags = sh.index.tags_of(sid)
                key = tuple(tags.get(k, "") for k in group_tags)
                gid = gid_of.get(key)
                if gid is None:
                    gid = len(group_keys)
                    gid_of[key] = gid
                    group_keys.append(key)
                scan_plan.append((sh, sid, gid))
        if hinted:
            sc.mixed_series_level = True  # consumed at the series level
        if not scan_plan and not (remote_mode == "meta" and live is not None):
            # clustered "meta" scans proceed with an empty local plan:
            # the groups may exist only as remote partials
            return None
        return ScanContext(
            sc, shards, tmin, tmax, schema, tag_keys, group_time, aligned, W,
            group_tags, group_keys, scan_plan, live,
        )

    # -- aggregate path -----------------------------------------------------


    def _select_agg(self, stmt, db, rp, mst, now_ns, calls, trace=tracing.NOOP) -> list[dict]:
        from opengemini_tpu.query import partials as pmod

        # resolve agg specs + fields (before planning: the set decides
        # whether remote data arrives as partials or raw columns)
        aggs = []  # (out_name, spec, params, field_name)
        for f in stmt.fields:
            for call in _calls_in(f.expr):
                spec, params, field_name = _resolve_call(call)
                aggs.append((call, spec, params, field_name))

        pushdown = (
            self.router is not None
            # getattr: duck-typed router stubs without the full surface
            # keep the raw column-exchange path
            and getattr(self.router, "has_peers", lambda: False)()
            and all(
                spec.name in pmod.MERGEABLE
                or spec.name in pmod.MULTISET_MERGEABLE
                for _c, spec, _p, _f in aggs
            )
            and not any(f.lower() == "time" for _c, _s, _p, f in aggs)
        )
        attempts = max(self.router.rf, 1) if pushdown else 1
        for attempt in range(attempts):
            try:
                return self._select_agg_run(
                    stmt, db, rp, mst, now_ns, aggs, pushdown, trace
                )
            except pcluster.PartialsUnavailable:
                # a live peer cannot serve partials (e.g. rolling
                # upgrade): the raw column exchange still works
                return self._select_agg_run(
                    stmt, db, rp, mst, now_ns, aggs, False, trace
                )
            except pcluster.PartialsRetry as e:
                # a peer died mid-query: primary ownership shifted, the
                # whole plan (live set, local primary filter) is stale
                if attempt == attempts - 1:
                    raise QueryError(str(e)) from e
        raise AssertionError("unreachable")


    def _select_agg_run(self, stmt, db, rp, mst, now_ns, aggs, pushdown,
                        trace=tracing.NOOP) -> list[dict]:
        from opengemini_tpu.query import partials as pmod

        with trace.span("map_shards") as sp:
            ctx = self._scan_context(
                stmt, db, rp, mst, now_ns,
                remote_mode="meta" if pushdown else "raw",
            )
            if ctx is not None:
                sp.add_field("shards", len(ctx.shards))
                sp.add_field("series", len(ctx.scan_plan))
                sp.add_field("groups x windows", f"{len(ctx.group_keys)} x {ctx.W}")
        if ctx is None:
            return []
        sc, shards = ctx.sc, ctx.shards
        tmin, tmax = ctx.tmin, ctx.tmax
        group_time, aligned, W = ctx.group_time, ctx.aligned, ctx.W
        group_tags, group_keys, scan_plan = ctx.group_tags, ctx.group_keys, ctx.scan_plan
        schema = ctx.schema

        num_groups = len(group_keys)
        num_segments = num_groups * W

        # aggregates over the `time` pseudo-field (count/first/last/min/max
        # of row timestamps) are computed host-side from scanned row times
        time_aggs = [a for a in aggs if a[3].lower() == "time"]
        for _c, spec, _p, _f in time_aggs:
            if spec.name not in ("count", "first", "last", "min", "max"):
                raise QueryError(f"{spec.name}(time) is not supported")
        aggs = [a for a in aggs if a[3].lower() != "time"]
        # influx: COUNT/COUNT(DISTINCT ...) over a TAG answers a constant
        # 0 (tags are not countable fields; server_test.go
        # Aggregates_IntMany 'count distinct select tag')
        tag_count_aggs = [
            a for a in aggs
            if a[1].name in ("count", "count_distinct")
            and a[3] not in schema and a[3] in sc.tag_keys
        ]
        aggs = [a for a in aggs if a not in tag_count_aggs]

        needed_fields = sorted({a[3] for a in aggs})
        field_filter_fields = sorted(cond.row_filter_refs(sc))
        read_fields = sorted(set(needed_fields) | set(field_filter_fields))
        if time_aggs and not read_fields:
            read_fields = None  # time-only aggregates: read every field

        dtype = templates.compute_dtype()
        per_field_aggs: dict[str, list] = {}
        for _call, spec, _params, fname in aggs:
            per_field_aggs.setdefault(fname, []).append(spec.name)
        grid_ctx = (W, group_time.every_ns) if group_time else None
        batches: dict[str, object] = {
            f: pick_batch(schema, per_field_aggs[f], f, dtype, grid_ctx)
            for f in needed_fields
        }

        # incremental result cache (reference inc_agg_transform +
        # lib/resultcache): GROUP BY time() windows whose shards took no
        # writes since the last execution are served from cached
        # (value, count) cells; only the stale hull is scanned/computed
        cache_plan = None
        if (
            group_time is not None
            and W >= 1
            and aggs  # tag-count-only statements have nothing to cache
            # OGT_RESULT_CACHE=0 opts out (A/B runs — e.g. the offload
            # bench — must see every execution, not one per panel)
            and os.environ.get("OGT_RESULT_CACHE", "1") not in ("", "0")
            and self.router is None
            and ctx.live is None
            and not time_aggs
            and len(ctx.group_keys) <= 20_000  # cache growth gate
            and W <= 16_384  # > _MAX_WINDOWS would evict itself every run
            and all(hasattr(sh, "data_version") for sh in shards)
        ):
            from opengemini_tpu.query import resultcache as rcache

            fp = rcache.fingerprint(
                db, rp, mst, sc, group_time, group_tags,
                stmt.group_by_all_tags,
                [(spec.name, params, fname)
                 for _c, spec, params, fname in aggs],
            )
            cache_plan = rcache.CachePlan(
                self._inc_cache, fp, shards, aligned,
                group_time.every_ns, W, len(aggs), tmin, tmax)
        full_hit = cache_plan is not None and not cache_plan.scan_ranges
        scan_ranges = [(tmin, tmax)]
        if cache_plan is not None and cache_plan.scan_ranges:
            # disjoint stale runs: a now()-relative dashboard query scans
            # only its partial edge windows + actually-written windows
            scan_ranges = [
                (max(tmin, lo), min(tmax, hi))
                for lo, hi in cache_plan.scan_ranges
            ]

        # materialized-rollup splice (storage/rollup.py + rollupplan.py):
        # windows below the rollup watermark and not dirty are answered
        # from persisted rollup cells; the raw scan shrinks to the live
        # tail + re-dirtied windows.  Runs INSIDE the result-cache's
        # stale set so both layers compose; nothing here executes when no
        # rollup spec matches (engine.rollup_mgr is None pass-through).
        rollup_plan = None
        if (
            not full_hit
            and group_time is not None
            and aggs
            and not time_aggs
            and self.router is None
            and ctx.live is None
            and getattr(self.engine, "rollup_mgr", None) is not None
        ):
            from opengemini_tpu.query import rollupplan as rplan

            rollup_plan = rplan.try_plan(
                self.engine.rollup_mgr, db, rp, mst, sc, ctx, aggs,
                schema, cache_plan, tmin, tmax)
        if rollup_plan is not None:
            with trace.span("rollup") as sp:
                t0_rollup = _time.perf_counter_ns()
                rollup_plan.fetch()
                TRACKER.add_stage_ns(
                    TRACKER.current_qid(), "rollup",
                    _time.perf_counter_ns() - t0_rollup)
                sp.add_field("windows_spliced", len(rollup_plan.serve))
                sp.add_field("rollup_rows", rollup_plan.rows_read)
            if rollup_plan.serve:
                scan_ranges = rollup_plan.scan_ranges
            else:
                rollup_plan = None
        # no raw scan at all: every window comes from the result cache
        # and/or the rollup splice
        no_scan = full_hit or (rollup_plan is not None and not scan_ranges)

        # string fields: count counts, mean answers influx's constant 0,
        # stddev answers null (server_test.go Aggregates_String — the
        # zero payload of string columns makes both fall out below);
        # everything else is rejected (reference supports first/last on
        # strings — host path, later round)
        for call, spec, params, field_name in aggs:
            if schema.get(field_name) == FieldType.STRING and \
                    spec.name not in ("count", "mean", "stddev"):
                raise QueryError(
                    f"{spec.name}() is not supported on string field {field_name!r}"
                )
        # selector ordering uses an int32 (hi, lo) split of rel ns; guard the
        # 2^61 ns (~73 year) cliff explicitly rather than wrapping silently
        if tmax - aligned >= (1 << 61):
            raise QueryError("time range too large (over ~73 years) for aggregation")

        # pre-aggregation fast path (reference: immutable/pre_aggregation.go
        # block skipping, SURVEY.md §7 'before device transfer'): for
        # full-range count/sum/mean with no field filter, chunks wholly
        # inside the range contribute their stored (count, sum) WITHOUT
        # being decoded or transferred. Safe only when the series' sources
        # cannot overlap (no memtable rows in range, non-overlapping chunks).
        pre_eligible = (
            not group_time
            and not time_aggs
            and not sc.has_row_filter
            and all(spec.name in ("count", "sum", "mean") for _c, spec, _p, _f in aggs)
            # remote proxies carry no chunk metadata: full decode for them
            and all(getattr(sh, "supports_preagg", False) for sh in shards)
        )
        # pre-agg accumulators: int64 for INT fields (stored vsum values are
        # exact python ints), float64 otherwise
        def _pre_dtype(f):
            return np.int64 if schema.get(f) == FieldType.INT else np.float64

        pre_count = (
            {f: np.zeros(num_segments, np.int64) for f in needed_fields}
            if pre_eligible else {}
        )
        pre_sum = (
            {f: np.zeros(num_segments, _pre_dtype(f)) for f in needed_fields}
            if pre_eligible else {}
        )
        sum_fields = {f for _c, spec, _p, f in aggs if spec.name != "count"}

        time_segs: list[np.ndarray] = []
        time_vals: list[np.ndarray] = []
        pre_used = False
        sliced_out = None

        # decoded-column cache, device tier (storage/colcache.py): stamp
        # grid batches with a scan signature so their padded device
        # buffers are retained and a repeated identical scan skips the
        # host->device transfer (and the grid scatter). Local
        # deterministic scans only — no remote peers. Under a device
        # mesh the retained buffers are MESH-SHARDED (grid.py puts the
        # cold grid straight into the sharded layout), so warm mesh
        # queries skip the per-query shard_leading_axis copy entirely.
        device_token = None
        if (
            group_time is not None
            and self.router is None
            and ctx.live is None
            and colcache_mod.GLOBAL.device_enabled()
        ):
            device_token = _device_scan_token(
                db, rp, mst, sc, group_time, group_tags,
                stmt.group_by_all_tags, tmin, tmax, aligned, W, dtype,
                scan_ranges, shards)
        if device_token is not None:
            for f, b in batches.items():
                if hasattr(b, "device_cache_token"):
                    b.device_cache_token = f"{device_token}|{f}"

        # at-spec scans: window-aligned time slicing bounds host/device
        # memory and overlaps decode with device compute (VERDICT r4 #1;
        # reference analogue: the record-plan batch reader streams chunks,
        # engine/record_plan.go:75)
        slice_plan = None
        if (
            group_time is not None
            and not time_aggs
            and not pre_eligible
            and not no_scan
            and self.router is None
            and ctx.live is None
            and W >= 8
        ):
            slice_plan = _plan_scan_slices(
                shards, mst, scan_plan, aligned, group_time.every_ns, W,
                tmin, tmax)

        cc_before = (colcache_mod.GLOBAL.counters()
                     if colcache_mod.GLOBAL.enabled() else None)
        # per-query working-set reservation (utils/governor.py): charge
        # the chunk-meta estimate against the unified memory ledger for
        # the scan's duration; a reservation that would overdraw the
        # ledger kills this query through the tracker (clean error, no
        # OOM).  Zero-cost no-op when the governor is disabled.
        reservation = contextlib.nullcontext()
        if GOVERNOR.enabled() and not no_scan:
            est = estimate_scan_bytes(
                shards, mst, tmin, tmax,
                len(read_fields) if read_fields is not None else
                len(schema) or 1)
            reservation = GOVERNOR.scan_reservation(
                TRACKER.current_qid(), est)
        with reservation, trace.span("scan") as scan_span:
            if no_scan:
                rows_scanned = 0
            elif slice_plan is not None:
                rows_scanned, sliced_out = self._scan_sliced(
                    slice_plan, scan_plan, scan_ranges, sc, mst, group_time,
                    needed_fields, read_fields, dtype, schema,
                    per_field_aggs, num_groups, device_token,
                )
            else:
                rows_scanned, pre_used = self._scan_monolithic(
                    scan_plan, scan_ranges, sc, mst, group_time, tmin, W,
                    needed_fields, read_fields, dtype, aligned, batches,
                    time_aggs, time_segs, time_vals, pre_eligible,
                    pre_count, pre_sum, sum_fields, tmax,
                )
            scan_span.add_field("rows", rows_scanned)
            if slice_plan is not None:
                scan_span.add_field("slices", len(slice_plan))
        STATS.incr("executor", "rows_scanned", rows_scanned)
        # decoded-column cache attribution for EXPLAIN ANALYZE / query
        # stage stats: the scan-interval delta of the process-global
        # counters (concurrent queries can bleed in; the per-query exact
        # time also lands on this query via querytracker stages)
        if cc_before is not None:
            cc_after = colcache_mod.GLOBAL.counters()
            with trace.span("colcache") as sp:
                for key in ("hits", "misses", "device_hits",
                            "device_misses"):
                    sp.add_field(key, cc_after[key] - cc_before[key])
                sp.add_field(
                    "time_ms",
                    round((cc_after["time_ns"] - cc_before["time_ns"])
                          / 1e6, 3))
                sp.add_field("bytes_resident", cc_after["bytes"])
                sp.add_field("device_bytes", cc_after["device_bytes"])

        # run aggregates on device
        agg_results = {}  # id(call) -> (values, sel, counts)
        dv_before = devobs.span_snapshot() if devobs.enabled() else None
        with trace.span("device_compute") as sp:
            for call, spec, params, field_name in aggs:
                TRACKER.check()  # kill between device batch dispatches
                if no_scan:
                    # every window served from cache/rollup: no scan, no
                    # device work
                    dt = (np.int64 if isinstance(
                        batches[field_name], ragged.IntExactBatch)
                        and spec.name in ("sum", "count") else np.float64)
                    agg_results[id(call)] = (
                        np.zeros(num_segments, dt), None,
                        np.zeros(num_segments, np.int64), spec,
                        field_name, None)
                    continue
                if sliced_out is not None:
                    out, sel, counts = _stitch_sliced(
                        sliced_out, spec, params, field_name,
                        num_groups, W, num_segments)
                elif group_time and getattr(
                        batches[field_name], "supports_want_sel", False):
                    # GROUP BY time(): selector timestamps are never
                    # consulted (window start renders instead), so skip
                    # the selector-index kernels entirely — the imat
                    # build + lex scans were most of the grid path's
                    # cost for max()/min() scans
                    out, sel, counts = batches[field_name].run(
                        spec, num_segments, params, want_sel=False)
                else:
                    out, sel, counts = batches[field_name].run(
                        spec, num_segments, params)
                if spec.name == "percentile" and params:
                    # influx: rank floor(n*q/100+0.5)-1 < 0 yields NO row
                    # for the window (the device kernel clamps to the
                    # minimum sample; zero the counts so it renders empty)
                    qv = float(params[0])
                    ok = np.floor(counts * qv / 100.0 + 0.5) >= 1
                    if not ok.all():
                        counts = np.where(ok, counts, 0)
                if spec.name == "stddev" and \
                        schema.get(field_name) == FieldType.STRING:
                    # string stddev renders null rows (influx
                    # Aggregates_String; numeric singletons stay 0 — the
                    # reference's NewStdDevReduce rule)
                    out = np.where(counts > 0, np.nan, out)
                if pre_used:
                    # combine device partials with pre-agg contributions
                    pc = pre_count[field_name]
                    ps = pre_sum[field_name]
                    if spec.name == "count":
                        out = out + pc
                    elif spec.name == "sum":
                        out = out + ps
                    else:  # mean = (dev_sum + pre_sum) / (dev_cnt + pre_cnt)
                        dev_sum, _s, _c = batches[field_name].run(
                            aggmod.get("sum"), num_segments
                        )
                        total_c = counts + pc
                        out = (dev_sum + ps) / np.maximum(total_c, 1)
                    counts = counts + pc.astype(counts.dtype)
                agg_results[id(call)] = (out, sel, counts, spec, field_name, None)
            if time_aggs:
                import dataclasses as _dc

                seg_all = (
                    np.concatenate(time_segs) if time_segs
                    else np.empty(0, np.int32)
                )
                t_all = (
                    np.concatenate(time_vals) if time_vals
                    else np.empty(0, np.int64)
                )
                tcounts = np.bincount(seg_all, minlength=num_segments).astype(np.int64)
            for call, spec, params, field_name in tag_count_aggs:
                out = np.zeros(num_segments, np.int64)
                # the constant-0 row emits in EVERY window: under
                # GROUP BY time() the reference renders the shortcut per
                # window (window 0 alone would truncate the series to one
                # row); without time grouping W == 1 and this is the
                # single constant row as before
                counts = np.ones(num_segments, np.int64)  # rows render as 0
                agg_results[id(call)] = (out, None, counts, spec,
                                         field_name, None)
            for call, spec, _params, _f in time_aggs:
                if spec.name == "count":
                    tout = tcounts
                elif spec.name in ("last", "max"):
                    tout = np.full(num_segments, np.iinfo(np.int64).min, np.int64)
                    np.maximum.at(tout, seg_all, t_all)
                else:  # first/min
                    tout = np.full(num_segments, np.iinfo(np.int64).max, np.int64)
                    np.minimum.at(tout, seg_all, t_all)
                spec2 = _dc.replace(spec, int_output=True)
                agg_results[id(call)] = (tout, None, tcounts, spec2, "time", tout)
            sp.add_field("aggregates", len(aggs))
            sp.add_field("segments", num_segments)
            if sliced_out is not None:
                sp.add_field(
                    "batch_rows",
                    {f: sum(sb[f].n for _w0, _ws, sb in sliced_out)
                     for f in needed_fields})
                sp.add_field(
                    "layouts",
                    {f: "sliced[" + ",".join(sorted(
                        {sb[f].layout_name() for _w0, _ws, sb in sliced_out}
                        or {"empty"})) + "]"
                     for f in needed_fields})
            else:
                sp.add_field(
                    "batch_rows", {f: b.n for f, b in batches.items()}
                )
                # EXPLAIN ANALYZE shows which layout actually executed per
                # field (a GridBatch may have fallen back internally, or
                # not have run at all on a full cache hit)
                sp.add_field(
                    "layouts", {f: b.layout_name() for f, b in batches.items()}
                )
            STATS.incr("executor", "device_batches", len(aggs))
            if dv_before is not None:
                # devobs delta attribution (compiles + transfer bytes
                # this span caused; concurrent queries can bleed in —
                # the per-query exact time lands via the device_*
                # tracker stages)
                dv_after = devobs.span_snapshot()
                for key in ("compiles", "h2d_bytes", "d2h_bytes",
                            "reshard_bytes"):
                    sp.add_field(key, dv_after[key] - dv_before[key])
                sp.add_field("compile_wall_ms", round(
                    dv_after["compile_wall_ms"]
                    - dv_before["compile_wall_ms"], 3))

        has_remote_data = any(
            isinstance(sh, pcluster.MetaShard) for sh in shards
        )
        if pushdown and ctx.live is not None and has_remote_data:
            # aggregate pushdown: peers computed the same grid over their
            # shards; merge their O(groups x windows) partial arrays
            # (reference: rpc_transform partial agg + merge_transform)
            from opengemini_tpu.sql import astjson

            with trace.span("remote_partials") as sp:
                req = {
                    "db": db, "rp": rp, "mst": mst,
                    "tmin": tmin, "tmax": tmax, "aligned": aligned,
                    "every_ns": group_time.every_ns if group_time else 0,
                    "offset_ns": group_time.offset_ns if group_time else 0,
                    "W": W, "group_tags": group_tags,
                    "aggs": per_field_aggs,
                    "tag_expr": astjson.to_json(sc.tag_expr),
                    "field_expr": astjson.to_json(sc.field_expr),
                    "mixed_expr": astjson.to_json(sc.mixed_expr),
                    "mixed_series_level": sc.mixed_series_level,
                    # the COORDINATOR's tag-key view: peers must evaluate
                    # mixed trees against the same classification — a tag
                    # absent from a peer's local index must still inject
                    # as an empty-string column (r3 ADVICE #2)
                    "tag_keys": sorted(sc.tag_keys),
                }
                peer_docs = self.router.select_partials(req, ctx.live)
                for doc in peer_docs:
                    # stitch each replica's span subtree (shipped in the
                    # partials header) under this RPC span — parentage
                    # was fixed by the wire ctx the request carried
                    trace.graft(doc.pop("trace", None))
                if peer_docs:
                    pmod.merge_remote_partials(
                        agg_results, aggs, batches, group_keys, W,
                        peer_docs, group_tags,
                    )
                sp.add_field("peers", len(peer_docs))

        if rollup_plan is not None:
            # before the cache merge: the cache persists the spliced
            # windows (they sit in its stale set) from these arrays
            group_keys = rollup_plan.merge(agg_results, aggs, group_keys)
        if cache_plan is not None:
            with trace.span("inc_cache"):
                group_keys = cache_plan.merge(agg_results, aggs, group_keys)
        with trace.span("render"):
            return self._render_agg(
                stmt, mst, group_tags, group_keys, aligned, W, agg_results,
                batches, schema, tmin,
            )


    def _scan_monolithic(
        self, scan_plan, scan_ranges, sc, mst, group_time, tmin, W,
        needed_fields, read_fields, dtype, aligned, batches,
        time_aggs, time_segs, time_vals, pre_eligible,
        pre_count, pre_sum, sum_fields, tmax,
    ) -> tuple[int, bool]:
        """The classic single-pass scan: decode every series in range into
        `batches`. Returns (rows_scanned, pre_used).

        Pipelined (storage/scanpool.py): bulk shard reads double-buffer —
        unit N+1 decodes on a prefetch thread (which itself fans chunk
        decodes across the worker pool) while unit N's rows feed the
        device batches. Per-series records coalesce through a staging
        buffer so each field takes ONE contiguous batch add per scan
        instead of one tiny append per series."""
        rows_scanned = 0
        pre_used = False
        fmask = None

        def _scan_record(rec, seg, sids=None):
            if time_aggs:
                m = fmask if fmask is not None else slice(None)
                time_segs.append(seg[m])
                time_vals.append(rec.times[m])
            _add_record_to_batches(
                rec, seg, aligned, needed_fields, batches, dtype, fmask,
                sids=sids,
            )

        # batched multi-series path: one bulk decode per shard when
        # many series are scanned (packed colstore chunks decode once
        # for all their series; kills the per-sid Python loop that
        # dominated config #5 — BASELINE.md round-2 profile)
        remaining_plan = scan_plan
        if not pre_eligible:
            by_shard: dict[int, tuple] = {}
            for sh, sid, gid in scan_plan:
                by_shard.setdefault(id(sh), (sh, []))[1].append((sid, gid))
            remaining_plan = []
            units = []  # thunks: () -> (sh, sid_sorted, gid_sorted, sid_arr, rec)
            for sh, pairs in by_shard.values():
                if len(pairs) < 64 or not hasattr(sh, "read_series_bulk"):
                    remaining_plan.extend(
                        (sh, sid, gid) for sid, gid in pairs)
                    continue
                sid_list = np.asarray([p[0] for p in pairs], np.int64)
                gid_list = np.asarray([p[1] for p in pairs], np.int64)
                o = np.argsort(sid_list)
                sid_sorted, gid_sorted = sid_list[o], gid_list[o]
                for rlo, rhi in scan_ranges:
                    units.append(
                        lambda sh=sh, ss=sid_sorted, gs=gid_sorted,
                        rlo=rlo, rhi=rhi:
                        (sh, ss, gs) + sh.read_series_bulk(
                            mst, ss, rlo, rhi, fields=read_fields))
            for sh, sid_sorted, gid_sorted, sid_arr, rec in \
                    scanpool.prefetch_ordered(units):
                TRACKER.check()
                if len(rec) == 0:
                    continue
                rows_scanned += len(rec)
                fmask = (
                    cond.eval_row_filter(sc, rec, sid_arr=sid_arr,
                                         index=sh.index)
                    if sc.has_row_filter
                    else None
                )
                gid_rows = gid_sorted[
                    np.searchsorted(sid_sorted, sid_arr)]
                if group_time:
                    widx, _ = winmod.window_index(
                        rec.times, tmin, group_time.every_ns,
                        group_time.offset_ns)
                    seg = (gid_rows * W + widx.astype(np.int64)
                           ).astype(np.int32)
                else:
                    seg = gid_rows.astype(np.int32)
                _scan_record(rec, seg, sids=sid_arr)
        # per-series tail: stage rows and materialize ONE contiguous
        # array set per field at the end (per-chunk concatenation in this
        # loop was the executor-side hot spot at high cardinality)
        stager = _ScanStager(needed_fields, dtype, batches, time_aggs,
                             time_segs, time_vals, aligned) \
            if not pre_eligible and remaining_plan else None
        for sh, sid, gid in remaining_plan:
            TRACKER.check()  # KILL QUERY cancellation point
            if pre_eligible:
                handled, got_rows = self._scan_preagg(
                    sh, mst, sid, gid, tmin, tmax, needed_fields,
                    batches, pre_count, pre_sum, dtype, aligned, sum_fields,
                )
                if handled:
                    pre_used = True
                    rows_scanned += got_rows
                    continue
            for rlo, rhi in scan_ranges:
                rec = sh.read_series(mst, sid, rlo, rhi,
                                     fields=read_fields)
                if len(rec) == 0:
                    continue
                rows_scanned += len(rec)
                fmask = (
                    cond.eval_row_filter(
                        sc, rec, tags=sh.index.tags_of(sid))
                    if sc.has_row_filter
                    else None
                )
                if group_time:
                    widx, _ = winmod.window_index(
                        rec.times, tmin, group_time.every_ns,
                        group_time.offset_ns)
                    seg = (gid * W + widx.astype(np.int64)
                           ).astype(np.int32)
                else:
                    seg = np.full(len(rec), gid, dtype=np.int32)
                if stager is not None:
                    stager.add(rec, seg, fmask, sid)
                else:
                    _scan_record(rec, seg, sids=sid)
        if stager is not None:
            stager.flush()
        return rows_scanned, pre_used

    def _scan_sliced(
        self, slice_plan, scan_plan, scan_ranges, sc, mst, group_time,
        needed_fields, read_fields, dtype, schema, per_field_aggs,
        num_groups, device_token=None,
    ) -> tuple[int, list]:
        """Window-aligned sliced scan: each slice decodes into its own
        batch set, then the device kernels for that slice are DISPATCHED
        (not materialized) before the next slice decodes — on a real
        accelerator the device crunches slice k while the host decodes
        k+1 (the double-buffering VERDICT r4 #1 asked for). Returns
        (rows_scanned, [(w0, W_s, {field: batch})])."""
        rows_scanned = 0
        out = []
        STATS.incr("executor", "sliced_scans")
        for (w0, W_s, lo, hi) in slice_plan:
            TRACKER.check()
            ranges = [(max(lo, rlo), min(hi, rhi))
                      for rlo, rhi in scan_ranges
                      if max(lo, rlo) < min(hi, rhi)]
            if not ranges:
                continue
            sbatches = {
                f: pick_batch(schema, per_field_aggs[f], f, dtype,
                              (W_s, group_time.every_ns))
                for f in needed_fields
            }
            if device_token is not None:
                # per-slice signature: same scan, distinct window span
                for f, b in sbatches.items():
                    if hasattr(b, "device_cache_token"):
                        b.device_cache_token = \
                            f"{device_token}|{f}|{w0}:{W_s}"
            got, _pre = self._scan_monolithic(
                scan_plan, ranges, sc, mst, group_time, lo, W_s,
                needed_fields, read_fields, dtype, lo, sbatches,
                [], [], [], False, {}, {}, set(), hi,
            )
            rows_scanned += got
            for f, b in sbatches.items():
                prefetch = getattr(b, "prefetch", None)
                if prefetch is not None:
                    prefetch(num_groups * W_s, per_field_aggs[f])
            out.append((w0, W_s, sbatches))
        return rows_scanned, out

    def _scan_preagg(
        self, sh, mst, sid, gid, tmin, tmax, needed_fields,
        batches, pre_count, pre_sum, dtype, aligned, sum_fields,
    ) -> tuple[bool, int]:
        """Try the pre-agg path for one series. Returns (handled, rows):
        handled=False -> caller does the normal decode+batch scan. No side
        effects until the whole series validates."""
        needs_merge, srcs = _series_needs_merged_decode(sh, mst, sid, tmin, tmax)
        if needs_merge:
            return False, 0  # dedup required: decode via read_series
        if not srcs:
            return True, 0  # nothing in range at all
        # validate: every fully-covered chunk must carry a sum for fields
        # that need one (bool/string columns store count-only pre-agg)
        contrib: list[tuple[str, int, float | None]] = []
        full_rows = 0
        partials = []
        for r, c in srcs:
            if tmin <= c.tmin and c.tmax < tmax:
                for fname in needed_fields:
                    loc = c.cols.get(fname)
                    if loc is None:
                        continue
                    pre = loc["pre"]
                    if not pre.count:
                        continue
                    if fname in sum_fields and pre.vsum is None:
                        return False, 0
                    contrib.append((fname, pre.count, pre.vsum))
                full_rows += c.rows
            else:
                partials.append((r, c))
        for fname, cnt, vsum in contrib:
            pre_count[fname][gid] += cnt
            if vsum is not None:
                pre_sum[fname][gid] += vsum
        rows = full_rows
        for r, c in partials:
            try:
                rec = r.read_chunk(
                    mst, c, needed_fields).slice_time(tmin, tmax)
            except CorruptFile as e:
                # media damage on the pre-agg decode path: quarantine
                # through the owning shard (raises FileQuarantined)
                # rather than surfacing a raw codec error
                handler = getattr(sh, "note_corrupt", None)
                if handler is not None:
                    handler(e)
                raise
            if not len(rec):
                continue
            rows += len(rec)
            seg = np.full(len(rec), gid, dtype=np.int32)
            _add_record_to_batches(
                rec, seg, aligned, needed_fields, batches, dtype, None,
                sids=sid,
            )
        return True, rows


    def _group_tags(self, stmt, shards, mst) -> list[str]:
        if stmt.group_by_all_tags:
            keys: set[str] = set()
            for sh in shards:
                keys.update(sh.index.tag_keys(mst))
            return sorted(keys)
        return list(stmt.group_by_tags)


    def _render_agg(
        self, stmt, mst, group_tags, group_keys, aligned, W, agg_results,
        batches, schema, tmin,
    ) -> list[dict]:
        group_time = stmt.group_by_time
        every = group_time.every_ns if group_time else 0

        columns = ["time"]
        col_exprs = []
        used_names: dict[str, int] = {}
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.VarRef) and e.name.lower() == "time":
                continue  # explicit `time` is always column 0
            name = f.alias or _default_field_name(f.expr)
            k = used_names.get(name, 0)
            used_names[name] = k + 1
            if k:
                name = f"{name}_{k}"
            columns.append(name)
            col_exprs.append(f.expr)

        # selector fast path: a single selector call (bare, or wrapped in
        # scalar math like `max(rx) * 1`), no GROUP BY time -> result time
        # is the selected point's own timestamp (reference
        # TestServer_Query_Aggregates_Math#2)
        single_selector = None
        if not group_time and len(col_exprs) == 1:
            calls = _calls_in(col_exprs[0])
            if len(calls) == 1:
                entry = agg_results.get(id(calls[0]))
                if entry and entry[3].is_selector:
                    single_selector = entry

        host_times = (
            batches[single_selector[4]].host_times()
            if single_selector is not None and single_selector[5] is None
            else None
        )
        out_series = []
        order = sorted(range(len(group_keys)), key=lambda g: group_keys[g])
        for g in order:
            key = group_keys[g]
            rows = []
            for w in range(W):
                seg = g * W + w
                t_out = aligned + w * every if group_time else (aligned if aligned else 0)
                vals = []
                any_present = False
                for expr in col_exprs:
                    v, present = _eval_output_expr(expr, agg_results, seg, schema)
                    any_present = any_present or present
                    vals.append(v)
                if single_selector is not None:
                    out, sel, counts, spec, fname, times_abs = single_selector
                    if counts[seg] > 0:
                        t_out = (
                            int(times_abs[seg]) if times_abs is not None
                            else int(host_times[sel[seg]])
                        )
                rows.append((t_out, vals, any_present))
            if not any(p for _t, _v, p in rows):
                # zero matching points in the whole range: no series at
                # all, regardless of fill (TestServer_Query_Fill#2)
                continue
            count_idx = tuple(
                i for i, e in enumerate(col_exprs)
                if isinstance(_strip_expr(e), ast.Call)
                and _strip_expr(e).name in ("count", "count_distinct")
            )
            rows = _apply_fill(rows, stmt, columns, count_idx)
            if not stmt.ascending:
                rows.reverse()
            if stmt.offset:
                rows = rows[stmt.offset :]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {
                "name": mst,
                "columns": columns,
                "values": [[t] + v for t, v, _p in rows],
            }
            if group_tags:
                series["tags"] = dict(zip(group_tags, key))
            out_series.append(series)
        return out_series

    # -- percentile_approx (chunk-histogram sketches) ------------------------


